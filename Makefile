# Developer/CI entry points.  Everything runs on the virtual CPU mesh
# unless the environment points JAX at real hardware.

PY ?= python

.PHONY: test lint lint-changed lockcheck smoke serve-smoke obs-smoke slo-smoke tenancy-smoke mem-smoke chaos-smoke mesh-smoke cache-smoke kernel-smoke fleet-smoke program-smoke watch-smoke bench bench-link bench-verify checks-corpus rules-cache perf-gate

# Tier-1: the suite the driver holds the repo to (fast, CPU, no slow marks).
# Lint runs first — a graftlint finding fails the build before pytest
# collection starts, and costs ~2s when clean.  The perf gate rides the
# fast path too: one smoke bench run vs the checked-in baseline.
test: lint
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider
	$(MAKE) chaos-smoke
	$(MAKE) mesh-smoke
	$(MAKE) cache-smoke
	$(MAKE) kernel-smoke
	$(MAKE) fleet-smoke
	$(MAKE) program-smoke
	$(MAKE) watch-smoke
	$(MAKE) perf-gate

# Static analysis: graftlint (project rules GL001-GL015, always available)
# plus ruff + mypy when the environment has them (the pinned CI container
# may not; config lives in pyproject.toml either way).
lint:
	$(PY) -m tools.graftlint
	@if $(PY) -c "import ruff" 2>/dev/null || command -v ruff >/dev/null; then \
		ruff check trivy_tpu tools bench.py; \
	else \
		echo "lint: ruff not installed, skipping (config in pyproject.toml)"; \
	fi
	@if command -v mypy >/dev/null; then \
		mypy --config-file pyproject.toml; \
	else \
		echo "lint: mypy not installed, skipping (config in pyproject.toml)"; \
	fi

# Fast pre-commit loop: only .py files changed vs HEAD.
lint-changed:
	$(PY) -m tools.graftlint --changed

# The runtime sanitizer over the threaded suites: lock-order cycles and
# owner-role violations anywhere in the run fail the session (see
# tests/conftest.py pytest_sessionfinish).
# (test_lockcheck.py is deliberately absent: its unit tests create
# violations on purpose and reset the graph, which would blind the
# session-end gate for everything before them; they run in tier-1.)
lockcheck:
	TRIVY_TPU_LOCKCHECK=1 JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_serve_scheduler.py tests/test_serve_reload.py \
		tests/test_chunk_pipeline.py tests/test_tenancy.py \
		tests/test_mesh.py \
		-q -m 'not slow' -p no:cacheprovider

# CI smoke: tiny-corpus bench.py --smoke on CPU (pipeline depth 2) via the
# slow-marked subprocess test, which asserts the single-JSON-line contract
# and nonzero h2d overlap accounting — plus the codec parity smoke: the
# same corpus with TRIVY_TPU_LINK_CODEC=off and =auto must produce
# byte-identical findings.
smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_bench_smoke.py::test_bench_smoke_subprocess \
		tests/test_bench_smoke.py::test_smoke_codec_off_vs_auto \
		-q -p no:cacheprovider

# Server-mode smoke: boot the batching server on a random port, fire
# concurrent ScanSecrets, assert every request succeeds, the /metrics
# fill/coalescing counters are nonzero (>= one batch carried items from
# two or more requests), and shutdown drains cleanly.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve_smoke.py \
		-m serve_smoke -q -p no:cacheprovider

# Observability smoke: span-tree / off-by-default / exposition-lint tests
# plus a BENCH_OBS-only bench run (disabled-path no-op span overhead < 2%
# of scan wall asserted; findings off-vs-on byte-identical).
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_obs_trace.py \
		tests/test_obs_metrics.py tests/test_observability.py \
		-q -p no:cacheprovider && \
	BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 BENCH_HITDENSE=0 \
		BENCH_LINK=0 BENCH_SERVE=0 BENCH_COLDSTART=0 BENCH_LICENSE=0 \
		BENCH_IMAGE=0 BENCH_TENANT=0 BENCH_MEM=0 BENCH_FAULT=0 \
		BENCH_MULTICHIP=0 BENCH_CACHE=0 BENCH_FLEET=0 BENCH_PROGRAMS=0 BENCH_DELTA=0 \
		$(PY) bench.py --smoke

# SLO / flight-recorder smoke: boot the server with a deliberately tight
# latency objective, drive mixed-tenant traffic with one induced breach,
# then assert the /debug/slo budget math recomputes from its own window
# sums, a flight record captured the breach (span tree + scheduler
# snapshot), top-K tenant series + "_other" rollup hold on /metrics, and
# the same request carried an X-Trivy-Explain phase breakdown.
slo-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_slo_smoke.py \
		-m slo_smoke -q -p no:cacheprovider

# Multi-tenant serving smoke (trivy_tpu/tenancy/): lane routing, WRR
# fairness, pool LRU/warm re-admit, quota 429s, rules push e2e — with the
# lock-order sanitizer armed — then a BENCH_TENANT-only bench run (lane
# fill ratio, cross-tenant shared batches, pool hit rate, zero-recompile
# evict/re-admit cycle on the single-JSON-line contract).
tenancy-smoke:
	TRIVY_TPU_LOCKCHECK=1 JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_tenancy.py tests/test_rules_push.py \
		-q -m 'not slow' -p no:cacheprovider && \
	BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 BENCH_HITDENSE=0 \
		BENCH_LINK=0 BENCH_SERVE=0 BENCH_COLDSTART=0 BENCH_LICENSE=0 \
		BENCH_IMAGE=0 BENCH_OBS=0 BENCH_MEM=0 BENCH_FAULT=0 \
		BENCH_MULTICHIP=0 BENCH_CACHE=0 BENCH_FLEET=0 BENCH_PROGRAMS=0 BENCH_DELTA=0 \
		$(PY) bench.py --smoke

# Device-memory observatory smoke: memwatch ledger units, pool
# estimate-vs-measured reconciliation, pressure watermark e2e
# (soft -> LRU eviction, hard -> 429 + Retry-After, hbm-pressure flight
# records) — then a BENCH_MEM-only bench run (ledger conservation, pool
# reconciliation delta, soft-evict latency, per-device memory_stats).
mem-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_memwatch.py \
		-m mem_smoke -q -p no:cacheprovider && \
	BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 BENCH_HITDENSE=0 \
		BENCH_LINK=0 BENCH_SERVE=0 BENCH_COLDSTART=0 BENCH_LICENSE=0 \
		BENCH_IMAGE=0 BENCH_TENANT=0 BENCH_OBS=0 BENCH_FAULT=0 \
		BENCH_MULTICHIP=0 BENCH_CACHE=0 BENCH_FLEET=0 BENCH_PROGRAMS=0 BENCH_DELTA=0 \
		$(PY) bench.py --smoke

# Chaos smoke: the fault-injection serve suite (tests/test_chaos_serve.py,
# -m chaos).  Arms the in-repo fault plane on the dispatch/device/rpc
# seams and asserts the failure-domain contract: byte-identical findings
# under per-batch degradation, zero lost tickets, breaker opens under
# sustained failure and re-closes when the fault budget clears, and a
# 20%-connection-reset RPC profile completes every request.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m chaos \
		-p no:cacheprovider

# Mesh execution plane smoke (trivy_tpu/mesh/): topology/plan units plus
# the 1/2/4/8-device byte-parity fuzz — tests/conftest.py forces 8 XLA
# host devices, so the CPU run exercises real 8-way sharding.
mesh-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_mesh.py \
		-m mesh_smoke -q -p no:cacheprovider

# Fleet result-cache smoke (trivy_tpu/cache/): the cold->warm image
# re-scan must do zero device dispatches and zero analyzer re-runs with
# byte-identical findings, and a ruleset-digest change must invalidate
# exactly the affected entries (-m cache_smoke) — then a BENCH_CACHE-only
# bench run (warm hit rate 1.0, zero-dispatch warm pass, cold/warm report
# parity, wall speedup on the single-JSON-line contract).
cache-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_cache_tiered.py \
		-m cache_smoke -q -p no:cacheprovider && \
	BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 BENCH_HITDENSE=0 \
		BENCH_LINK=0 BENCH_SERVE=0 BENCH_COLDSTART=0 BENCH_LICENSE=0 \
		BENCH_IMAGE=0 BENCH_TENANT=0 BENCH_OBS=0 BENCH_MEM=0 \
		BENCH_FAULT=0 BENCH_MULTICHIP=0 BENCH_FLEET=0 BENCH_PROGRAMS=0 BENCH_DELTA=0 \
		$(PY) bench.py --smoke

# Megakernel smoke (ops/megakernel.py + registry/aotcache.py): parity
# fuzz of the one-dispatch MXU kernel vs the staged fused pipeline vs
# the host oracle across codec modes and forced-host-device counts,
# plus the AOT executable store's compile-once assertion (a warm
# registry start performs ZERO kernel compiles) and the scheduler's
# megakernel -> staged-sieve step-down rung.
kernel-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_megakernel.py \
		-m kernel_smoke -q -p no:cacheprovider

# Fleet plane smoke (trivy_tpu/fleet/): ring determinism pins, the member
# health machine, router spill policy, keep-alive transport regression,
# and the 2-member in-process e2e (affinity convergence, drain failover
# with zero dropped requests, byte parity vs a single host) — then a
# BENCH_FLEET-only bench run (2-process aggregate throughput, affinity
# hit rate, SIGTERM failover with zero dropped tickets on the
# single-JSON-line contract).
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py \
		-q -p no:cacheprovider && \
	BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 BENCH_HITDENSE=0 \
		BENCH_LINK=0 BENCH_SERVE=0 BENCH_COLDSTART=0 BENCH_LICENSE=0 \
		BENCH_IMAGE=0 BENCH_TENANT=0 BENCH_OBS=0 BENCH_MEM=0 \
		BENCH_FAULT=0 BENCH_MULTICHIP=0 BENCH_CACHE=0 BENCH_PROGRAMS=0 BENCH_DELTA=0 \
		$(PY) bench.py --smoke

# Device scan-program smoke (trivy_tpu/programs/): the multi-program
# demux parity fuzz — secret + license verdicts from ONE sieve pass,
# byte-identical to the single-program engines across codec modes and
# 1/2/4/8 forced host devices on NUL-heavy/exact-tile/jumbo blobs —
# plus the warm-registry zero-recompile and compile-time anchor-coverage
# contracts.
program-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_programs.py \
		-m program_smoke -q -p no:cacheprovider

# Continuous-scanning-plane smoke (trivy_tpu/watch/): poller dedupe,
# zero-dispatch planning on a re-pushed identical image, the
# re-verification sweep touching only invalidated verdicts, webhook
# at-least-once under injected rpc.recv/watch.poll faults, JSONL
# ordering — then a BENCH_DELTA-only bench run (warm_dispatches 0,
# sweep_touched_ratio 0.5, byte-identical re-verdicts on the
# single-JSON-line contract).
watch-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_watch.py \
		-q -p no:cacheprovider && \
	BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 BENCH_HITDENSE=0 \
		BENCH_LINK=0 BENCH_SERVE=0 BENCH_COLDSTART=0 BENCH_LICENSE=0 \
		BENCH_IMAGE=0 BENCH_TENANT=0 BENCH_OBS=0 BENCH_MEM=0 \
		BENCH_FAULT=0 BENCH_MULTICHIP=0 BENCH_CACHE=0 BENCH_FLEET=0 \
		BENCH_PROGRAMS=0 $(PY) bench.py --smoke

# Performance regression gate: one smoke bench run (heavy sections off,
# primary corpus only) appends to a throwaway ledger, then
# `trivy-tpu perf gate` holds it against the checked-in baseline
# (tools/perfgate/baseline.json) and exits non-zero on any metric
# outside its per-metric tolerance.  After an INTENTIONAL perf change,
# refresh the baseline per tools/perfgate/README.md.
perf-gate:
	rm -f /tmp/trivy-tpu-perf-ledger.jsonl && \
	BENCH_LEDGER_FILE=/tmp/trivy-tpu-perf-ledger.jsonl \
		BENCH_DETAIL_FILE=/tmp/trivy-tpu-perf-detail.json \
		BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 \
		BENCH_HITDENSE=0 BENCH_LINK=0 BENCH_SERVE=0 BENCH_COLDSTART=0 \
		BENCH_LICENSE=0 BENCH_IMAGE=0 BENCH_TENANT=0 BENCH_OBS=0 \
		JAX_PLATFORMS=cpu $(PY) bench.py --smoke >/dev/null && \
	JAX_PLATFORMS=cpu $(PY) -m trivy_tpu.cli perf gate \
		--ledger /tmp/trivy-tpu-perf-ledger.jsonl \
		--baseline tools/perfgate/baseline.json

# Full benchmark (honest corpora; on CPU this takes a while).
bench:
	$(PY) bench.py

# Link-codec economics only: raw vs coded H2D bytes, effective link rate,
# D2H compaction ratios, full-corpus coded-vs-raw findings identity
# (bench.py BENCH_LINK section with every other section off).
bench-link:
	BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 BENCH_HITDENSE=0 \
		BENCH_SERVE=0 BENCH_COLDSTART=0 BENCH_LICENSE=0 BENCH_IMAGE=0 \
		BENCH_TENANT=0 BENCH_FAULT=0 BENCH_MULTICHIP=0 BENCH_CACHE=0 \
		BENCH_FLEET=0 BENCH_PROGRAMS=0 BENCH_DELTA=0 BENCH_FILES=2000 BENCH_PARITY=sample \
		$(PY) bench.py

# Verify-backend economics only: the hit-dense corpus under host-DFA vs
# legacy device-stream vs fused device-resident verify (bench.py
# bench_verify_backends).  `--smoke` keeps the corpus small enough for
# CPU CI; on TPU hosts drop it for the real device_vs_dfa / fused_vs_dfa
# rows the perf-gate baseline tracks.
bench-verify:
	BENCH_KERNEL=0 BENCH_RULE_SCALING=0 BENCH_DEVICE=0 BENCH_LINK=0 \
		BENCH_SERVE=0 BENCH_COLDSTART=0 BENCH_LICENSE=0 BENCH_IMAGE=0 \
		BENCH_TENANT=0 BENCH_MEM=0 BENCH_FAULT=0 BENCH_MULTICHIP=0 \
		BENCH_CACHE=0 BENCH_FLEET=0 BENCH_PROGRAMS=0 BENCH_DELTA=0 $(PY) bench.py --smoke

# Precompile the builtin ruleset into the registry cache (trivy_tpu/registry/)
# so every later scan/server process warm-starts without compiling rules.
# Honors TRIVY_TPU_RULES_CACHE_DIR / TRIVY_TPU_SECRET_CONFIG.
rules-cache:
	JAX_PLATFORMS=cpu $(PY) -m trivy_tpu.cli rules compile && \
		JAX_PLATFORMS=cpu $(PY) -m trivy_tpu.cli rules verify

# The check corpora: every builtin IaC check and every snapshot cloud
# check must keep a fail + pass fixture pair (the cloud corpus includes
# a drift test that fails when a snapshot check gains no fixture).
checks-corpus:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_iac_checks_corpus.py tests/test_cloud_checks_corpus.py \
		tests/test_trivy_checks_snapshot.py \
		-q -p no:cacheprovider
