"""OS detection analyzers.

Mirrors pkg/fanal/analyzer/os/: the generic os-release analyzer
(release/release.go) plus the distro-specific release files (alpine, debian,
ubuntu, amazon, redhat-base families).
"""

from __future__ import annotations

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.atypes import OS

# OS family constants (pkg/fanal/analyzer/const.go / types const)
ALPINE = "alpine"
DEBIAN = "debian"
UBUNTU = "ubuntu"
REDHAT = "redhat"
CENTOS = "centos"
ROCKY = "rocky"
ALMA = "alma"
FEDORA = "fedora"
ORACLE = "oracle"
AMAZON = "amazon"
SUSE_ENTERPRISE = "suse linux enterprise server"
OPENSUSE = "opensuse"
OPENSUSE_LEAP = "opensuse-leap"
OPENSUSE_TUMBLEWEED = "opensuse-tumbleweed"
PHOTON = "photon"
WOLFI = "wolfi"
CHAINGUARD = "chainguard"
MARINER = "cbl-mariner"

# release/release.go:51-77 ID -> family mapping
_OS_RELEASE_IDS = {
    "alpine": ALPINE,
    "opensuse-tumbleweed": OPENSUSE_TUMBLEWEED,
    "opensuse-leap": OPENSUSE_LEAP,
    "opensuse": OPENSUSE_LEAP,
    "sles": SUSE_ENTERPRISE,
    "photon": PHOTON,
    "wolfi": WOLFI,
    "chainguard": CHAINGUARD,
    "mariner": MARINER,
    "fedora": FEDORA,
}


def parse_os_release(content: bytes) -> tuple[str, str]:
    """Returns (id, version_id)."""
    os_id = version_id = ""
    for line in content.decode("utf-8", errors="replace").splitlines():
        key, _, value = line.partition("=")
        key, value = key.strip(), value.strip().strip("\"'")
        if key == "ID":
            os_id = value
        elif key == "VERSION_ID":
            version_id = value
    return os_id, version_id


class OSReleaseAnalyzer(Analyzer):
    """analyzer/os/release/release.go."""

    REQUIRED = {"etc/os-release", "usr/lib/os-release"}

    def type(self) -> str:
        return "os-release"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path in self.REQUIRED

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        os_id, version_id = parse_os_release(inp.content)
        family = _OS_RELEASE_IDS.get(os_id)
        if family is None or not version_id:
            return None
        return AnalysisResult(os=OS(family=family, name=version_id))


class AlpineReleaseAnalyzer(Analyzer):
    """analyzer/os/alpine/alpine.go — etc/alpine-release holds the version."""

    def type(self) -> str:
        return "alpine-release"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path == "etc/alpine-release"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        ver = inp.content.decode("utf-8", errors="replace").strip()
        if not ver:
            return None
        return AnalysisResult(os=OS(family=ALPINE, name=ver))


class DebianVersionAnalyzer(Analyzer):
    """analyzer/os/debian — etc/debian_version (when no os-release ID)."""

    def type(self) -> str:
        return "debian-version"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path == "etc/debian_version"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        ver = inp.content.decode("utf-8", errors="replace").strip()
        if not ver or "/" in ver:  # sid/testing strings carry no version
            return None
        return AnalysisResult(os=OS(family=DEBIAN, name=ver))


class LsbReleaseAnalyzer(Analyzer):
    """analyzer/os/ubuntu — etc/lsb-release (DISTRIB_ID=Ubuntu)."""

    def type(self) -> str:
        return "ubuntu"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path == "etc/lsb-release"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        fields = {}
        for line in inp.content.decode("utf-8", errors="replace").splitlines():
            k, _, v = line.partition("=")
            fields[k.strip()] = v.strip().strip('"')
        if fields.get("DISTRIB_ID") == "Ubuntu" and fields.get("DISTRIB_RELEASE"):
            return AnalysisResult(
                os=OS(family=UBUNTU, name=fields["DISTRIB_RELEASE"])
            )
        return None


class RedHatReleaseAnalyzer(Analyzer):
    """analyzer/os/redhatbase — etc/redhat-release & friends."""

    FILES = {
        "etc/redhat-release",
        "etc/centos-release",
        "etc/rocky-release",
        "etc/almalinux-release",
        "etc/oracle-release",
        "etc/fedora-release",
        "etc/system-release",
    }
    _FAMILIES = [
        ("CentOS", CENTOS),
        ("Rocky", ROCKY),
        ("AlmaLinux", ALMA),
        ("Oracle", ORACLE),
        ("Fedora", FEDORA),
        ("Amazon", AMAZON),
        ("Red Hat", REDHAT),
    ]

    def type(self) -> str:
        return "redhatbase"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path in self.FILES

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        import re

        text = inp.content.decode("utf-8", errors="replace")
        m = re.search(r"(\d+(?:\.\d+)*)", text)
        if not m:
            return None
        for marker, family in self._FAMILIES:
            if marker.lower() in text.lower():
                return AnalysisResult(os=OS(family=family, name=m.group(1)))
        return None


class AmazonReleaseAnalyzer(Analyzer):
    """analyzer/os/amazonlinux/amazonlinux.go — etc/system-release (AL1/2)
    or usr/lib/system-release (AL2022/2023); version text follows the
    'Amazon Linux [release]' prefix."""

    REQUIRED = {"etc/system-release", "usr/lib/system-release"}

    def type(self) -> str:
        return "amazon"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path in self.REQUIRED

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        for line in inp.content.decode("utf-8", "replace").splitlines():
            fields = line.split()
            if not line.startswith("Amazon Linux") or len(fields) < 3:
                continue
            # "Amazon Linux release 2 (Karoo)" / "Amazon Linux release
            # 2023.3.x" -> version after 'release'; "Amazon Linux 2023.x"
            # (AL2022/2023 usr/lib form) has no 'release' token.
            if fields[2] == "release" and len(fields) >= 4:
                name = " ".join(fields[3:])
            else:
                name = " ".join(fields[2:])
            return AnalysisResult(os=OS(family=AMAZON, name=name))
        return None


class MarinerReleaseAnalyzer(Analyzer):
    """analyzer/os/mariner/mariner.go — etc/mariner-release:
    'CBL-Mariner <version>'."""

    def type(self) -> str:
        return "cbl-mariner"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path == "etc/mariner-release"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        for line in inp.content.decode("utf-8", "replace").splitlines():
            fields = line.split()
            if line.startswith("CBL-Mariner") and len(fields) >= 2:
                return AnalysisResult(os=OS(family=MARINER, name=fields[1]))
        return None


register_analyzer(OSReleaseAnalyzer)
register_analyzer(AlpineReleaseAnalyzer)
register_analyzer(DebianVersionAnalyzer)
register_analyzer(LsbReleaseAnalyzer)
register_analyzer(RedHatReleaseAnalyzer)
register_analyzer(AmazonReleaseAnalyzer)
register_analyzer(MarinerReleaseAnalyzer)
