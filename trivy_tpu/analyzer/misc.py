"""Discovery analyzers: embedded SBOMs, executable digests, Red Hat
buildinfo, installed Python package metadata.

Mirrors pkg/fanal/analyzer/{sbom,executable,buildinfo} and the python-pkg
analyzer under language/python/packaging.
"""

from __future__ import annotations

import hashlib
import json
import re

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.atypes import Application, Package

# ---------------------------------------------------------------------------
# Embedded SBOMs (pkg/fanal/analyzer/sbom/sbom.go)
# ---------------------------------------------------------------------------

_SBOM_SUFFIXES = (".spdx", ".spdx.json", ".cdx", ".cdx.json")


class SbomFileAnalyzer(Analyzer):
    """SBOMs shipped inside the artifact (e.g. bitnami images publish
    per-component SPDX files) feed their packages straight into the scan."""

    def type(self) -> str:
        return "sbom"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.lower().endswith(_SBOM_SUFFIXES) and size < 8 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        from trivy_tpu.sbom import decode_sbom

        try:
            detail, _fmt = decode_sbom(inp.content.decode("utf-8", "replace"))
        except Exception:
            return None
        apps = list(detail.applications)
        # Bitnami layout: jars listed in opt/bitnami SBOMs exist next to the
        # SBOM file; anchor the application path there (sbom.go:45-57).
        for app in apps:
            if not app.file_path:
                app.file_path = inp.file_path
        # OS packages (apk/deb/rpm purls) land in detail.packages; wrap
        # them like build_sbom_reference does so they are not dropped.
        pkg_infos = list(detail.package_infos)
        if detail.packages:
            from trivy_tpu.atypes import PackageInfo

            pkg_infos.append(
                PackageInfo(file_path=inp.file_path, packages=detail.packages)
            )
        if not apps and not pkg_infos:
            return None
        return AnalysisResult(package_infos=pkg_infos, applications=apps)


# ---------------------------------------------------------------------------
# Executable digests (pkg/fanal/analyzer/executable) — Rekor lookup keys
# ---------------------------------------------------------------------------

_ELF_MAGIC = b"\x7fELF"


class ExecutableAnalyzer(Analyzer):
    """Disabled unless the scan opts into Rekor SBOM sources
    (--sbom-sources rekor): hashing every binary costs a full-content pass
    per executable and nothing else consumes the digests (the reference
    gates the same way, artifact.Option.RekorURL/SBOMSources)."""

    def __init__(self) -> None:
        self._enabled = False

    def init(self, options) -> None:
        self._enabled = "rekor" in getattr(options, "sbom_sources", [])

    def version(self) -> int:
        return 1

    def type(self) -> str:
        return "executable"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return self._enabled and bool(mode & 0o111) and size > 0

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        if not inp.content.startswith(_ELF_MAGIC):
            return None
        digest = "sha256:" + hashlib.sha256(inp.content).hexdigest()
        result = AnalysisResult()
        result.configs.append(
            {
                "Type": "executable",
                "FilePath": inp.file_path,
                "Digest": digest,
            }
        )
        return result


# ---------------------------------------------------------------------------
# Red Hat buildinfo (pkg/fanal/analyzer/buildinfo)
# ---------------------------------------------------------------------------

_NVR_RE = re.compile(r'"com\.redhat\.component"\s*=\s*"([^"]+)"')
_ARCH_RE = re.compile(r'"architecture"\s*=\s*"([^"]+)"')
_RELEASE_RE = re.compile(r'"release"\s*=\s*"([^"]+)"')
_VERSION_RE = re.compile(r'"version"\s*=\s*"([^"]+)"')


class ContentManifestAnalyzer(Analyzer):
    """root/buildinfo/content_manifests/*.json -> content sets (the Red Hat
    repo identifiers vuln matching keys off)."""

    def type(self) -> str:
        return "redhat-content-manifest"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return (
            file_path.startswith("root/buildinfo/content_manifests/")
            and file_path.endswith(".json")
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        try:
            doc = json.loads(inp.content)
        except ValueError:
            return None
        sets = doc.get("content_sets") or []
        if not sets:
            return None
        result = AnalysisResult()
        result.build_info = {"ContentSets": list(sets)}
        return result


class DockerfileLabelAnalyzer(Analyzer):
    """root/buildinfo/Dockerfile-* -> nvr + arch from Red Hat labels."""

    def type(self) -> str:
        return "redhat-dockerfile"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        name = file_path.rsplit("/", 1)[-1]
        return file_path.startswith("root/buildinfo/") and name.startswith(
            "Dockerfile-"
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        text = inp.content.decode("utf-8", errors="replace")
        comp = _NVR_RE.search(text)
        arch = _ARCH_RE.search(text)
        if not comp:
            return None
        version = _VERSION_RE.search(text)
        release = _RELEASE_RE.search(text)
        nvr = comp.group(1)
        if version and release:
            nvr = f"{comp.group(1)}-{version.group(1)}-{release.group(1)}"
        result = AnalysisResult()
        result.build_info = {
            "Nvr": nvr,
            "Arch": arch.group(1) if arch else "",
        }
        return result


# ---------------------------------------------------------------------------
# Installed Python packages (language/python/packaging) — egg-info/dist-info
# ---------------------------------------------------------------------------

_META_NAME = re.compile(r"^Name:\s*(.+)$", re.MULTILINE)
_META_VERSION = re.compile(r"^Version:\s*(.+)$", re.MULTILINE)
_META_LICENSE = re.compile(r"^License:\s*(.+)$", re.MULTILINE)


class PythonPkgAnalyzer(Analyzer):
    """Installed distributions: *.egg-info, *.egg-info/PKG-INFO,
    *.dist-info/METADATA."""

    def type(self) -> str:
        return "python-pkg"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        if file_path.endswith(".egg-info"):
            return True
        return file_path.endswith(
            (".egg-info/PKG-INFO", ".dist-info/METADATA")
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        text = inp.content.decode("utf-8", errors="replace")
        name = _META_NAME.search(text)
        version = _META_VERSION.search(text)
        if not name or not version:
            return None
        lic = _META_LICENSE.search(text)
        pkg = Package(
            id=f"{name.group(1).strip()}@{version.group(1).strip()}",
            name=name.group(1).strip().lower(),
            version=version.group(1).strip(),
            licenses=[lic.group(1).strip()] if lic else [],
            file_path=inp.file_path,
        )
        return AnalysisResult(
            applications=[
                Application(
                    app_type="python-pkg",
                    file_path=inp.file_path,
                    packages=[pkg],
                )
            ]
        )


register_analyzer(SbomFileAnalyzer)
register_analyzer(ExecutableAnalyzer)
register_analyzer(ContentManifestAnalyzer)
register_analyzer(DockerfileLabelAnalyzer)
register_analyzer(PythonPkgAnalyzer)
