"""Secret analyzer: pre-filter + adapter onto the device secret engine.

Mirrors pkg/fanal/analyzer/secret/secret.go — skip lists (:28-42), Required
gate (:115-153), binary sniff (utils.IsBinary, pkg/fanal/utils/utils.go:76-93),
``\r`` stripping (:91), leading ``/`` for image-extracted files (:97-99) — but
implements BatchAnalyzer so all claimed files of a walk board the device as one
packed batch.
"""

from __future__ import annotations

import os

from trivy_tpu.analyzer.core import (
    TYPE_SECRET,
    AnalysisInput,
    AnalysisResult,
    AnalyzerOptions,
    BatchAnalyzer,
    register_analyzer,
)
from trivy_tpu.rules.model import load_config

VERSION = 1

# secret.go:28-42
SKIP_FILES = {
    "go.mod",
    "go.sum",
    "package-lock.json",
    "yarn.lock",
    "pnpm-lock.yaml",
    "Pipfile.lock",
    "Gemfile.lock",
}
SKIP_DIRS = {".git", "node_modules"}
# Component-exact substring needles for the batched claim pass: dir
# components are always followed by "/" in a full path, the basename never
# is (derived so SKIP_DIRS edits propagate).
_SKIP_DIR_NEEDLES = tuple(f"/{d}/" for d in SKIP_DIRS)
SKIP_EXTS = {
    ".jpg", ".png", ".gif", ".doc", ".pdf", ".bin", ".svg", ".socket",
    ".deb", ".rpm", ".zip", ".gz", ".gzip", ".tar", ".pyc",
}


# Bytes the reference's control-byte heuristic accepts as text; translate()
# deletes them at C speed, so anything left over marks the file binary.
_TEXT_BYTES = bytes(
    b
    for b in range(256)
    if not (
        b < 7 or b == 11 or (13 < b < 27) or (27 < b < 0x20) or b == 0x7F
    )
)


def is_binary(head: bytes) -> bool:
    """utils.IsBinary control-byte heuristic over the first 300 bytes
    (pkg/fanal/utils/utils.go:76-93)."""
    return bool(head[:300].translate(None, _TEXT_BYTES))


class SecretAnalyzer(BatchAnalyzer):
    """pkg/fanal/analyzer/secret/secret.go SecretAnalyzer."""

    def __init__(self) -> None:
        self._engine = None
        self._config_path = ""
        self._config_skip_paths: frozenset[str] = frozenset()
        self._backend = "auto"
        self._server_addr = ""
        self._fleet_config = ""
        self._server_token = ""
        self._timeout_s = 0.0
        self._rules_cache_dir = ""
        self._pipeline_depth: int | None = None
        self._resident_chunks: int | None = None
        self._explain = False

    def init(self, options: AnalyzerOptions) -> None:
        opt = options.secret_scanner_option
        self._config_path = opt.config_path
        self._backend = opt.backend
        self._server_addr = getattr(opt, "server_addr", "")
        self._fleet_config = getattr(opt, "fleet_config", "")
        self._server_token = getattr(opt, "server_token", "")
        self._timeout_s = getattr(opt, "timeout_s", 0.0)
        self._rules_cache_dir = getattr(opt, "rules_cache_dir", "")
        self._ruleset_select = getattr(opt, "ruleset_select", "")
        self._pipeline_depth = getattr(opt, "pipeline_depth", None)
        self._resident_chunks = getattr(opt, "resident_chunks", None)
        self._explain = getattr(opt, "explain", False)
        self._config_skip_paths = self._build_config_skip_paths(self._config_path)

    @staticmethod
    def _build_config_skip_paths(config_path: str) -> frozenset[str]:
        """Forms of the secret-config path to exclude from scanning.

        The reference skips the scanned file whose path equals
        filepath.Base(configPath) (secret.go:138).  Basename alone misses
        the common case where the config lives in a subdirectory of the
        scan tree and the walker reports it by relative path — a config
        given as ``configs/trivy-secret.yaml`` arrives at required() as
        exactly that string, never as the bare basename, so the file's own
        example rules would be scanned and reported as findings.  Skip the
        normalized relative path too; path normalization keeps the match
        exact (no suffix matching), so ``other/configs/trivy-secret.yaml``
        is still scanned.
        """
        if not config_path:
            return frozenset()
        norm = os.path.normpath(config_path).replace(os.sep, "/")
        if norm.startswith("./"):
            norm = norm[2:]
        return frozenset({os.path.basename(config_path), norm})

    @property
    def engine(self):
        if self._engine is None:
            config = load_config(self._config_path)
            if self._backend == "server":
                # The sidecar split: raw (path, blob) items board the scan
                # server's continuous batcher instead of a local engine, so
                # concurrent client processes share one device batch.
                from trivy_tpu.rpc.client import RemoteSecretEngine

                if not self._server_addr and not self._fleet_config:
                    raise ValueError(
                        "--secret-backend server requires --server "
                        "or --fleet-config"
                    )
                router = None
                if self._fleet_config:
                    # Fleet mode: batches route across the member table
                    # by ruleset digest with health-aware failover
                    # instead of pinning to one address.
                    from trivy_tpu.fleet import FleetRouter
                    from trivy_tpu.fleet.membership import (
                        FleetMembership,
                        load_fleet_config,
                    )

                    router = FleetRouter(
                        FleetMembership.from_config(
                            load_fleet_config(self._fleet_config)
                        ),
                        token=self._server_token,
                        timeout_s=self._timeout_s or 300.0,
                    )
                self._engine = RemoteSecretEngine(
                    self._server_addr,
                    token=self._server_token,
                    timeout_s=self._timeout_s,
                    ruleset_select=self._ruleset_select,
                    explain=self._explain,
                    router=router,
                )
            else:
                # All local backends go through the factory, which maps the
                # CLI aliases (cpu/tpu/native) and — when the registry is on
                # — warm-starts from a cached compiled artifact instead of
                # recompiling the ruleset in-process.
                from trivy_tpu.engine.hybrid import make_secret_engine
                from trivy_tpu.registry.store import resolve_rules_cache_dir

                kw = {}
                if self._pipeline_depth is not None:
                    kw["pipeline_depth"] = self._pipeline_depth
                if self._resident_chunks is not None:
                    kw["resident_chunks"] = self._resident_chunks
                self._engine = make_secret_engine(
                    config=config,
                    backend=self._backend,
                    rules_cache_dir=resolve_rules_cache_dir(
                        self._rules_cache_dir
                    ),
                    **kw,
                )
        return self._engine

    def type(self) -> str:
        return TYPE_SECRET

    def version(self) -> int:
        return VERSION

    def required(self, file_path: str, size: int, mode: int) -> bool:
        """secret.go:115-153."""
        if size < 10:
            return False
        dirname, fname = os.path.split(file_path)
        if SKIP_DIRS.intersection(dirname.replace(os.sep, "/").split("/")):
            return False
        if fname in SKIP_FILES:
            return False
        if self._config_skip_paths and (
            file_path.replace(os.sep, "/") in self._config_skip_paths
        ):
            return False
        if os.path.splitext(fname)[1] in SKIP_EXTS:
            return False
        if self.engine_allow_path(file_path):
            return False
        return True

    def engine_allow_path(self, file_path: str) -> bool:
        eng = self.engine
        ruleset = getattr(eng, "ruleset", None)
        return bool(ruleset and ruleset.allow_path(file_path))

    def _required_batch_loop(
        self, files: list[tuple[str, int]], allowed: list[bool]
    ) -> list[bool]:
        """Per-file gate loop (the exact reference order of checks); used
        when the joined fast path cannot apply."""
        skip_ext_tuple = tuple(SKIP_EXTS)
        cfg_skips = self._config_skip_paths
        sep = os.sep
        out = []
        for (path, size), al in zip(files, allowed):
            if size < 10 or al:
                out.append(False)
                continue
            p = path.replace(sep, "/") if sep != "/" else path
            slashed = "/" + p
            if any(nd in slashed for nd in _SKIP_DIR_NEEDLES):
                out.append(False)
                continue
            base = p.rsplit("/", 1)[-1]
            if base in SKIP_FILES:
                out.append(False)
                continue
            if cfg_skips and p in cfg_skips:
                out.append(False)
                continue
            if base.endswith(skip_ext_tuple) and (
                os.path.splitext(base)[1] in SKIP_EXTS
            ):
                out.append(False)
                continue
            out.append(True)
        return out

    def required_batch(self, files: list[tuple[str, int]]) -> list[bool]:
        """required() over a corpus in one pass — identical verdicts, with
        every gate running at C speed (secret.go:115-153):

        - allow paths: RuleSet.allow_paths (literal-find tiers)
        - skip dirs / skip files / skip exts: str.find of component-exact
          needles over the newline-joined "/"-prefixed paths; the rare
          ext hit is re-verified with splitext so leading-dot basenames
          (".png") keep the reference's semantics

        A per-file Python loop here was ~1us x files — the single largest
        cost of the gating pass at 100k files."""
        ruleset = getattr(self.engine, "ruleset", None)
        if ruleset is not None:
            allowed = ruleset.allow_paths([p for p, _ in files])
        else:
            allowed = [False] * len(files)
        sep = os.sep
        if self._config_skip_paths or any(
            "\n" in p or (sep != "/" and sep in p) for p, _ in files
        ):
            return self._required_batch_loop(files, allowed)

        from trivy_tpu.rules.model import iter_needle_lines, joined_lines

        n = len(files)
        out = [True] * n
        for i, ((_p, size), al) in enumerate(zip(files, allowed)):
            if size < 10 or al:
                out[i] = False
        slashed = ["/" + p for p, _ in files]
        joined, starts = joined_lines(slashed)

        def mark(needle: str, verify=None) -> None:
            for li in iter_needle_lines(joined, starts, needle):
                if out[li] and (verify is None or verify(li)):
                    out[li] = False

        for d in SKIP_DIRS:
            mark(f"/{d}/")
        for fname in SKIP_FILES:
            mark(f"/{fname}\n")

        def ext_ok(li: int) -> bool:
            base = slashed[li].rsplit("/", 1)[-1]
            return os.path.splitext(base)[1] in SKIP_EXTS

        for ext in SKIP_EXTS:
            mark(f"{ext}\n", verify=ext_ok)
        return out

    @staticmethod
    def _effective_path(inp: AnalysisInput) -> str:
        # Files extracted from images have no dir; they get a leading "/"
        # (secret.go:94-99).
        return inp.file_path if inp.dir else "/" + inp.file_path

    def analyze_batch(self, inputs: list[AnalysisInput]) -> AnalysisResult | None:
        items: list[tuple[str, bytes]] = []
        for inp in inputs:
            if is_binary(inp.content):
                continue
            content = inp.content.replace(b"\r", b"")
            items.append((self._effective_path(inp), content))
        if not items:
            return None

        eng = self.engine
        if hasattr(eng, "scan_batch"):
            results = eng.scan_batch(items)
        else:
            results = [eng.scan(p, c) for p, c in items]

        secrets = [r for r in results if r.findings]
        if not secrets:
            return None
        return AnalysisResult(secrets=secrets)


register_analyzer(SecretAnalyzer)
