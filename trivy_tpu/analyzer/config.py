"""Config (IaC) analyzers: route files into the misconf scanners.

Mirrors pkg/fanal/analyzer/config/* + the pkg/misconf façade routing
(scanner.go:82-112).
"""

from __future__ import annotations

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    PostAnalyzer,
    register_analyzer,
    register_post_analyzer,
)
from trivy_tpu.misconf.dockerfile import scan_dockerfile
from trivy_tpu.misconf.kubernetes import scan_kubernetes


class DockerfileAnalyzer(Analyzer):
    def type(self) -> str:
        return "dockerfile"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        name = file_path.rsplit("/", 1)[-1].lower()
        return (
            name == "dockerfile"
            or name.startswith("dockerfile.")
            or name.endswith(".dockerfile")
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        mc = scan_dockerfile(inp.file_path, inp.content)
        if not mc.failures and not mc.successes:
            return None
        return AnalysisResult(misconfigs=[mc])


class KubernetesYamlAnalyzer(Analyzer):
    def type(self) -> str:
        return "kubernetes"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith((".yaml", ".yml")) and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        mc = scan_kubernetes(inp.file_path, inp.content)
        if mc is None or (not mc.failures and not mc.successes):
            return None
        return AnalysisResult(misconfigs=[mc])


def _scan_with_engine(inp: AnalysisInput) -> AnalysisResult | None:
    """Shared routing body: content-sniffing engine scan, dropping empty
    results (used by every engine-backed config analyzer)."""
    from trivy_tpu.iac.engine import shared_scanner

    mc = shared_scanner().scan(inp.file_path, inp.content)
    if mc is None or (not mc.failures and not mc.successes):
        return None
    return AnalysisResult(misconfigs=[mc])


class TerraformAnalyzer(Analyzer):
    """Route .tf files through the rego engine (the reference's terraform
    scanner seat, pkg/misconf/scanner.go:82-112)."""

    def type(self) -> str:
        return "terraform"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith((".tf", ".tf.json")) and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        return _scan_with_engine(inp)


class ConfigJsonAnalyzer(Analyzer):
    """Route JSON config files (CloudFormation templates, Azure ARM,
    terraform plans, k8s JSON, generic custom-check json) through the
    shared engine, which content-sniffs the concrete type
    (pkg/iac/detection)."""

    def type(self) -> str:
        return "config-json"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        # .tf.json belongs to TerraformAnalyzer; claiming it here would
        # scan the file twice and duplicate every finding.
        return (
            file_path.endswith((".json", ".template"))
            and not file_path.endswith(".tf.json")
            and size < 1 << 20
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        return _scan_with_engine(inp)


class TomlConfigAnalyzer(Analyzer):
    """Generic TOML routing; only fires when custom toml-namespace checks
    are loaded (the engine gates parsing)."""

    def type(self) -> str:
        return "config-toml"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith(".toml") and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        return _scan_with_engine(inp)


class HelmPostAnalyzer(PostAnalyzer):
    """Helm chart scanning (pkg/iac/scanners/helm scanner.go): claims
    Chart.yaml + values.yaml + templates/** into the composite FS, renders
    each chart after the walk, and routes the manifests through the
    kubernetes checks.  Needs the post-analyzer seat because rendering
    requires the whole chart, not one file."""

    def type(self) -> str:
        return "helm"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        if size >= 1 << 20:  # everything claimed here lands in MapFS whole
            return False
        name = file_path.rsplit("/", 1)[-1]
        if name in ("Chart.yaml", "values.yaml"):
            return True
        return "templates/" in file_path and name.endswith(
            (".yaml", ".yml", ".tpl")
        )

    def post_analyze(self, fs) -> AnalysisResult | None:
        from trivy_tpu.iac.engine import shared_scanner
        from trivy_tpu.iac.helm import HelmError, find_charts, render_chart

        charts = find_charts(fs.paths())
        if not charts:
            return None
        misconfigs = []
        for root, members in charts.items():
            prefix = root + "/" if root else ""
            files = {p[len(prefix) :]: fs.read(p) for p in members}
            try:
                rendered = render_chart(files, chart_root=root)
            except HelmError as e:
                import logging

                logging.getLogger(__name__).warning(
                    "helm chart %s failed to render: %s", root or ".", e
                )
                continue
            for rel_path, text in rendered.items():
                full = prefix + rel_path
                mc = shared_scanner().scan(full, text.encode())
                if mc is not None and (mc.failures or mc.successes):
                    mc.file_type = "helm"
                    misconfigs.append(mc)
        if not misconfigs:
            return None
        return AnalysisResult(misconfigs=misconfigs)


register_analyzer(DockerfileAnalyzer)
register_analyzer(ConfigJsonAnalyzer)
register_analyzer(TomlConfigAnalyzer)
register_post_analyzer(HelmPostAnalyzer)
register_analyzer(KubernetesYamlAnalyzer)
register_analyzer(TerraformAnalyzer)
