"""Config (IaC) analyzers: route files into the misconf scanners.

Mirrors pkg/fanal/analyzer/config/* + the pkg/misconf façade routing
(scanner.go:82-112).
"""

from __future__ import annotations

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    PostAnalyzer,
    register_analyzer,
    register_post_analyzer,
)
from trivy_tpu.misconf.dockerfile import scan_dockerfile
from trivy_tpu.misconf.kubernetes import scan_kubernetes


class DockerfileAnalyzer(Analyzer):
    def type(self) -> str:
        return "dockerfile"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        name = file_path.rsplit("/", 1)[-1].lower()
        return (
            name == "dockerfile"
            or name.startswith("dockerfile.")
            or name.endswith(".dockerfile")
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        mc = scan_dockerfile(inp.file_path, inp.content)
        if not mc.failures and not mc.successes:
            return None
        return AnalysisResult(misconfigs=[mc])


class KubernetesYamlAnalyzer(Analyzer):
    def type(self) -> str:
        return "kubernetes"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith((".yaml", ".yml")) and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        mc = scan_kubernetes(inp.file_path, inp.content)
        if mc is None or (not mc.failures and not mc.successes):
            return None
        return AnalysisResult(misconfigs=[mc])


def _scan_with_engine(inp: AnalysisInput) -> AnalysisResult | None:
    """Shared routing body: content-sniffing engine scan, dropping empty
    results (used by every engine-backed config analyzer)."""
    from trivy_tpu.iac.engine import shared_scanner

    mc = shared_scanner().scan(inp.file_path, inp.content)
    if mc is None or (not mc.failures and not mc.successes):
        return None
    return AnalysisResult(misconfigs=[mc])


class TerraformAnalyzer(Analyzer):
    """Route .tf files through the rego engine (the reference's terraform
    scanner seat, pkg/misconf/scanner.go:82-112)."""

    def type(self) -> str:
        return "terraform"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith((".tf", ".tf.json")) and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        return _scan_with_engine(inp)


class ConfigJsonAnalyzer(Analyzer):
    """Route JSON config files (CloudFormation templates, Azure ARM,
    terraform plans, k8s JSON, generic custom-check json) through the
    shared engine, which content-sniffs the concrete type
    (pkg/iac/detection)."""

    def type(self) -> str:
        return "config-json"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        # .tf.json belongs to TerraformAnalyzer; claiming it here would
        # scan the file twice and duplicate every finding.
        return (
            file_path.endswith((".json", ".template"))
            and not file_path.endswith(".tf.json")
            and size < 1 << 20
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        return _scan_with_engine(inp)


class TomlConfigAnalyzer(Analyzer):
    """Generic TOML routing; only fires when custom toml-namespace checks
    are loaded (the engine gates parsing)."""

    def type(self) -> str:
        return "config-toml"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith(".toml") and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        return _scan_with_engine(inp)


class HelmPostAnalyzer(PostAnalyzer):
    """Helm chart scanning (pkg/iac/scanners/helm scanner.go): claims
    Chart.yaml + values.yaml + templates/** into the composite FS, renders
    each chart after the walk, and routes the manifests through the
    kubernetes checks.  Needs the post-analyzer seat because rendering
    requires the whole chart, not one file."""

    def type(self) -> str:
        return "helm"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        if size >= 1 << 20:  # everything claimed here lands in MapFS whole
            return False
        name = file_path.rsplit("/", 1)[-1]
        if name in ("Chart.yaml", "values.yaml"):
            return True
        return "templates/" in file_path and name.endswith(
            (".yaml", ".yml", ".tpl")
        )

    def post_analyze(self, fs) -> AnalysisResult | None:
        from trivy_tpu.iac.engine import shared_scanner
        from trivy_tpu.iac.helm import HelmError, find_charts, render_chart

        charts = find_charts(fs.paths())
        if not charts:
            return None
        misconfigs = []
        for root, members in charts.items():
            prefix = root + "/" if root else ""
            files = {p[len(prefix) :]: fs.read(p) for p in members}
            try:
                rendered = render_chart(files, chart_root=root)
            except HelmError as e:
                import logging

                logging.getLogger(__name__).warning(
                    "helm chart %s failed to render: %s", root or ".", e
                )
                continue
            for rel_path, text in rendered.items():
                full = prefix + rel_path
                mc = shared_scanner().scan(full, text.encode())
                if mc is not None and (mc.failures or mc.successes):
                    mc.file_type = "helm"
                    misconfigs.append(mc)
        if not misconfigs:
            return None
        return AnalysisResult(misconfigs=misconfigs)


def _is_tfvars(name: str) -> bool:
    """Auto-loaded variable files (terraform's own load set)."""
    return name == "terraform.tfvars" or name.endswith(".auto.tfvars")


_INIT_MANIFEST = ".terraform/modules/modules.json"


def _is_init_manifest(path: str) -> bool:
    # Component-exact: a dir literally named "x.terraform" must not match.
    return path == _INIT_MANIFEST or path.endswith("/" + _INIT_MANIFEST)


class TerraformModulePostAnalyzer(PostAnalyzer):
    """Terraform module expansion (pkg/iac/scanners/terraform executor):
    a `module` block with a local relative source evaluates the child
    directory's merged files with the caller's arguments overriding the
    child's variable defaults.  Needs the post-analyzer seat — the child
    dir and the caller are different files.

    The module-aware result is emitted under the child's file path; the
    applier's last-write-wins merge lets it override the per-file
    defaults-only scan of the same file."""

    def type(self) -> str:
        return "terraform-module"

    def version(self) -> int:
        return 4  # v4: terraform-init manifest module resolution

    def required(self, file_path: str, size: int, mode: int) -> bool:
        # .tf only: the expansion below reads HCL syntax (module calls in
        # .tf.json are out of scope, so those files are not buffered).
        # terraform.tfvars / *.auto.tfvars join the composite FS so root
        # directories evaluate with their variable assignments, and
        # `terraform init` module manifests join so registry/git module
        # calls resolve to their downloaded directories.
        if _is_tfvars(file_path.rsplit("/", 1)[-1]):
            return size < 1 << 20
        if _is_init_manifest(file_path):
            return size < 1 << 20
        return file_path.endswith(".tf") and size < 1 << 20

    @staticmethod
    def _resolved_calls(
        docs: list[dict], overrides: dict | None = None
    ) -> dict[str, dict]:
        """Module blocks with arguments resolved in the CALLER's scope.

        Caller-side expressions (encrypt = var.secure) must resolve
        against the caller's variables/locals, never leak as raw
        reference strings into the child (a junk truthy string would
        flip checks).  Still-unresolved references are dropped so the
        child keeps its own default."""
        import re

        from trivy_tpu.iac.hcl import terraform_docs_input

        resolved = terraform_docs_input(docs, overrides)
        calls: dict[str, dict] = {}
        for name, blk in (resolved.get("module") or {}).items():
            if not isinstance(blk, dict):
                continue
            calls[name] = {
                k: v
                for k, v in blk.items()
                if not (
                    isinstance(v, str)
                    and re.match(r"^(var|local|module|data)\.", v)
                )
            }
        return calls

    def post_analyze(self, fs) -> AnalysisResult | None:
        import logging
        import posixpath

        from trivy_tpu.iac.engine import shared_scanner
        from trivy_tpu.iac.hcl import parse_hcl, terraform_docs_input
        from trivy_tpu.misconf.types import Misconfiguration

        logger = logging.getLogger(__name__)

        def norm_child(parent: str, source: str) -> str:
            d = posixpath.normpath(posixpath.join(parent, source))
            return "" if d == "." else d

        def manifest_child(parent_dir: str, call_name: str) -> str:
            """Downloaded dir for a registry/git call: top-level calls use
            the bare manifest key; calls made from inside a downloaded
            module use the dotted key ("vol.child")."""
            entries = manifests.get(parent_dir)
            if entries is not None:
                return entries.get(call_name, "")
            rk = manifest_dirs.get(parent_dir)
            if rk is not None:
                root, key = rk
                return manifests.get(root, {}).get(
                    f"{key}.{call_name}", ""
                )
            return ""

        by_dir: dict[str, dict[str, dict]] = {}  # dir -> path -> parsed doc
        tfvars_files: dict[str, list[str]] = {}  # dir -> tfvars paths
        # `terraform init` manifests: root dir -> {module key -> module dir},
        # plus the reverse dir -> (root, key) index so calls made FROM a
        # downloaded module resolve their nested registry children through
        # the dotted manifest keys ("vol.child").  This is how registry/git
        # module sources resolve offline — the reference evaluates the
        # downloaded .terraform/modules tree the same way
        # (pkg/iac/scanners/terraform); no network fetch here.
        import json as _json

        manifests: dict[str, dict[str, str]] = {}
        manifest_dirs: dict[str, tuple[str, str]] = {}
        for path in fs.paths():
            if _is_init_manifest(path):
                root = path[: -len(_INIT_MANIFEST)].rstrip("/")
                try:
                    doc = _json.loads(fs.read(path).decode("utf-8", "replace"))
                    entries = {}
                    for m in doc.get("Modules") or []:
                        key, mdir = m.get("Key", ""), m.get("Dir", "")
                        if key and mdir and mdir not in (".", ""):
                            full = posixpath.normpath(
                                posixpath.join(root, mdir)
                            )
                            entries[key] = full
                            manifest_dirs[full] = (root, key)
                    if entries:
                        manifests[root] = entries
                except Exception:
                    pass
                continue
            if _is_tfvars(path.rsplit("/", 1)[-1]):
                tfvars_files.setdefault(posixpath.dirname(path), []).append(
                    path
                )
                continue
            if not path.endswith(".tf"):
                continue
            try:
                doc = parse_hcl(fs.read(path).decode("utf-8", "replace"))
            except Exception:
                continue
            by_dir.setdefault(posixpath.dirname(path), {})[path] = doc

        # Terraform's variable precedence: terraform.tfvars loads first,
        # then *.auto.tfvars in lexical order (later wins).
        tfvars_by_dir: dict[str, dict] = {}
        for d, paths in tfvars_files.items():
            merged: dict = {}
            for path in sorted(
                paths,
                key=lambda p: (
                    0 if p.rsplit("/", 1)[-1] == "terraform.tfvars" else 1,
                    p,
                ),
            ):
                try:
                    doc = parse_hcl(fs.read(path).decode("utf-8", "replace"))
                except Exception:
                    continue
                merged.update(
                    {k: v for k, v in doc.items() if not k.startswith("__")}
                )
            if merged:
                tfvars_by_dir[d] = merged

        # Two passes over module calls.  Pass A: resolve WITHOUT tfvars to
        # learn which dirs are module sources (module `source` must be a
        # literal, so tfvars cannot change the topology).  Pass B:
        # re-resolve ROOT dirs only with their tfvars — terraform loads
        # tfvars for the root module alone, so a stray tfvars inside a
        # referenced child dir must influence neither its own evaluation
        # nor its grandchild module arguments.
        calls_by_dir: dict[str, dict[str, dict]] = {}
        child_dirs: set[str] = set()
        for parent_dir, docs_by_path in sorted(by_dir.items()):
            try:
                calls = self._resolved_calls(list(docs_by_path.values()))
            except Exception:
                calls = {}
            calls_by_dir[parent_dir] = calls
            for cname, blk in calls.items():
                source = str(blk.get("source", ""))
                if source.startswith(("./", "../")):
                    child_dirs.add(norm_child(parent_dir, source))
                elif source:
                    mdir = manifest_child(parent_dir, cname)
                    if mdir:
                        child_dirs.add(mdir)
        for parent_dir, values in sorted(tfvars_by_dir.items()):
            if parent_dir in child_dirs or parent_dir not in by_dir:
                continue
            try:
                calls_by_dir[parent_dir] = self._resolved_calls(
                    list(by_dir[parent_dir].values()), overrides=values
                )
            except Exception:
                pass

        misconfigs = []
        # child dir -> list of per-instantiation evaluated Misconfigurations
        per_child: dict[str, list] = {}
        # Root dirs with tfvars evaluate PER FILE with the dir-wide
        # variable scope + tfvars (ScannerWithTFVarsPaths): findings keep
        # their own file's Target instead of migrating to main.tf.
        for d, values in sorted(tfvars_by_dir.items()):
            if d not in by_dir or d in child_dirs:
                continue
            dir_vars: dict = {}
            for doc in by_dir[d].values():
                for vname, blk in (doc.get("variable") or {}).items():
                    if isinstance(blk, dict) and "default" in blk:
                        dir_vars[vname] = blk["default"]
            dir_vars.update(
                {k: v for k, v in values.items() if not k.startswith("__")}
            )
            for p in sorted(by_dir[d]):
                try:
                    doc = terraform_docs_input(
                        [by_dir[d][p]], overrides=dir_vars
                    )
                except Exception as e:
                    logger.warning(
                        "tfvars evaluation failed for %s: %s", p, e
                    )
                    continue
                mc = shared_scanner().evaluate(p, "terraform", [doc])
                if mc.failures or mc.successes:
                    misconfigs.append(mc)
        # Worklist over module instantiations so caller arguments flow
        # through CHAINS (root -> vol -> child): evaluating a child under
        # its effective arguments also re-resolves the child's own module
        # calls under those arguments and enqueues the grandchildren.
        # Dedup on (child dir, effective args) bounds recursion/cycles.
        work: list[tuple[str, str, dict]] = []
        for parent_dir, calls in sorted(calls_by_dir.items()):
            for name, blk in sorted(calls.items()):
                work.append((parent_dir, name, blk))
        seen_inst: set = set()
        budget = 2048  # runaway-cycle backstop
        while work and budget > 0:
            budget -= 1
            parent_dir, name, blk = work.pop(0)
            source = str(blk.get("source", ""))
            if source.startswith(("./", "../")):
                child_dir = norm_child(parent_dir, source)
            else:
                # Registry/git sources resolve through the
                # `terraform init` manifest (incl. dotted keys for
                # nested calls); without an entry (no init, or never
                # downloaded) the call is skipped — module downloads
                # are never performed here.
                child_dir = manifest_child(parent_dir, name)
                if not child_dir:
                    continue
            child_docs = by_dir.get(child_dir)
            if not child_docs:
                continue
            inst_key = (
                child_dir,
                tuple(sorted((k, repr(v)) for k, v in blk.items())),
            )
            if inst_key in seen_inst:
                continue
            seen_inst.add(inst_key)
            docs_sorted = [child_docs[p] for p in sorted(child_docs)]
            try:
                doc = terraform_docs_input(docs_sorted, overrides=blk)
            except Exception as e:
                logger.warning(
                    "module %s (%s) failed to evaluate: %s",
                    name, child_dir, e,
                )
                continue
            mc = shared_scanner().evaluate(
                child_dir or ".", "terraform", [doc]
            )
            per_child.setdefault(child_dir, []).append(mc)
            try:
                sub_calls = self._resolved_calls(docs_sorted, overrides=blk)
            except Exception:
                sub_calls = {}
            for sname, sblk in sorted(sub_calls.items()):
                work.append((child_dir, sname, sblk))

        for child_dir, mcs in sorted(per_child.items()):
            child_paths = sorted(by_dir.get(child_dir, {}))
            if not child_paths:
                continue
            report_path = next(
                (
                    p
                    for p in child_paths
                    if posixpath.basename(p) == "main.tf"
                ),
                child_paths[0],
            )
            # Merge across instantiations: any FAIL survives (two callers
            # of the same module must not mask each other), a check
            # PASSes only when every instantiation passed.
            merged = Misconfiguration(
                file_type="terraform", file_path=report_path
            )
            seen_failures = set()
            for mc in mcs:
                for f in mc.failures:
                    key = (f.check_id, f.message)
                    if key not in seen_failures:
                        seen_failures.add(key)
                        merged.failures.append(f)
            failed_ids = {f.check_id for f in merged.failures}
            seen_pass = set()
            for mc in mcs:
                for s in mc.successes:
                    if s.check_id not in failed_ids | seen_pass:
                        seen_pass.add(s.check_id)
                        merged.successes.append(s)
            misconfigs.append(merged)
            # The instantiated evaluation supersedes the defaults-only
            # per-file scans of EVERY child file; empty entries clear the
            # stale ones under the applier's last-write-wins merge.
            for p in child_paths:
                if p != report_path:
                    misconfigs.append(
                        Misconfiguration(file_type="terraform", file_path=p)
                    )
        if not misconfigs:
            return None
        return AnalysisResult(misconfigs=misconfigs)


register_analyzer(DockerfileAnalyzer)
register_analyzer(ConfigJsonAnalyzer)
register_analyzer(TomlConfigAnalyzer)
register_post_analyzer(HelmPostAnalyzer)
register_post_analyzer(TerraformModulePostAnalyzer)
register_analyzer(KubernetesYamlAnalyzer)
register_analyzer(TerraformAnalyzer)
