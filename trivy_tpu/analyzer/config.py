"""Config (IaC) analyzers: route files into the misconf scanners.

Mirrors pkg/fanal/analyzer/config/* + the pkg/misconf façade routing
(scanner.go:82-112).
"""

from __future__ import annotations

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.misconf.dockerfile import scan_dockerfile
from trivy_tpu.misconf.kubernetes import scan_kubernetes


class DockerfileAnalyzer(Analyzer):
    def type(self) -> str:
        return "dockerfile"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        name = file_path.rsplit("/", 1)[-1].lower()
        return (
            name == "dockerfile"
            or name.startswith("dockerfile.")
            or name.endswith(".dockerfile")
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        mc = scan_dockerfile(inp.file_path, inp.content)
        if not mc.failures and not mc.successes:
            return None
        return AnalysisResult(misconfigs=[mc])


class KubernetesYamlAnalyzer(Analyzer):
    def type(self) -> str:
        return "kubernetes"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith((".yaml", ".yml")) and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        mc = scan_kubernetes(inp.file_path, inp.content)
        if mc is None or (not mc.failures and not mc.successes):
            return None
        return AnalysisResult(misconfigs=[mc])


class TerraformAnalyzer(Analyzer):
    """Route .tf files through the rego engine (the reference's terraform
    scanner seat, pkg/misconf/scanner.go:82-112)."""

    def type(self) -> str:
        return "terraform"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith((".tf", ".tf.json")) and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        from trivy_tpu.iac.engine import shared_scanner

        mc = shared_scanner().scan(inp.file_path, inp.content)
        if mc is None or (not mc.failures and not mc.successes):
            return None
        return AnalysisResult(misconfigs=[mc])


class ConfigJsonAnalyzer(Analyzer):
    """Route JSON config files (CloudFormation templates, Azure ARM,
    terraform plans, k8s JSON, generic custom-check json) through the
    shared engine, which content-sniffs the concrete type
    (pkg/iac/detection)."""

    def type(self) -> str:
        return "config-json"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        # .tf.json belongs to TerraformAnalyzer; claiming it here would
        # scan the file twice and duplicate every finding.
        return (
            file_path.endswith((".json", ".template"))
            and not file_path.endswith(".tf.json")
            and size < 1 << 20
        )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        from trivy_tpu.iac.engine import shared_scanner

        mc = shared_scanner().scan(inp.file_path, inp.content)
        if mc is None or (not mc.failures and not mc.successes):
            return None
        return AnalysisResult(misconfigs=[mc])


class TomlConfigAnalyzer(Analyzer):
    """Generic TOML routing; only fires when custom toml-namespace checks
    are loaded (the engine gates parsing)."""

    def type(self) -> str:
        return "config-toml"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith(".toml") and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        from trivy_tpu.iac.engine import shared_scanner

        mc = shared_scanner().scan(inp.file_path, inp.content)
        if mc is None or (not mc.failures and not mc.successes):
            return None
        return AnalysisResult(misconfigs=[mc])


register_analyzer(DockerfileAnalyzer)
register_analyzer(ConfigJsonAnalyzer)
register_analyzer(TomlConfigAnalyzer)
register_analyzer(KubernetesYamlAnalyzer)
register_analyzer(TerraformAnalyzer)
