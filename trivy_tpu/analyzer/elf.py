"""Minimal ELF reader for binary dependency analyzers.

Just enough structure for two consumers: section lookup by name (the Rust
cargo-auditable ``.dep-v0`` payload) and virtual-address translation via
PT_LOAD program headers (the Go buildinfo pointer format).  Both 32- and
64-bit, both endiannesses.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

ELF_MAGIC = b"\x7fELF"


class ElfError(ValueError):
    pass


@dataclass
class Section:
    name: str
    offset: int
    size: int
    addr: int


@dataclass
class Segment:  # PT_LOAD
    vaddr: int
    offset: int
    filesz: int


class ElfFile:
    def __init__(self, data: bytes):
        if len(data) < 64 or not data.startswith(ELF_MAGIC):
            raise ElfError("not an ELF file")
        self.data = data
        ei_class, ei_data = data[4], data[5]
        if ei_class not in (1, 2) or ei_data not in (1, 2):
            raise ElfError("bad ELF ident")
        self.is64 = ei_class == 2
        self.end = "<" if ei_data == 1 else ">"
        if self.is64:
            (
                self.e_phoff,
                self.e_shoff,
            ) = struct.unpack_from(f"{self.end}QQ", data, 0x20)
            self.e_phentsize, self.e_phnum = struct.unpack_from(
                f"{self.end}HH", data, 0x36
            )
            self.e_shentsize, self.e_shnum, self.e_shstrndx = struct.unpack_from(
                f"{self.end}HHH", data, 0x3A
            )
        else:
            (
                self.e_phoff,
                self.e_shoff,
            ) = struct.unpack_from(f"{self.end}II", data, 0x1C)
            self.e_phentsize, self.e_phnum = struct.unpack_from(
                f"{self.end}HH", data, 0x2A
            )
            self.e_shentsize, self.e_shnum, self.e_shstrndx = struct.unpack_from(
                f"{self.end}HHH", data, 0x2E
            )

    def segments(self) -> list[Segment]:
        out = []
        for i in range(self.e_phnum):
            off = self.e_phoff + i * self.e_phentsize
            if off + self.e_phentsize > len(self.data):
                break
            p_type = struct.unpack_from(f"{self.end}I", self.data, off)[0]
            if p_type != 1:  # PT_LOAD
                continue
            if self.is64:
                p_offset, p_vaddr = struct.unpack_from(
                    f"{self.end}QQ", self.data, off + 8
                )
                p_filesz = struct.unpack_from(f"{self.end}Q", self.data, off + 32)[0]
            else:
                p_offset, p_vaddr = struct.unpack_from(
                    f"{self.end}II", self.data, off + 4
                )
                p_filesz = struct.unpack_from(f"{self.end}I", self.data, off + 16)[0]
            out.append(Segment(vaddr=p_vaddr, offset=p_offset, filesz=p_filesz))
        return out

    def sections(self) -> list[Section]:
        secs = []
        raw = []
        for i in range(self.e_shnum):
            off = self.e_shoff + i * self.e_shentsize
            if off + self.e_shentsize > len(self.data):
                break
            sh_name = struct.unpack_from(f"{self.end}I", self.data, off)[0]
            if self.is64:
                sh_addr, sh_offset, sh_size = struct.unpack_from(
                    f"{self.end}QQQ", self.data, off + 0x10
                )
            else:
                sh_addr, sh_offset, sh_size = struct.unpack_from(
                    f"{self.end}III", self.data, off + 0x0C
                )
            raw.append((sh_name, sh_addr, sh_offset, sh_size))
        if not raw or self.e_shstrndx >= len(raw):
            return []
        _, _, str_off, str_size = raw[self.e_shstrndx]
        strtab = self.data[str_off : str_off + str_size]
        for sh_name, sh_addr, sh_offset, sh_size in raw:
            end = strtab.find(b"\x00", sh_name)
            if end < 0:
                continue
            secs.append(
                Section(
                    name=strtab[sh_name:end].decode("latin-1"),
                    offset=sh_offset,
                    size=sh_size,
                    addr=sh_addr,
                )
            )
        return secs

    def section_data(self, name: str) -> bytes | None:
        for s in self.sections():
            if s.name == name:
                return self.data[s.offset : s.offset + s.size]
        return None

    def vaddr_to_offset(self, vaddr: int) -> int | None:
        for seg in self.segments():
            if seg.vaddr <= vaddr < seg.vaddr + seg.filesz:
                return seg.offset + (vaddr - seg.vaddr)
        return None
