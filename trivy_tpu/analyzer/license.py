"""License analyzers.

Mirrors pkg/fanal/analyzer/licensing/ (license-file analyzer) and
pkg/licensing/classifier.go with a two-tier design: the primary
classifier is the batched full-text similarity matmul in
trivy_tpu/license/classifier.py (the licenseclassifier analogue), and
the distinctive-phrase sieve below is the fallback for texts under the
confidence threshold plus the corpus-blind veto for licenses the
full-text corpus cannot represent (e.g. AGPL-3.0 vs GPL-3.0).
"""

from __future__ import annotations

import re

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    BatchAnalyzer,
    register_analyzer,
)
from trivy_tpu.ltypes import LICENSE_TYPE_FILE, LicenseFile, LicenseFinding

# Filenames the license-file analyzer claims
# (pkg/fanal/analyzer/licensing/license.go requiredFiles + patterns).
_LICENSE_FILE_RE = re.compile(
    r"^(licen[sc]e|copying|copyright|notice)([-._].*)?$", re.IGNORECASE
)
SKIP_DIRS = {"node_modules", ".git", "vendor"}

# Distinctive phrases over normalized text (lowercase, collapsed whitespace).
# Each entry: (SPDX id, [phrases — ALL must appear]).
_PHRASES: list[tuple[str, list[str]]] = [
    ("Apache-2.0", ["apache license", "version 2.0"]),
    # "remote network interaction" is AGPL-3.0's own section 13 heading;
    # the license NAME appears in GPL-3.0 section 13 and MPL-2.0's
    # Secondary Licenses clause, so it cannot distinguish on its own.
    ("AGPL-3.0", ["gnu affero general public license", "remote network interaction"]),
    ("LGPL-3.0", ["gnu lesser general public license", "version 3"]),
    ("LGPL-2.1", ["gnu lesser general public license", "version 2.1"]),
    ("GPL-3.0", ["gnu general public license", "version 3"]),
    ("GPL-2.0", ["gnu general public license", "version 2"]),
    ("MPL-2.0", ["mozilla public license", "version 2.0"]),
    ("EPL-2.0", ["eclipse public license", "v 2.0"]),
    (
        "BSD-3-Clause",
        [
            "redistribution and use in source and binary forms",
            "neither the name",
        ],
    ),
    (
        "BSD-2-Clause",
        ["redistribution and use in source and binary forms"],
    ),
    (
        "MIT",
        [
            "permission is hereby granted, free of charge",
            "the software is provided \"as is\"",
        ],
    ),
    (
        "ISC",
        [
            "permission to use, copy, modify, and/or distribute this software",
        ],
    ),
    ("Unlicense", ["this is free and unencumbered software"]),
    ("CC0-1.0", ["cc0 1.0"]),
    ("Zlib", ["this software is provided 'as-is'", "zlib"]),
]


def normalize(text: str) -> str:
    return re.sub(r"\s+", " ", text.lower())


def classify_text(text: str) -> list[LicenseFinding]:
    """pkg/licensing/classifier.go Classify, phrase-based."""
    text = normalize(text)
    findings = []
    for spdx_id, phrases in _PHRASES:
        if all(p in text for p in phrases):
            findings.append(LicenseFinding.of(spdx_id, confidence=0.9))
            break  # first (most specific) match wins
    return findings


def classify(content: bytes) -> list[LicenseFinding]:
    return classify_text(content.decode("utf-8", errors="replace"))


class LicenseFileAnalyzer(BatchAnalyzer):
    """analyzer/licensing/license.go + pkg/licensing/classifier.go.

    Batch-first: every claimed license file in the scan classifies in ONE
    hashed-trigram similarity matmul (trivy_tpu/license/classifier.py) —
    the full-text analogue of google/licenseclassifier — with the phrase
    sieve as fallback for texts below the confidence threshold (heavily
    edited or truncated license files)."""

    def type(self) -> str:
        return "license-file"

    def version(self) -> int:
        # v1 was the phrase sieve alone.  The classification outcome also
        # depends on the host's license corpus (/usr/share/common-licenses
        # presence and contents), so the corpus digest participates in the
        # version — two hosts with different corpora must not share
        # cached blobs for the same artifact.
        from trivy_tpu.license import shared_classifier

        return 2_000_000 + shared_classifier().corpus_digest % 1_000_000

    def required(self, file_path: str, size: int, mode: int) -> bool:
        parts = file_path.split("/")
        if SKIP_DIRS.intersection(parts[:-1]):
            return False
        return bool(_LICENSE_FILE_RE.match(parts[-1])) and size < 1 << 20

    def analyze_batch(self, inputs: list) -> AnalysisResult | None:
        if not inputs:
            return None
        from trivy_tpu.license import shared_classifier

        clf = shared_classifier()
        texts = [
            inp.content.decode("utf-8", errors="replace") for inp in inputs
        ]
        matches = clf.classify_batch(texts)
        licenses = []
        for inp, text, match in zip(inputs, texts, matches):
            if match is not None and match.confidence >= 0.99:
                # Essentially-exact corpus match: the phrase sieve can
                # add nothing (a verbatim corpus text merely MENTIONING
                # another license must not be vetoed) — skip its pass.
                findings = [
                    LicenseFinding.of(match.license, confidence=match.confidence)
                ]
            else:
                phrase = classify_text(text)
                if match is None:
                    findings = phrase
                # Corpus-blind veto: licenses absent from the full-text
                # corpus score high against near-identical relatives
                # (AGPL-3.0 vs GPL-3.0 is ~0.98 cosine).  When the phrase
                # sieve names a license the corpus cannot represent, its
                # more specific answer wins.
                elif (
                    phrase
                    and phrase[0].name != match.license
                    and phrase[0].name not in clf.names
                ):
                    findings = phrase
                else:
                    findings = [
                        LicenseFinding.of(
                            match.license, confidence=match.confidence
                        )
                    ]
            if not findings:
                continue
            licenses.append(
                LicenseFile(
                    license_type=LICENSE_TYPE_FILE,
                    file_path=inp.file_path,
                    findings=findings,
                )
            )
        return AnalysisResult(licenses=licenses) if licenses else None


class DpkgLicenseAnalyzer(Analyzer):
    """analyzer/licensing dpkg copyright files
    (usr/share/doc/<pkg>/copyright) — machine-readable DEP-5 headers."""

    _RE = re.compile(r"^usr/share/doc/([^/]+)/copyright$")

    def type(self) -> str:
        return "dpkg-license"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return bool(self._RE.match(file_path))

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        m = self._RE.match(inp.file_path)
        pkg_name = m.group(1) if m else ""
        licenses: list[str] = []
        for line in inp.content.decode("utf-8", errors="replace").splitlines():
            if line.lower().startswith("license:"):
                name = line.split(":", 1)[1].strip()
                if name and name not in licenses:
                    licenses.append(name)
        if not licenses:
            findings = classify(inp.content)
            licenses = [f.name for f in findings]
        if not licenses:
            return None
        return AnalysisResult(
            licenses=[
                LicenseFile(
                    license_type="dpkg",
                    file_path=inp.file_path,
                    pkg_name=pkg_name,
                    findings=[LicenseFinding.of(n) for n in licenses],
                )
            ]
        )


register_analyzer(LicenseFileAnalyzer)
register_analyzer(DpkgLicenseAnalyzer)
