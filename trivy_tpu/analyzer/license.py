"""License analyzers.

Mirrors pkg/fanal/analyzer/licensing/ (license-file analyzer) and
pkg/licensing/classifier.go with a two-tier design: the primary
classifier is the batched full-text similarity matmul in
trivy_tpu/license/classifier.py (the licenseclassifier analogue), and
the distinctive-phrase sieve (trivy_tpu/license/phrases.py) is the
fallback for texts under the confidence threshold plus the corpus-blind
veto for licenses the full-text corpus cannot represent (AGPL-3.0 vs
GPL-3.0).  The decision tree itself lives in trivy_tpu/license/decide.py
so the device license scan program (trivy_tpu/programs/license.py)
shares it verbatim.

Backend selection (TRIVY_TPU_LICENSE_BACKEND):
  auto    (default) device license program when it builds, host otherwise
  device  force the device program (fails back to host with a warning)
  host    the direct classifier path, no sieve

The device backend runs the anchor-token gram sieve over every claimed
file and classifies only sieve candidates — on real scans virtually no
claimed file is a license text, so the ~3-20ms/text host fingerprint is
paid for the handful of true candidates instead of every COPYING-shaped
path.  Verdicts are byte-identical to the host path (the program's
necessary-condition contract; see programs/license.py).
"""

from __future__ import annotations

import logging
import os
import re

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    BatchAnalyzer,
    register_analyzer,
)
from trivy_tpu.license.decide import decide_findings

# Phrase-sieve surface re-exported for compatibility: the sieve moved to
# trivy_tpu/license/phrases.py so the device program can import it
# without pulling the analyzer registry in.
from trivy_tpu.license.phrases import (  # noqa: F401  (re-exports)
    _PHRASES,
    classify,
    classify_text,
    normalize,
)
from trivy_tpu.ltypes import LICENSE_TYPE_FILE, LicenseFile

logger = logging.getLogger(__name__)

# Filenames the license-file analyzer claims
# (pkg/fanal/analyzer/licensing/license.go requiredFiles + patterns).
_LICENSE_FILE_RE = re.compile(
    r"^(licen[sc]e|copying|copyright|notice)([-._].*)?$", re.IGNORECASE
)
SKIP_DIRS = {"node_modules", ".git", "vendor"}

_BACKEND_ENV = "TRIVY_TPU_LICENSE_BACKEND"

# Lazy singleton license-program engine for the device backend; False
# marks a failed build/scan so the fallback is paid once, not per batch.
_program_engine = None


def _device_engine():
    """The shared license-only program engine, or None (host fallback).
    One anchor-ruleset compile per process; a build failure pins the
    host path permanently with a single warning."""
    global _program_engine
    if _program_engine is False:
        return None
    if _program_engine is None:
        try:
            from trivy_tpu.programs import (
                LicenseScanProgram,
                make_program_engine,
            )

            _program_engine = make_program_engine([LicenseScanProgram()])
        except Exception as e:
            logger.warning(
                "device license program unavailable (%s); using the host "
                "classifier path",
                e,
            )
            _program_engine = False
            return None
    return _program_engine


def _decide_batch(paths: list[str], texts: list[str]) -> list[list]:
    """Per-file findings via the selected backend.  Device and host run
    the same decision tree (license/decide.py); the device backend just
    prunes non-candidates with the anchor sieve first."""
    global _program_engine
    backend = os.environ.get(_BACKEND_ENV, "auto").strip().lower() or "auto"
    if backend not in ("auto", "device", "host"):
        logger.warning("unknown %s=%r; using auto", _BACKEND_ENV, backend)
        backend = "auto"
    if backend != "host":
        eng = _device_engine()
        if eng is not None:
            try:
                items = [
                    (p, t.encode("utf-8", errors="replace"))
                    for p, t in zip(paths, texts)
                ]
                return eng.scan_programs(items)["license"]
            except Exception as e:
                logger.warning(
                    "device license scan failed (%s); using the host "
                    "classifier path",
                    e,
                )
                _program_engine = False
    return decide_findings(texts)


class LicenseFileAnalyzer(BatchAnalyzer):
    """analyzer/licensing/license.go + pkg/licensing/classifier.go.

    Batch-first: every claimed license file in the scan classifies in ONE
    hashed-trigram similarity matmul (trivy_tpu/license/classifier.py) —
    the full-text analogue of google/licenseclassifier — with the phrase
    sieve as fallback for texts below the confidence threshold (heavily
    edited or truncated license files).  On the device backend the
    anchor-token gram sieve prunes the batch first (see module
    docstring)."""

    def type(self) -> str:
        return "license-file"

    def version(self) -> int:
        # v1 was the phrase sieve alone.  The classification outcome also
        # depends on the host's license corpus (/usr/share/common-licenses
        # presence and contents), so the corpus digest participates in the
        # version — two hosts with different corpora must not share
        # cached blobs for the same artifact.
        from trivy_tpu.license import shared_classifier

        return 2_000_000 + shared_classifier().corpus_digest % 1_000_000

    def required(self, file_path: str, size: int, mode: int) -> bool:
        parts = file_path.split("/")
        if SKIP_DIRS.intersection(parts[:-1]):
            return False
        return bool(_LICENSE_FILE_RE.match(parts[-1])) and size < 1 << 20

    def analyze_batch(self, inputs: list) -> AnalysisResult | None:
        if not inputs:
            return None
        texts = [
            inp.content.decode("utf-8", errors="replace") for inp in inputs
        ]
        paths = [inp.file_path for inp in inputs]
        licenses = []
        for inp, findings in zip(inputs, _decide_batch(paths, texts)):
            if not findings:
                continue
            licenses.append(
                LicenseFile(
                    license_type=LICENSE_TYPE_FILE,
                    file_path=inp.file_path,
                    findings=findings,
                )
            )
        return AnalysisResult(licenses=licenses) if licenses else None


class DpkgLicenseAnalyzer(Analyzer):
    """analyzer/licensing dpkg copyright files
    (usr/share/doc/<pkg>/copyright) — machine-readable DEP-5 headers."""

    _RE = re.compile(r"^usr/share/doc/([^/]+)/copyright$")

    def type(self) -> str:
        return "dpkg-license"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return bool(self._RE.match(file_path))

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        m = self._RE.match(inp.file_path)
        pkg_name = m.group(1) if m else ""
        licenses: list[str] = []
        for line in inp.content.decode("utf-8", errors="replace").splitlines():
            if line.lower().startswith("license:"):
                name = line.split(":", 1)[1].strip()
                if name and name not in licenses:
                    licenses.append(name)
        if not licenses:
            findings = classify(inp.content)
            licenses = [f.name for f in findings]
        if not licenses:
            return None
        from trivy_tpu.ltypes import LicenseFinding

        return AnalysisResult(
            licenses=[
                LicenseFile(
                    license_type="dpkg",
                    file_path=inp.file_path,
                    pkg_name=pkg_name,
                    findings=[LicenseFinding.of(n) for n in licenses],
                )
            ]
        )


register_analyzer(LicenseFileAnalyzer)
register_analyzer(DpkgLicenseAnalyzer)
