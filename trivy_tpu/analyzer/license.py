"""License analyzers.

Mirrors pkg/fanal/analyzer/licensing/ (license-file analyzer) and
pkg/licensing/classifier.go — but instead of google/licenseclassifier's
full-text model, classification uses distinctive normalized phrases per SPDX
license (a keyword-sieve design, same shape as the secret engine's probe
pass: cheap necessary-condition matching, host confirmation by phrase count).
"""

from __future__ import annotations

import re

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.ltypes import LICENSE_TYPE_FILE, LicenseFile, LicenseFinding

# Filenames the license-file analyzer claims
# (pkg/fanal/analyzer/licensing/license.go requiredFiles + patterns).
_LICENSE_FILE_RE = re.compile(
    r"^(licen[sc]e|copying|copyright|notice)([-._].*)?$", re.IGNORECASE
)
SKIP_DIRS = {"node_modules", ".git", "vendor"}

# Distinctive phrases over normalized text (lowercase, collapsed whitespace).
# Each entry: (SPDX id, [phrases — ALL must appear]).
_PHRASES: list[tuple[str, list[str]]] = [
    ("Apache-2.0", ["apache license", "version 2.0"]),
    ("AGPL-3.0", ["gnu affero general public license", "version 3"]),
    ("LGPL-3.0", ["gnu lesser general public license", "version 3"]),
    ("LGPL-2.1", ["gnu lesser general public license", "version 2.1"]),
    ("GPL-3.0", ["gnu general public license", "version 3"]),
    ("GPL-2.0", ["gnu general public license", "version 2"]),
    ("MPL-2.0", ["mozilla public license", "version 2.0"]),
    ("EPL-2.0", ["eclipse public license", "v 2.0"]),
    (
        "BSD-3-Clause",
        [
            "redistribution and use in source and binary forms",
            "neither the name",
        ],
    ),
    (
        "BSD-2-Clause",
        ["redistribution and use in source and binary forms"],
    ),
    (
        "MIT",
        [
            "permission is hereby granted, free of charge",
            "the software is provided \"as is\"",
        ],
    ),
    (
        "ISC",
        [
            "permission to use, copy, modify, and/or distribute this software",
        ],
    ),
    ("Unlicense", ["this is free and unencumbered software"]),
    ("CC0-1.0", ["cc0 1.0"]),
    ("Zlib", ["this software is provided 'as-is'", "zlib"]),
]


def normalize(text: str) -> str:
    return re.sub(r"\s+", " ", text.lower())


def classify(content: bytes) -> list[LicenseFinding]:
    """pkg/licensing/classifier.go Classify, phrase-based."""
    text = normalize(content.decode("utf-8", errors="replace"))
    findings = []
    for spdx_id, phrases in _PHRASES:
        if all(p in text for p in phrases):
            findings.append(LicenseFinding.of(spdx_id, confidence=0.9))
            break  # first (most specific) match wins
    return findings


class LicenseFileAnalyzer(Analyzer):
    """analyzer/licensing/license.go."""

    def type(self) -> str:
        return "license-file"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        parts = file_path.split("/")
        if SKIP_DIRS.intersection(parts[:-1]):
            return False
        return bool(_LICENSE_FILE_RE.match(parts[-1])) and size < 1 << 20

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        findings = classify(inp.content)
        if not findings:
            return None
        return AnalysisResult(
            licenses=[
                LicenseFile(
                    license_type=LICENSE_TYPE_FILE,
                    file_path=inp.file_path,
                    findings=findings,
                )
            ]
        )


class DpkgLicenseAnalyzer(Analyzer):
    """analyzer/licensing dpkg copyright files
    (usr/share/doc/<pkg>/copyright) — machine-readable DEP-5 headers."""

    _RE = re.compile(r"^usr/share/doc/([^/]+)/copyright$")

    def type(self) -> str:
        return "dpkg-license"

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return bool(self._RE.match(file_path))

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        m = self._RE.match(inp.file_path)
        pkg_name = m.group(1) if m else ""
        licenses: list[str] = []
        for line in inp.content.decode("utf-8", errors="replace").splitlines():
            if line.lower().startswith("license:"):
                name = line.split(":", 1)[1].strip()
                if name and name not in licenses:
                    licenses.append(name)
        if not licenses:
            findings = classify(inp.content)
            licenses = [f.name for f in findings]
        if not licenses:
            return None
        return AnalysisResult(
            licenses=[
                LicenseFile(
                    license_type="dpkg",
                    file_path=inp.file_path,
                    pkg_name=pkg_name,
                    findings=[LicenseFinding.of(n) for n in licenses],
                )
            ]
        )


register_analyzer(LicenseFileAnalyzer)
register_analyzer(DpkgLicenseAnalyzer)
