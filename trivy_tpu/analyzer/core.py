"""Analyzer plugin layer: registry, group, batched dispatch.

Mirrors pkg/fanal/analyzer/analyzer.go (registry :26-27, interfaces :71-83,
group construction :315-370, AnalyzeFile fan-out :396-448, result merge :245)
— with one deliberate architectural change: the reference dispatches a
goroutine per (file × analyzer); here the group first *collects* the files
each analyzer claims, then hands batch-capable analyzers (the device secret
engine) the whole batch at once so the TPU sees large, padded, data-parallel
input instead of file-at-a-time calls.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field
from typing import Callable, Iterable

logger = logging.getLogger("trivy_tpu.analyzer")

from trivy_tpu.ftypes import Secret
from trivy_tpu.walker.fs import FileEntry

# ---------------------------------------------------------------------------
# Analyzer type constants (pkg/fanal/analyzer/const.go)
# ---------------------------------------------------------------------------

TYPE_SECRET = "secret"
TYPE_LICENSE_FILE = "license-file"
TYPE_OS_RELEASE = "os-release"
TYPE_APK = "apk"
TYPE_DPKG = "dpkg"
TYPE_RPM = "rpm"


@dataclass
class AnalyzerOptions:
    """analyzer.AnalyzerOptions (analyzer.go:55-66)."""

    group: str = ""
    disabled_analyzers: list[str] = field(default_factory=list)
    secret_scanner_option: "SecretScannerOption" = None  # type: ignore[assignment]
    file_patterns: dict[str, list[re.Pattern[str]]] = field(default_factory=dict)
    parallel: int = 5
    # Per-scan extension analyzers (module manager), scoped to this group
    # rather than the process-global registry.
    extra_analyzers: list = field(default_factory=list)
    sbom_sources: list = field(default_factory=list)  # --sbom-sources
    # Artifact options that change blob contents without changing analyzer
    # versions (e.g. the Rekor URL attestations resolve against) — hashed
    # into diff-id-keyed blob cache keys the way the reference hashes
    # artifact.Option (artifact.go calcCacheKey).
    cache_key_extra: str = ""

    def __post_init__(self) -> None:
        if self.secret_scanner_option is None:
            self.secret_scanner_option = SecretScannerOption()


@dataclass
class SecretScannerOption:
    """analyzer.SecretScannerOption."""

    config_path: str = ""
    # "auto" (hybrid: host sieve + cost-gated device verify — the product
    # default; never boots a device runtime by itself), "tpu" (all-device
    # sieve), "cpu" (oracle), "server" (raw items ship to the scan server's
    # continuous cross-request batcher — trivy_tpu/serve/).
    backend: str = "auto"
    # backend == "server": where the engine lives and how to authenticate.
    server_addr: str = ""
    server_token: str = ""
    # backend == "server": fleet member YAML (--fleet-config).  Non-empty
    # routes every batch through a digest-affine FleetRouter over the
    # member table instead of pinning to server_addr — trivy_tpu/fleet/.
    fleet_config: str = ""
    # Forwarded as the request TimeoutMs so server-side tickets inherit the
    # client's --timeout.  0 = unbounded.
    timeout_s: float = 0.0
    # Compiled-ruleset registry directory ("" = the default cache dir,
    # "off"/"none" = disabled).  Warm-started local engines skip regex
    # compilation entirely — see trivy_tpu/registry/.
    rules_cache_dir: str = ""
    # Link tuning forwarded to local engines (None = engine defaults /
    # TRIVY_TPU_PIPELINE_DEPTH / TRIVY_TPU_RESIDENT_CHUNKS).
    pipeline_depth: int | None = None
    resident_chunks: int | None = None
    # backend == "server": digest of a pushed ruleset every request should
    # scan under ("" = the server's default) — per-tenant ruleset pinning
    # against the server's resident pool (trivy_tpu/tenancy/).
    ruleset_select: str = ""
    # backend == "server": ask for the per-phase timing breakdown on every
    # batch response (--explain) — trivy_tpu/obs/.
    explain: bool = False


@dataclass
class AnalysisInput:
    """analyzer.AnalysisInput (analyzer.go:128-134)."""

    dir: str
    file_path: str
    size: int
    mode: int
    content: bytes


@dataclass
class AnalysisResult:
    """analyzer.AnalysisResult (analyzer.go:152-184) — merge + canonical sort."""

    os: object | None = None
    package_infos: list = field(default_factory=list)
    applications: list = field(default_factory=list)
    secrets: list[Secret] = field(default_factory=list)
    licenses: list = field(default_factory=list)
    misconfigs: list = field(default_factory=list)
    configs: list = field(default_factory=list)
    system_installed_files: list[str] = field(default_factory=list)
    build_info: dict | None = None  # Red Hat buildinfo (content sets, nvr)

    def merge(self, other: "AnalysisResult | None") -> None:
        """AnalysisResult.Merge (analyzer.go:245-313)."""
        if other is None:
            return
        if other.os is not None:
            self.os = _merge_os(self.os, other.os)
        self.package_infos.extend(other.package_infos)
        self.applications.extend(other.applications)
        self.secrets.extend(other.secrets)
        self.licenses.extend(other.licenses)
        self.misconfigs.extend(other.misconfigs)
        self.configs.extend(other.configs)
        self.system_installed_files.extend(other.system_installed_files)
        if other.build_info:
            merged = dict(self.build_info or {})
            merged.update(other.build_info)
            self.build_info = merged

    def sort(self) -> None:
        """AnalysisResult.Sort (analyzer.go:186-243); secrets :219-229."""
        self.package_infos.sort(key=lambda p: p.file_path)
        self.applications.sort(key=lambda a: a.file_path)
        for secret in self.secrets:
            secret.findings.sort(
                key=lambda f: (f.rule_id, f.start_line, f.end_line)
            )
        self.secrets.sort(key=lambda s: s.file_path)
        self.licenses.sort(key=lambda l: getattr(l, "file_path", ""))
        self.misconfigs.sort(key=lambda m: getattr(m, "file_path", ""))

    def is_empty(self) -> bool:
        return not (
            self.os
            or self.package_infos
            or self.applications
            or self.secrets
            or self.licenses
            or self.misconfigs
            or self.configs
            or self.system_installed_files
        )


def _merge_os(base, new):
    """types.OS merge semantics (pkg/fanal/types/artifact.go OS.Merge)."""
    if base is None:
        return new
    if new is None:
        return base
    merged = base
    if getattr(new, "family", ""):
        merged.family = new.family
    if getattr(new, "name", ""):
        merged.name = new.name
    if getattr(new, "extended_support", False):
        merged.extended_support = True
    return merged


class Analyzer:
    """Per-file analyzer interface (analyzer.go:71-77)."""

    def type(self) -> str:
        raise NotImplementedError

    def version(self) -> int:
        raise NotImplementedError

    def required(self, file_path: str, size: int, mode: int) -> bool:
        raise NotImplementedError

    def init(self, options: AnalyzerOptions) -> None:  # analyzer.Initializer
        pass

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        raise NotImplementedError


class BatchAnalyzer(Analyzer):
    """Batch-capable analyzer: receives every claimed file at once.

    TPU-native extension point: the secret engine implements this so blobs are
    packed/padded/tiled as one device batch instead of per-file calls.
    """

    def analyze_batch(self, inputs: list[AnalysisInput]) -> AnalysisResult | None:
        raise NotImplementedError

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        return self.analyze_batch([inp])


class PostAnalyzer:
    """analyzer.PostAnalyzer (analyzer.go:78-83): claims files during the
    walk (copied into its composite FS) and analyzes them together after
    the walk, with cross-file context (composite_fs.go / mapfs)."""

    def init(self, options: "AnalyzerOptions") -> None:
        pass

    def type(self) -> str:
        raise NotImplementedError

    def version(self) -> int:
        raise NotImplementedError

    def required(self, file_path: str, size: int, mode: int) -> bool:
        raise NotImplementedError

    def post_analyze(self, fs) -> "AnalysisResult | None":
        raise NotImplementedError


_REGISTRY: list[Callable[[], Analyzer]] = []
_POST_REGISTRY: list[Callable[[], PostAnalyzer]] = []


def register_analyzer(factory: Callable[[], Analyzer]) -> None:
    """analyzer.RegisterAnalyzer (analyzer.go:93)."""
    _REGISTRY.append(factory)


def register_post_analyzer(factory: Callable[[], PostAnalyzer]) -> None:
    """analyzer.RegisterPostAnalyzer (analyzer.go:102)."""
    _POST_REGISTRY.append(factory)


def registered_analyzers() -> list[Callable[[], Analyzer]]:
    return list(_REGISTRY)


def _ensure_builtin_registered() -> None:
    # Import modules whose import side-effect registers analyzers (mirrors the
    # reference's `_ "…/analyzer/all"` blank imports).
    from trivy_tpu.analyzer import binary as _binary  # noqa: F401
    from trivy_tpu.analyzer import config as _config  # noqa: F401
    from trivy_tpu.analyzer import java as _java  # noqa: F401
    from trivy_tpu.analyzer import lang as _lang  # noqa: F401
    from trivy_tpu.analyzer import lang_extra as _lang_extra  # noqa: F401
    from trivy_tpu.analyzer import license as _license  # noqa: F401
    from trivy_tpu.analyzer import misc as _misc  # noqa: F401
    from trivy_tpu.analyzer import os_release as _os  # noqa: F401
    from trivy_tpu.analyzer import pkg_apk as _apk  # noqa: F401
    from trivy_tpu.analyzer import pkg_dpkg as _dpkg  # noqa: F401
    from trivy_tpu.analyzer import pkg_rpm as _rpm  # noqa: F401
    from trivy_tpu.analyzer import secret as _secret  # noqa: F401


class AnalyzerGroup:
    """analyzer.AnalyzerGroup (analyzer.go:315-370, 396-448)."""

    def __init__(self, options: AnalyzerOptions | None = None):
        self.options = options or AnalyzerOptions()
        _ensure_builtin_registered()
        self.analyzers: list[Analyzer] = []
        for factory in _REGISTRY:
            a = factory()
            if a.type() in self.options.disabled_analyzers:
                continue
            a.init(self.options)
            self.analyzers.append(a)
        for extra in self.options.extra_analyzers:
            if extra.type() in self.options.disabled_analyzers:
                continue
            extra.init(self.options)
            self.analyzers.append(extra)
        self.post_analyzers: list[PostAnalyzer] = []
        for factory in _POST_REGISTRY:
            p = factory()
            if p.type() in self.options.disabled_analyzers:
                continue
            p.init(self.options)
            self.post_analyzers.append(p)
        self._post_fs: list = [None] * len(self.post_analyzers)

    def _file_pattern_match(self, analyzer_type: str, file_path: str) -> bool:
        """--file-patterns type:regex claim override (analyzer.go
        filePatternMatch): a matching path is handed to that analyzer even
        when its own required() declines the name."""
        patterns = self.options.file_patterns.get(analyzer_type)
        return bool(patterns) and any(p.search(file_path) for p in patterns)

    def analyzer_versions(self) -> dict[str, int]:
        """AnalyzerVersions (analyzer.go:372-381) — cache-key component."""
        versions = {a.type(): a.version() for a in self.analyzers}
        versions.update({p.type(): p.version() for p in self.post_analyzers})
        for t in self.options.disabled_analyzers:
            versions.setdefault(t, 0)
        return versions

    def post_analyze(self) -> "AnalysisResult":
        """PostAnalyze over each post-analyzer's composite FS
        (analyzer.go:506 PostAnalyzerFS); clears the collected FSes so the
        group can be reused per layer."""
        result = AnalysisResult()
        for i, p in enumerate(self.post_analyzers):
            fs = self._post_fs[i]
            self._post_fs[i] = None
            if fs is None or len(fs) == 0:
                continue
            try:
                res = p.post_analyze(fs)
            except Exception:
                # One malformed tree must not abort the scan — the same
                # tolerance analyze_entries gives per-file analyzers.
                logger.warning(
                    "post-analyzer %s failed", p.type(), exc_info=True
                )
                continue
            if res is not None:
                result.merge(res)
        return result

    def analyze_entries(
        self,
        dir: str,
        entries: Iterable[FileEntry],
        disabled: set[str] | None = None,
    ) -> AnalysisResult:
        """Claim pass + batched dispatch (replaces AnalyzeFile fan-out).

        `disabled`: analyzer types suppressed for THIS call only — the
        per-layer disabling seam (base layers skip secret scanning,
        image.go:209-213)."""
        from trivy_tpu import deadline

        claims: dict[int, list[FileEntry]] = {i: [] for i in range(len(self.analyzers))}
        entries = list(entries)  # metadata + lazy openers only
        # Analyzers exposing required_batch (the secret analyzer: batched
        # allow-path regex) answer the claim pass for all entries at once.
        batch_req: dict[int, list[bool]] = {}
        for i, a in enumerate(self.analyzers):
            if disabled and a.type() in disabled:
                continue
            rb = getattr(a, "required_batch", None)
            if rb is not None:
                batch_req[i] = rb([(e.path, e.size) for e in entries])
        for k, entry in enumerate(entries):
            deadline.check()
            for i, a in enumerate(self.analyzers):
                if disabled and a.type() in disabled:
                    continue
                br = batch_req.get(i)
                if self._file_pattern_match(a.type(), entry.path) or (
                    br[k]
                    if br is not None
                    else a.required(entry.path, entry.size, entry.mode)
                ):
                    claims[i].append(entry)
            for j, p in enumerate(self.post_analyzers):
                if disabled and p.type() in disabled:
                    continue
                if not (
                    self._file_pattern_match(p.type(), entry.path)
                    or p.required(entry.path, entry.size, entry.mode)
                ):
                    continue
                # Copy into the post-analyzer's composite FS
                # (analyzer.go:506 + composite_fs.go): the file is read now
                # — the walk's opener may not outlive this pass (layer tars).
                if self._post_fs[j] is None:
                    from trivy_tpu.mapfs import MapFS

                    self._post_fs[j] = MapFS()
                try:
                    self._post_fs[j].write_file(entry.path, entry.opener())
                except OSError:
                    continue

        result = AnalysisResult()
        for i, a in enumerate(self.analyzers):
            deadline.check()
            batch = claims[i]
            if not batch:
                continue
            if isinstance(a, BatchAnalyzer):
                # Bound resident bytes: contents are read slice-by-slice so a
                # huge tree never sits fully in host memory (the reference
                # streams per file; we stream per device-batch).
                for slice_entries in _byte_bounded(batch, MAX_BATCH_BYTES):
                    inputs = _read_inputs(dir, slice_entries)
                    try:
                        result.merge(a.analyze_batch(inputs))
                    except deadline.ScanTimeoutError:
                        raise  # --timeout must stop the scan, not log on
                    except Exception:
                        # Same per-file tolerance the non-batch path has
                        # (analyzer.go:415-417): one failing slice must not
                        # abort the scan; its files are lost, loudly.
                        logger.warning(
                            "batch analyzer %s failed on a %d-file slice",
                            a.type(),
                            len(inputs),
                            exc_info=True,
                        )
            else:
                for entry in batch:
                    inputs = _read_inputs(dir, [entry])
                    if not inputs:
                        continue
                    try:
                        result.merge(a.analyze(inputs[0]))
                    except Exception:
                        # One malformed file must not abort the scan
                        # (analyzer.go:415-417 tolerates per-file errors).
                        logger.warning(
                            "analyzer %s failed on %s",
                            a.type(),
                            entry.path,
                            exc_info=True,
                        )
        result.sort()
        return result


MAX_BATCH_BYTES = 256 << 20  # per device-batch host residency cap
# Entries above this analyze in their own singleton slice: a near-100MiB
# file must not stack on top of a quarter-gigabyte of batchmates (the
# fanal cached-file role, pkg/fanal/walker/cached_file.go — the spill
# itself lives at the source layer here: registry blobs arrive as
# disk-backed SpooledTemporaryFiles, daemon exports as temp tars, and
# layer/fs openers re-read lazily from those seekable stores, so slices
# are the only place whole contents are resident).
BIG_ENTRY_BYTES = 32 << 20


def _byte_bounded(entries: list[FileEntry], max_bytes: int):
    group: list[FileEntry] = []
    total = 0
    for e in entries:
        if e.size > BIG_ENTRY_BYTES:
            # Big entries slice alone; the in-progress small-file group
            # keeps accumulating (results are merged+sorted, so yield
            # order is not load-bearing, and fragmenting small batches
            # around each big file would waste per-batch dispatch).
            yield [e]
            continue
        if group and total + e.size > max_bytes:
            yield group
            group, total = [], 0
        group.append(e)
        total += e.size
    if group:
        yield group


def _read_inputs(dir: str, entries: list[FileEntry]) -> list[AnalysisInput]:
    inputs = []
    for entry in entries:
        try:
            content = entry.opener()
        except OSError:
            continue  # per-file errors tolerated (analyzer.go:415-417)
        inputs.append(
            AnalysisInput(
                dir=dir,
                file_path=entry.path,
                size=entry.size,
                mode=entry.mode,
                content=content,
            )
        )
    return inputs
