"""Binary dependency analyzers: Go buildinfo and Rust cargo-auditable.

Go toolchains stamp module lists into every binary (the public buildinfo
format read by ``debug/buildinfo``); cargo-auditable embeds a
zlib-compressed JSON crate list in a ``.dep-v0`` ELF section.  Reference
behavior: analyzer/language/golang/binary/binary.go and
analyzer/language/rust/binary/binary.go with their parsers
(dependency/parser/golang/binary/parse.go:49-120,
dependency/parser/rust/binary/parse.go:40-70 — runtime-kind crates only).

Both are from-scratch readers over the documented formats — no toolchain
or cgo involvement, so they run anywhere the scanner does.
"""

from __future__ import annotations

import json
import logging
import zlib

from trivy_tpu.analyzer.core import (
    Analyzer,
    AnalysisInput,
    AnalysisResult,
    register_analyzer,
)
from trivy_tpu.analyzer.elf import ELF_MAGIC, ElfError, ElfFile
from trivy_tpu.atypes import Application, Package

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# Go buildinfo

_BUILDINFO_MAGIC = b"\xff Go buildinf:"
# The modinfo string is fenced by these 16-byte sentinels (the toolchain's
# runtime/debug modinfo markers).
_INFO_START = bytes.fromhex("3077af0c9274080241e1c107e6d618e6")
_INFO_END = bytes.fromhex("f9324331861820720082521041164164")


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while pos < len(data):
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            break
    raise ValueError("bad uvarint")


def _read_varlen_string(data: bytes, pos: int) -> tuple[bytes, int]:
    n, pos = _read_uvarint(data, pos)
    if pos + n > len(data):
        raise ValueError("truncated string")
    return data[pos : pos + n], pos + n


def _read_go_string_ptr(elf: ElfFile, addr: int, ptr_size: int, big: bool) -> bytes:
    """Pointer-format (pre-go1.18) string: addr -> (data ptr, len) header."""
    off = elf.vaddr_to_offset(addr)
    if off is None or off + 2 * ptr_size > len(elf.data):
        raise ValueError("bad string pointer")
    order = "big" if big else "little"
    data_ptr = int.from_bytes(elf.data[off : off + ptr_size], order)
    length = int.from_bytes(elf.data[off + ptr_size : off + 2 * ptr_size], order)
    doff = elf.vaddr_to_offset(data_ptr)
    if doff is None or length > 1 << 24 or doff + length > len(elf.data):
        raise ValueError("bad string data pointer")
    return elf.data[doff : doff + length]


def read_go_buildinfo(content: bytes) -> tuple[str, str] | None:
    """Locate the buildinfo header; returns (go_version, modinfo) or None.

    Header layout (32 bytes): magic[14], ptrSize, flags.  Flag bit 0x2
    selects the inline format (go1.18+): two varint-prefixed strings at
    offset 32.  Otherwise two ptrSize pointers at offset 16 reference Go
    string headers, reachable only through ELF PT_LOAD translation.
    """
    pos = content.find(_BUILDINFO_MAGIC)
    if pos < 0 or pos + 32 > len(content):
        return None
    ptr_size = content[pos + 14]
    flags = content[pos + 15]
    try:
        if flags & 0x2:  # inline strings
            go_version, p = _read_varlen_string(content, pos + 32)
            modinfo, _ = _read_varlen_string(content, p)
        else:
            if not content.startswith(ELF_MAGIC) or ptr_size not in (4, 8):
                return None  # pointer format only implemented for ELF
            big = bool(flags & 0x1)
            order = "big" if big else "little"
            elf = ElfFile(content)
            a1 = int.from_bytes(content[pos + 16 : pos + 16 + ptr_size], order)
            a2 = int.from_bytes(
                content[pos + 16 + ptr_size : pos + 16 + 2 * ptr_size], order
            )
            go_version = _read_go_string_ptr(elf, a1, ptr_size, big)
            modinfo = _read_go_string_ptr(elf, a2, ptr_size, big)
    except (ValueError, ElfError):
        return None
    # Sentinel stripping happens on bytes: the markers are not valid UTF-8.
    if len(modinfo) >= 32 and modinfo[:16] == _INFO_START:
        modinfo = modinfo[16:-16]
    return (
        go_version.decode("utf-8", "replace"),
        modinfo.decode("utf-8", "replace"),
    )


def parse_go_modinfo(go_version: str, modinfo: str) -> list[Package]:
    """Module lines -> packages (parse.go:49-120 semantics): the main
    module (skipping the unstamped ``(devel)`` pseudo-version), a ``stdlib``
    package carrying the toolchain version, deps, and ``=>`` replacements
    overriding the preceding dep."""
    pkgs: list[Package] = []
    if go_version:
        v = go_version.removeprefix("go")
        pkgs.append(Package(id=f"stdlib@{v}", name="stdlib", version=v))
    last_dep: Package | None = None
    for line in modinfo.split("\n"):
        parts = line.split("\t")
        if len(parts) >= 3 and parts[0] == "mod":
            version = parts[2]
            if version == "(devel)":
                # Stamped -ldflags versions are not recoverable without
                # symbol analysis; report the module without a version the
                # way the reference falls back (parse.go:63-68).
                version = ""
            pkgs.append(
                Package(
                    id=f"{parts[1]}@{version}" if version else parts[1],
                    name=parts[1],
                    version=version,
                )
            )
        elif len(parts) >= 3 and parts[0] == "dep":
            if not parts[1] or parts[2] == "Devel":
                continue  # old-toolchain artifacts (parse.go:79-84)
            last_dep = Package(
                id=f"{parts[1]}@{parts[2]}", name=parts[1], version=parts[2]
            )
            pkgs.append(last_dep)
        elif len(parts) >= 3 and parts[0] == "=>" and last_dep is not None:
            last_dep.name = parts[1]
            last_dep.version = parts[2]
            last_dep.id = f"{parts[1]}@{parts[2]}"
    return [p for p in pkgs if p.name]


class GoBinaryAnalyzer(Analyzer):
    """analyzer/language/golang/binary/binary.go: executables only."""

    def version(self) -> int:
        return 1

    def type(self) -> str:
        return "gobinary"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return bool(mode & 0o111) and size > 0

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        info = read_go_buildinfo(inp.content)
        if info is None:
            return None
        pkgs = parse_go_modinfo(*info)
        if not pkgs:
            return None
        result = AnalysisResult()
        result.applications.append(
            Application(
                app_type="gobinary", file_path=inp.file_path, packages=pkgs
            )
        )
        return result


# ---------------------------------------------------------------------------
# Rust cargo-auditable

_DEP_SECTION = ".dep-v0"


def read_rust_audit(content: bytes) -> list[Package] | None:
    """cargo-auditable payload: zlib JSON in the ``.dep-v0`` ELF section.

    Only runtime-kind crates are reported (parse.go:52-54); build/dev
    dependencies never ship in the binary's attack surface.
    """
    if not content.startswith(ELF_MAGIC):
        return None
    try:
        raw = ElfFile(content).section_data(_DEP_SECTION)
    except ElfError:
        return None
    if not raw:
        return None
    try:
        doc = json.loads(zlib.decompress(raw))
    except (zlib.error, ValueError):
        logger.debug("undecodable .dep-v0 payload")
        return None
    pkgs = []
    for p in doc.get("packages") or []:
        if p.get("kind", "runtime") != "runtime":
            continue
        name, version = p.get("name", ""), p.get("version", "")
        if not name or not version:
            continue
        pkgs.append(Package(id=f"{name}@{version}", name=name, version=version))
    return pkgs or None


class RustBinaryAnalyzer(Analyzer):
    """analyzer/language/rust/binary/binary.go."""

    def version(self) -> int:
        return 1

    def type(self) -> str:
        return "rustbinary"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return bool(mode & 0o111) and size > 0

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        pkgs = read_rust_audit(inp.content)
        if pkgs is None:
            return None
        result = AnalysisResult()
        result.applications.append(
            Application(
                app_type="rustbinary", file_path=inp.file_path, packages=pkgs
            )
        )
        return result


register_analyzer(GoBinaryAnalyzer)
register_analyzer(RustBinaryAnalyzer)
