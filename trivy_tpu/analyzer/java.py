"""Java analyzers: jar/war/ear archives, pom.xml, gradle lockfiles.

Mirrors pkg/fanal/analyzer/language/java/jar/jar.go (archive walking:
pom.properties GAV extraction, nested WEB-INF/BOOT-INF jars, manifest and
filename fallbacks, digest->GAV lookup in the Java DB) and the pom/gradle
parsers under pkg/dependency/parser/java/.
"""

from __future__ import annotations

import hashlib
import io
import re
import zipfile
import xml.etree.ElementTree as ET

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.atypes import Application, Package

JAR = "jar"
POM = "pom"
GRADLE = "gradle"

_JAR_EXTS = (".jar", ".war", ".ear", ".par")
_NESTED_DIRS = ("WEB-INF/lib/", "BOOT-INF/lib/")
_FILENAME_RE = re.compile(r"^(?P<artifact>[A-Za-z0-9_.-]+?)-(?P<version>\d[\w.+-]*?)(?:-(?:sources|javadoc|tests))?$")


def _pkg(name: str, version: str, file_path: str = "") -> Package:
    return Package(
        id=f"{name}@{version}" if version else name,
        name=name,
        version=version,
        file_path=file_path,
    )


def parse_jar(
    content: bytes, file_path: str, javadb=None, depth: int = 0
) -> list[Package]:
    """One archive -> packages (jar.go parseArtifact).

    Resolution order per archive: pom.properties inside (authoritative,
    possibly several for shaded jars), else Java-DB digest lookup, else
    manifest/filename heuristics.  Nested jars under WEB-INF/BOOT-INF lib
    dirs recurse (depth-capped)."""
    if depth > 2:
        return []
    try:
        zf = zipfile.ZipFile(io.BytesIO(content))
    except (zipfile.BadZipFile, ValueError):
        return []
    out: list[Package] = []
    props_found = False
    manifest: dict[str, str] = {}
    for name in zf.namelist():
        if name.endswith("pom.properties"):
            try:
                props = _parse_properties(zf.read(name))
            except (KeyError, OSError):
                continue
            g, a, v = (
                props.get("groupId", ""),
                props.get("artifactId", ""),
                props.get("version", ""),
            )
            if g and a and v:
                props_found = True
                out.append(_pkg(f"{g}:{a}", v, file_path))
        elif name == "META-INF/MANIFEST.MF":
            try:
                manifest = _parse_manifest(zf.read(name))
            except (KeyError, OSError):
                pass
        elif depth < 2 and name.lower().endswith(_JAR_EXTS) and any(
            name.startswith(d) for d in _NESTED_DIRS
        ):
            try:
                nested = zf.read(name)
            except (KeyError, OSError):
                continue
            out.extend(
                parse_jar(nested, f"{file_path}/{name}", javadb, depth + 1)
            )

    if not props_found:
        gav = None
        if javadb is not None:
            sha1 = hashlib.sha1(content).hexdigest()
            gav = javadb.lookup(sha1)
        if gav:
            out.append(_pkg(f"{gav[0]}:{gav[1]}", gav[2], file_path))
        else:
            pkg = _from_manifest_or_name(manifest, file_path)
            if pkg is not None:
                # SearchByArtifactID fallback (client.go:149): a DB that
                # indexes by artifactId (the SQLite trivy-java-db) can
                # recover the groupId for a bare artifact-version name.
                search = getattr(javadb, "search_by_artifact_id", None)
                if search is not None and ":" not in pkg.name:
                    gid = search(pkg.name, pkg.version)
                    if gid:
                        pkg.name = f"{gid}:{pkg.name}"
                out.append(pkg)
    return out


def _parse_properties(data: bytes) -> dict[str, str]:
    props: dict[str, str] = {}
    for line in data.decode("utf-8", "replace").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        k, _, v = line.partition("=")
        props[k.strip()] = v.strip()
    return props


def _parse_manifest(data: bytes) -> dict[str, str]:
    out: dict[str, str] = {}
    for line in data.decode("utf-8", "replace").splitlines():
        if ":" in line and not line.startswith(" "):
            k, _, v = line.partition(":")
            out[k.strip()] = v.strip()
    return out


def _from_manifest_or_name(manifest: dict[str, str], file_path: str):
    """jar.go's fallbacks: bundle/implementation attributes, then the
    artifact-version filename convention."""
    group = manifest.get("Implementation-Vendor-Id") or ""
    artifact = (
        manifest.get("Implementation-Title")
        or manifest.get("Bundle-SymbolicName")
        or ""
    )
    version = (
        manifest.get("Implementation-Version")
        or manifest.get("Bundle-Version")
        or ""
    )
    if artifact and version:
        name = f"{group}:{artifact}" if group else artifact
        return _pkg(name, version, file_path)
    stem = file_path.rsplit("/", 1)[-1]
    for ext in _JAR_EXTS:
        if stem.lower().endswith(ext):
            stem = stem[: -len(ext)]
            break
    m = _FILENAME_RE.match(stem)
    if m:
        return _pkg(m.group("artifact"), m.group("version"), file_path)
    return None


class JarAnalyzer(Analyzer):
    """pkg/fanal/analyzer/language/java/jar/jar.go (post-analyzer seat)."""

    def __init__(self) -> None:
        self._javadb = None
        self._javadb_loaded = False

    def type(self) -> str:
        return JAR

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.lower().endswith(_JAR_EXTS)

    def _db(self):
        if not self._javadb_loaded:
            from trivy_tpu.javadb import open_default_javadb

            self._javadb = open_default_javadb()
            self._javadb_loaded = True
        return self._javadb

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        pkgs = parse_jar(inp.content, inp.file_path, self._db())
        if not pkgs:
            return None
        return AnalysisResult(
            applications=[
                Application(
                    app_type=JAR, file_path=inp.file_path, packages=pkgs
                )
            ]
        )


# ---------------------------------------------------------------------------
# pom.xml
# ---------------------------------------------------------------------------

_NS_RE = re.compile(r"\{[^}]*\}")
_PROP_RE = re.compile(r"\$\{([^}]+)\}")


def parse_pom(content: bytes) -> list[Package]:
    """pkg/dependency/parser/java/pom: project GAV + dependencies, with
    property interpolation and parent-version inheritance inside the file.
    Versions that stay unresolved (external parents/BOMs) are dropped, like
    the reference without remote repository access."""
    try:
        root = ET.fromstring(content)
    except ET.ParseError:
        return []

    def local(el):
        return _NS_RE.sub("", el.tag)

    def find(el, name):
        for child in el:
            if local(child) == name:
                return child
        return None

    def text(el, name, default=""):
        child = find(el, name)
        return (child.text or "").strip() if child is not None else default

    props: dict[str, str] = {}
    parent = find(root, "parent")
    group = text(root, "groupId") or (text(parent, "groupId") if parent is not None else "")
    version = text(root, "version") or (text(parent, "version") if parent is not None else "")
    artifact = text(root, "artifactId")
    props["project.groupId"] = props["pom.groupId"] = group
    props["project.version"] = props["pom.version"] = version
    props["project.artifactId"] = artifact
    props_el = find(root, "properties")
    if props_el is not None:
        for child in props_el:
            props[local(child)] = (child.text or "").strip()

    def interp(s: str) -> str:
        for _ in range(5):
            m = _PROP_RE.search(s)
            if not m:
                return s
            val = props.get(m.group(1))
            if val is None:
                return ""
            s = s[: m.start()] + val + s[m.end():]
        return s

    out: list[Package] = []
    ig, iv = interp(group), interp(version)
    if ig and artifact and iv:
        out.append(_pkg(f"{ig}:{artifact}", iv))
    deps = find(root, "dependencies")
    if deps is not None:
        for dep in deps:
            if local(dep) != "dependency":
                continue
            g = interp(text(dep, "groupId"))
            a = interp(text(dep, "artifactId"))
            v = interp(text(dep, "version"))
            scope = text(dep, "scope")
            if scope in ("test", "provided", "system"):
                continue
            if g and a and v:
                out.append(_pkg(f"{g}:{a}", v))
    return out


class PomAnalyzer(Analyzer):
    def type(self) -> str:
        return POM

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.rsplit("/", 1)[-1] == "pom.xml"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        pkgs = parse_pom(inp.content)
        if not pkgs:
            return None
        return AnalysisResult(
            applications=[
                Application(app_type=POM, file_path=inp.file_path, packages=pkgs)
            ]
        )


# ---------------------------------------------------------------------------
# gradle.lockfile
# ---------------------------------------------------------------------------


def parse_gradle_lock(content: bytes) -> list[Package]:
    """pkg/dependency/parser/java/gradle: "group:artifact:version=configs"."""
    out = []
    for line in content.decode("utf-8", "replace").splitlines():
        line = line.strip()
        if not line or line.startswith("#") or line.startswith("empty="):
            continue
        coord = line.partition("=")[0]
        parts = coord.split(":")
        if len(parts) == 3:
            g, a, v = parts
            out.append(_pkg(f"{g}:{a}", v))
    return out


class GradleLockAnalyzer(Analyzer):
    def type(self) -> str:
        return GRADLE

    def version(self) -> int:
        return 1

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.rsplit("/", 1)[-1] == "gradle.lockfile"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        pkgs = parse_gradle_lock(inp.content)
        if not pkgs:
            return None
        return AnalysisResult(
            applications=[
                Application(
                    app_type=GRADLE, file_path=inp.file_path, packages=pkgs
                )
            ]
        )


register_analyzer(JarAnalyzer)
register_analyzer(PomAnalyzer)
register_analyzer(GradleLockAnalyzer)
