"""RPM database analyzer (pkg/fanal/analyzer/pkg/rpm/rpm.go).

Reads the rpmdb of RHEL-family images.  Modern databases (RHEL9+, Fedora,
recent Amazon Linux) are sqlite — parsed with the stdlib sqlite3 module;
legacy BerkeleyDB hash databases (`Packages` on RHEL/CentOS <= 8, Amazon
Linux 2) read through the from-scratch BDB reader (trivy_tpu/db/bdb.py).
ndb databases (`Packages.db`, SLE 15 / openSUSE Tumbleweed) read through
trivy_tpu/db/ndb.py.  All three feed the same rpm header-blob decoder
(the store format: two big-endian counts, an index of 16-byte (tag,
type, offset, count) entries, then the data region), matching the
reference's go-rpmdb coverage.
"""

from __future__ import annotations

import logging
import os
import sqlite3
import struct
import tempfile

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.atypes import Package, PackageInfo

logger = logging.getLogger(__name__)

RPM = "rpm"

_SQLITE_PATHS = (
    "var/lib/rpm/rpmdb.sqlite",
    "usr/lib/sysimage/rpm/rpmdb.sqlite",
)
_BDB_PATHS = (
    "var/lib/rpm/Packages",
    "usr/lib/sysimage/rpm/Packages",
)
_NDB_PATHS = (
    "var/lib/rpm/Packages.db",
    "usr/lib/sysimage/rpm/Packages.db",
)

# rpm header tags (rpmtag.h)
_TAG_NAME = 1000
_TAG_VERSION = 1001
_TAG_RELEASE = 1002
_TAG_EPOCH = 1003
_TAG_ARCH = 1022
_TAG_SOURCERPM = 1044
_TAG_LICENSE = 1014
_TAG_MODULARITYLABEL = 5096


def parse_header_blob(blob: bytes) -> dict[int, object]:
    """Decode an rpm header store: il, dl (4-byte BE counts), il 16-byte
    index entries, then the data region.  Returns tag -> decoded value for
    the string/int types the analyzer needs."""
    if len(blob) < 8:
        return {}
    il, dl = struct.unpack(">II", blob[:8])
    index_end = 8 + il * 16
    if il > 65536 or len(blob) < index_end + dl:
        return {}
    data = blob[index_end : index_end + dl]
    out: dict[int, object] = {}
    for i in range(il):
        tag, typ, off, count = struct.unpack(
            ">IIII", blob[8 + i * 16 : 8 + (i + 1) * 16]
        )
        if off > len(data):
            continue
        if typ == 6 or typ == 9:  # STRING / I18NSTRING (first value)
            end = data.find(b"\x00", off)
            if end != -1:
                out[tag] = data[off:end].decode("utf-8", "replace")
        elif typ == 4 and count >= 1 and off + 4 <= len(data):  # INT32
            out[tag] = struct.unpack(">I", data[off : off + 4])[0]
        elif typ == 3 and count >= 1 and off + 2 <= len(data):  # INT16
            out[tag] = struct.unpack(">H", data[off : off + 2])[0]
        elif typ == 8:  # STRING_ARRAY (first value suffices here)
            end = data.find(b"\x00", off)
            if end != -1:
                out[tag] = data[off:end].decode("utf-8", "replace")
    return out


def _src_name(sourcerpm: str) -> str:
    """name-version-release.src.rpm -> name (rpm.go splitFileName)."""
    s = sourcerpm
    for suffix in (".src.rpm", ".nosrc.rpm", ".rpm"):
        if s.endswith(suffix):
            s = s[: -len(suffix)]
            break
    # strip release then version
    s, _, _ = s.rpartition("-")
    s, _, _ = s.rpartition("-")
    return s


def _packages_from_blobs(blobs) -> list[Package]:
    out: list[Package] = []
    for blob in blobs:
        hdr = parse_header_blob(blob)
        name = hdr.get(_TAG_NAME, "")
        version = hdr.get(_TAG_VERSION, "")
        if not name or not version:
            continue
        release = hdr.get(_TAG_RELEASE, "")
        epoch = int(hdr.get(_TAG_EPOCH, 0) or 0)
        srpm = hdr.get(_TAG_SOURCERPM, "")
        out.append(
            Package(
                id=f"{name}@{version}-{release}",
                name=str(name),
                version=str(version),
                release=str(release),
                epoch=epoch,
                arch=str(hdr.get(_TAG_ARCH, "")),
                src_name=_src_name(str(srpm)) if srpm else str(name),
                src_version=str(version),
                src_release=str(release),
                licenses=[str(hdr[_TAG_LICENSE])] if _TAG_LICENSE in hdr else [],
            )
        )
    return out


def parse_rpmdb_sqlite(content: bytes) -> list[Package]:
    """The sqlite rpmdb: table Packages(hnum, blob) of header stores."""
    with tempfile.NamedTemporaryFile(suffix=".sqlite", delete=False) as tmp:
        tmp.write(content)
        path = tmp.name
    try:
        conn = sqlite3.connect(path)
        try:
            rows = conn.execute("SELECT blob FROM Packages").fetchall()
        finally:
            conn.close()
    except sqlite3.DatabaseError:
        return []
    finally:
        os.unlink(path)
    return _packages_from_blobs(blob for (blob,) in rows)


def parse_rpmdb_bdb(content: bytes) -> list[Package]:
    """The BDB hash rpmdb (CentOS <= 8 `Packages`): one header blob per
    stored value."""
    from trivy_tpu.db.bdb import BdbError, BdbHashReader

    try:
        return _packages_from_blobs(BdbHashReader(content).values())
    except BdbError as e:
        logger.warning("unreadable BerkeleyDB rpm database: %s", e)
        return []


def parse_rpmdb_ndb(content: bytes) -> list[Package]:
    """The ndb rpmdb (SLE 15 / Tumbleweed `Packages.db`)."""
    from trivy_tpu.db.ndb import NdbError, NdbReader

    try:
        return _packages_from_blobs(NdbReader(content).values())
    except NdbError as e:
        logger.warning("unreadable ndb rpm database: %s", e)
        return []


class RpmDbAnalyzer(Analyzer):
    def type(self) -> str:
        return RPM

    def version(self) -> int:
        return 3  # v2: BDB hash parsed; v3: ndb Packages.db parsed

    def required(self, file_path: str, size: int, mode: int) -> bool:
        p = file_path.lstrip("/")
        return p in _SQLITE_PATHS or p in _BDB_PATHS or p in _NDB_PATHS

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        from trivy_tpu.db.bdb import is_bdb_hash
        from trivy_tpu.db.ndb import is_ndb

        if is_bdb_hash(inp.content):
            pkgs = parse_rpmdb_bdb(inp.content)
        elif is_ndb(inp.content):
            pkgs = parse_rpmdb_ndb(inp.content)
        else:
            pkgs = parse_rpmdb_sqlite(inp.content)
        if not pkgs:
            return None
        return AnalysisResult(
            package_infos=[
                PackageInfo(file_path=inp.file_path, packages=pkgs)
            ]
        )


register_analyzer(RpmDbAnalyzer)
