"""dpkg package database analyzer (pkg/fanal/analyzer/pkg/dpkg/dpkg.go).

Parses `var/lib/dpkg/status` and `var/lib/dpkg/status.d/*` — RFC822 stanzas
with Package/Status/Version/Source/Architecture fields.  The `Source:` field
may carry an explicit version in parentheses.
"""

from __future__ import annotations

import re

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.atypes import Package, PackageInfo
from trivy_tpu.detector.version_cmp import _deb_split as split_version

STATUS_FILE = "var/lib/dpkg/status"
STATUS_DIR = "var/lib/dpkg/status.d/"

_SOURCE_RE = re.compile(r"^(\S+)(?:\s+\((.+)\))?$")


def parse_dpkg_status(content: bytes) -> list[Package]:
    packages: list[Package] = []
    for stanza in re.split(r"\n\s*\n", content.decode("utf-8", errors="replace")):
        fields: dict[str, str] = {}
        key = ""
        for line in stanza.splitlines():
            if line.startswith((" ", "\t")):
                if key:
                    fields[key] += "\n" + line.strip()
                continue
            key, _, value = line.partition(":")
            fields[key.strip()] = value.strip()

        name = fields.get("Package", "")
        version = fields.get("Version", "")
        status = fields.get("Status", "installed")
        if not name or not version or "installed" not in status.split():
            continue

        src_name, src_version = name, version
        if fields.get("Source"):
            m = _SOURCE_RE.match(fields["Source"])
            if m:
                src_name = m.group(1)
                if m.group(2):
                    src_version = m.group(2)

        epoch, _, _ = split_version(version)
        s_epoch, _, _ = split_version(src_version)
        depends = []
        for dep in fields.get("Depends", "").split(","):
            dep = dep.strip().split(" ")[0].split(":")[0]
            if dep:
                depends.append(dep)

        packages.append(
            Package(
                id=f"{name}@{version}",
                name=name,
                version=version,
                epoch=epoch,
                arch=fields.get("Architecture", ""),
                src_name=src_name,
                src_version=src_version,
                src_epoch=s_epoch,
                depends_on=sorted(set(depends)),
            )
        )
    return packages


class DpkgAnalyzer(Analyzer):
    def type(self) -> str:
        return "dpkg"

    def version(self) -> int:
        return 3

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path == STATUS_FILE or file_path.startswith(STATUS_DIR)

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        packages = parse_dpkg_status(inp.content)
        if not packages:
            return None
        return AnalysisResult(
            package_infos=[
                PackageInfo(file_path=inp.file_path, packages=packages)
            ]
        )


register_analyzer(DpkgAnalyzer)
