"""apk package database analyzer (pkg/fanal/analyzer/pkg/apk/apk.go).

Parses `lib/apk/db/installed` — stanzas of single-letter fields:
P: name, V: version, A: arch, L: license, o: origin (source package),
D/r: dependencies/provides.
"""

from __future__ import annotations

from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.atypes import Package, PackageInfo

REQUIRED_FILE = "lib/apk/db/installed"


def parse_apk_db(content: bytes) -> tuple[list[Package], list[str]]:
    """Returns (packages, installed_files): F:/R: stanza fields list each
    package's directory/file entries (apk.go collects them for the
    system-file filter, SystemInstalledFiles)."""
    packages: list[Package] = []
    installed_files: list[str] = []
    cur: dict[str, str] = {}
    depends: list[str] = []
    cur_dir = ""

    def flush() -> None:
        nonlocal cur, depends, cur_dir
        cur_dir = ""
        if cur.get("P") and cur.get("V"):
            name, version = cur["P"], cur["V"]
            packages.append(
                Package(
                    id=f"{name}@{version}",
                    name=name,
                    version=version,
                    arch=cur.get("A", ""),
                    src_name=cur.get("o", name),
                    src_version=version,
                    licenses=[l for l in cur.get("L", "").split(" AND ") if l],
                    depends_on=sorted(set(depends)),
                )
            )
        cur, depends = {}, []

    for raw in content.decode("utf-8", errors="replace").splitlines():
        if not raw.strip():
            flush()
            continue
        key, _, value = raw.partition(":")
        if key == "F":
            cur_dir = value
            continue
        if key == "R":
            installed_files.append(f"{cur_dir}/{value}" if cur_dir else value)
            continue
        if key == "D":
            for dep in value.split():
                dep = dep.split("=")[0].split("<")[0].split(">")[0].split("~")[0]
                if dep and not dep.startswith("!"):
                    depends.append(dep)
        elif key:
            cur[key] = value
    flush()
    return packages, installed_files


class ApkAnalyzer(Analyzer):
    def type(self) -> str:
        return "apk"

    def version(self) -> int:
        return 2

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path == REQUIRED_FILE

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        packages, installed_files = parse_apk_db(inp.content)
        if not packages:
            return None
        return AnalysisResult(
            package_infos=[
                PackageInfo(file_path=inp.file_path, packages=packages)
            ],
            system_installed_files=installed_files,
        )


register_analyzer(ApkAnalyzer)
