"""Additional language ecosystem analyzers: Conan, Conda, Pub, Mix,
CocoaPods, Swift.

Reference parity targets: dependency/parser/c/conan/parse.go (v1
graph_lock nodes + v2 requires), conda/meta/parse.go and
conda/environment/parse.go, dart/pub/parse.go (pubspec.lock packages),
hex/mix/parse.go (mix.lock :hex tuples), swift/cocoapods/parse.go
(Podfile.lock PODS) and swift/swift/parse.go (Package.resolved v1/v2).
"""

from __future__ import annotations

import json
import logging
import os
import re

import yaml

from trivy_tpu.analyzer.core import (
    Analyzer,
    AnalysisInput,
    AnalysisResult,
    register_analyzer,
)
from trivy_tpu.atypes import Application, Package

logger = logging.getLogger(__name__)


def _app(app_type: str, file_path: str, pkgs: list[Package]) -> AnalysisResult:
    result = AnalysisResult()
    result.applications.append(
        Application(app_type=app_type, file_path=file_path, packages=pkgs)
    )
    return result


def _pkg(name: str, version: str) -> Package:
    return Package(id=f"{name}@{version}" if version else name, name=name, version=version)


class _FileNameAnalyzer(Analyzer):
    """Analyzer triggered by an exact basename match."""

    FILE_NAME = ""
    TYPE = ""
    VERSION = 1

    def version(self) -> int:
        return self.VERSION

    def type(self) -> str:
        return self.TYPE

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return os.path.basename(file_path) == self.FILE_NAME

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        try:
            pkgs = self.parse(inp.content)
        except Exception as e:
            logger.warning("%s: cannot parse %s: %s", self.TYPE, inp.file_path, e)
            return None
        if not pkgs:
            return None
        return _app(self.TYPE, inp.file_path, pkgs)

    def parse(self, content: bytes) -> list[Package]:
        raise NotImplementedError


class ConanLockAnalyzer(_FileNameAnalyzer):
    """conan.lock (parse.go:60-120): v1 graph_lock nodes keyed by id ("0"
    is the consumer project, skipped); v2 flat requires list.  Refs look
    like name/version[@user/channel][#rev]."""

    FILE_NAME = "conan.lock"
    TYPE = "conan"

    @staticmethod
    def _ref_to_pkg(ref: str) -> Package | None:
        ref = ref.split("#")[0].split("@")[0].split("%")[0]
        name, _, version = ref.partition("/")
        if not name or not version:
            return None
        return _pkg(name, version)

    def parse(self, content: bytes) -> list[Package]:
        doc = json.loads(content)
        pkgs = []
        nodes = (doc.get("graph_lock") or {}).get("nodes") or {}
        for node_id, node in nodes.items():
            if node_id == "0":  # the consumer project itself
                continue
            p = self._ref_to_pkg(node.get("ref") or "")
            if p:
                pkgs.append(p)
        for ref in doc.get("requires") or []:  # lockfile v2
            p = self._ref_to_pkg(ref)
            if p:
                pkgs.append(p)
        return pkgs


class CondaMetaAnalyzer(Analyzer):
    """conda-meta/<pkg>.json environment records (conda/meta/parse.go)."""

    def version(self) -> int:
        return 1

    def type(self) -> str:
        return "conda-pkg"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        norm = file_path.replace(os.sep, "/")
        return norm.endswith(".json") and "conda-meta/" in norm

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        try:
            doc = json.loads(inp.content)
        except ValueError:
            return None
        name, version = doc.get("name", ""), doc.get("version", "")
        if not name or not version:
            return None
        pkg = _pkg(name, version)
        if doc.get("license"):
            pkg.licenses = [doc["license"]]
        return _app("conda-pkg", inp.file_path, [pkg])


class CondaEnvironmentAnalyzer(_FileNameAnalyzer):
    """environment.yml (conda/environment/parse.go): "name=version[=build]"
    entries; unpinned specs keep an empty version."""

    FILE_NAME = "environment.yml"
    TYPE = "conda-environment"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return os.path.basename(file_path) in (
            "environment.yml",
            "environment.yaml",
        )

    _DEP = re.compile(
        r"^(?P<name>[A-Za-z0-9_.-]+)\s*(?P<spec>(?:[=<>!~].*)?)$"
    )

    def parse(self, content: bytes) -> list[Package]:
        doc = yaml.safe_load(content) or {}
        pkgs = []
        for dep in doc.get("dependencies") or []:
            if not isinstance(dep, str):
                continue  # nested pip: lists etc.
            m = self._DEP.match(dep.strip())
            if m is None:
                continue
            # Only exact "=version[=build]" pins count as versions; range
            # specs (">=3.9", "<2", "=1.2.*") cannot be vuln-matched and
            # keep an empty version like the reference's unpinned warning.
            vm = re.fullmatch(
                r"={1,2}(?P<v>[0-9][\w.!+-]*)(=.*)?", m["spec"]
            )
            pkgs.append(_pkg(m["name"], vm["v"] if vm else ""))
        return pkgs


class PubLockAnalyzer(_FileNameAnalyzer):
    """pubspec.lock (dart/pub/parse.go): YAML packages map; dev and
    transitive dependencies are all kept (the lock cannot distinguish
    transitive-dev from transitive-main)."""

    FILE_NAME = "pubspec.lock"
    TYPE = "pub"

    def parse(self, content: bytes) -> list[Package]:
        doc = yaml.safe_load(content) or {}
        pkgs = []
        for name, dep in (doc.get("packages") or {}).items():
            version = str((dep or {}).get("version", ""))
            if name and version:
                pkgs.append(_pkg(name, version))
        return pkgs


_MIX_LINE = re.compile(
    rb'^\s*"(?P<name>[^"]+)":\s*\{:hex,\s*:[\w]+,\s*"(?P<version>[^"]+)"'
)


class MixLockAnalyzer(_FileNameAnalyzer):
    """mix.lock (hex/mix/parse.go): one Elixir tuple per line,
    '"name": {:hex, :name, "version", ...}'.  Git tuples carry a quoted
    URL where :hex lines carry the package atom, so they never match the
    pattern — mirroring the reference's skip of git dependencies."""

    FILE_NAME = "mix.lock"
    TYPE = "hex"

    def parse(self, content: bytes) -> list[Package]:
        pkgs = []
        for line in content.splitlines():
            m = _MIX_LINE.match(line)
            if m is not None:
                pkgs.append(_pkg(m["name"].decode(), m["version"].decode()))
        return pkgs


_POD_DEP = re.compile(r"^(?P<name>\S+)\s+\((?P<version>[^()\s]+)\)$")


class CocoaPodsAnalyzer(_FileNameAnalyzer):
    """Podfile.lock (swift/cocoapods/parse.go): PODS entries are either
    plain strings "Name (1.2.3)" or one-key maps with child dep lists;
    subspec names like Alamofire/Core are kept as-is."""

    FILE_NAME = "Podfile.lock"
    TYPE = "cocoapods"

    def parse(self, content: bytes) -> list[Package]:
        doc = yaml.safe_load(content) or {}
        pkgs = {}
        for pod in doc.get("PODS") or []:
            entries = [pod] if isinstance(pod, str) else list(pod or {})
            for entry in entries:
                m = _POD_DEP.match(str(entry).strip())
                if m is None:
                    logger.debug("cocoapods: cannot parse %r", entry)
                    continue
                pkgs[m["name"]] = _pkg(m["name"], m["version"])
        return list(pkgs.values())


class SwiftAnalyzer(_FileNameAnalyzer):
    """Package.resolved (swift/swift/parse.go): v1 object.pins use
    repositoryURL, v2 pins use location; names are the URL without the
    https:// prefix and .git suffix, versions fall back to the branch."""

    FILE_NAME = "Package.resolved"
    TYPE = "swift"

    def parse(self, content: bytes) -> list[Package]:
        doc = json.loads(content)
        version = doc.get("version", 1)
        pins = (
            doc.get("pins")
            if version > 1
            else (doc.get("object") or {}).get("pins")
        ) or []
        pkgs = []
        for pin in pins:
            url = pin.get("location" if version > 1 else "repositoryURL", "")
            name = url.removeprefix("https://").removesuffix(".git")
            state = pin.get("state") or {}
            ver = state.get("version") or state.get("branch") or ""
            if name and ver:
                pkgs.append(_pkg(name, ver))
        return pkgs


register_analyzer(ConanLockAnalyzer)
register_analyzer(CondaMetaAnalyzer)
register_analyzer(CondaEnvironmentAnalyzer)
register_analyzer(PubLockAnalyzer)
register_analyzer(MixLockAnalyzer)
register_analyzer(CocoaPodsAnalyzer)
register_analyzer(SwiftAnalyzer)


class _PathAnalyzer(Analyzer):
    """Analyzer with TYPE/VERSION class attrs; subclasses define
    required() and analyze()."""

    TYPE = ""
    VERSION = 1

    def version(self) -> int:
        return self.VERSION

    def type(self) -> str:
        return self.TYPE


def _components(file_path: str) -> list[str]:
    return file_path.replace(os.sep, "/").split("/")


class GemspecAnalyzer(_PathAnalyzer):
    """Installed gem specifications (ruby/gemspec/parse.go): .gemspec files
    under a specifications/ directory carry `s.name = "x"` /
    `s.version = "1.2"` assignments (quoted or .freeze forms)."""

    TYPE = "gemspec"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return (
            file_path.endswith(".gemspec")
            and "specifications" in _components(file_path)[:-1]
        )

    _NAME_RE = re.compile(
        rb'\.name\s*=\s*["\']([^"\']+)["\']'
    )
    _VERSION_RE = re.compile(
        rb'\.version\s*=\s*(?:Gem::Version\.new\()?["\']([^"\']+)["\']'
    )
    _LICENSE_RE = re.compile(
        rb'\.licenses?\s*=\s*\[?["\']([^"\']+)["\']'
    )

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        name = self._NAME_RE.search(inp.content)
        version = self._VERSION_RE.search(inp.content)
        if not name or not version:
            return None
        pkg = _pkg(
            name.group(1).decode("utf-8", "replace"),
            version.group(1).decode("utf-8", "replace"),
        )
        lic = self._LICENSE_RE.search(inp.content)
        if lic:
            pkg.licenses = [lic.group(1).decode("utf-8", "replace")]
        return _app(self.TYPE, inp.file_path, [pkg])


class DotnetDepsAnalyzer(_PathAnalyzer):
    """.deps.json runtime dependency files (dotnet/core_deps/parse.go):
    libraries keyed "Name/Version" with type "package" (case-insensitive,
    as the reference's EqualFold)."""

    TYPE = "dotnet-core"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        return file_path.endswith(".deps.json")

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        try:
            doc = json.loads(inp.content)
        except ValueError as e:
            logger.warning("deps.json %s: %s", inp.file_path, e)
            return None
        pkgs = []
        for key, lib in (doc.get("libraries") or {}).items():
            if not isinstance(lib, dict) or str(lib.get("type", "")).lower() != "package":
                continue
            name, _, ver = key.partition("/")
            if name and ver:
                pkgs.append(_pkg(name, ver))
        if not pkgs:
            return None
        return _app(self.TYPE, inp.file_path, pkgs)


class PackagesPropsAnalyzer(_PathAnalyzer):
    """Central package management props files (dotnet packages_props
    parser): <PackageVersion Include="x" Version="1.2"/> items, any
    attribute order; $()-interpolated values are skipped."""

    TYPE = "packages-props"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        base = os.path.basename(file_path).lower()
        return base in ("directory.packages.props", "packages.props")

    _ELEM_RE = re.compile(
        rb"<Package(?:Version|Reference)\s([^>]*?)/?>", re.IGNORECASE
    )
    _ATTR_RE = re.compile(rb"""(\w+)\s*=\s*["']([^"']*)["']""")

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        pkgs = []
        for m in self._ELEM_RE.finditer(inp.content):
            attrs = {
                k.lower(): v
                for k, v in self._ATTR_RE.findall(m.group(1))
            }
            name = attrs.get(b"include", b"")
            ver = attrs.get(b"version", b"")
            if name and ver and b"$" not in name and b"$" not in ver:
                pkgs.append(_pkg(name.decode(), ver.decode()))
        if not pkgs:
            return None
        return _app(self.TYPE, inp.file_path, pkgs)


class NodePkgAnalyzer(_PathAnalyzer):
    """Installed node packages (nodejs/packagejson parser): package.json
    under node_modules/ carries the installed package's own name/version.
    Scoped to node_modules (unlike the reference's any-package.json) so the
    npm composite-FS post-analyzer keeps owning project manifests."""

    TYPE = "node-pkg"

    def required(self, file_path: str, size: int, mode: int) -> bool:
        parts = _components(file_path)
        return parts[-1] == "package.json" and "node_modules" in parts[:-1]

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        try:
            doc = json.loads(inp.content)
        except ValueError:
            return None
        name = doc.get("name", "")
        ver = doc.get("version", "")
        if not isinstance(name, str) or not name or not isinstance(ver, str):
            return None
        pkg = _pkg(name, ver)
        lic = doc.get("license")
        if isinstance(lic, str) and lic:
            pkg.licenses = [lic]
        elif isinstance(lic, dict) and lic.get("type"):
            pkg.licenses = [lic["type"]]
        return _app(self.TYPE, inp.file_path, [pkg])


class JuliaManifestAnalyzer(_FileNameAnalyzer):
    """Julia Manifest.toml (julia/manifest/parse.go): [[deps.Name]]
    entries with version (stdlib entries without version are skipped)."""

    FILE_NAME = "Manifest.toml"
    TYPE = "julia"

    def parse(self, content: bytes) -> list[Package]:
        from trivy_tpu.compat import tomllib

        doc = tomllib.loads(content.decode("utf-8", "replace"))
        deps = doc.get("deps") or {
            k: v for k, v in doc.items() if isinstance(v, list)
        }
        pkgs = []
        for name, entries in deps.items():
            if not isinstance(entries, list):
                continue
            for e in entries:
                if isinstance(e, dict) and e.get("version"):
                    pkgs.append(_pkg(name, str(e["version"])))
        return pkgs


register_analyzer(GemspecAnalyzer)
register_analyzer(DotnetDepsAnalyzer)
register_analyzer(PackagesPropsAnalyzer)
register_analyzer(NodePkgAnalyzer)
register_analyzer(JuliaManifestAnalyzer)
