"""Language lockfile analyzers.

Mirrors pkg/fanal/analyzer/language/* over the parsers in
pkg/dependency/parser/*: each analyzer claims its ecosystem's lockfile and
yields an Application with the pinned package list.  Pure text/JSON/TOML/YAML
parsing — the per-ecosystem vulnerability matching lives in
trivy_tpu/detector/library.py.
"""

from __future__ import annotations

import json
import re

import yaml

from trivy_tpu.analyzer.core import (
    PostAnalyzer,
    register_post_analyzer,
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register_analyzer,
)
from trivy_tpu.atypes import Application, Package

# App type constants (pkg/fanal/types/const.go)
NPM = "npm"
YARN = "yarn"
PNPM = "pnpm"
PIP = "pip"
PIPENV = "pipenv"
POETRY = "poetry"
GO_MOD = "gomod"
CARGO = "cargo"
COMPOSER = "composer"
BUNDLER = "bundler"
NUGET = "nuget"
GRADLE = "gradle"


class _LockfileAnalyzer(Analyzer):
    """Base: claim by filename, parse to a package list."""

    app_type = ""
    analyzer_version = 1
    filenames: tuple[str, ...] = ()

    def type(self) -> str:
        return self.app_type

    def version(self) -> int:
        return self.analyzer_version

    def required(self, file_path: str, size: int, mode: int) -> bool:
        name = file_path.rsplit("/", 1)[-1]
        return name in self.filenames

    def parse(self, content: bytes) -> list[Package]:
        raise NotImplementedError

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        try:
            packages = self.parse(inp.content)
        except Exception:
            return None  # unparseable lockfiles are skipped, not fatal
        if not packages:
            return None
        packages.sort(key=lambda p: (p.name, p.version))
        return AnalysisResult(
            applications=[
                Application(
                    app_type=self.app_type,
                    file_path=inp.file_path,
                    packages=packages,
                )
            ]
        )


def _pkg(name: str, version: str, **kw) -> Package:
    return Package(id=f"{name}@{version}", name=name, version=version, **kw)


class NpmLockAnalyzer(_LockfileAnalyzer):
    """package-lock.json v1 (dependencies) and v2/v3 (packages)."""

    app_type = NPM
    filenames = ("package-lock.json",)

    def parse(self, content: bytes) -> list[Package]:
        data = json.loads(content)
        out: dict[str, Package] = {}
        if "packages" in data:  # lockfile v2/v3
            for path, meta in data["packages"].items():
                if not path:  # the root project itself
                    continue
                name = meta.get("name") or path.rpartition("node_modules/")[2]
                version = meta.get("version", "")
                if not name or not version or meta.get("link"):
                    continue
                out[f"{name}@{version}"] = _pkg(
                    name, version, dev=bool(meta.get("dev"))
                )
        else:  # v1
            def walk(deps: dict, indirect: bool) -> None:
                for name, meta in (deps or {}).items():
                    version = meta.get("version", "")
                    if version:
                        out[f"{name}@{version}"] = _pkg(
                            name, version,
                            dev=bool(meta.get("dev")),
                            indirect=indirect,
                        )
                    walk(meta.get("dependencies"), True)

            walk(data.get("dependencies"), False)
        return list(out.values())


_YARN_HEADER = re.compile(r'^"?((?:@[^/"]+/)?[^@/"]+)@')
_YARN_VERSION = re.compile(r'^\s{2}version:?\s+"?([^"\s]+)"?')


class YarnLockAnalyzer(_LockfileAnalyzer):
    app_type = YARN
    filenames = ("yarn.lock",)

    def parse(self, content: bytes) -> list[Package]:
        out: dict[str, Package] = {}
        current: str | None = None
        for line in content.decode("utf-8", errors="replace").splitlines():
            if not line or line.startswith("#"):
                continue
            if not line.startswith(" "):
                m = _YARN_HEADER.match(line)
                current = m.group(1) if m else None
                continue
            m = _YARN_VERSION.match(line)
            if m and current:
                out[f"{current}@{m.group(1)}"] = _pkg(current, m.group(1))
                current = None
        return list(out.values())


class PnpmLockAnalyzer(_LockfileAnalyzer):
    app_type = PNPM
    filenames = ("pnpm-lock.yaml",)

    def parse(self, content: bytes) -> list[Package]:
        data = yaml.safe_load(content) or {}
        out = []
        for key in data.get("packages") or {}:
            # "/name@version(peer@dep)" or "/@scope/name@version" (v6);
            # "/name/1.0.0" (v5).  Peer-dependency suffixes are parenthesized
            # and contain '@'s of their own — strip them first.
            k = key.lstrip("/").split("(")[0]
            if "@" in k[1:]:
                name, _, version = k.rpartition("@")
            else:
                name, _, version = k.rpartition("/")
            if name and version:
                out.append(_pkg(name, version))
        return out


_REQ_LINE = re.compile(
    r"^([A-Za-z0-9._-]+)\s*(?:\[[^\]]*\])?\s*==\s*([A-Za-z0-9.*+!_-]+)"
)


class PipRequirementsAnalyzer(_LockfileAnalyzer):
    app_type = PIP
    filenames = ("requirements.txt",)

    def parse(self, content: bytes) -> list[Package]:
        out = []
        for line in content.decode("utf-8", errors="replace").splitlines():
            line = line.split("#")[0].strip()
            m = _REQ_LINE.match(line)
            if m:
                out.append(_pkg(m.group(1).lower(), m.group(2)))
        return out


class PipenvLockAnalyzer(_LockfileAnalyzer):
    app_type = PIPENV
    filenames = ("Pipfile.lock",)

    def parse(self, content: bytes) -> list[Package]:
        data = json.loads(content)
        out = []
        for section in ("default", "develop"):
            for name, meta in (data.get(section) or {}).items():
                version = (meta.get("version") or "").lstrip("=")
                if version:
                    out.append(_pkg(name.lower(), version, dev=section == "develop"))
        return out


class PoetryLockAnalyzer(_LockfileAnalyzer):
    app_type = POETRY
    filenames = ("poetry.lock",)

    def parse(self, content: bytes) -> list[Package]:
        from trivy_tpu.compat import tomllib

        data = tomllib.loads(content.decode("utf-8", errors="replace"))
        return [
            _pkg(p["name"].lower(), p["version"])
            for p in data.get("package", [])
            if p.get("name") and p.get("version")
        ]


class GoModAnalyzer(_LockfileAnalyzer):
    app_type = GO_MOD
    analyzer_version = 2
    filenames = ("go.mod",)

    def parse(self, content: bytes) -> list[Package]:
        out = []
        in_require = False
        for raw in content.decode("utf-8", errors="replace").splitlines():
            indirect = "// indirect" in raw
            line = raw.split("//")[0].strip()
            if line.startswith("require ("):
                in_require = True
                continue
            if in_require and line == ")":
                in_require = False
                continue
            parts = line.split()
            if in_require and len(parts) >= 2:
                out.append(_pkg(parts[0], parts[1].lstrip("v"), indirect=indirect))
            elif parts[:1] == ["require"] and len(parts) >= 3:
                out.append(_pkg(parts[1], parts[2].lstrip("v"), indirect=indirect))
        return out


class CargoLockAnalyzer(_LockfileAnalyzer):
    app_type = CARGO
    filenames = ("Cargo.lock",)

    def parse(self, content: bytes) -> list[Package]:
        from trivy_tpu.compat import tomllib

        data = tomllib.loads(content.decode("utf-8", errors="replace"))
        return [
            _pkg(p["name"], p["version"])
            for p in data.get("package", [])
            if p.get("name") and p.get("version")
        ]


class ComposerLockAnalyzer(_LockfileAnalyzer):
    app_type = COMPOSER
    filenames = ("composer.lock",)

    def parse(self, content: bytes) -> list[Package]:
        data = json.loads(content)
        out = []
        for section, dev in (("packages", False), ("packages-dev", True)):
            for p in data.get(section) or []:
                if p.get("name") and p.get("version"):
                    out.append(
                        _pkg(p["name"], p["version"].lstrip("v"), dev=dev)
                    )
        return out


_GEM_RE = re.compile(r"^\s{4}([A-Za-z0-9._-]+)\s+\(([^)]+)\)")


class GemfileLockAnalyzer(_LockfileAnalyzer):
    app_type = BUNDLER
    filenames = ("Gemfile.lock",)

    def parse(self, content: bytes) -> list[Package]:
        out = []
        in_gem = False
        for line in content.decode("utf-8", errors="replace").splitlines():
            if line.strip() == "GEM":
                in_gem = True
                continue
            if in_gem and line and not line.startswith(" "):
                in_gem = False
            if in_gem:
                m = _GEM_RE.match(line)
                if m:
                    out.append(_pkg(m.group(1), m.group(2)))
        return out


class NugetLockAnalyzer(_LockfileAnalyzer):
    app_type = NUGET
    filenames = ("packages.lock.json",)

    def parse(self, content: bytes) -> list[Package]:
        data = json.loads(content)
        out: dict[str, Package] = {}
        for deps in (data.get("dependencies") or {}).values():
            for name, meta in deps.items():
                version = meta.get("resolved", "")
                if version:
                    out[f"{name}@{version}"] = _pkg(name, version)
        return list(out.values())


# NpmLockAnalyzer is not registered per-file: npm runs as a post-analyzer
# (NpmPostAnalyzer below) so it can see the manifest and node_modules
# metadata through the composite FS.
for _cls in (
    PnpmLockAnalyzer,
    PipRequirementsAnalyzer,
    PipenvLockAnalyzer,
    PoetryLockAnalyzer,
    GoModAnalyzer,
    CargoLockAnalyzer,
    ComposerLockAnalyzer,
    GemfileLockAnalyzer,
    NugetLockAnalyzer,
    YarnLockAnalyzer,
):
    register_analyzer(_cls)


class NpmPostAnalyzer(PostAnalyzer):
    """pkg/fanal/analyzer/language/nodejs/npm/npm.go: the lockfile parse
    plus cross-file context from the composite FS — direct-dependency
    marking from the sibling package.json and license enrichment from
    node_modules/<name>/package.json.  The per-file analyzer cannot see
    those neighbors; this is the post-analyzer mechanism's seat
    (analyzer.go:506)."""

    def type(self) -> str:
        return NPM

    def version(self) -> int:
        return 2  # v1 was the plain per-file lock analyzer

    def required(self, file_path: str, size: int, mode: int) -> bool:
        name = file_path.rsplit("/", 1)[-1]
        if name == "package-lock.json":
            return True
        return name == "package.json" and size < 1 << 20

    def post_analyze(self, fs) -> AnalysisResult | None:
        import posixpath

        apps = []
        for lock_path in fs.glob("**/package-lock.json") + (
            ["package-lock.json"] if fs.exists("package-lock.json") else []
        ):
            try:
                pkgs = NpmLockAnalyzer().parse(fs.read(lock_path))
            except (ValueError, KeyError, TypeError):
                continue  # unparseable lockfiles are skipped, not fatal
            base = posixpath.dirname(lock_path)

            direct: set[str] = set()
            manifest = fs.siblings(lock_path, "package.json")
            if manifest is not None:
                try:
                    m = json.loads(fs.read(manifest))
                    for sect in ("dependencies", "devDependencies"):
                        direct.update((m.get(sect) or {}).keys())
                except ValueError:
                    pass

            for p in pkgs:
                if direct:
                    p.indirect = p.name not in direct
                nm = posixpath.join(base, "node_modules", p.name, "package.json")
                if fs.exists(nm):
                    try:
                        meta = json.loads(fs.read(nm))
                    except ValueError:
                        continue
                    lic = meta.get("license")
                    if isinstance(lic, dict):
                        lic = lic.get("type")
                    if isinstance(lic, str) and lic:
                        p.licenses = [lic]
            if not pkgs:
                continue  # empty lockfile: no Application, like the per-file path
            pkgs.sort(key=lambda p: (p.name, p.version))
            apps.append(
                Application(
                    app_type=NPM, file_path=lock_path, packages=pkgs
                )
            )
        if not apps:
            return None
        return AnalysisResult(applications=apps)


register_post_analyzer(NpmPostAnalyzer)
