"""Image-config analyzers: scan the serialized container config.

Mirrors pkg/fanal/analyzer/imgconf/secret/secret.go (secret scan over the
config JSON — catches credentials in ENV/history) and the history-dockerfile
misconfig analyzer (imgconf/dockerfile): the image history is reconstructed
into a Dockerfile and run through the dockerfile checks.
"""

from __future__ import annotations

import json

from trivy_tpu.ftypes import Secret
from trivy_tpu.misconf.dockerfile import scan_dockerfile
from trivy_tpu.misconf.types import Misconfiguration


def scan_config_secrets(config: dict, engine) -> Secret | None:
    """imgconf/secret/secret.go:39-60 — serialize config, reuse the engine."""
    if not config:
        return None
    content = json.dumps(config, indent=0, sort_keys=True).encode()
    result = engine.scan("config.json", content.replace(b"\r", b""))
    return result if result.findings else None


def history_to_dockerfile(config: dict) -> bytes:
    """imgconf/dockerfile: rebuild Dockerfile lines from history entries."""
    lines = []
    for h in config.get("history") or []:
        created_by = h.get("created_by", "")
        if not created_by:
            continue
        # docker stores "/bin/sh -c #(nop)  CMD ..." or "/bin/sh -c cmd"
        if "#(nop)" in created_by:
            instruction = created_by.split("#(nop)", 1)[1].strip()
        elif created_by.startswith("/bin/sh -c"):
            instruction = "RUN " + created_by[len("/bin/sh -c") :].strip()
        else:
            instruction = created_by
        lines.append(instruction)
    return ("\n".join(lines) + "\n").encode()


def scan_config_misconfig(config: dict) -> Misconfiguration | None:
    if not config or not config.get("history"):
        return None
    dockerfile = history_to_dockerfile(config)
    mc = scan_dockerfile("Dockerfile", dockerfile)
    mc.file_type = "dockerfile"
    if not mc.failures:
        return None
    mc.successes = []  # history reconstruction is lossy; report failures only
    return mc
