from trivy_tpu.analyzer.core import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    AnalyzerGroup,
    AnalyzerOptions,
    BatchAnalyzer,
    register_analyzer,
    registered_analyzers,
)

__all__ = [
    "AnalysisInput",
    "AnalysisResult",
    "Analyzer",
    "AnalyzerGroup",
    "AnalyzerOptions",
    "BatchAnalyzer",
    "register_analyzer",
    "registered_analyzers",
]
