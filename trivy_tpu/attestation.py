"""In-toto attestation parsing + Rekor transparency-log client
(pkg/attestation, pkg/rekor).

Attestations arrive as DSSE envelopes: a base64 payload holding an
in-toto statement whose predicate can be an SBOM (CycloneDX/SPDX).  The
Rekor client looks up entries by artifact digest (the executable
analyzer's sha256 keys) and decodes any SBOM attestation found — the
reference's `unpackaged` post-handler flow: binaries with no package
owner resolve their package lists from signed build attestations.
"""

from __future__ import annotations

import base64
import copy
import http.client
import json
import logging
import urllib.request
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

DEFAULT_REKOR_URL = "https://rekor.sigstore.dev"


class AttestationError(ValueError):
    pass


@dataclass
class Statement:
    """in-toto statement (attestation/attestation.go)."""

    type: str
    predicate_type: str
    subjects: list[dict] = field(default_factory=list)  # {name, digest{}}
    predicate: object = None


def parse_envelope(doc: dict) -> Statement:
    """DSSE envelope -> in-toto statement.  The payload is base64; the
    payloadType must be in-toto JSON."""
    if doc.get("payloadType") not in (
        "application/vnd.in-toto+json",
        "application/vnd.dsse.envelope.v1+json",
    ):
        raise AttestationError(
            f"unsupported payloadType {doc.get('payloadType')!r}"
        )
    try:
        payload = json.loads(base64.b64decode(doc.get("payload", "")))
    except (ValueError, TypeError) as e:
        raise AttestationError(f"bad attestation payload: {e}") from e
    return Statement(
        type=payload.get("_type", ""),
        predicate_type=payload.get("predicateType", ""),
        subjects=list(payload.get("subject") or []),
        predicate=payload.get("predicate"),
    )


def sbom_from_statement(stmt: Statement):
    """Decode an SBOM predicate into an ArtifactDetail, or None for
    non-SBOM attestations."""
    pred = stmt.predicate
    if isinstance(pred, dict) and "Data" in pred:  # cosign predicate wrapper
        pred = pred["Data"]
    if isinstance(pred, str):
        try:
            pred = json.loads(pred)
        except ValueError:
            return None
    if not isinstance(pred, dict):
        return None
    if pred.get("bomFormat") == "CycloneDX":
        from trivy_tpu.sbom.cyclonedx import decode
    elif pred.get("spdxVersion"):
        from trivy_tpu.sbom.spdx import decode
    else:
        return None
    try:
        return decode(pred)
    except Exception:
        logger.warning("undecodable SBOM attestation", exc_info=True)
        return None


@dataclass
class RekorClient:
    """pkg/rekor client: digest -> entry UUIDs -> decoded entry bodies."""

    url: str = DEFAULT_REKOR_URL

    def _post(self, path: str, body: dict) -> object:
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            self.url.rstrip("/") + path,
            data=data,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    def _get(self, path: str) -> object:
        with urllib.request.urlopen(
            self.url.rstrip("/") + path, timeout=60
        ) as resp:
            return json.loads(resp.read())

    def search_by_digest(self, sha256_hex: str) -> list[str]:
        """POST /api/v1/index/retrieve {hash: sha256:<hex>} -> entry UUIDs."""
        out = self._post(
            "/api/v1/index/retrieve", {"hash": f"sha256:{sha256_hex}"}
        )
        return list(out) if isinstance(out, list) else []

    def get_attestation(self, uuid: str) -> Statement | None:
        """GET /api/v1/log/entries/<uuid>: the entry's attestation.data is
        base64 DSSE."""
        entry = self._get(f"/api/v1/log/entries/{uuid}")
        if not isinstance(entry, dict):
            return None
        for body in entry.values():
            if not isinstance(body, dict):
                continue
            att = body.get("attestation") or {}
            data = att.get("data")
            if not data:
                continue
            try:
                env = json.loads(base64.b64decode(data))
                return parse_envelope(env)
            except (ValueError, AttestationError):
                continue
        return None

    def sbom_for_digest(self, sha256_hex: str):
        """The unpackaged flow: first SBOM attestation for an artifact
        digest, decoded, or None."""
        # OSError covers URLError plus the read-phase failures urlopen's
        # timeout doesn't convert (TimeoutError, ConnectionResetError);
        # HTTPException covers truncated/garbled responses (IncompleteRead,
        # BadStatusLine) — one flaky response must degrade per digest, not
        # abort the handler.
        try:
            uuids = self.search_by_digest(sha256_hex)
        except (OSError, ValueError, http.client.HTTPException) as e:
            logger.warning("rekor lookup failed for %s: %s", sha256_hex, e)
            return None
        for uuid in uuids[:5]:
            try:
                stmt = self.get_attestation(uuid)
            except (OSError, ValueError, http.client.HTTPException):
                continue
            if stmt is None:
                continue
            detail = sbom_from_statement(stmt)
            if detail is not None:
                return detail
        return None


def rekor_unpackaged_handler(rekor_url: str):
    """Build the `unpackaged` post-handler (handler/unpackaged): executable
    digests with no owning package resolve package lists from Rekor SBOM
    attestations.  Register via trivy_tpu.handler.register_post_handler
    when --sbom-sources rekor is active."""
    client = RekorClient(rekor_url)
    # digest -> ArtifactDetail | None: the same binary recurring across
    # layers (or as copies in one tree) costs one network round trip, and a
    # no-attestation answer is remembered too.
    resolved: dict[str, object] = {}

    def handler(result) -> None:
        for rec in list(result.configs):
            if not isinstance(rec, dict) or rec.get("Type") != "executable":
                continue
            digest = rec.get("Digest", "").removeprefix("sha256:")
            if not digest:
                continue
            if digest not in resolved:
                resolved[digest] = client.sbom_for_digest(digest)
            if resolved[digest] is None:
                continue
            # Fresh copies per occurrence: the loop below sets file_path and
            # the result owns what it appends — the cached detail must stay
            # pristine for the next occurrence/layer.
            detail = copy.deepcopy(resolved[digest])
            for app in detail.applications:
                if not app.file_path:
                    app.file_path = rec.get("FilePath", "")
            result.applications.extend(detail.applications)
            result.package_infos.extend(detail.package_infos)
            if detail.packages:
                # OS packages (apk/deb/rpm purls) decode into the flat
                # packages list; blobs carry them as PackageInfo groups.
                from trivy_tpu.atypes import PackageInfo

                result.package_infos.append(
                    PackageInfo(
                        file_path=rec.get("FilePath", ""),
                        packages=list(detail.packages),
                    )
                )

    return handler
