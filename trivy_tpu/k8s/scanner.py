"""k8s scan fan-out (pkg/k8s/scanner + commands/cluster.go).

Per enumerated workload: the manifest runs through the rego kubernetes
checks; every container image it references scans through the image
pipeline (daemon/registry chain).  Per-resource failures are recorded on
the resource (resource.Error) instead of sinking the cluster scan —
unreachable registries and RBAC holes are normal in a live cluster.
Owned resources (pods of a deployment's replicaset etc.) are skipped when
their controller is also enumerated, matching the reference's dedup.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field

from trivy_tpu.k8s.report import RBAC_RESOURCE_KINDS, K8sReport, K8sResource

logger = logging.getLogger(__name__)


def _images_of(resource: dict) -> list[str]:
    spec = resource.get("spec") or {}
    pod = (
        spec.get("template", {}).get("spec")
        or spec.get("jobTemplate", {})
        .get("spec", {})
        .get("template", {})
        .get("spec")
        or (spec if "containers" in spec else {})
    )
    out = []
    for section in ("initContainers", "containers"):
        for c in pod.get(section) or []:
            img = c.get("image")
            if img:
                out.append(img)
    return out


_ENUMERATED_KINDS = {
    "Pod", "Deployment", "StatefulSet", "DaemonSet", "ReplicaSet", "Job",
    "CronJob",
}


def _owned(resource: dict) -> bool:
    """Skip only resources whose controller kind is itself enumerated —
    a pod owned by a CRD controller (Rollout, static-pod Node ref) has no
    covering row and must be scanned directly."""
    refs = (resource.get("metadata") or {}).get("ownerReferences") or []
    return any(
        r.get("controller") and r.get("kind") in _ENUMERATED_KINDS
        for r in refs
    )


@dataclass
class K8sScanner:
    scanners: list[str] = field(default_factory=lambda: ["misconfig"])
    insecure_registry: bool = False
    db_dir: str = ""
    _vuln_detector: object = field(default=None, repr=False)
    _vuln_ready: bool = field(default=False, repr=False)

    def scan(
        self, resources: list[dict], cluster_name: str = ""
    ) -> K8sReport:
        report = K8sReport(cluster_name=cluster_name)
        scanned_images: dict[str, list] = {}
        for resource in resources:
            if _owned(resource):
                continue  # controller-owned: the controller row covers it
            meta = resource.get("metadata") or {}
            res = K8sResource(
                namespace=meta.get("namespace", ""),
                kind=resource.get("kind", ""),
                name=meta.get("name", ""),
            )
            try:
                is_rbac = res.kind in RBAC_RESOURCE_KINDS
                if ("misconfig" in self.scanners) or (
                    is_rbac and "rbac" in self.scanners
                ):
                    res.results.extend(self._scan_manifest(resource))
                if not is_rbac and {"vuln", "secret"} & set(self.scanners):
                    for image in _images_of(resource):
                        res.results.extend(
                            self._scan_image(image, scanned_images)
                        )
            except Exception as e:  # per-resource tolerance
                logger.warning(
                    "k8s scan failed for %s/%s", res.kind, res.name,
                    exc_info=True,
                )
                res.error = str(e)
            report.resources.append(res)
        return report

    def _scan_manifest(self, resource: dict) -> list:
        from trivy_tpu.ftypes import Result, ResultClass
        from trivy_tpu.iac.engine import shared_scanner

        meta = resource.get("metadata") or {}
        name = f"{resource.get('kind')}/{meta.get('name', '')}"
        mc = shared_scanner().scan(
            f"{name}.json", json.dumps(resource).encode()
        )
        if mc is None or not (mc.failures or mc.successes):
            return []
        return [
            Result(
                target=name,
                result_class=ResultClass.CONFIG,
                result_type="kubernetes",
                misconfigurations=mc.failures,
            )
        ]

    def _scan_image(self, image: str, cache: dict[str, object]) -> list:
        if image in cache:
            hit = cache[image]
            if isinstance(hit, Exception):
                raise hit  # one timeout per unreachable image, not per resource
            return hit
        from trivy_tpu.artifact.image import ImageArtifact
        from trivy_tpu.cache.store import MemoryCache
        from trivy_tpu.commands.run import (
            Options,
            _analyzer_options,
            _init_vuln_scanner,
        )
        from trivy_tpu.image import resolve_image
        from trivy_tpu.scanner.service import LocalDriver, ScanOptions, Scanner

        try:
            source = resolve_image(
                image, insecure_registry=self.insecure_registry
            )
        except Exception as e:
            cache[image] = e
            raise
        mem = MemoryCache()
        options = Options(
            target=image,
            scanners=[s for s in self.scanners if s != "misconfig"],
            db_dir=self.db_dir,
            secret_backend="auto",  # the CLI-wide default (hybrid fallback)
        )
        if not self._vuln_ready:
            # One DB open per cluster scan, not per image.
            self._vuln_detector = _init_vuln_scanner(options)
            self._vuln_ready = True
        artifact = ImageArtifact(
            image, mem,
            analyzer_options=_analyzer_options(options, "image"),
            source=source,
        )
        driver = LocalDriver(mem, vuln_detector=self._vuln_detector)
        scanner = Scanner(artifact=artifact, driver=driver)
        report = scanner.scan_artifact(
            ScanOptions(scanners=list(options.scanners))
        )
        results = list(report.results)
        cache[image] = results
        return results
