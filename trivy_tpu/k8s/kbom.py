"""KBOM: the Kubernetes bill of materials.

pkg/k8s/scanner/scanner.go clusterInfoToReportResources analogue —
`k8s --format cyclonedx` emits a CycloneDX 1.5 BOM of the CLUSTER itself
rather than scan findings: the cluster root component, every node with its
OS / kubelet / container-runtime components, and the container images the
workloads run, wired together with dependency relationships.

Cluster facts come from the live API (/version, /api/v1/nodes) — the
reference's node-collector gathers the same fields from node status.
"""

from __future__ import annotations

import uuid
from typing import Any

from trivy_tpu.k8s.client import KubeClient
from trivy_tpu.k8s.scanner import _images_of, _owned


def _component(
    ctype: str, name: str, version: str = "", purl: str = "",
    properties: dict[str, str] | None = None,
) -> dict:
    ref = purl or f"{ctype}:{name}@{version or 'unknown'}"
    out: dict[str, Any] = {"bom-ref": ref, "type": ctype, "name": name}
    if version:
        out["version"] = version
    if purl:
        out["purl"] = purl
    if properties:
        out["properties"] = [
            {"name": f"trivy-tpu:resource:{k}", "value": v}
            for k, v in sorted(properties.items())
        ]
    return out


def _image_purl(image: str) -> tuple[str, str, str]:
    """(name, version, purl) for a container image reference."""
    base, _, digest = image.partition("@")
    name, _, tag = base.rpartition(":")
    if not name or "/" in tag:  # no tag present
        name, tag = base, ""
    repo = name.rsplit("/", 1)[-1]
    version = digest or tag
    purl = f"pkg:oci/{repo}"
    if version:
        purl += f"@{version.replace(':', '%3A')}"
    if "/" in name:
        purl += f"?repository_url={name}"
    return name, version, purl


def _split_os_image(os_image: str) -> tuple[str, str]:
    """('red hat enterprise linux', '8.6') from 'Red Hat Enterprise Linux
    8.6': the version starts at the first digit-led token, so multi-word
    distro names survive intact."""
    tokens = os_image.split()
    for i, tok in enumerate(tokens):
        if tok[:1].isdigit():
            return " ".join(tokens[:i]).lower(), " ".join(tokens[i:])
    return os_image.lower(), ""


def build_kbom(
    client: KubeClient, cluster_name: str = "", namespace: str = ""
) -> dict:
    """CycloneDX 1.5 JSON document describing the cluster (or one
    namespace's workloads).  API failures PROPAGATE as KubeConfigError —
    an expired token must not read as a healthy empty cluster (the same
    contract as KubeClient.list_workloads)."""
    ver = client.get("/version")
    k8s_version = ver.get("gitVersion", "")

    root = _component(
        "platform",
        cluster_name or "kubernetes-cluster",
        k8s_version,
        purl=f"pkg:k8s/kubernetes@{k8s_version}" if k8s_version else "",
    )
    # Components dedup by bom-ref: shared node software (same kubelet,
    # same OS image across the fleet) must appear ONCE — CycloneDX
    # requires unique bom-refs.
    by_ref: dict[str, dict] = {}

    def add(comp: dict) -> str:
        return by_ref.setdefault(comp["bom-ref"], comp)["bom-ref"]

    dependencies: list[dict] = [{"ref": root["bom-ref"], "dependsOn": []}]
    root_deps = dependencies[0]["dependsOn"]

    nodes = client.get("/api/v1/nodes").get("items") or []
    for node in nodes:
        meta = node.get("metadata") or {}
        info = (node.get("status") or {}).get("nodeInfo") or {}
        nname = meta.get("name", "node")
        node_comp = _component(
            "platform", nname,
            properties={
                "architecture": info.get("architecture", ""),
                "kernelVersion": info.get("kernelVersion", ""),
                "nodeRole": (
                    "master"
                    if {
                        "node-role.kubernetes.io/control-plane",
                        "node-role.kubernetes.io/master",  # legacy kubeadm
                    } & set(meta.get("labels") or {})
                    else "worker"
                ),
                "operatingSystem": info.get("operatingSystem", ""),
            },
        )
        root_deps.append(add(node_comp))
        node_deps: list[str] = []

        os_image = info.get("osImage", "")
        if os_image:
            os_name, os_ver = _split_os_image(os_image)
            node_deps.append(add(_component(
                "operating-system", os_name, os_ver
            )))
        kubelet = info.get("kubeletVersion", "")
        if kubelet:
            node_deps.append(add(_component(
                "application", "k8s.io/kubelet", kubelet,
                purl=f"pkg:k8s/kubelet@{kubelet}",
            )))
        runtime = info.get("containerRuntimeVersion", "")
        if runtime:
            rname, _, rver = runtime.partition("://")
            node_deps.append(add(_component(
                "application", rname, rver,
                purl=f"pkg:golang/{rname}@{rver}" if rver else "",
            )))
        dependencies.append(
            {"ref": node_comp["bom-ref"], "dependsOn": node_deps}
        )

    # Workload images (deduplicated; controller-owned pods are covered by
    # their controllers, mirroring the scan path's ownership rule).
    seen: set[str] = set()
    for resource in client.list_workloads(namespace=namespace):
        if _owned(resource):
            continue
        for image in _images_of(resource):
            if image in seen:
                continue
            seen.add(image)
            name, version, purl = _image_purl(image)
            root_deps.append(add(_component(
                "container", name, version, purl=purl
            )))

    return {
        "bomFormat": "CycloneDX",
        "specVersion": "1.5",
        "serialNumber": f"urn:uuid:{uuid.uuid4()}",
        "version": 1,
        "metadata": {"component": root},
        "components": list(by_ref.values()),
        "dependencies": dependencies,
    }
