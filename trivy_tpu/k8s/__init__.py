"""Kubernetes super-command (pkg/k8s).

Enumerates cluster resources through the Kubernetes API (kubeconfig auth),
fans out inner scans — misconfiguration checks over each workload manifest
and vulnerability/secret scans over every referenced container image — and
aggregates per-resource results into the k8s report (summary or all).
"""

from trivy_tpu.k8s.client import KubeClient, KubeConfigError, load_kubeconfig
from trivy_tpu.k8s.scanner import K8sScanner
from trivy_tpu.k8s.report import K8sReport, K8sResource, write_k8s_report

__all__ = [
    "KubeClient",
    "KubeConfigError",
    "load_kubeconfig",
    "K8sScanner",
    "K8sReport",
    "K8sResource",
    "write_k8s_report",
]
