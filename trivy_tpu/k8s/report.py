"""k8s report model + writers (pkg/k8s/report).

Per-resource results aggregate into the summary table (rows per resource,
finding counts bucketed by severity per scanner class) or the full report
(every inner Result, the reference's --report all)."""

from __future__ import annotations

import json
import sys
from dataclasses import dataclass, field

_SEV_ORDER = ("CRITICAL", "HIGH", "MEDIUM", "LOW", "UNKNOWN")


@dataclass
class K8sResource:
    namespace: str = ""
    kind: str = ""
    name: str = ""
    results: list = field(default_factory=list)
    error: str = ""

    def counts(self) -> dict[str, dict[str, int]]:
        """Per scanner class, severity -> count."""
        out: dict[str, dict[str, int]] = {}

        def bump(klass: str, severity: str) -> None:
            sev = severity if severity in _SEV_ORDER else "UNKNOWN"
            out.setdefault(klass, {})
            out[klass][sev] = out[klass].get(sev, 0) + 1

        for r in self.results:
            for v in getattr(r, "vulnerabilities", []) or []:
                bump("Vulnerabilities", v.severity)
            for m in getattr(r, "misconfigurations", []) or []:
                if getattr(m, "status", "FAIL") == "FAIL":
                    bump("Misconfigurations", m.severity)
            for s in getattr(r, "secrets", []) or []:
                bump("Secrets", s.severity)
        return out

    def to_json(self, full: bool) -> dict:
        out: dict = {
            "Namespace": self.namespace,
            "Kind": self.kind,
            "Name": self.name,
        }
        if self.error:
            out["Error"] = self.error
        if full:
            out["Results"] = [r.to_json() for r in self.results]
        else:
            out["Summary"] = self.counts()
        return out


RBAC_RESOURCE_KINDS = frozenset(
    {"Role", "RoleBinding", "ClusterRole", "ClusterRoleBinding"}
)


def rbac_resource(res: "K8sResource") -> bool:
    """The reference's rbacResource split (pkg/k8s/report/report.go:201):
    RBAC kinds report under a separate 'RBAC Assessment' section."""
    return res.kind in RBAC_RESOURCE_KINDS


@dataclass
class K8sReport:
    cluster_name: str = ""
    resources: list[K8sResource] = field(default_factory=list)

    def to_json(self, full: bool = False) -> dict:
        out = {
            "SchemaVersion": 2,
            "ClusterName": self.cluster_name,
            "Resources": [
                r.to_json(full)
                for r in self.resources
                if not rbac_resource(r)
            ],
        }
        rbac = [r.to_json(full) for r in self.resources if rbac_resource(r)]
        if rbac:
            out["RBACAssessment"] = rbac
        return out


def write_k8s_report(
    report: K8sReport, fmt: str = "table", full: bool = False, out=None
) -> None:
    out = out or sys.stdout
    if fmt == "json":
        json.dump(report.to_json(full), out, indent=2)
        out.write("\n")
        return
    out.write(f"\nCluster: {report.cluster_name or '(unnamed)'}\n")

    def write_rows(resources, title):
        if not resources:
            return
        out.write(f"\n{title}\n")
        header = (
            f"{'Namespace':12} {'Kind':12} {'Name':28} "
            f"{'Vuln C/H/M/L':14} {'Misconf C/H/M/L':16} {'Secrets':8}\n"
        )
        out.write(header)
        out.write("-" * len(header) + "\n")
        for res in resources:
            counts = res.counts()

            def fmt4(klass: str) -> str:
                c = counts.get(klass, {})
                return "/".join(
                    str(c.get(s, 0))
                    for s in ("CRITICAL", "HIGH", "MEDIUM", "LOW")
                )

            secrets = sum(counts.get("Secrets", {}).values())
            out.write(
                f"{res.namespace:12} {res.kind:12} {res.name:28} "
                f"{fmt4('Vulnerabilities'):14} {fmt4('Misconfigurations'):16} "
                f"{secrets:<8}\n"
            )
            if res.error:
                out.write(f"    error: {res.error}\n")

    write_rows(
        [r for r in report.resources if not rbac_resource(r)],
        "Workload Assessment",
    )
    write_rows(
        [r for r in report.resources if rbac_resource(r)],
        "RBAC Assessment",
    )
