"""Kubernetes API client: kubeconfig parsing + resource enumeration.

The reference rides the trivy-kubernetes library; this client speaks the
API directly with stdlib HTTP: kubeconfig contexts resolve to (server,
auth) where auth is a bearer token, basic credentials, or client
certificates (an mTLS ssl context).  Enumerated kinds mirror the
reference's artifact list: workloads always, RBAC resources
(Role/RoleBinding/ClusterRole/ClusterRoleBinding) when the rbac scanner
is on or ``--include-kinds`` names them (pkg/k8s/commands/cluster.go).

Divergence: the reference's node-collector job (a privileged pod it
schedules to collect kubelet/node file facts) is NOT implemented — this
build never mutates the cluster; node inventory comes read-only from the
KBOM path (k8s/kbom.py nodeInfo).
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import urllib.error
import urllib.request
from dataclasses import dataclass, field

import yaml

WORKLOAD_KINDS = (
    # (kind, api path, namespaced collection name)
    ("Pod", "/api/v1", "pods"),
    ("Deployment", "/apis/apps/v1", "deployments"),
    ("StatefulSet", "/apis/apps/v1", "statefulsets"),
    ("DaemonSet", "/apis/apps/v1", "daemonsets"),
    ("ReplicaSet", "/apis/apps/v1", "replicasets"),
    ("Job", "/apis/batch/v1", "jobs"),
    ("CronJob", "/apis/batch/v1", "cronjobs"),
)

_RBAC_API = "/apis/rbac.authorization.k8s.io/v1"
RBAC_KINDS = (
    # (kind, api path, collection); ClusterRole(Binding) are cluster-scoped
    ("Role", _RBAC_API, "roles"),
    ("RoleBinding", _RBAC_API, "rolebindings"),
    ("ClusterRole", _RBAC_API, "clusterroles"),
    ("ClusterRoleBinding", _RBAC_API, "clusterrolebindings"),
)
_CLUSTER_SCOPED = {"ClusterRole", "ClusterRoleBinding"}


def select_kinds(
    include_kinds: list[str] | None, rbac: bool, workloads: bool = True
):
    """Resolve the enumerated kind tuples from ``--include-kinds`` (kind
    names, case-insensitive).  Empty: workload kinds when any workload
    scanner is active, RBAC kinds when the rbac scanner is on — an
    rbac-only scan must not list every pod in a large cluster just to
    print guaranteed-empty rows."""
    universe = WORKLOAD_KINDS + RBAC_KINDS
    if include_kinds:
        wanted = {k.strip().lower() for k in include_kinds if k.strip()}
        unknown = wanted - {k.lower() for k, _a, _c in universe}
        if unknown:
            raise KubeConfigError(
                f"--include-kinds: unknown kinds {sorted(unknown)}"
            )
        return tuple(t for t in universe if t[0].lower() in wanted)
    out: tuple = ()
    if workloads:
        out += WORKLOAD_KINDS
    if rbac:
        out += RBAC_KINDS
    return out


class KubeConfigError(RuntimeError):
    def __init__(self, msg: str, status: int = 0):
        super().__init__(msg)
        self.status = status


@dataclass
class KubeAuth:
    server: str
    token: str = ""
    username: str = ""
    password: str = ""
    client_cert_data: bytes = b""
    client_key_data: bytes = b""
    ca_data: bytes = b""
    insecure: bool = False


def _b64field(d: dict, key: str) -> bytes:
    v = d.get(key, "")
    return base64.b64decode(v) if v else b""


def load_kubeconfig(path: str = "", context: str = "") -> KubeAuth:
    """Resolve (server, auth) from a kubeconfig (KUBECONFIG or
    ~/.kube/config by default), honoring the selected/current context."""
    path = (
        path
        or os.environ.get("KUBECONFIG", "")
        or os.path.expanduser("~/.kube/config")
    )
    try:
        with open(path, encoding="utf-8") as f:
            doc = yaml.safe_load(f) or {}
    except (OSError, yaml.YAMLError) as e:
        raise KubeConfigError(f"cannot load kubeconfig {path}: {e}") from e

    ctx_name = context or doc.get("current-context", "")
    try:
        contexts = {
            c["name"]: c.get("context") or {}
            for c in doc.get("contexts") or []
        }
        clusters = {
            c["name"]: c.get("cluster") or {}
            for c in doc.get("clusters") or []
        }
        users = {u["name"]: u.get("user") or {} for u in doc.get("users") or []}
    except (KeyError, TypeError) as e:
        raise KubeConfigError(f"malformed kubeconfig {path}: {e}") from e
    if ctx_name not in contexts:
        raise KubeConfigError(f"kubeconfig context {ctx_name!r} not found")
    ctx = contexts[ctx_name]
    cluster = clusters.get(ctx.get("cluster", ""))
    if cluster is None:
        raise KubeConfigError(f"cluster {ctx.get('cluster')!r} not found")
    user = users.get(ctx.get("user", ""), {})

    token = user.get("token", "")
    token_file = user.get("tokenFile", "")
    if not token and token_file:
        try:
            with open(token_file, encoding="utf-8") as f:
                token = f.read().strip()
        except OSError:
            pass
    return KubeAuth(
        server=cluster.get("server", "").rstrip("/"),
        token=token,
        username=user.get("username", ""),
        password=user.get("password", ""),
        client_cert_data=_b64field(user, "client-certificate-data"),
        client_key_data=_b64field(user, "client-key-data"),
        ca_data=_b64field(cluster, "certificate-authority-data"),
        insecure=bool(cluster.get("insecure-skip-tls-verify")),
    )


@dataclass
class KubeClient:
    auth: KubeAuth
    _ctx: ssl.SSLContext | None = field(default=None, repr=False)

    def _ssl_context(self) -> ssl.SSLContext | None:
        if not self.auth.server.startswith("https"):
            return None
        if self._ctx is None:
            ctx = ssl.create_default_context()
            if self.auth.insecure:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            elif self.auth.ca_data:
                ctx.load_verify_locations(
                    cadata=self.auth.ca_data.decode("utf-8", "replace")
                )
            if self.auth.client_cert_data and self.auth.client_key_data:
                # ssl wants files; write key material to a private tempdir
                d = tempfile.mkdtemp(prefix="trivy-tpu-kube-")
                cert = os.path.join(d, "cert.pem")
                key = os.path.join(d, "key.pem")
                try:
                    with open(cert, "wb") as f:
                        f.write(self.auth.client_cert_data)
                    with open(key, "wb") as f:
                        f.write(self.auth.client_key_data)
                    os.chmod(key, 0o600)
                    ctx.load_cert_chain(cert, key)
                finally:
                    # The context holds the loaded chain; the private key
                    # must not linger on disk.
                    import shutil

                    shutil.rmtree(d, ignore_errors=True)
            self._ctx = ctx
        return self._ctx

    def get(self, path: str) -> dict:
        url = self.auth.server + path
        headers = {"Accept": "application/json"}
        if self.auth.token:
            headers["Authorization"] = f"Bearer {self.auth.token}"
        elif self.auth.username:
            cred = base64.b64encode(
                f"{self.auth.username}:{self.auth.password}".encode()
            ).decode()
            headers["Authorization"] = f"Basic {cred}"
        req = urllib.request.Request(url, headers=headers)
        try:
            with urllib.request.urlopen(
                req, timeout=60, context=self._ssl_context()
            ) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise KubeConfigError(f"GET {path}: HTTP {e.code}", e.code) from e
        except (urllib.error.URLError, ValueError) as e:
            raise KubeConfigError(f"GET {path}: {e}") from e

    def list_workloads(
        self, namespace: str = "", kinds: tuple = WORKLOAD_KINDS
    ) -> list[dict]:
        """All resources of `kinds` (cluster-wide or one namespace), each
        a full resource dict with kind/metadata/spec.  Cluster-scoped
        kinds (ClusterRole/ClusterRoleBinding) always enumerate at the
        cluster level — a namespace filter cannot apply to them."""
        out: list[dict] = []
        for kind, api, collection in kinds:
            if namespace and kind not in _CLUSTER_SCOPED:
                path = f"{api}/namespaces/{namespace}/{collection}"
            else:
                path = f"{api}/{collection}"
            try:
                doc = self.get(path)
            except KubeConfigError as e:
                if e.status == 404:
                    continue  # API group absent (minimal clusters)
                # Auth/network failures must not read as an empty cluster.
                raise
            for item in doc.get("items") or []:
                item.setdefault("kind", kind)
                item.setdefault(
                    "apiVersion", api.removeprefix("/apis/").removeprefix("/api/")
                )
                out.append(item)
        return out
