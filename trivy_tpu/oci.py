"""Generic OCI-artifact downloader (pkg/oci/artifact.go:60,103 analogue).

Databases, check bundles, and the Java index are distributed as OCI
artifacts: an image manifest whose layers carry artifact-specific media
types.  This module pulls such an artifact's matching layer blob through
the same Distribution client the image sources use (trivy_tpu/image/
registry.py) — one auth/transport stack for images and artifacts alike.
"""

from __future__ import annotations

from trivy_tpu.image.registry import RegistryClient, RegistryError, parse_reference

__all__ = ["OciArtifact", "RegistryError"]


class OciArtifact:
    """One remote OCI artifact (e.g. ghcr.io/aquasecurity/trivy-db:2)."""

    def __init__(self, ref: str, insecure: bool = False):
        self.ref = ref
        self.client = RegistryClient(insecure=insecure)

    def download_layer(self, media_type: str):
        """Fetch the first layer whose mediaType matches; returns an open
        spooled temp file (caller closes).  pkg/oci/artifact.go:103 Download
        with its media-type filter."""
        ref = parse_reference(self.ref)
        manifest, _ = self.client.get_manifest(ref)
        for layer in manifest.get("layers", []):
            if layer.get("mediaType") == media_type:
                return self.client.get_blob(ref, layer["digest"])
        raise RegistryError(
            f"oci: no layer with media type {media_type!r} in {self.ref}"
        )
