"""Watch-plane configuration: sources, stream sinks, poll cadence.

One YAML document (``--watch-config`` / ``trivy-tpu watch --config``)
declares everything the continuous-scanning plane needs:

    watch:
      poll_interval_s: 30
      sources:
        - type: registry           # tag-list poller (image/registry.py)
          reference: localhost:5000/team/app
          insecure: true
        - type: feed               # JSONL event feed (file path or URL)
          path: /var/run/registry-events.jsonl
      stream:
        jsonl: /var/log/trivy-tpu/verdict-deltas.jsonl
        webhook: http://alerts.internal:9000/hooks/trivy
        webhook_queue: 256
        webhook_attempts: 5
      content_store_mb: 64

The ``watch:`` nesting is optional (mirroring fleet config: the same
file can carry both planes).  Validation is all-up-front with typed
errors — a watch daemon that silently polls nothing is worse than one
that refuses to start.
"""

from __future__ import annotations

from dataclasses import dataclass, field

DEFAULT_POLL_INTERVAL_S = 30.0
DEFAULT_WEBHOOK_QUEUE = 256
DEFAULT_WEBHOOK_ATTEMPTS = 5
DEFAULT_CONTENT_STORE_MB = 64

SOURCE_KINDS = ("registry", "feed")


class WatchConfigError(ValueError):
    pass


@dataclass(frozen=True)
class SourceConfig:
    """One event source: a registry repository to poll tags on, or a
    JSONL change feed to tail (local file or HTTP URL)."""

    kind: str  # "registry" | "feed"
    reference: str = ""  # registry kind: repo reference (host/repo[:tag])
    path: str = ""  # feed kind: file path or http(s):// URL
    insecure: bool = False  # registry kind: plain-http registry

    @property
    def label(self) -> str:
        return self.reference or self.path


@dataclass(frozen=True)
class StreamConfig:
    """Where verdict deltas go: an ordered JSONL sink and/or an
    at-least-once webhook endpoint."""

    jsonl_path: str = ""
    webhook_url: str = ""
    webhook_queue: int = DEFAULT_WEBHOOK_QUEUE
    webhook_attempts: int = DEFAULT_WEBHOOK_ATTEMPTS


@dataclass(frozen=True)
class WatchConfig:
    sources: tuple[SourceConfig, ...] = ()
    stream: StreamConfig = field(default_factory=StreamConfig)
    poll_interval_s: float = DEFAULT_POLL_INTERVAL_S
    programs: tuple[str, ...] = ("secret",)
    content_store_mb: int = DEFAULT_CONTENT_STORE_MB


def parse_watch_config(doc: dict) -> WatchConfig:
    """Validate one parsed watch YAML document (top-level or nested
    under a `watch:` key)."""
    if not isinstance(doc, dict):
        raise WatchConfigError("watch config must be a mapping")
    if isinstance(doc.get("watch"), dict):
        doc = doc["watch"]
    raw_sources = doc.get("sources")
    if not isinstance(raw_sources, list) or not raw_sources:
        raise WatchConfigError("watch config needs a non-empty sources list")
    sources: list[SourceConfig] = []
    for i, entry in enumerate(raw_sources):
        if not isinstance(entry, dict):
            raise WatchConfigError(f"sources[{i}] must be a mapping")
        kind = str(entry.get("type") or entry.get("kind") or "")
        if kind not in SOURCE_KINDS:
            raise WatchConfigError(
                f"sources[{i}].type must be one of {', '.join(SOURCE_KINDS)}"
            )
        reference = str(entry.get("reference") or "")
        path = str(entry.get("path") or entry.get("url") or "")
        if kind == "registry" and not reference:
            raise WatchConfigError(f"sources[{i}] (registry) needs reference")
        if kind == "feed" and not path:
            raise WatchConfigError(f"sources[{i}] (feed) needs path or url")
        sources.append(
            SourceConfig(
                kind=kind,
                reference=reference,
                path=path,
                insecure=bool(entry.get("insecure", False)),
            )
        )
    raw_stream = doc.get("stream") or {}
    if not isinstance(raw_stream, dict):
        raise WatchConfigError("watch stream must be a mapping")
    try:
        stream = StreamConfig(
            jsonl_path=str(
                raw_stream.get("jsonl") or raw_stream.get("jsonl_path") or ""
            ),
            webhook_url=str(
                raw_stream.get("webhook")
                or raw_stream.get("webhook_url")
                or ""
            ),
            webhook_queue=int(
                raw_stream.get("webhook_queue", DEFAULT_WEBHOOK_QUEUE)
            ),
            webhook_attempts=int(
                raw_stream.get("webhook_attempts", DEFAULT_WEBHOOK_ATTEMPTS)
            ),
        )
    except (TypeError, ValueError):
        raise WatchConfigError(
            "stream webhook_queue/webhook_attempts must be integers"
        ) from None
    if stream.webhook_queue < 1 or stream.webhook_attempts < 1:
        raise WatchConfigError(
            "stream webhook_queue/webhook_attempts must be >= 1"
        )
    try:
        interval = float(
            doc.get("poll_interval_s", DEFAULT_POLL_INTERVAL_S)
        )
    except (TypeError, ValueError):
        raise WatchConfigError("poll_interval_s must be a number") from None
    if interval <= 0:
        raise WatchConfigError("poll_interval_s must be > 0")
    programs = tuple(
        str(p) for p in (doc.get("programs") or ["secret"])
    )
    if not programs:
        raise WatchConfigError("programs must be a non-empty list")
    try:
        store_mb = int(doc.get("content_store_mb", DEFAULT_CONTENT_STORE_MB))
    except (TypeError, ValueError):
        raise WatchConfigError("content_store_mb must be an integer") from None
    if store_mb < 1:
        raise WatchConfigError("content_store_mb must be >= 1")
    return WatchConfig(
        sources=tuple(sources),
        stream=stream,
        poll_interval_s=interval,
        programs=programs,
        content_store_mb=store_mb,
    )


def load_watch_config(path: str) -> WatchConfig:
    """Read and validate a watch YAML file (--watch-config)."""
    import yaml

    with open(path, encoding="utf-8") as f:
        doc = yaml.safe_load(f)
    return parse_watch_config(doc or {})
