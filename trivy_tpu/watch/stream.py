"""Verdict-delta stream: ordered JSONL sink + at-least-once webhook.

The watch plane's output is not verdicts but *changes to verdicts*:
per (image, blob), which findings appeared, disappeared, or changed
since the last verdict for that blob.  Event shape (one JSON object
per line / per webhook POST):

    {"seq": 7, "ts": 1754460000.1, "image": "team/app:latest",
     "blob": "sha256:…", "ruleset_digest": "sha256:…",
     "added": [finding…], "removed": [finding…], "changed": [finding…]}

Ordering: `seq` is assigned and the JSONL line written under one lock,
so the file's line order IS the sequence order — a consumer that tails
the file replays history exactly.

Delivery: the webhook emitter is a bounded FIFO drained by a single
worker thread (one worker = published order is POST order).  Each POST
rides RpcClient.call, inheriting the full rpc/client.py discipline —
jittered exponential backoff, Retry-After floors, the process-wide
retry budget, and the ``rpc.recv`` chaos seam — plus an outer per-event
attempt budget with its own backoff.  An event is only dropped after
that outer budget exhausts (counted + flight-captured); anything less
than total endpoint death delivers at least once, possibly more (the
endpoint must dedupe on `seq`).
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.parse

from trivy_tpu import lockcheck
from trivy_tpu.ftypes import Secret, SecretFinding

DEFAULT_QUEUE_MAX = 256
DEFAULT_ATTEMPTS = 5
EMIT_BACKOFF_BASE_S = 0.1
EMIT_BACKOFF_CAP_S = 5.0


def _finding_key(f: SecretFinding) -> tuple:
    """Identity of a finding inside one blob: same rule at the same
    location is the "same" finding (its content may still change)."""
    return (f.rule_id, f.start_line, f.end_line)


def diff_findings(
    old: Secret | None, new: Secret | None
) -> tuple[list[dict], list[dict], list[dict]]:
    """(added, removed, changed) finding JSON between two verdicts for
    one blob.  `changed` = same (rule, span) identity, different body
    (e.g. the matched text moved under a rules update)."""
    old_map = {
        _finding_key(f): f for f in (old.findings if old else [])
    }
    new_map = {
        _finding_key(f): f for f in (new.findings if new else [])
    }
    added = [
        f.to_json() for k, f in new_map.items() if k not in old_map
    ]
    removed = [
        f.to_json() for k, f in old_map.items() if k not in new_map
    ]
    changed = [
        f.to_json()
        for k, f in new_map.items()
        if k in old_map and f.to_json() != old_map[k].to_json()
    ]
    return added, removed, changed


class WebhookEmitter:
    """At-least-once delivery of delta events to one HTTP endpoint."""

    sleep = staticmethod(time.sleep)  # test seam (mirrors RpcClient)

    def __init__(
        self,
        url: str,
        queue_max: int = DEFAULT_QUEUE_MAX,
        attempts: int = DEFAULT_ATTEMPTS,
        client=None,
        flight=None,
    ):
        from trivy_tpu.rpc.client import RpcClient

        parts = urllib.parse.urlsplit(
            url if "://" in url else f"http://{url}"
        )
        self.path = parts.path or "/"
        self.url = url
        self.client = client or RpcClient(
            f"{parts.scheme}://{parts.netloc}", timeout_s=30.0
        )
        self.attempts = max(1, int(attempts))
        self.flight = flight
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, queue_max))
        self._lock = lockcheck.make_lock("watch.webhook")
        # All owner: _lock.
        self.enqueued = 0
        self.delivered = 0
        self.retried = 0
        self.dropped_full = 0
        self.dropped_failed = 0
        self._worker = threading.Thread(
            target=self._drain_loop, name="watch-webhook", daemon=True
        )
        self._worker.start()

    def emit(self, event: dict) -> bool:
        """Queue one event; False = queue full (counted, captured)."""
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            with self._lock:
                self.dropped_full += 1
            self._capture(event, "watch-emit-queue-full")
            return False
        with self._lock:
            self.enqueued += 1
        return True

    def _drain_loop(self) -> None:
        while True:
            event = self._queue.get()
            if event is None:  # close() sentinel
                self._queue.task_done()
                return
            try:
                self._deliver(event)
            finally:
                self._queue.task_done()

    def _deliver(self, event: dict) -> None:
        """One event, at-least-once: the event is not surrendered until
        a POST succeeds or the outer attempt budget exhausts.  Each
        attempt is itself a full RpcClient.call retry loop, so injected
        rpc.recv resets/truncations are absorbed two layers deep."""
        last = ""
        for attempt in range(self.attempts):
            try:
                self.client.call(self.path, event)
                with self._lock:
                    self.delivered += 1
                return
            except Exception as e:
                last = f"{type(e).__name__}: {e}"
                with self._lock:
                    self.retried += 1
            if attempt + 1 < self.attempts:
                self.sleep(
                    min(
                        EMIT_BACKOFF_CAP_S,
                        EMIT_BACKOFF_BASE_S * (2**attempt),
                    )
                )
        with self._lock:
            self.dropped_failed += 1
        self._capture(event, f"watch-emit-failed: {last}")

    def _capture(self, event: dict, reason: str) -> None:
        if self.flight is None:
            return
        self.flight.capture(
            method="watch.emit",
            reason=reason[:200],
            trace_id=f"watch-seq-{event.get('seq', '?')}",
        )

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued event resolved (delivered or
        dropped); False on timeout."""
        deadline = time.monotonic() + timeout_s
        while self._queue.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.01)
        return not self._queue.unfinished_tasks

    def close(self) -> None:
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "url": self.url,
                "queued": self._queue.qsize(),
                "enqueued": self.enqueued,
                "delivered": self.delivered,
                "retried": self.retried,
                "dropped_full": self.dropped_full,
                "dropped_failed": self.dropped_failed,
            }


class VerdictDeltaStream:
    """Delta computation + fan-out to the JSONL sink and the webhook.

    Per-blob previous verdicts live in a bounded map keyed by blob
    digest (content-addressed: the same blob under two images has one
    verdict history, which is also what the result cache says)."""

    def __init__(
        self,
        jsonl_path: str = "",
        emitter: WebhookEmitter | None = None,
        max_tracked_blobs: int = 4096,
        clock=time.time,
    ):
        self.jsonl_path = jsonl_path
        self.emitter = emitter
        self.max_tracked_blobs = max_tracked_blobs
        self._clock = clock
        self._lock = lockcheck.make_lock("watch.stream")
        # All owner: _lock.
        self._seq = 0
        self._prev: dict[str, Secret] = {}  # blob digest -> last verdict
        self.published = 0
        self.unchanged = 0
        self.jsonl_lines = 0

    def publish(
        self,
        image: str,
        blob_digest: str,
        new: Secret,
        ruleset_digest: str = "",
        old: Secret | None = None,
    ) -> dict | None:
        """Compute and ship the delta for one fresh verdict.  `old`
        overrides the tracked history (the sweeper passes the verdict
        it read under the OLD ruleset digest); None falls back to what
        this stream last saw for the blob.  Returns the event, or None
        when nothing changed (no event is emitted — an unchanged
        verdict is the steady state, not news)."""
        with self._lock:
            base = old if old is not None else self._prev.get(blob_digest)
            added, removed, changed = diff_findings(base, new)
            if base is not None and not (added or removed or changed):
                self.unchanged += 1
                self._remember(blob_digest, new)
                return None
            self._seq += 1
            event = {
                "seq": self._seq,
                "ts": round(self._clock(), 3),
                "image": image,
                "blob": blob_digest,
                "ruleset_digest": ruleset_digest,
                "added": added,
                "removed": removed,
                "changed": changed,
            }
            self._remember(blob_digest, new)
            self.published += 1
            # JSONL write under the seq lock: line order == seq order.
            if self.jsonl_path:
                with open(self.jsonl_path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(event, sort_keys=True) + "\n")
                self.jsonl_lines += 1
        if self.emitter is not None:
            self.emitter.emit(event)
        return event

    def _remember(self, blob_digest: str, verdict: Secret) -> None:  # graftlint: holds(_lock)
        if (
            blob_digest not in self._prev
            and len(self._prev) >= self.max_tracked_blobs
        ):
            # Bounded: drop the oldest-inserted entry.  Losing history
            # for a blob only means its next verdict reports everything
            # as "added" — safe, and strictly bounded memory.
            self._prev.pop(next(iter(self._prev)))
        self._prev[blob_digest] = verdict

    def flush(self, timeout_s: float = 10.0) -> bool:
        if self.emitter is not None:
            return self.emitter.flush(timeout_s)
        return True

    def close(self) -> None:
        if self.emitter is not None:
            self.emitter.close()

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "seq": self._seq,
                "published": self.published,
                "unchanged": self.unchanged,
                "jsonl_path": self.jsonl_path,
                "jsonl_lines": self.jsonl_lines,
                "tracked_blobs": len(self._prev),
            }
        if self.emitter is not None:
            snap["webhook"] = self.emitter.snapshot()
        return snap
