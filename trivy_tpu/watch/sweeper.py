"""Re-verification sweeper: a ruleset push invalidates exactly its own
cached verdicts, and the sweeper re-earns them.

When `rules push` / SIGHUP changes the active ruleset digest from OLD
to NEW, every cached verdict keyed under OLD is stale — and *only*
those.  The sweeper walks the result cache's per-(ruleset digest,
program id) reverse index (cache/results.py `indexed_blobs`), so the
candidate set is precisely the invalidated entries: verdicts under
other digests (other tenants' pinned rulesets, other programs) are
never touched, which is what `sweep_touched_ratio < 1` on a mixed
corpus measures.

Per candidate blob:
- already re-verdicted under NEW (a scan raced the sweep) -> skip,
  drop the OLD entry;
- bytes present in the content store -> re-scan under NEW, store the
  verdict (byte-identical to a cold scan of the same bytes — same
  engine, same stable blob-digest path), publish the OLD->NEW delta,
  drop the OLD entry;
- bytes evicted from the content store -> count as missing-content and
  drop the OLD entry anyway (a later change event will re-scan it as
  novel; keeping a stale verdict would be worse).

Failures are absorbed per blob (counted + flight-captured with reason
"watch-sweep"), never fatal — one unscannable blob must not leave the
rest of the corpus stale.
"""

from __future__ import annotations

import time

from trivy_tpu import lockcheck


class ReverifySweeper:
    def __init__(
        self,
        result_cache,
        scan_fn,
        content_store,
        programs: tuple[str, ...] = ("secret",),
        on_verdict=None,
        flight=None,
    ):
        self.result_cache = result_cache
        # scan_fn(items, ruleset_digest): re-verdicts must run under the
        # NEW ruleset, not whatever lane is default — on a server this
        # routes through the scheduler's per-digest lanes.
        self.scan_fn = scan_fn
        self.content_store = content_store
        self.programs = tuple(programs) or ("secret",)
        # on_verdict(blob_digest, old_verdict, new_verdict): stream seam.
        self.on_verdict = on_verdict
        self.flight = flight
        self._lock = lockcheck.make_lock("watch.sweeper")
        self.sweeps_total = 0  # owner: _lock
        self._progress: dict = {"state": "idle"}  # owner: _lock

    def sweep(self, old_digest: str, new_digest: str) -> dict:
        """Re-verify everything OLD invalidated; returns the summary
        (also retained as `progress()` for /debug/watch)."""
        if not old_digest or not new_digest or old_digest == new_digest:
            return {"state": "skipped", "old": old_digest,
                    "new": new_digest, "total": 0, "touched": 0}
        t0 = time.perf_counter()
        prog = {
            "state": "running",
            "old": old_digest,
            "new": new_digest,
            "started_ts": round(time.time(), 3),
            "total": 0,
            "touched": 0,
            "skipped_current": 0,
            "missing_content": 0,
            "failures": 0,
        }
        with self._lock:
            self.sweeps_total += 1
            self._progress = prog
        for pid in self.programs:
            candidates = self.result_cache.indexed_blobs(old_digest, pid)
            prog["total"] += len(candidates)
            for blob_digest in candidates:
                try:
                    self._reverify(blob_digest, old_digest, new_digest,
                                   pid, prog)
                except Exception as e:
                    prog["failures"] += 1
                    self._capture(blob_digest, e)
        prog["state"] = "done"
        prog["elapsed_s"] = round(time.perf_counter() - t0, 3)
        prog["touched_ratio"] = (
            prog["touched"] / prog["total"] if prog["total"] else 0.0
        )
        return dict(prog)

    def _reverify(
        self,
        blob_digest: str,
        old_digest: str,
        new_digest: str,
        pid: str,
        prog: dict,
    ) -> None:
        if self.result_cache.exists(blob_digest, new_digest, pid):
            prog["skipped_current"] += 1
            self.result_cache.remove(blob_digest, old_digest, pid)
            return
        data = self.content_store.get(blob_digest)
        if data is None:
            prog["missing_content"] += 1
            self.result_cache.remove(blob_digest, old_digest, pid)
            return
        old_verdict = self.result_cache.get(
            blob_digest, old_digest, path=blob_digest, program_id=pid
        )
        new_verdict = self.scan_fn([(blob_digest, data)], new_digest)[0]
        self.result_cache.put(
            blob_digest, new_digest, new_verdict, program_id=pid
        )
        prog["touched"] += 1
        if self.on_verdict is not None:
            self.on_verdict(blob_digest, old_verdict, new_verdict)
        self.result_cache.remove(blob_digest, old_digest, pid)

    def _capture(self, blob_digest: str, e: Exception) -> None:
        if self.flight is None:
            return
        self.flight.capture(
            method="watch.sweep",
            reason=f"watch-sweep: {type(e).__name__}: {e}"[:200],
            trace_id=f"watch-{blob_digest[:24]}",
        )

    def progress(self) -> dict:
        with self._lock:
            return dict(self._progress)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "sweeps_total": self.sweeps_total,
                "progress": dict(self._progress),
            }
