"""Event sources: where the watch plane learns that an image changed.

A source's whole job is to answer ``poll()`` with the ``(repo, tag,
digest)`` change records since its last call.  Two implementations:

- :class:`RegistryTagPoller` — lists a repository's tags over the
  Distribution API (image/registry.py transport, so auth/token flows
  and plain-http test registries come for free) and resolves each tag
  to its current manifest digest;
- :class:`FeedTailer` — tails a JSONL event feed (a local file fed by
  a registry's notification webhook, or an HTTP endpoint serving the
  same lines), one ``{"repo":…, "tag":…, "digest":…}`` object per line.

Dedupe lives in the shared base: a record is emitted only when the
digest for its (repo, tag) differs from the last one this source saw,
so an unchanged tag list costs zero downstream work and a re-push under
the same tag (new digest) surfaces exactly once.

Every poll crosses the ``watch.poll`` fault seam before any I/O.  A
poll that faults (injected or real) emits nothing AND updates nothing:
the last-seen map only advances on success, so the change is simply
picked up by the next healthy poll — the at-least-once half of the
delta pipeline starts here.
"""

from __future__ import annotations

import json
import time
import urllib.request
from dataclasses import dataclass

from trivy_tpu import faults


@dataclass(frozen=True)
class ChangeRecord:
    """One observed image change: `repo:tag` now points at `digest`."""

    repo: str
    tag: str
    digest: str
    source: str = ""

    @property
    def image(self) -> str:
        return f"{self.repo}:{self.tag}"


class EventSource:
    """Base source: dedupe + stats; subclasses implement `_poll_raw`."""

    kind = "base"

    def __init__(self, name: str):
        self.name = name
        self._last_seen: dict[tuple[str, str], str] = {}
        self.polls = 0
        self.errors = 0
        self.emitted = 0
        self.deduped = 0
        self.last_poll_ts = 0.0
        self.last_error = ""

    def _poll_raw(self) -> list[tuple[str, str, str]]:
        raise NotImplementedError

    def poll(self) -> list[ChangeRecord]:
        """Change records since the last successful poll.  Failures are
        absorbed (counted, remembered in `last_error`) and yield [] —
        the poll loop must outlive any single flaky registry."""
        self.polls += 1
        try:
            faults.fire("watch.poll")
            raw = self._poll_raw()
        except Exception as e:
            self.errors += 1
            self.last_error = f"{type(e).__name__}: {e}"
            return []
        self.last_poll_ts = time.time()
        out: list[ChangeRecord] = []
        for repo, tag, digest in raw:
            key = (repo, tag)
            if self._last_seen.get(key) == digest:
                self.deduped += 1
                continue
            self._last_seen[key] = digest
            out.append(
                ChangeRecord(repo=repo, tag=tag, digest=digest,
                             source=self.name)
            )
        self.emitted += len(out)
        return out

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "polls": self.polls,
            "errors": self.errors,
            "emitted": self.emitted,
            "deduped": self.deduped,
            "tracked_tags": len(self._last_seen),
            "last_poll_ts": self.last_poll_ts,
            "last_error": self.last_error,
        }


class RegistryTagPoller(EventSource):
    """Poll one repository's tag list and resolve each tag's digest.

    Reuses the RegistryClient transport (Bearer/Basic auth, insecure
    local registries) — `client` is injectable for tests."""

    kind = "registry"

    def __init__(self, reference: str, insecure: bool = False, client=None):
        super().__init__(name=reference)
        from trivy_tpu.image.registry import RegistryClient, parse_reference

        self.ref = parse_reference(reference)
        self.client = client or RegistryClient(insecure=insecure)

    def _poll_raw(self) -> list[tuple[str, str, str]]:
        from trivy_tpu.image.registry import Reference

        # Records carry the fully-qualified repo (registry host included)
        # so the planner's resolver can re-parse them without this
        # source's context.
        repo = f"{self.ref.registry}/{self.ref.repository}"
        out: list[tuple[str, str, str]] = []
        for tag in self.client.list_tags(self.ref):
            digest = self.client.subject_digest(
                Reference(
                    registry=self.ref.registry,
                    repository=self.ref.repository,
                    tag=tag,
                )
            )
            out.append((repo, tag, digest))
        return out


class FeedTailer(EventSource):
    """Tail a JSONL change feed: one {"repo","tag","digest"} per line.

    File feeds track a byte offset (only new bytes are read each poll);
    HTTP feeds re-GET the body and skip the lines already consumed.
    Malformed lines are counted and skipped, never fatal — a webhook
    relay that wrote a torn line must not wedge the plane."""

    kind = "feed"

    def __init__(self, path: str):
        super().__init__(name=path)
        self.path = path
        self._is_url = path.startswith(("http://", "https://"))
        self._offset = 0  # file: byte offset; url: consumed line count
        self.malformed = 0

    def _read_new_lines(self) -> list[str]:
        if self._is_url:
            with urllib.request.urlopen(self.path, timeout=30) as resp:
                lines = resp.read().decode("utf-8", "replace").splitlines()
            fresh = lines[self._offset:]
            self._offset = len(lines)
            return fresh
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read()
        # Only consume complete lines; a partial trailing line stays in
        # the file for the next poll (the writer may still be appending).
        head, sep, _tail = chunk.rpartition(b"\n")
        if not sep:
            return []
        self._offset += len(head) + 1
        return head.decode("utf-8", "replace").splitlines()

    def _poll_raw(self) -> list[tuple[str, str, str]]:
        out: list[tuple[str, str, str]] = []
        for line in self._read_new_lines():
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
                repo = str(doc["repo"])
                tag = str(doc.get("tag") or "latest")
                digest = str(doc["digest"])
            except (ValueError, KeyError, TypeError):
                self.malformed += 1
                continue
            out.append((repo, tag, digest))
        return out

    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["malformed"] = self.malformed
        return snap


def build_sources(configs) -> list[EventSource]:
    """SourceConfig list -> constructed sources (config.py kinds)."""
    out: list[EventSource] = []
    for sc in configs:
        if sc.kind == "registry":
            out.append(
                RegistryTagPoller(sc.reference, insecure=sc.insecure)
            )
        else:
            out.append(FeedTailer(sc.path))
    return out
