"""Delta planner: change records in, novel-blob scan tickets out.

The economics the watch plane exists for: at steady state almost every
change event resolves to blobs the fleet has already scanned, so the
planner's job is to prove that *before* any bytes move.  Per record:

1. resolve the image's blob (layer) digests — `resolve_fn(record)`
   returns ``[(blob_digest, fetch_fn), ...]`` with fetch deferred, so
   resolution costs manifest reads only;
2. narrow with the artifact cache's `missing_blobs` diff (the PR 14
   MissingBlobs seam: blobs whose analysis the cache already holds);
3. probe the result cache's `exists()` for every configured program —
   only a blob missing a verdict under the ACTIVE ruleset digest is
   novel;
4. fetch + dispatch only the novel blobs through `scan_fn` (the serve
   scheduler on a daemon, a local engine in the CLI), store verdicts,
   and hand each (record, blob, verdict) to `on_verdict` for the
   delta stream.

A re-pushed identical image therefore costs: one manifest resolve, one
`missing_blobs` round, N existence probes — and zero fetches, zero
device dispatches, zero analyzer runs (the BENCH_DELTA acceptance
gate).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable

from trivy_tpu import lockcheck
from trivy_tpu.watch.sources import ChangeRecord


class ContentStore:
    """Bounded digest->bytes LRU holding recently fetched blob contents.

    The re-verification sweeper needs the *bytes* of previously scanned
    blobs to re-verdict them under a new ruleset; refetching every blob
    from its registry would turn each `rules push` into a full image
    pull.  The planner feeds every fetch through here, so the sweep's
    working set is usually resident.  Strictly bounded (LRU by bytes):
    blobs evicted before a sweep are simply reported as missing-content
    and skipped."""

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = max(1, int(max_bytes))
        self._lock = lockcheck.make_lock("watch.content_store")
        self._data: OrderedDict[str, bytes] = OrderedDict()  # owner: _lock
        self._bytes = 0  # owner: _lock
        self.evictions = 0  # owner: _lock

    def put(self, digest: str, data: bytes) -> None:
        if len(data) > self.max_bytes:
            return  # larger than the whole store: not worth caching
        with self._lock:
            prev = self._data.pop(digest, None)
            if prev is not None:
                self._bytes -= len(prev)
            self._data[digest] = data
            self._bytes += len(data)
            while self._bytes > self.max_bytes and self._data:
                _, old = self._data.popitem(last=False)
                self._bytes -= len(old)
                self.evictions += 1

    def get(self, digest: str) -> bytes | None:
        with self._lock:
            data = self._data.get(digest)
            if data is not None:
                self._data.move_to_end(digest)
            return data

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "blobs": len(self._data),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "evictions": self.evictions,
            }


class DeltaPlanner:
    """Turn change records into the minimum set of device dispatches."""

    def __init__(
        self,
        result_cache,
        scan_fn: Callable[[list[tuple[str, bytes]]], list],
        ruleset_digest_fn: Callable[[], str],
        resolve_fn: Callable[[ChangeRecord], list],
        artifact_cache=None,
        content_store: ContentStore | None = None,
        programs: tuple[str, ...] = ("secret",),
        on_verdict=None,
    ):
        self.result_cache = result_cache
        self.scan_fn = scan_fn
        self.ruleset_digest_fn = ruleset_digest_fn
        self.resolve_fn = resolve_fn
        self.artifact_cache = artifact_cache
        self.content_store = content_store
        self.programs = tuple(programs) or ("secret",)
        # on_verdict(record, blob_digest, verdict): the stream seam.
        self.on_verdict = on_verdict
        self._lock = lockcheck.make_lock("watch.planner")
        # All owner: _lock.
        self.events_seen = 0
        self.resolve_errors = 0
        self.blobs_probed = 0
        self.blobs_cached = 0
        self.blobs_novel = 0
        self.dispatches = 0  # device dispatches (novel blobs scanned)
        self.dispatch_errors = 0
        self.fetch_bytes = 0

    def _is_novel(self, blob_digest: str, ruleset_digest: str) -> bool:
        """Novel = missing a cached verdict for ANY configured program.
        (One program's hit must not mask another's miss — a license
        verdict never answers a secret probe and vice versa.)"""
        return not all(
            self.result_cache.exists(blob_digest, ruleset_digest, pid)
            for pid in self.programs
        )

    def plan(self, records: list[ChangeRecord]) -> dict:
        """Process one poll's records; returns the cycle summary."""
        summary = {
            "events": len(records),
            "blobs": 0,
            "novel": 0,
            "cached": 0,
            "dispatched": 0,
            "errors": 0,
        }
        for record in records:
            out = self.handle(record)
            summary["blobs"] += out["blobs"]
            summary["novel"] += out["novel"]
            summary["cached"] += out["cached"]
            summary["dispatched"] += out["dispatched"]
            summary["errors"] += out["errors"]
        return summary

    def handle(self, record: ChangeRecord) -> dict:
        """One change record end to end: resolve, probe, dispatch."""
        with self._lock:
            self.events_seen += 1
        out = {"blobs": 0, "novel": 0, "cached": 0, "dispatched": 0,
               "errors": 0}
        try:
            resolved = self.resolve_fn(record)
        except Exception:
            with self._lock:
                self.resolve_errors += 1
            out["errors"] += 1
            return out
        digest = self.ruleset_digest_fn()
        blob_digests = [d for d, _ in resolved]
        out["blobs"] = len(blob_digests)
        # Artifact-level fast path: the MissingBlobs diff narrows to
        # blobs whose analysis the artifact cache lacks, and marks this
        # manifest digest as seen for the next identical push.
        if self.artifact_cache is not None and record.digest:
            try:
                self.artifact_cache.missing_blobs(
                    record.digest, blob_digests
                )
            except Exception:
                pass  # advisory only; the verdict probes decide
        novel: list[tuple[str, Callable[[], bytes]]] = []
        for blob_digest, fetch_fn in resolved:
            with self._lock:
                self.blobs_probed += 1
            if self._is_novel(blob_digest, digest):
                novel.append((blob_digest, fetch_fn))
            else:
                with self._lock:
                    self.blobs_cached += 1
        out["cached"] = out["blobs"] - len(novel)
        out["novel"] = len(novel)
        with self._lock:
            self.blobs_novel += len(novel)
        if not novel:
            return out
        # Fetch only what must be scanned.  Paths are the blob digests
        # themselves: stable names keep stored verdicts byte-identical
        # regardless of which image/tag surfaced the blob.
        items: list[tuple[str, bytes]] = []
        fetched: list[str] = []
        for blob_digest, fetch_fn in novel:
            try:
                data = fetch_fn()
            except Exception:
                out["errors"] += 1
                continue
            if self.content_store is not None:
                self.content_store.put(blob_digest, data)
            with self._lock:
                self.fetch_bytes += len(data)
            items.append((blob_digest, data))
            fetched.append(blob_digest)
        if not items:
            return out
        try:
            verdicts = self.scan_fn(items)
        except Exception:
            with self._lock:
                self.dispatch_errors += 1
            out["errors"] += 1
            return out
        with self._lock:
            self.dispatches += len(items)
        out["dispatched"] = len(items)
        for blob_digest, verdict in zip(fetched, verdicts):
            # Idempotent when the scheduler already stored it (daemon
            # path); load-bearing for the CLI's local-engine path.
            self.result_cache.put(blob_digest, digest, verdict)
            if self.on_verdict is not None:
                self.on_verdict(record, blob_digest, verdict)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            probed = self.blobs_probed
            cached = self.blobs_cached
            return {
                "events_seen": self.events_seen,
                "resolve_errors": self.resolve_errors,
                "blobs_probed": probed,
                "blobs_cached": cached,
                "blobs_novel": self.blobs_novel,
                "dispatches": self.dispatches,
                "dispatch_errors": self.dispatch_errors,
                "fetch_bytes": self.fetch_bytes,
                "hit_rate": (cached / probed) if probed else None,
            }
