"""WatchService: the continuous-scanning plane assembled and running.

Composition root for the watch subsystem: event sources feed the delta
planner on a poll loop, `rules push`/SIGHUP schedules re-verification
sweeps, and both paths publish verdict deltas through the stream.  The
server embeds one via :func:`build_watch_service` (--watch-config) and
surfaces `snapshot()` at GET /debug/watch; the CLI (`trivy-tpu watch`)
drives the same object with a local engine.

Threading: the poll loop and each sweep run on their own daemon
threads; `poll_once()` / `sweep_now()` are the synchronous forms tests
and the CLI's --once mode call directly.  All cross-thread state lives
behind the component locks (sources are only touched from the poll
thread; planner/sweeper/stream counters carry their own locks).
"""

from __future__ import annotations

import threading
import time

from trivy_tpu import lockcheck
from trivy_tpu.watch.config import WatchConfig
from trivy_tpu.watch.planner import ContentStore, DeltaPlanner
from trivy_tpu.watch.sources import EventSource, build_sources
from trivy_tpu.watch.stream import VerdictDeltaStream, WebhookEmitter
from trivy_tpu.watch.sweeper import ReverifySweeper


class WatchService:
    def __init__(
        self,
        sources: list[EventSource],
        planner: DeltaPlanner,
        sweeper: ReverifySweeper,
        stream: VerdictDeltaStream,
        content_store: ContentStore | None = None,
        poll_interval_s: float = 30.0,
        clock=time.time,
    ):
        self.sources = list(sources)
        self.planner = planner
        self.sweeper = sweeper
        self.stream = stream
        self.content_store = content_store
        self.poll_interval_s = float(poll_interval_s)
        self._clock = clock
        self._stop = threading.Event()
        self._loop_thread: threading.Thread | None = None
        self._lock = lockcheck.make_lock("watch.service")
        self.cycles = 0  # owner: _lock
        self.last_cycle_ts = 0.0  # owner: _lock
        self.last_cycle: dict = {}  # owner: _lock

    # -- poll plane --------------------------------------------------------

    def poll_once(self) -> dict:
        """One full poll cycle across every source (synchronous: tests,
        CLI --once, and each loop iteration)."""
        cycle = {"records": 0, "events": 0, "novel": 0, "cached": 0,
                 "dispatched": 0, "errors": 0, "blobs": 0}
        for source in self.sources:
            records = source.poll()
            cycle["records"] += len(records)
            summary = self.planner.plan(records)
            for k in ("events", "blobs", "novel", "cached",
                      "dispatched", "errors"):
                cycle[k] += summary[k]
        with self._lock:
            self.cycles += 1
            self.last_cycle_ts = self._clock()
            self.last_cycle = dict(cycle)
        return cycle

    def start(self) -> None:
        """Start the background poll loop (idempotent)."""
        if self._loop_thread is not None and self._loop_thread.is_alive():
            return
        self._stop.clear()
        self._loop_thread = threading.Thread(
            target=self._loop, name="watch-poll", daemon=True
        )
        self._loop_thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                pass  # per-source errors are already absorbed; belt+braces
            self._stop.wait(self.poll_interval_s)

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._loop_thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)

    def close(self) -> None:
        self.stop()
        self.stream.close()

    # -- sweep plane -------------------------------------------------------

    def sweep_now(self, old_digest: str, new_digest: str) -> dict:
        """Synchronous re-verification sweep (tests, CLI)."""
        return self.sweeper.sweep(old_digest, new_digest)

    def schedule_sweep(self, old_digest: str, new_digest: str) -> bool:
        """Kick a sweep on a background thread after a ruleset change;
        False = nothing to do (no change, or digests unknown)."""
        if not old_digest or not new_digest or old_digest == new_digest:
            return False
        threading.Thread(
            target=self.sweeper.sweep,
            args=(old_digest, new_digest),
            name="watch-sweep",
            daemon=True,
        ).start()
        return True

    # -- observation -------------------------------------------------------

    def lag_s(self) -> float | None:
        """Seconds since the last completed poll cycle (None before the
        first) — the /debug/watch freshness signal."""
        with self._lock:
            last = self.last_cycle_ts
        if not last:
            return None
        return max(0.0, self._clock() - last)

    def snapshot(self) -> dict:
        with self._lock:
            cycles = self.cycles
            last_cycle = dict(self.last_cycle)
        snap = {
            "enabled": True,
            "poll_interval_s": self.poll_interval_s,
            "running": bool(
                self._loop_thread is not None
                and self._loop_thread.is_alive()
            ),
            "cycles": cycles,
            "lag_s": self.lag_s(),
            "last_cycle": last_cycle,
            "sources": [s.snapshot() for s in self.sources],
            "planner": self.planner.snapshot(),
            "sweep": self.sweeper.snapshot(),
            "stream": self.stream.snapshot(),
        }
        if self.content_store is not None:
            snap["content_store"] = self.content_store.snapshot()
        return snap

    def register_collectors(self, registry) -> None:
        """Export the trivy_tpu_watch_* families into a server registry,
        folding the plane's monotonic tallies in by delta at scrape time
        (the gate/cache/fleet collect-hook discipline).  Source labels
        come from the static watch config and outcome/result labels are
        enums — all bounded, so GL007's governor requirement does not
        apply."""
        m_events = registry.counter(
            "trivy_tpu_watch_events_total",
            "change records emitted by each watch event source",
            ("source",),
        )
        m_poll_errors = registry.counter(
            "trivy_tpu_watch_poll_errors_total",
            "failed polls by watch event source",
            ("source",),
        )
        m_blobs = registry.counter(
            "trivy_tpu_watch_blobs_total",
            "blobs the delta planner probed, by outcome "
            "(cached = verdict already held, novel = dispatched)",
            ("outcome",),
        )
        m_emit = registry.counter(
            "trivy_tpu_watch_emit_total",
            "verdict-delta webhook deliveries by result",
            ("result",),
        )
        m_sweeps = registry.counter(
            "trivy_tpu_watch_sweeps_total",
            "re-verification sweeps started",
        )
        g_sweep = registry.gauge(
            "trivy_tpu_watch_sweep_progress",
            "fraction of the current/last sweep's candidates processed "
            "(1.0 = complete or idle)",
        )
        g_lag = registry.gauge(
            "trivy_tpu_watch_poll_lag_seconds",
            "seconds since the last completed poll cycle",
        )
        exported: dict[tuple[int, str], float] = {}

        def _fold(family, labelname: str, value: str, total: float) -> None:
            key = (id(family), f"{labelname}={value}")
            delta = total - exported.get(key, 0)
            if delta > 0:
                family.labels(**{labelname: value}).inc(  # graftlint: ignore[GL007]
                    delta
                )
                exported[key] = total

        def _collect() -> None:
            for s in self.sources:
                snap = s.snapshot()
                _fold(m_events, "source", snap["name"], snap["emitted"])
                _fold(
                    m_poll_errors, "source", snap["name"], snap["errors"]
                )
            p = self.planner.snapshot()
            _fold(m_blobs, "outcome", "cached", p["blobs_cached"])
            _fold(m_blobs, "outcome", "novel", p["blobs_novel"])
            st = self.stream.snapshot()
            hook = st.get("webhook") or {}
            if hook:
                _fold(m_emit, "result", "delivered", hook["delivered"])
                _fold(m_emit, "result", "retried", hook["retried"])
                _fold(
                    m_emit, "result", "dropped",
                    hook["dropped_full"] + hook["dropped_failed"],
                )
            sw = self.sweeper.snapshot()
            delta = sw["sweeps_total"] - exported.get((0, "sweeps"), 0)
            if delta > 0:
                m_sweeps.inc(delta)
                exported[(0, "sweeps")] = sw["sweeps_total"]
            prog = sw["progress"]
            total = prog.get("total") or 0
            done = (
                prog.get("touched", 0)
                + prog.get("skipped_current", 0)
                + prog.get("missing_content", 0)
                + prog.get("failures", 0)
            )
            g_sweep.set(done / total if total else 1.0)
            g_lag.set(self.lag_s() or 0.0)

        registry.add_collect_hook(_collect)


def registry_resolver(client):
    """The production resolve_fn: manifest digests -> layer blob
    descriptors over one RegistryClient.  Fetches are deferred lambdas
    (the planner only pays for novel blobs)."""
    from trivy_tpu.image.registry import parse_reference

    def resolve(record):
        ref_str = (
            f"{record.repo}@{record.digest}"
            if record.digest.startswith("sha256:")
            else f"{record.repo}:{record.tag}"
        )
        ref = parse_reference(ref_str)
        manifest, _raw = client.get_manifest(ref)

        def _fetch(digest: str) -> bytes:
            with client.get_blob(ref, digest) as f:
                return f.read()

        return [
            (layer["digest"], lambda d=layer["digest"]: _fetch(d))
            for layer in manifest.get("layers", [])
        ]

    return resolve


def build_watch_service(
    config: WatchConfig,
    result_cache,
    scan_fn,
    ruleset_digest_fn,
    artifact_cache=None,
    flight=None,
    resolve_fn=None,
    sources: list[EventSource] | None = None,
    sweep_scan_fn=None,
) -> WatchService:
    """Assemble a WatchService from a parsed WatchConfig.  The server
    and CLI both enter here; tests inject `sources`/`resolve_fn` fakes.
    This factory is also the GL015 boundary: event-source and webhook
    construction happen inside trivy_tpu/watch/, never in serve/rpc
    code."""
    if sources is None:
        sources = build_sources(config.sources)
    if resolve_fn is None:
        from trivy_tpu.image.registry import RegistryClient

        insecure = any(s.insecure for s in config.sources)
        resolve_fn = registry_resolver(RegistryClient(insecure=insecure))
    content_store = ContentStore(config.content_store_mb << 20)
    emitter = None
    if config.stream.webhook_url:
        emitter = WebhookEmitter(
            config.stream.webhook_url,
            queue_max=config.stream.webhook_queue,
            attempts=config.stream.webhook_attempts,
            flight=flight,
        )
    stream = VerdictDeltaStream(
        jsonl_path=config.stream.jsonl_path, emitter=emitter
    )

    def _on_planned(record, blob_digest, verdict):
        stream.publish(
            record.image, blob_digest, verdict,
            ruleset_digest=ruleset_digest_fn(),
        )

    planner = DeltaPlanner(
        result_cache,
        scan_fn,
        ruleset_digest_fn,
        resolve_fn,
        artifact_cache=artifact_cache,
        content_store=content_store,
        programs=config.programs,
        on_verdict=_on_planned,
    )

    def _on_swept(blob_digest, old_verdict, new_verdict):
        stream.publish(
            "", blob_digest, new_verdict,
            ruleset_digest=ruleset_digest_fn(), old=old_verdict,
        )

    if sweep_scan_fn is None:
        # Default: re-verdict on the same engine the planner dispatches
        # to (correct when the caller hot-reloads that engine in place,
        # e.g. the CLI; servers pass a digest-routing sweep_scan_fn).
        sweep_scan_fn = lambda items, _digest: scan_fn(items)  # noqa: E731
    sweeper = ReverifySweeper(
        result_cache,
        sweep_scan_fn,
        content_store,
        programs=config.programs,
        on_verdict=_on_swept,
        flight=flight,
    )
    return WatchService(
        sources,
        planner,
        sweeper,
        stream,
        content_store=content_store,
        poll_interval_s=config.poll_interval_s,
    )
