"""Continuous scanning plane (ROADMAP item 3, the last scale axis).

Registry-event-driven delta dispatch: event sources observe image
changes, the delta planner proves which blobs are genuinely novel
before any bytes move, the re-verification sweeper re-earns exactly
the verdicts a ruleset push invalidated, and the verdict-delta stream
publishes what changed.  See each module's docstring; the composition
root is service.build_watch_service.

This package is also a lint boundary: graftlint GL015 ("watch-seam")
requires event-source I/O and webhook emission to happen only inside
trivy_tpu/watch/ — serve/rpc/engine code reaches the plane through
build_watch_service, never by constructing pollers or emitters
directly on a scheduler thread.
"""

from trivy_tpu.watch.config import (
    SourceConfig,
    StreamConfig,
    WatchConfig,
    WatchConfigError,
    load_watch_config,
    parse_watch_config,
)
from trivy_tpu.watch.planner import ContentStore, DeltaPlanner
from trivy_tpu.watch.service import (
    WatchService,
    build_watch_service,
    registry_resolver,
)
from trivy_tpu.watch.sources import (
    ChangeRecord,
    EventSource,
    FeedTailer,
    RegistryTagPoller,
    build_sources,
)
from trivy_tpu.watch.stream import (
    VerdictDeltaStream,
    WebhookEmitter,
    diff_findings,
)
from trivy_tpu.watch.sweeper import ReverifySweeper

__all__ = [
    "ChangeRecord",
    "ContentStore",
    "DeltaPlanner",
    "EventSource",
    "FeedTailer",
    "RegistryTagPoller",
    "ReverifySweeper",
    "SourceConfig",
    "StreamConfig",
    "VerdictDeltaStream",
    "WatchConfig",
    "WatchConfigError",
    "WatchService",
    "WebhookEmitter",
    "build_sources",
    "build_watch_service",
    "diff_findings",
    "load_watch_config",
    "parse_watch_config",
    "registry_resolver",
]
