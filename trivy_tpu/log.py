"""Logging setup: the reference's colored console handler discipline
(pkg/log): level-colored prefixes on a tty, plain text otherwise,
--debug/--quiet verbosity control, per-module loggers unchanged.
"""

from __future__ import annotations

import logging
import sys

_COLORS = {
    logging.DEBUG: "\x1b[35m",  # magenta
    logging.INFO: "\x1b[34m",  # blue
    logging.WARNING: "\x1b[33m",  # yellow
    logging.ERROR: "\x1b[31m",  # red
    logging.CRITICAL: "\x1b[31;1m",
}
_RESET = "\x1b[0m"


class ConsoleFormatter(logging.Formatter):
    def __init__(self, color: bool):
        super().__init__(datefmt="%Y-%m-%dT%H:%M:%S")
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        level = record.levelname
        if self.color:
            c = _COLORS.get(record.levelno, "")
            level = f"{c}{level}{_RESET}"
        prefix = f"{self.formatTime(record, self.datefmt)}\t{level}\t"
        name = record.name.removeprefix("trivy_tpu.")
        msg = record.getMessage()
        out = f"{prefix}[{name}] {msg}"
        if record.exc_info:
            out += "\n" + self.formatException(record.exc_info)
        return out


def setup(
    debug: bool = False, quiet: bool = False, no_color: bool = False
) -> None:
    """Install the console handler on the package root logger.

    Idempotent: replaces a previously-installed handler, so tests and
    repeated main() calls do not stack duplicates."""
    logger = logging.getLogger("trivy_tpu")
    for h in list(logger.handlers):
        if getattr(h, "_trivy_console", False):
            logger.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    handler._trivy_console = True  # type: ignore[attr-defined]
    color = not no_color and sys.stderr.isatty()
    handler.setFormatter(ConsoleFormatter(color))
    logger.addHandler(handler)
    # Propagation stays on: the root logger has no handlers in CLI use
    # (no double printing) and log-capture tooling relies on it.
    if quiet:
        logger.setLevel(logging.ERROR)
    elif debug:
        logger.setLevel(logging.DEBUG)
    else:
        logger.setLevel(logging.INFO)
