"""Logging setup: the reference's colored console handler discipline
(pkg/log): level-colored prefixes on a tty, plain text otherwise,
--debug/--quiet verbosity control, per-module loggers unchanged.

`--log-format json` swaps the console formatter for one JSON object per
line (ts/level/logger/msg), stamped with the ambient span's trace_id when
one is open — the key that joins a log line to its request's span tree.
"""

from __future__ import annotations

import json
import logging
import sys
import time

_COLORS = {
    logging.DEBUG: "\x1b[35m",  # magenta
    logging.INFO: "\x1b[34m",  # blue
    logging.WARNING: "\x1b[33m",  # yellow
    logging.ERROR: "\x1b[31m",  # red
    logging.CRITICAL: "\x1b[31;1m",
}
_RESET = "\x1b[0m"


class ConsoleFormatter(logging.Formatter):
    def __init__(self, color: bool):
        super().__init__(datefmt="%Y-%m-%dT%H:%M:%S")
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        level = record.levelname
        if self.color:
            c = _COLORS.get(record.levelno, "")
            level = f"{c}{level}{_RESET}"
        prefix = f"{self.formatTime(record, self.datefmt)}\t{level}\t"
        name = record.name.removeprefix("trivy_tpu.")
        msg = record.getMessage()
        out = f"{prefix}[{name}] {msg}"
        if record.exc_info:
            out += "\n" + self.formatException(record.exc_info)
        return out


class JsonFormatter(logging.Formatter):
    """One JSON object per line.  trace_id appears only when a span is
    open on the emitting thread (obs/trace.py contextvar) — server logs
    correlate to /debug/traces without any per-call plumbing."""

    def format(self, record: logging.LogRecord) -> str:
        from trivy_tpu.obs import trace as obs_trace

        out = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.localtime(record.created)
            ),
            "level": record.levelname,
            "logger": record.name.removeprefix("trivy_tpu."),
            "msg": record.getMessage(),
        }
        trace_id = obs_trace.current_trace_id()
        if trace_id:
            out["trace_id"] = trace_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def setup(
    debug: bool = False, quiet: bool = False, no_color: bool = False,
    log_format: str = "console",
) -> None:
    """Install the console handler on the package root logger.

    Idempotent: replaces a previously-installed handler, so tests and
    repeated main() calls do not stack duplicates."""
    logger = logging.getLogger("trivy_tpu")
    for h in list(logger.handlers):
        if getattr(h, "_trivy_console", False):
            logger.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    handler._trivy_console = True  # type: ignore[attr-defined]
    if log_format == "json":
        handler.setFormatter(JsonFormatter())
    else:
        color = not no_color and sys.stderr.isatty()
        handler.setFormatter(ConsoleFormatter(color))
    logger.addHandler(handler)
    # Propagation stays on: the root logger has no handlers in CLI use
    # (no double printing) and log-capture tooling relies on it.
    if quiet:
        logger.setLevel(logging.ERROR)
    elif debug:
        logger.setLevel(logging.DEBUG)
    else:
        logger.setLevel(logging.INFO)
