"""CLI entry: the command tree.

Mirrors pkg/commands/app.go (NewApp :65) with argparse instead of cobra.
Subcommands map 1:1 to the reference's: fs, rootfs, image, repository, sbom,
convert, server, config, version.  Every flag also binds an env var
(``TRIVY_TPU_<FLAG>``), like the reference's viper env binding.
"""

from __future__ import annotations

import argparse
import os
import sys

from trivy_tpu import __version__
from trivy_tpu.commands.run import (
    TARGET_FILESYSTEM,
    TARGET_IMAGE,
    TARGET_REPOSITORY,
    TARGET_ROOTFS,
    TARGET_SBOM,
    TARGET_VM,
    Options,
    run,
)
from trivy_tpu.result.filter import SEVERITIES


# Config-file layer (the viper config file, pkg/flag/*): values from
# trivy.yaml (or --config FILE) sit under env vars, which sit under explicit
# CLI flags — flag > env > config file > built-in default.
_CONFIG_FILE: dict[str, object] = {}


class ConfigFileError(ValueError):
    pass


def _load_config_file(argv) -> None:
    """Pre-pass: find --config (default ./trivy.yaml) and flatten it.

    Nested groups flatten with dashes ({"db": {"repository": R}} ->
    "db-repository"), matching the reference's dotted config keys."""
    global _CONFIG_FILE
    _CONFIG_FILE = {}
    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument(
        "--config", default=os.environ.get("TRIVY_TPU_CONFIG", "trivy.yaml")
    )
    known, _ = pre.parse_known_args(argv)
    if not os.path.exists(known.config):
        return
    import yaml

    try:
        with open(known.config, encoding="utf-8") as f:
            doc = yaml.safe_load(f) or {}
    except (OSError, yaml.YAMLError) as e:
        # A broken config file must fail the run, not silently fall back to
        # defaults (the reference's viper load is a hard error).
        raise ConfigFileError(f"bad config file {known.config}: {e}") from e
    flat: dict[str, object] = {}

    def walk(prefix: str, node) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{k}-", v)
        else:
            flat[prefix[:-1]] = node

    walk("", doc if isinstance(doc, dict) else {})
    _CONFIG_FILE = flat


def _env_default(name: str, default):
    env = os.environ.get(f"TRIVY_TPU_{name.upper().replace('-', '_')}")
    if env is not None:
        return env
    val = _CONFIG_FILE.get(name)
    if val is None:
        return default
    if isinstance(val, list):
        return ",".join(str(v) for v in val)
    return val


def _int_default(name: str, default: int) -> int:
    val = _env_default(name, default)
    try:
        return int(val)
    except (TypeError, ValueError) as e:
        raise ConfigFileError(
            f"{name} must be an integer, got {val!r} (env/config)"
        ) from e


def _opt_int_default(name: str) -> int | None:
    """Like _int_default but with no built-in fallback: None means "flag
    absent, let the engine pick its own default" (the engine layers read
    the same TRIVY_TPU_* env vars, so the binding here only matters for
    config-file values and explicit flags)."""
    val = _env_default(name, None)
    if val is None or val == "":
        return None
    try:
        return int(val)
    except (TypeError, ValueError) as e:
        raise ConfigFileError(
            f"{name} must be an integer, got {val!r} (env/config)"
        ) from e


def _float_default(name: str, default: float) -> float:
    val = _env_default(name, default)
    try:
        return float(val)
    except (TypeError, ValueError) as e:
        raise ConfigFileError(
            f"{name} must be a number, got {val!r} (env/config)"
        ) from e


def _bool_default(name: str, default: bool = False) -> bool:
    val = _env_default(name, default)
    if isinstance(val, bool):
        return val
    return str(val).strip().lower() in ("1", "true", "yes", "on")


def _parse_duration(s) -> float:
    """"300", "300s", "5m", "1h30m" -> seconds (flag.DurationFlag)."""
    if isinstance(s, (int, float)):
        return float(s)
    total = 0.0
    num = ""
    units = {"s": 1, "m": 60, "h": 3600}
    for ch in str(s).strip():
        if ch.isdigit() or ch == ".":
            num += ch
        elif ch in units and num:
            total += float(num) * units[ch]
            num = ""
        else:
            raise ValueError(f"bad duration: {s!r}")
    if num:
        total += float(num)
    return total


def _add_scan_flags(p: argparse.ArgumentParser, default_scanners: str) -> None:
    p.add_argument("target")
    p.add_argument(
        "--scanners",
        default=_env_default("scanners", default_scanners),
        help="comma-separated: vuln,secret,misconfig,license",
    )
    p.add_argument(
        "--severity",
        default=_env_default("severity", ",".join(SEVERITIES)),
        help="comma-separated severities to report",
    )
    p.add_argument("-f", "--format", default=_env_default("format", "table"))
    p.add_argument("-o", "--output", default=_env_default("output", ""))
    p.add_argument(
        "--exit-code", type=int, default=_int_default("exit-code", 0)
    )
    p.add_argument(
        "--skip-files", action="append",
        default=[s for s in str(_env_default("skip-files", "")).split(",") if s],
    )
    p.add_argument(
        "--skip-dirs", action="append",
        default=[s for s in str(_env_default("skip-dirs", "")).split(",") if s],
    )
    p.add_argument(
        "--file-patterns", action="append",
        default=[
            s for s in str(_env_default("file-patterns", "")).split(",") if s
        ],
        help="analyzer file-name override, repeatable: type:regex "
        "(e.g. pip:requirements-.*\\.txt)",
    )
    p.add_argument(
        "--secret-config", default=_env_default("secret-config", "trivy-secret.yaml")
    )
    p.add_argument(
        "--secret-backend",
        choices=["auto", "hybrid", "tpu", "cpu", "native", "server"],
        default=_env_default("secret-backend", "auto"),
        help="auto = hybrid when the native sieve builds else device engine, "
        "hybrid = C++ host pre-sieve + confirm, tpu = device sieve engine, "
        "native = C++ host sieve via the device engine flow, "
        "cpu = oracle engine, "
        "server = ship raw items to the scan server's continuous "
        "cross-request batcher (requires --server or --fleet-config)",
    )
    p.add_argument(
        "--ruleset",
        default=_env_default("ruleset", ""),
        help="with --secret-backend server: digest of a pushed ruleset to "
        "scan under (see `rules push`; default = the server's ruleset)",
    )
    p.add_argument(
        "--rules-cache-dir",
        default=_env_default("rules-cache-dir", ""),
        help="compiled-ruleset registry directory (default "
        "~/.cache/trivy-tpu/rulesets; 'off' disables warm starts)",
    )
    p.add_argument(
        "--pipeline-depth", type=int,
        default=_opt_int_default("pipeline-depth"),
        help="chunks staged ahead in the device upload pipeline "
        "(default: engine-chosen; TRIVY_TPU_PIPELINE_DEPTH)",
    )
    p.add_argument(
        "--resident-chunks", type=int,
        default=_opt_int_default("resident-chunks"),
        help="device-resident chunk LRU capacity — repeated chunks skip "
        "the host-device link entirely (default 32; "
        "TRIVY_TPU_RESIDENT_CHUNKS)",
    )
    p.add_argument(
        "--mesh", default=_env_default("mesh", ""),
        help="device mesh for data-parallel scans: N or NxM devices, "
        "'auto' (mesh only on multi-chip TPU), 'none' to force "
        "single-device (default auto; TRIVY_TPU_MESH)",
    )
    p.add_argument("--ignorefile", default=_env_default("ignorefile", ".trivyignore"))
    p.add_argument(
        "--debug", action="store_true", default=_bool_default("debug")
    )
    p.add_argument(
        "--quiet", "-q", action="store_true", default=_bool_default("quiet")
    )
    p.add_argument(
        "--no-color", action="store_true", default=_bool_default("no-color")
    )
    p.add_argument(
        "--profile-dir", default=_env_default("profile-dir", ""),
        help="write a JAX profiler trace of the scan to this directory",
    )
    p.add_argument(
        "--trace", action="store_true", default=_bool_default("trace"),
        help="attach rego evaluation traces to misconfiguration findings",
    )
    p.add_argument(
        "--trace-out", default=_env_default("trace-out", ""),
        help="write host span timeline (Chrome-trace JSON) to this path",
    )
    p.add_argument(
        "--explain", action="store_true", default=_bool_default("explain"),
        help="with --secret-backend server: request the per-phase timing "
        "breakdown (queue wait, batch fill, engine phases) for each "
        "batch and print it after the scan",
    )
    p.add_argument(
        "--log-format", choices=("console", "json"),
        default=_env_default("log-format", "console"),
        help="log line format: console (default) or one JSON object per line",
    )
    p.add_argument("--cache-dir", default=_env_default("cache-dir", ""))
    p.add_argument(
        "--cache-backend",
        default=_env_default("cache-backend", "memory"),
        help="memory | fs | redis://host:port[/db] | s3://bucket/prefix "
        "(fs/redis/s3 run as a tiered chain: memory -> fs -> remote, "
        "remote errors degrade to local tiers)",
    )
    p.add_argument(
        "--cache-ttl", type=int, default=int(_env_default("cache-ttl", "0")),
        help="remote cache tier entry TTL in seconds (0 = keep forever; "
        "redis/s3 backends only)",
    )
    p.add_argument(
        "--server", default=_env_default("server", ""),
        help="server address (client mode)",
    )
    p.add_argument(
        "--fleet-config", default=_env_default("fleet-config", ""),
        help="fleet member YAML (client mode): route scans across the "
        "fleet by ruleset digest with health-aware failover instead of "
        "pinning to one --server address",
    )
    p.add_argument(
        "--token", default=_env_default("token", ""),
        help="server auth token",
    )
    p.add_argument(
        "--username", default=_env_default("username", ""),
        help="private registry username (TRIVY_TPU_USERNAME)",
    )
    p.add_argument(
        "--password", default=_env_default("password", ""),
        help="private registry password (prefer the env var)",
    )
    p.add_argument(
        "--server-wire", default=_env_default("server-wire", "json"),
        choices=["json", "protobuf"],
        help="Twirp wire format for client mode",
    )
    p.add_argument("--db-dir", default=_env_default("db-dir", ""),
                   help="vulnerability DB directory")
    p.add_argument(
        "--list-all-pkgs", action="store_true",
        default=_bool_default("list-all-pkgs"),
    )
    p.add_argument(
        "--template", default=_env_default("template", ""),
        help="template for -f template",
    )
    p.add_argument(
        "--vex", default=_env_default("vex", ""),
        help="OpenVEX/CycloneDX VEX document",
    )
    p.add_argument(
        "--include-non-failures", action="store_true",
        default=_bool_default("include-non-failures"),
    )
    p.add_argument(
        "--config-check", action="append",
        default=[
            s for s in str(_env_default("config-check", "")).split(",") if s
        ],
        help="directory with custom .rego checks (repeatable)",
    )
    p.add_argument(
        "--db-repository", default=_env_default("db-repository", ""),
        help="OCI reference to pull the vulnerability DB from",
    )
    p.add_argument(
        "--skip-db-update", action="store_true",
        default=_bool_default("skip-db-update"),
    )
    p.add_argument(
        "--java-db-repository", default=_env_default("java-db-repository", ""),
        help="OCI reference to pull the Java index DB from",
    )
    p.add_argument(
        "--ignore-policy", default=_env_default("ignore-policy", ""),
        help="rego file whose 'ignore' rule filters findings",
    )
    p.add_argument(
        "--checks-bundle-repository",
        default=_env_default("checks-bundle-repository", ""),
        help="OCI reference to pull extra .rego checks from",
    )
    p.add_argument(
        "--compliance", default=_env_default("compliance", ""),
        help="compliance spec: builtin name or @/path/to/spec.yaml",
    )
    p.add_argument(
        "--module-dir", default=_env_default("module-dir", ""),
        help="directory of extension modules (custom analyzers/hooks)",
    )
    p.add_argument(
        "--sbom-sources", action="append",
        default=[s for s in str(_env_default("sbom-sources", "")).split(",") if s],
        help="external SBOM sources (rekor enables executable digesting)",
    )
    p.add_argument(
        "--rekor-url", default=_env_default("rekor-url", ""),
        help="Rekor transparency-log URL for attestation lookups",
    )
    p.add_argument(
        "--report", choices=["summary", "all"],
        default=_env_default("report", "summary"),
        help="compliance report granularity",
    )
    p.add_argument(
        "--timeout", default=_env_default("timeout", "5m"),
        help="scan timeout, e.g. 300s / 5m / 1h (default 5m)",
    )
    p.add_argument(
        "--config", default=os.environ.get("TRIVY_TPU_CONFIG", "trivy.yaml"),
        help="YAML config file merged under flags and env vars",
    )
    p.add_argument(
        "--insecure", action="store_true",
        default=_bool_default("insecure"),
        help="allow plain-http registry access (images and DB pulls)",
    )


def _options_from_args(args: argparse.Namespace) -> Options:
    return Options(
        target=args.target,
        scanners=[s for s in args.scanners.split(",") if s],
        severities=[s for s in args.severity.upper().split(",") if s],
        format=args.format,
        output=args.output,
        exit_code=args.exit_code,
        cache_dir=args.cache_dir,
        cache_backend=args.cache_backend,
        cache_ttl=getattr(args, "cache_ttl", 0),
        skip_files=args.skip_files,
        skip_dirs=args.skip_dirs,
        file_patterns=list(getattr(args, "file_patterns", []) or []),
        secret_config=args.secret_config,
        secret_backend=args.secret_backend,
        ruleset_select=getattr(args, "ruleset", ""),
        rules_cache_dir=getattr(args, "rules_cache_dir", ""),
        pipeline_depth=getattr(args, "pipeline_depth", None),
        resident_chunks=getattr(args, "resident_chunks", None),
        ignore_file=args.ignorefile if os.path.exists(args.ignorefile) else "",
        server_addr=args.server,
        fleet_config=getattr(args, "fleet_config", ""),
        username=getattr(args, "username", ""),
        password=getattr(args, "password", ""),
        server_wire=getattr(args, "server_wire", "json"),
        token=args.token,
        db_dir=args.db_dir,
        list_all_packages=args.list_all_pkgs,
        template=args.template,
        vex_path=args.vex,
        include_non_failures=args.include_non_failures,
        config_check=list(args.config_check),
        db_repository=args.db_repository,
        java_db_repository=args.java_db_repository,
        skip_db_update=args.skip_db_update,
        timeout=_parse_duration(args.timeout),
        ignore_policy=args.ignore_policy,
        checks_bundle_repository=args.checks_bundle_repository,
        compliance=args.compliance,
        compliance_report=args.report,
        module_dir=args.module_dir,
        sbom_sources=list(args.sbom_sources),
        rekor_url=args.rekor_url,
        profile_dir=getattr(args, "profile_dir", ""),
        trace=getattr(args, "trace", False),
        trace_out=getattr(args, "trace_out", ""),
        explain=getattr(args, "explain", False),
        log_format=getattr(args, "log_format", "console"),
    )


def _aws_command(args) -> int:
    import json as _json

    from trivy_tpu.cloud import AwsError, AwsScanner

    try:
        scanner = AwsScanner(
            services=args.service or ["s3"],
            endpoint=args.endpoint,
            region=args.region,
        )
        misconfigs = scanner.scan()
    except AwsError as e:
        print(f"trivy-tpu: {e}", file=sys.stderr)
        return 2
    failures = [f for mc in misconfigs for f in mc.failures]
    for err in scanner.errors:
        print(f"trivy-tpu: aws: {err}", file=sys.stderr)
    out = sys.stdout
    close = False
    if args.output:
        try:
            out = open(args.output, "w", encoding="utf-8")
        except OSError as e:
            print(f"trivy-tpu: cannot write {args.output}: {e}", file=sys.stderr)
            return 2
        close = True
    try:
        if args.format == "json":
            _json.dump(
                {
                    "ArtifactType": "aws_account",
                    "Results": [mc.to_json() for mc in misconfigs],
                },
                out, indent=2,
            )
            out.write("\n")
        else:
            out.write("\nAWS account scan\n")
            for f in failures:
                out.write(
                    f"{f.check_id:14} {f.severity:9} {f.message}\n"
                )
            if not failures:
                out.write("no failed checks\n")
    finally:
        if close:
            out.close()
    if scanner.errors:
        # Degraded enumeration must not read as a clean account.
        return args.exit_code or 2
    if args.exit_code and failures:
        return args.exit_code
    return 0


def _k8s_command(args) -> int:
    from trivy_tpu.k8s import (
        K8sScanner,
        KubeClient,
        KubeConfigError,
        load_kubeconfig,
        write_k8s_report,
    )

    try:
        auth = load_kubeconfig(args.kubeconfig, args.context)
        client = KubeClient(auth)
        if args.format == "cyclonedx":
            # KBOM mode (scanner.go:63-70): emit the cluster bill of
            # materials instead of scan findings.
            import json as _json

            from trivy_tpu.k8s.kbom import build_kbom

            ns = "" if args.k8s_target == "cluster" else args.k8s_target
            doc = build_kbom(client, cluster_name=auth.server, namespace=ns)
            if args.output:
                with open(args.output, "w", encoding="utf-8") as f:
                    _json.dump(doc, f, indent=2)
                    f.write("\n")
            else:
                _json.dump(doc, sys.stdout, indent=2)
                print()
            return 0
        from trivy_tpu.k8s.client import select_kinds

        scanners = [s for s in args.scanners.split(",") if s]
        unknown = set(scanners) - {"misconfig", "vuln", "secret", "rbac"}
        if unknown:
            # A typo'd scanner must not read as a clean cluster.
            print(
                f"trivy-tpu: unknown k8s scanners {sorted(unknown)} "
                "(expected misconfig,vuln,secret,rbac)",
                file=sys.stderr,
            )
            return 2
        kinds = select_kinds(
            [k for k in args.include_kinds.split(",") if k],
            rbac="rbac" in scanners,
            workloads=bool({"misconfig", "vuln", "secret"} & set(scanners)),
        )
        namespace = "" if args.k8s_target == "cluster" else args.k8s_target
        resources = client.list_workloads(namespace=namespace, kinds=kinds)
    except KubeConfigError as e:
        print(f"trivy-tpu: {e}", file=sys.stderr)
        return 2
    scanner = K8sScanner(
        scanners=scanners,
        insecure_registry=args.insecure,
        db_dir=args.db_dir,
    )
    report = scanner.scan(resources, cluster_name=auth.server)
    full = args.report == "all"
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            write_k8s_report(report, args.format, full, out=f)
    else:
        write_k8s_report(report, args.format, full)
    if args.exit_code and any(
        r.counts() or r.error for r in report.resources
    ):
        # Findings AND per-resource scan errors both fail the run: an
        # unreachable registry must not turn CI green.
        return args.exit_code
    return 0


def _plugin_command(args) -> int:
    from trivy_tpu import plugin as plugin_mod

    try:
        if args.plugin_command == "install":
            p = plugin_mod.install(args.src)
            print(f"installed plugin {p.name} {p.version}")
        elif args.plugin_command == "uninstall":
            plugin_mod.uninstall(args.name)
            print(f"uninstalled plugin {args.name}")
        elif args.plugin_command == "list":
            for p in plugin_mod.list_plugins():
                print(f"{p.name}\t{p.version}\t{p.usage or p.description}")
        elif args.plugin_command == "info":
            p = plugin_mod.find(args.name)
            if p is None:
                print(f"trivy-tpu: plugin {args.name!r} not installed",
                      file=sys.stderr)
                return 2
            print(f"name: {p.name}\nversion: {p.version}\n"
                  f"usage: {p.usage}\ndescription: {p.description}")
        elif args.plugin_command == "run":
            p = plugin_mod.find(args.name)
            if p is None:
                print(f"trivy-tpu: plugin {args.name!r} not installed",
                      file=sys.stderr)
                return 2
            return p.run(list(args.plugin_args))
        else:
            print("trivy-tpu: plugin {install|uninstall|list|info|run}",
                  file=sys.stderr)
            return 2
        return 0
    except plugin_mod.PluginError as e:
        print(f"trivy-tpu: {e}", file=sys.stderr)
        return 2


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trivy-tpu", description="TPU-native security scanner"
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="command")

    p_fs = sub.add_parser("fs", help="scan a local filesystem")
    _add_scan_flags(p_fs, "vuln,secret")
    p_fs.set_defaults(kind=TARGET_FILESYSTEM)

    p_rootfs = sub.add_parser("rootfs", help="scan an unpacked root filesystem")
    _add_scan_flags(p_rootfs, "vuln")
    p_rootfs.set_defaults(kind=TARGET_ROOTFS)

    p_image = sub.add_parser("image", help="scan a container image archive")
    _add_scan_flags(p_image, "vuln,secret")
    p_image.add_argument(
        "--input", default="", help="tar archive path (docker save / OCI layout)"
    )
    p_image.set_defaults(kind=TARGET_IMAGE)

    p_repo = sub.add_parser("repository", aliases=["repo"], help="scan a git repository")
    _add_scan_flags(p_repo, "vuln,secret")
    p_repo.add_argument("--branch", default="")
    p_repo.add_argument("--tag", default="")
    p_repo.add_argument("--commit", default="")
    p_repo.set_defaults(kind=TARGET_REPOSITORY)

    p_sbom = sub.add_parser("sbom", help="scan an SBOM (CycloneDX/SPDX JSON)")
    _add_scan_flags(p_sbom, "vuln")
    p_sbom.set_defaults(kind=TARGET_SBOM)

    p_vm = sub.add_parser("vm", help="scan a raw VM disk image")
    _add_scan_flags(p_vm, "vuln,secret")
    p_vm.set_defaults(kind=TARGET_VM)

    p_convert = sub.add_parser("convert", help="convert a saved JSON report")
    p_convert.add_argument("report")
    p_convert.add_argument("-f", "--format", default="table")
    p_convert.add_argument("-o", "--output", default="")
    p_convert.add_argument("--severity", default=",".join(SEVERITIES))
    p_convert.add_argument("--template", default="")

    p_server = sub.add_parser("server", help="run the scan server")
    p_server.add_argument("--listen", default="localhost:4954")
    p_server.add_argument("--cache-dir", default="")
    p_server.add_argument(
        "--cache-backend", default=_env_default("cache-backend", ""),
        help="server artifact/result cache: fs | redis://host:port | "
        "s3://bucket/prefix ('' = fs when --cache-dir is set, else memory); "
        "non-memory backends run as a tiered chain with degrade-on-error",
    )
    p_server.add_argument(
        "--cache-ttl", type=int, default=int(_env_default("cache-ttl", "0")),
        help="remote cache tier entry TTL seconds (redis/s3 backends)",
    )
    p_server.add_argument("--token", default="")
    p_server.add_argument("--db-dir", default="")
    # Continuous cross-request batcher knobs (trivy_tpu/serve/); each binds
    # TRIVY_TPU_<FLAG> like every other flag.
    p_server.add_argument(
        "--batch-window-ms", type=float,
        default=_float_default("batch-window-ms", 4.0),
        help="fill-or-timeout coalescing window for the secret batcher",
    )
    p_server.add_argument(
        "--max-batch-bytes", type=int,
        default=_int_default("max-batch-bytes", 8 << 20),
        help="dispatch a batch early once its payload reaches this size",
    )
    p_server.add_argument(
        "--max-queue-depth", type=int,
        default=_int_default("max-queue-depth", 256),
        help="admission queue bound; beyond it requests get 429 + Retry-After",
    )
    p_server.add_argument(
        "--max-inflight-per-client", type=int,
        default=_int_default("max-inflight-per-client", 8),
        help="per-client in-flight ticket cap (fairness under load)",
    )
    # Multi-tenant ruleset serving (trivy_tpu/tenancy/): compiled-engine
    # residency pool + per-tenant admission quotas.
    p_server.add_argument(
        "--max-resident-rulesets", type=int,
        default=_int_default("max-resident-rulesets", 4),
        help="compiled-ruleset LRU slots the server keeps device-resident "
        "for per-request ruleset selection",
    )
    p_server.add_argument(
        "--max-resident-mb", type=int,
        default=_int_default("max-resident-mb", 0),
        help="estimated device MB cap across resident rulesets "
        "(0 = count-bounded only)",
    )
    p_server.add_argument(
        "--tenant-rps", type=float,
        default=_float_default("tenant-rps", 0.0),
        help="default per-tenant requests/s quota; over-rate requests get "
        "429 with an exact Retry-After (0 = unlimited)",
    )
    p_server.add_argument(
        "--tenant-burst", type=float,
        default=_float_default("tenant-burst", 0.0),
        help="per-tenant request token-bucket depth (0 = max(rps, 1))",
    )
    p_server.add_argument(
        "--tenant-bytes-per-sec", type=float,
        default=_float_default("tenant-bytes-per-sec", 0.0),
        help="default per-tenant payload bytes/s quota (0 = unlimited)",
    )
    p_server.add_argument(
        "--tenant-bytes-burst", type=float,
        default=_float_default("tenant-bytes-burst", 0.0),
        help="per-tenant byte token-bucket depth (0 = one second of rate)",
    )
    p_server.add_argument(
        "--max-tenant-series", type=int,
        default=_int_default("max-tenant-series", 16),
        help="tenants that get their own metric series (top-K by request "
        'volume); the long tail rolls up into tenant="_other"',
    )
    p_server.add_argument(
        "--slo-config",
        default=_env_default("slo-config", ""),
        help="YAML per-method latency/error objectives overriding the "
        "defaults (burn rates served at GET /debug/slo)",
    )
    p_server.add_argument(
        "--flight-out",
        default=_env_default("flight-out", ""),
        help="append flight-recorder breach records (span tree + "
        "scheduler snapshot) to this JSONL file as they are captured",
    )
    p_server.add_argument(
        "--flight-out-max-mb", type=float,
        default=_float_default("flight-out-max-mb", 64.0),
        help="size cap on the --flight-out file; at the cap it rotates to "
        "<path>.1 (one backup) and overwritten records count into "
        "trivy_tpu_flight_dropped_total (0 = uncapped)",
    )
    p_server.add_argument(
        "--secret-config",
        default=_env_default("secret-config", ""),
        help="secret-config the server engine loads; SIGHUP or "
        "POST /admin/ruleset/reload re-reads it and hot-swaps at a "
        "batch boundary",
    )
    p_server.add_argument(
        "--rules-cache-dir",
        default=_env_default("rules-cache-dir", ""),
        help="compiled-ruleset registry directory (default "
        "~/.cache/trivy-tpu/rulesets; 'off' disables warm starts)",
    )
    p_server.add_argument(
        "--pipeline-depth", type=int,
        default=_opt_int_default("pipeline-depth"),
        help="chunks staged ahead in the server engine's device pipeline "
        "(default: engine-chosen; TRIVY_TPU_PIPELINE_DEPTH)",
    )
    p_server.add_argument(
        "--resident-chunks", type=int,
        default=_opt_int_default("resident-chunks"),
        help="device-resident chunk LRU capacity for the server engine "
        "(default 32; TRIVY_TPU_RESIDENT_CHUNKS)",
    )
    p_server.add_argument(
        "--mesh", default=_env_default("mesh", ""),
        help="device mesh for the server's engines: N or NxM devices, "
        "'auto' (mesh only on multi-chip TPU), 'none' to force "
        "single-device (default auto; TRIVY_TPU_MESH)",
    )
    p_server.add_argument(
        "--hbm-soft-pct", type=float,
        default=_float_default("hbm-soft-pct", 85.0),
        help="device-memory soft watermark as %% of the HBM bytes_limit: "
        "above it admission LRU-evicts resident rulesets (measured bytes) "
        "back under the line (0 disables)",
    )
    p_server.add_argument(
        "--hbm-hard-pct", type=float,
        default=_float_default("hbm-hard-pct", 95.0),
        help="device-memory hard watermark as %% of the HBM bytes_limit: "
        "above it new submissions get 429 + Retry-After until pressure "
        "drops (0 disables)",
    )
    p_server.add_argument(
        "--breaker-threshold", type=int,
        default=_int_default("breaker-threshold", 3),
        help="device-dispatch failures inside --breaker-window-s before "
        "the circuit breaker opens and batches route straight to the "
        "host DFA path",
    )
    p_server.add_argument(
        "--breaker-window-s", type=float,
        default=_float_default("breaker-window-s", 30.0),
        help="sliding window the breaker counts dispatch failures over",
    )
    p_server.add_argument(
        "--breaker-cooldown-s", type=float,
        default=_float_default("breaker-cooldown-s", 5.0),
        help="open -> half-open probe timer: after this long one probe "
        "batch tests the device and success re-closes the breaker",
    )
    p_server.add_argument(
        "--fleet-config", default=_env_default("fleet-config", ""),
        help="fleet member YAML shared by every host in the fleet; "
        "turns on GET /debug/fleet, X-Trivy-Fleet-* response headers, "
        "and affinity accounting (requires this host to appear in the "
        "members list — see --fleet-member)",
    )
    p_server.add_argument(
        "--fleet-member", default=_env_default("fleet-member", ""),
        help="which member of --fleet-config THIS process answers as "
        "(overrides the YAML's `self:` so one shared file serves the "
        "whole fleet)",
    )
    p_server.add_argument(
        "--profile-dir",
        default=_env_default("profile-dir", ""),
        help="default output directory for POST /admin/profile/start "
        "windows (JAX device trace + host spans)",
    )
    p_server.add_argument(
        "--log-format", choices=("console", "json"),
        default=_env_default("log-format", "console"),
        help="log line format: console (default) or one JSON object per "
        "line with trace_id correlation",
    )
    p_server.add_argument(
        "--watch-config", default=_env_default("watch-config", ""),
        help="continuous-scanning plane YAML (event sources + verdict-"
        "delta stream); requires --cache-backend so the delta planner "
        "can probe cached verdicts — see GET /debug/watch",
    )

    # Continuous scanning without a server: poll sources with a local
    # engine (the watch plane's CLI entry; the server embeds the same
    # plane via --watch-config).
    p_watch = sub.add_parser(
        "watch",
        help="continuously scan registry/feed changes with a local engine",
    )
    p_watch.add_argument(
        "--watch-config", default=_env_default("watch-config", ""),
        help="watch-plane YAML: event sources, poll interval, verdict-"
        "delta stream sinks (required)",
    )
    p_watch.add_argument(
        "--once", action="store_true", default=_bool_default("once"),
        help="run one poll cycle, print the JSON summary, and exit "
        "(smoke tests / cron)",
    )
    p_watch.add_argument("--cache-dir", default=_env_default("cache-dir", ""))
    p_watch.add_argument(
        "--cache-backend", default=_env_default("cache-backend", ""),
        help="result-cache backend: memory | fs | redis://… | s3://… "
        "('' = fs when --cache-dir is set, else memory)",
    )
    p_watch.add_argument(
        "--cache-ttl", type=int, default=int(_env_default("cache-ttl", "0")),
        help="remote cache tier entry TTL seconds (redis/s3 backends)",
    )
    p_watch.add_argument(
        "--secret-config", default=_env_default("secret-config", ""),
        help="secret-config YAML the local engine scans with",
    )
    p_watch.add_argument(
        "--rules-cache-dir", default=_env_default("rules-cache-dir", ""),
        help="compiled-ruleset registry directory (default "
        "~/.cache/trivy-tpu/rulesets; 'off' disables warm starts)",
    )
    p_watch.add_argument(
        "--log-format", choices=("console", "json"),
        default=_env_default("log-format", "console"),
    )
    p_watch.add_argument(
        "--debug", action="store_true", default=_bool_default("debug")
    )

    # Ruleset registry maintenance: precompile, list, verify artifacts.
    p_rules = sub.add_parser(
        "rules", help="manage the compiled-ruleset registry"
    )
    rules_sub = p_rules.add_subparsers(dest="rules_command")
    pr_compile = rules_sub.add_parser(
        "compile",
        help="compile a secret-config into the cache (cold-start killer)",
    )
    pr_compile.add_argument(
        "--secret-config", default=_env_default("secret-config", "")
    )
    pr_compile.add_argument(
        "--rules-cache-dir", default=_env_default("rules-cache-dir", "")
    )
    pr_compile.add_argument(
        "--warmup", action="store_true", default=_bool_default("warmup"),
        help="also AOT pre-lower/compile the sieve step kernels for the "
        "configured shape buckets",
    )
    pr_ls = rules_sub.add_parser("ls", help="list cached compiled artifacts")
    pr_ls.add_argument(
        "--rules-cache-dir", default=_env_default("rules-cache-dir", "")
    )
    pr_verify = rules_sub.add_parser(
        "verify",
        help="prove a cached artifact round-trips to byte-identical "
        "findings on the builtin corpus",
    )
    pr_verify.add_argument(
        "--secret-config", default=_env_default("secret-config", "")
    )
    pr_verify.add_argument(
        "--rules-cache-dir", default=_env_default("rules-cache-dir", "")
    )
    pr_push = rules_sub.add_parser(
        "push",
        help="compile a secret-config and install it into a running "
        "server's registry by digest (scans select it via RulesetDigest)",
    )
    pr_push.add_argument(
        "--server", default=_env_default("server", ""),
        help="server address (host:port or URL); required",
    )
    pr_push.add_argument(
        "--token", default=_env_default("token", ""),
        help="server auth token (Trivy-Tpu-Token header)",
    )
    pr_push.add_argument(
        "--secret-config", default=_env_default("secret-config", ""),
        help="secret-config YAML to push (empty = builtin rules only)",
    )
    pr_push.add_argument(
        "--rules-cache-dir", default=_env_default("rules-cache-dir", ""),
        help="local cache the client-side compile lands in",
    )
    pr_push.add_argument(
        "--compile-on-server", action="store_true",
        default=_bool_default("compile-on-server"),
        help="ship only the YAML and let the server compile (default: "
        "compile locally and upload the validated artifact)",
    )
    pr_push.add_argument(
        "--no-admit", action="store_true", default=_bool_default("no-admit"),
        help="register the ruleset without making it device-resident",
    )

    # Performance observatory: bench-ledger trajectory, run diffs, and the
    # CI regression gate over a checked-in baseline.
    p_perf = sub.add_parser(
        "perf", help="bench-ledger reports and regression gating"
    )
    perf_sub = p_perf.add_subparsers(dest="perf_command")
    pf_report = perf_sub.add_parser(
        "report", help="render the recent bench-ledger trajectory"
    )
    pf_report.add_argument(
        "--ledger", default=_env_default("ledger", ""),
        help="bench ledger JSONL (default BENCH_LEDGER_FILE or "
        "BENCH_LEDGER.jsonl)",
    )
    pf_report.add_argument(
        "--limit", type=int, default=_int_default("limit", 10),
        help="most-recent runs to include",
    )
    pf_diff = perf_sub.add_parser(
        "diff", help="per-metric deltas between two ledger runs"
    )
    pf_diff.add_argument(
        "--ledger", default=_env_default("ledger", "")
    )
    pf_diff.add_argument(
        "--base", type=int, default=_int_default("base", -2),
        help="base run index (negative = from the end; default -2)",
    )
    pf_diff.add_argument(
        "--head", type=int, default=_int_default("head", -1),
        help="head run index (negative = from the end; default -1, the "
        "latest run)",
    )
    pf_gate = perf_sub.add_parser(
        "gate",
        help="exit non-zero when the latest run regresses past the "
        "baseline's per-metric tolerance",
    )
    pf_gate.add_argument(
        "--ledger", default=_env_default("ledger", "")
    )
    pf_gate.add_argument(
        "--baseline", default=_env_default("baseline", ""),
        help="baseline JSON with per-metric tolerances "
        "(tools/perfgate/baseline.json in CI)",
    )

    sub.add_parser("version", help="print version")

    p_plugin = sub.add_parser("plugin", help="manage plugins")
    plugin_sub = p_plugin.add_subparsers(dest="plugin_command")
    pp_install = plugin_sub.add_parser("install", help="install a plugin")
    pp_install.add_argument("src", help="directory, .tar.gz, or URL")
    pp_un = plugin_sub.add_parser("uninstall", help="remove a plugin")
    pp_un.add_argument("name")
    plugin_sub.add_parser("list", help="list installed plugins")
    pp_info = plugin_sub.add_parser("info", help="show plugin information")
    pp_info.add_argument("name")
    pp_run = plugin_sub.add_parser("run", help="run a plugin")
    pp_run.add_argument("name")
    pp_run.add_argument("plugin_args", nargs=argparse.REMAINDER)

    p_config = sub.add_parser("config", help="scan config files for misconfigurations")
    _add_scan_flags(p_config, "misconfig")
    p_config.set_defaults(kind=TARGET_FILESYSTEM)

    p_aws = sub.add_parser("aws", help="scan an AWS account")
    p_aws.add_argument(
        "--service", action="append", default=[],
        help="services to scan (s3, ec2; repeatable; default s3)",
    )
    p_aws.add_argument("--region", default=_env_default("region", ""))
    p_aws.add_argument(
        "--endpoint", default=_env_default("endpoint", ""),
        help="custom AWS endpoint (localstack etc.)",
    )
    p_aws.add_argument("-f", "--format", default=_env_default("format", "table"))
    p_aws.add_argument("-o", "--output", default="")
    p_aws.add_argument("--exit-code", type=int,
                       default=_int_default("exit-code", 0))

    p_k8s = sub.add_parser("k8s", help="scan a kubernetes cluster")
    p_k8s.add_argument(
        "k8s_target", nargs="?", default="cluster",
        help="'cluster' or a namespace name",
    )
    p_k8s.add_argument("--kubeconfig", default=_env_default("kubeconfig", ""))
    p_k8s.add_argument("--context", default="")
    p_k8s.add_argument(
        "--scanners", default=_env_default("scanners", "misconfig"),
        help="comma-separated: misconfig,vuln,secret,rbac",
    )
    p_k8s.add_argument(
        "--include-kinds", default=_env_default("include-kinds", ""),
        help="comma-separated kind names to enumerate (Pod, Deployment, "
             "Role, ClusterRoleBinding, ...); default: workloads, plus "
             "RBAC kinds when the rbac scanner is enabled",
    )
    p_k8s.add_argument("-f", "--format", default=_env_default("format", "table"))
    p_k8s.add_argument("-o", "--output", default="")
    p_k8s.add_argument("--report", choices=["summary", "all"], default="summary")
    p_k8s.add_argument("--insecure", action="store_true",
                       default=_bool_default("insecure"))
    p_k8s.add_argument("--db-dir", default=_env_default("db-dir", ""))
    p_k8s.add_argument("--exit-code", type=int,
                       default=_int_default("exit-code", 0))

    # Exposed for the plugin fall-through (aliases included), so the
    # known-command set cannot drift from the subparser registry.
    parser.subcommands = frozenset(sub.choices)
    return parser


def main(argv: list[str] | None = None) -> int:
    raw = list(argv) if argv is not None else sys.argv[1:]
    # Unknown top-level commands fall through to installed plugins
    # (app.go loadPluginCommands): `trivy-tpu <plugin> args...`.
    config_err: ConfigFileError | None = None
    parser = None
    try:
        _load_config_file(raw)  # must precede build_parser (flag defaults)
        parser = build_parser()
    except ConfigFileError as e:
        # Deferred: a broken config file must not block plugin dispatch
        # (plugins do not consume trivy.yaml); builtin commands still fail.
        config_err = e
    if raw and not raw[0].startswith("-"):
        known = (
            getattr(parser, "subcommands", frozenset())
            if parser is not None
            else frozenset()
        )
        if raw[0] not in known:
            from trivy_tpu.plugin import PluginError, find

            try:
                plugin = find(raw[0])
            except PluginError:
                plugin = None
            if plugin is not None:
                return plugin.run(raw[1:])
    if config_err is not None:
        print(f"trivy-tpu: {config_err}", file=sys.stderr)
        return 2
    args = parser.parse_args(argv)

    from trivy_tpu.log import setup as _setup_logging

    _setup_logging(
        debug=getattr(args, "debug", False),
        quiet=getattr(args, "quiet", False),
        no_color=getattr(args, "no_color", False),
        log_format=getattr(args, "log_format", "console"),
    )

    if args.command in (None, "version"):
        print(f"trivy-tpu version {__version__}")
        return 0

    # --mesh seats the topology override where every engine (scan or
    # server, built now or at a hot reload) resolves it: the env var
    # mesh/topology.get_mesh reads.  Validated here so a typo'd spec is
    # a usage error, not a mid-scan ValueError.
    mesh_spec = getattr(args, "mesh", "")
    if mesh_spec:
        from trivy_tpu.mesh import topology as mesh_topology

        try:
            mesh_topology.parse_spec(mesh_spec)
        except ValueError as e:
            print(f"trivy-tpu: {e}", file=sys.stderr)
            return 2
        os.environ["TRIVY_TPU_MESH"] = mesh_spec

    if args.command == "plugin":
        return _plugin_command(args)

    if args.command == "k8s":
        return _k8s_command(args)

    if args.command == "aws":
        return _aws_command(args)

    if args.command == "convert":
        from trivy_tpu.commands.convert import run_convert

        return run_convert(
            args.report, args.format, args.output, args.severity, args.template
        )

    if args.command == "rules":
        from trivy_tpu.commands.rules import run_rules

        return run_rules(args)

    if args.command == "perf":
        from trivy_tpu.commands.perf import run_perf

        return run_perf(args)

    if args.command == "watch":
        from trivy_tpu.commands.watch import run_watch

        return run_watch(args)

    if args.command == "server":
        from trivy_tpu.registry.store import resolve_rules_cache_dir
        from trivy_tpu.rpc.server import serve
        from trivy_tpu.serve import ServeConfig

        serve(
            args.listen,
            cache_dir=args.cache_dir,
            cache_backend=args.cache_backend,
            cache_ttl=args.cache_ttl,
            token=args.token,
            db_dir=args.db_dir,
            serve_config=ServeConfig(
                batch_window_ms=args.batch_window_ms,
                max_batch_bytes=args.max_batch_bytes,
                max_queue_depth=args.max_queue_depth,
                max_inflight_per_client=args.max_inflight_per_client,
                max_resident_rulesets=args.max_resident_rulesets,
                max_resident_bytes=args.max_resident_mb << 20,
                tenant_rps=args.tenant_rps,
                tenant_burst=args.tenant_burst,
                tenant_bytes_per_s=args.tenant_bytes_per_sec,
                tenant_bytes_burst=args.tenant_bytes_burst,
                max_tenant_series=args.max_tenant_series,
                hbm_soft_pct=args.hbm_soft_pct,
                hbm_hard_pct=args.hbm_hard_pct,
                breaker_threshold=args.breaker_threshold,
                breaker_window_s=args.breaker_window_s,
                breaker_cooldown_s=args.breaker_cooldown_s,
            ),
            secret_config=args.secret_config,
            rules_cache_dir=resolve_rules_cache_dir(args.rules_cache_dir),
            pipeline_depth=args.pipeline_depth,
            resident_chunks=args.resident_chunks,
            profile_dir=args.profile_dir,
            slo_config=args.slo_config,
            flight_out=args.flight_out,
            flight_out_max_mb=args.flight_out_max_mb,
            fleet_config=args.fleet_config,
            fleet_member=args.fleet_member,
            watch_config=args.watch_config,
        )
        return 0

    try:
        options = _options_from_args(args)
        if options.compliance_report not in ("summary", "all"):
            # argparse validates choices only for CLI-supplied values, not
            # env/config-sourced defaults.
            raise ValueError(
                f"--report must be summary or all, got "
                f"{options.compliance_report!r}"
            )
    except ValueError as e:  # e.g. a malformed --timeout duration
        print(f"trivy-tpu: {e}", file=sys.stderr)
        return 2
    if args.command == "config":
        options.scanners = ["misconfig"]
    if getattr(args, "input", ""):
        options.target = args.input
    options.insecure_registry = getattr(args, "insecure", False)
    try:
        return run(options, args.kind)
    except ModuleNotFoundError as e:
        print(f"trivy-tpu: {args.command}: not implemented yet ({e.name})", file=sys.stderr)
        return 2
    except Exception as e:
        from trivy_tpu.cache.redis import RedisError
        from trivy_tpu.cache.s3 import S3Error
        from trivy_tpu.commands.run import (
            CacheConfigError,
            OptionsError,
            ScanTimeoutError,
        )
        from trivy_tpu.compliance.spec import ComplianceError
        from trivy_tpu.db.client import DBError
        from trivy_tpu.image.registry import RegistryError

        from trivy_tpu.iac.rego import RegoError

        if isinstance(
            e,
            (DBError, RegistryError, ScanTimeoutError, ComplianceError,
             RegoError, CacheConfigError, OptionsError, RedisError, S3Error),
        ):
            print(f"trivy-tpu: {e}", file=sys.stderr)
            return 2
        raise


if __name__ == "__main__":
    sys.exit(main())
