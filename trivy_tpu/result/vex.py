"""VEX (Vulnerability Exploitability eXchange) ingestion.

Mirrors pkg/vex/vex.go: OpenVEX and CycloneDX-VEX documents suppress detected
vulnerabilities whose status is not_affected/fixed for the scanned product.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

SUPPRESS_STATUSES = {"not_affected", "fixed"}


@dataclass
class VexDocument:
    # (vuln_id, product purl or "" for any) -> status
    statements: dict[tuple[str, str], str] = field(default_factory=dict)

    def suppressed(self, vuln_id: str, purl: str = "") -> bool:
        for key in ((vuln_id, purl), (vuln_id, "")):
            status = self.statements.get(key)
            if status in SUPPRESS_STATUSES:
                return True
        return False


def load_vex(path: str) -> VexDocument:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if "statements" in data:  # OpenVEX
        return _parse_openvex(data)
    if data.get("bomFormat") == "CycloneDX":  # CycloneDX VEX
        return _parse_cyclonedx_vex(data)
    raise ValueError(f"unrecognized VEX document: {path}")


def _parse_openvex(data: dict) -> VexDocument:
    doc = VexDocument()
    for st in data.get("statements") or []:
        vuln = st.get("vulnerability", "")
        if isinstance(vuln, dict):  # v0.2.0 object form; older docs use a str
            vuln = vuln.get("name", "")
        status = st.get("status", "")
        products = st.get("products") or []
        if not products:
            doc.statements[(vuln, "")] = status
        for p in products:
            pid = p.get("@id", "") if isinstance(p, dict) else str(p)
            doc.statements[(vuln, pid)] = status
    return doc


def _parse_cyclonedx_vex(data: dict) -> VexDocument:
    doc = VexDocument()
    for v in data.get("vulnerabilities") or []:
        vuln_id = v.get("id", "")
        analysis = (v.get("analysis") or {}).get("state", "")
        # CycloneDX states map: not_affected / resolved -> suppress
        status = {
            "not_affected": "not_affected",
            "resolved": "fixed",
            "resolved_with_pedigree": "fixed",
        }.get(analysis, analysis)
        for affect in v.get("affects") or []:
            doc.statements[(vuln_id, affect.get("ref", ""))] = status
        if not v.get("affects"):
            doc.statements[(vuln_id, "")] = status
    return doc


def apply_vex(report, vex: VexDocument) -> None:
    """Filter hook (pkg/result/filter.go VEX step)."""
    from trivy_tpu.purl import package_url

    for result in report.results:
        kept = []
        for v in result.vulnerabilities:
            vid = getattr(v, "vulnerability_id", "")
            purl = ""
            try:
                purl = package_url(
                    result.result_type,
                    getattr(v, "pkg_name", ""),
                    getattr(v, "installed_version", ""),
                )
            except Exception:
                pass
            if not vex.suppressed(vid, purl):
                kept.append(v)
        result.vulnerabilities = kept
