"""VEX (Vulnerability Exploitability eXchange) ingestion.

Mirrors pkg/vex/vex.go: OpenVEX, CycloneDX-VEX, and CSAF documents
suppress detected vulnerabilities whose status is not_affected/fixed for
the scanned product (csaf.go:26-83: CVE match -> product_status range ->
product-tree purl match)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

SUPPRESS_STATUSES = {"not_affected", "fixed"}


@dataclass
class VexDocument:
    # (vuln_id, product purl or "" for any) -> status
    statements: dict[tuple[str, str], str] = field(default_factory=dict)

    def _by_vuln(self) -> dict[str, list[tuple[str, str]]]:
        # vuln_id -> [(purl, status)]: built once so suppressed() stays
        # O(statements-for-this-vuln), not O(all statements) per call.
        if not hasattr(self, "_index"):
            index: dict[str, list[tuple[str, str]]] = {}
            for (vid, vpurl), status in self.statements.items():
                index.setdefault(vid, []).append((vpurl, status))
            self._index = index
        return self._index

    def suppressed(self, vuln_id: str, purl: str = "") -> bool:
        for vpurl, status in self._by_vuln().get(vuln_id, []):
            if status not in SUPPRESS_STATUSES:
                continue
            if vpurl == "" or vpurl == purl:
                return True
            # Versionless VEX purls cover all versions of the package
            # (purl.Match semantics; CSAF trees commonly omit @version).
            if purl and _purl_matches(vpurl, purl):
                return True
        return False


def load_vex(path: str) -> VexDocument:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if "statements" in data:  # OpenVEX
        return _parse_openvex(data)
    if data.get("bomFormat") == "CycloneDX":  # CycloneDX VEX
        return _parse_cyclonedx_vex(data)
    if "document" in data and "vulnerabilities" in data:  # CSAF
        return _parse_csaf(data)
    raise ValueError(f"unrecognized VEX document: {path}")


def _parse_openvex(data: dict) -> VexDocument:
    doc = VexDocument()
    for st in data.get("statements") or []:
        vuln = st.get("vulnerability", "")
        if isinstance(vuln, dict):  # v0.2.0 object form; older docs use a str
            vuln = vuln.get("name", "")
        status = st.get("status", "")
        products = st.get("products") or []
        if not products:
            doc.statements[(vuln, "")] = status
        for p in products:
            pid = p.get("@id", "") if isinstance(p, dict) else str(p)
            doc.statements[(vuln, pid)] = status
    return doc


def _parse_cyclonedx_vex(data: dict) -> VexDocument:
    doc = VexDocument()
    for v in data.get("vulnerabilities") or []:
        vuln_id = v.get("id", "")
        analysis = (v.get("analysis") or {}).get("state", "")
        # CycloneDX states map: not_affected / resolved -> suppress
        status = {
            "not_affected": "not_affected",
            "resolved": "fixed",
            "resolved_with_pedigree": "fixed",
        }.get(analysis, analysis)
        for affect in v.get("affects") or []:
            doc.statements[(vuln_id, affect.get("ref", ""))] = status
        if not v.get("affects"):
            doc.statements[(vuln_id, "")] = status
    return doc


def _csaf_product_purls(tree: dict) -> dict[str, list[str]]:
    """product id -> purls, from the product tree's branches and
    relationships (csaf.go CollectProductIdentificationHelpers)."""
    purls: dict[str, list[str]] = {}

    def walk(branch: dict) -> None:
        product = branch.get("product") or {}
        pid = product.get("product_id", "")
        helper = product.get("product_identification_helper") or {}
        if pid and helper.get("purl"):
            purls.setdefault(pid, []).append(helper["purl"])
        for sub in branch.get("branches") or []:
            walk(sub)

    for b in (tree.get("branches") or []):
        walk(b)
    # Relationship products (e.g. "pkg as a component of product") inherit
    # the purls of the products they reference (csaf.go:96-118).  Chains
    # (pkg -> module -> stream) and forward references need iteration to a
    # fixpoint, not one document-order pass.
    rels = [
        (
            (rel.get("full_product_name") or {}).get("product_id", ""),
            rel.get("product_reference", ""),
        )
        for rel in tree.get("relationships") or []
    ]
    changed = True
    while changed:
        changed = False
        for full, ref in rels:
            if not full or ref not in purls:
                continue
            have = purls.setdefault(full, [])
            new = [p for p in purls[ref] if p not in have]
            if new:
                have.extend(new)
                changed = True
    return purls


def _purl_matches(vex_purl: str, pkg_purl: str) -> bool:
    """Version-insensitive prefix match: a versionless CSAF purl covers
    every version of the package (purl.Match semantics)."""
    if vex_purl == pkg_purl:
        return True
    base = vex_purl.split("?")[0]
    if "@" not in base.rsplit("/", 1)[-1]:
        return pkg_purl.split("?")[0].split("@")[0] == base
    return False


def _parse_csaf(data: dict) -> VexDocument:
    doc = VexDocument()
    product_purls = _csaf_product_purls(data.get("product_tree") or {})
    for vuln in data.get("vulnerabilities") or []:
        cve = vuln.get("cve", "")
        if not cve:
            continue
        status_map = vuln.get("product_status") or {}
        for status_key, status in (
            ("known_not_affected", "not_affected"),
            ("fixed", "fixed"),
        ):
            for pid in status_map.get(status_key) or []:
                for p in product_purls.get(pid, []):
                    doc.statements[(cve, p)] = status
    return doc


def apply_vex(report, vex: VexDocument) -> None:
    """Filter hook (pkg/result/filter.go VEX step)."""
    from trivy_tpu.purl import package_url

    for result in report.results:
        kept = []
        for v in result.vulnerabilities:
            vid = getattr(v, "vulnerability_id", "")
            purl = ""
            try:
                purl = package_url(
                    result.result_type,
                    getattr(v, "pkg_name", ""),
                    getattr(v, "installed_version", ""),
                )
            except Exception:
                pass
            if not vex.suppressed(vid, purl):
                kept.append(v)
        result.vulnerabilities = kept
