"""Result filtering: severity, ignore files.

Mirrors pkg/result/filter.go:39 Filter — severity filtering per finding class
and `.trivyignore` / `.trivyignore.yaml` suppression (filter.go:115-177).
VEX and OPA ignore-policy hooks keep the same call shape and land later.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import yaml

from trivy_tpu.ftypes import Report, Result

SEVERITIES = ["UNKNOWN", "LOW", "MEDIUM", "HIGH", "CRITICAL"]


@dataclass
class IgnoreFinding:
    """One .trivyignore(.yaml) entry (pkg/result/ignore.go)."""

    id: str
    paths: list[str] = field(default_factory=list)


@dataclass
class IgnoreConfig:
    vulnerabilities: list[IgnoreFinding] = field(default_factory=list)
    misconfigurations: list[IgnoreFinding] = field(default_factory=list)
    secrets: list[IgnoreFinding] = field(default_factory=list)
    licenses: list[IgnoreFinding] = field(default_factory=list)

    def match(self, kind: str, finding_id: str, path: str) -> bool:
        entries = getattr(self, kind)
        for e in entries:
            if e.id != finding_id:
                continue
            if not e.paths:
                return True
            import fnmatch

            if any(fnmatch.fnmatch(path, p) for p in e.paths):
                return True
        return False


def parse_ignore_file(path: str) -> IgnoreConfig:
    """Parses both the flat .trivyignore (one ID per line, # comments) and the
    YAML .trivyignore.yaml schema (ignore.go)."""
    cfg = IgnoreConfig()
    if not path or not os.path.exists(path):
        return cfg
    if path.endswith((".yml", ".yaml")):
        with open(path, encoding="utf-8") as f:
            raw = yaml.safe_load(f) or {}
        for kind in ("vulnerabilities", "misconfigurations", "secrets", "licenses"):
            for item in raw.get(kind) or []:
                getattr(cfg, kind).append(
                    IgnoreFinding(
                        id=item.get("id", ""), paths=list(item.get("paths") or [])
                    )
                )
        return cfg
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            fid = line.split()[0]
            # The flat file applies to every finding class.
            for kind in ("vulnerabilities", "misconfigurations", "secrets", "licenses"):
                getattr(cfg, kind).append(IgnoreFinding(id=fid))
    return cfg


@dataclass
class FilterOptions:
    severities: list[str] = field(default_factory=lambda: list(SEVERITIES))
    ignore_file: str = ""
    include_non_failures: bool = False
    vex_path: str = ""
    ignore_policy: str = ""  # --ignore-policy rego file (filter.go:242)


def filter_report(report: Report, options: FilterOptions) -> Report:
    """result.Filter (filter.go:39)."""
    if options.vex_path:
        from trivy_tpu.result.vex import apply_vex, load_vex

        apply_vex(report, load_vex(options.vex_path))
    ignore = parse_ignore_file(options.ignore_file)
    allowed = set(options.severities)
    policy = (
        _load_ignore_policy(options.ignore_policy)
        if options.ignore_policy
        else None
    )
    for result in report.results:
        _filter_result(result, allowed, ignore, options)
        if policy is not None:
            _apply_ignore_policy(result, policy)
    return report


def _load_ignore_policy(path: str):
    """--ignore-policy: a rego module whose boolean `ignore` rule decides
    per finding (filter.go:242-343, query data.trivy.ignore)."""
    from trivy_tpu.iac.rego import RegoError, parse_module

    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    except OSError as e:
        raise RegoError(f"cannot read ignore policy {path!r}: {e}") from e
    mod = parse_module(src, source_path=path)
    if "ignore" not in mod.rules:
        raise RegoError(f"ignore policy {path!r} defines no 'ignore' rule")
    return mod


def _policy_ignores(mod, finding_json: dict) -> bool:
    from trivy_tpu.iac.rego import _Evaluator, _Undefined

    ev = _Evaluator(finding_json, mod.rules)
    try:
        return bool(ev.eval_complete_rule("ignore"))
    except _Undefined:
        # Undefined result => not ignored (filter.go evaluate: undefined
        # handled as false).  Evaluator ERRORS (unknown builtin, step
        # limit) propagate — a broken policy must not read as "nothing
        # ignored" (the reference fails the run).
        return False


def _apply_ignore_policy(result: Result, mod) -> None:
    result.vulnerabilities = [
        v for v in result.vulnerabilities if not _policy_ignores(mod, v.to_json())
    ]
    result.misconfigurations = [
        m
        for m in result.misconfigurations
        if not _policy_ignores(mod, m.to_json())
    ]
    result.secrets = [
        s for s in result.secrets if not _policy_ignores(mod, s.to_json())
    ]
    result.licenses = [
        l
        for l in result.licenses
        if not _policy_ignores(
            mod, l.to_json() if hasattr(l, "to_json") else {}
        )
    ]


def _filter_result(
    result: Result,
    allowed: set[str],
    ignore: IgnoreConfig,
    options: FilterOptions,
) -> None:
    result.vulnerabilities = [
        v
        for v in result.vulnerabilities
        if (getattr(v, "severity", "UNKNOWN") or "UNKNOWN") in allowed
        and not ignore.match(
            "vulnerabilities",
            getattr(v, "vulnerability_id", ""),
            result.target,
        )
    ]
    result.secrets = [
        s
        for s in result.secrets
        if (s.severity or "UNKNOWN") in allowed
        and not ignore.match("secrets", s.rule_id, result.target)
    ]
    result.misconfigurations = [
        m
        for m in result.misconfigurations
        if (getattr(m, "severity", "UNKNOWN") or "UNKNOWN") in allowed
        and (options.include_non_failures or getattr(m, "status", "FAIL") == "FAIL")
        and not ignore.match(
            "misconfigurations",
            getattr(m, "check_id", "") or getattr(m, "id", ""),
            result.target,
        )
    ]
    result.licenses = [
        l
        for l in result.licenses
        if (getattr(l, "severity", "UNKNOWN") or "UNKNOWN") in allowed
        and not ignore.match("licenses", getattr(l, "name", ""), result.target)
    ]
