from trivy_tpu.result.filter import FilterOptions, filter_report

__all__ = ["FilterOptions", "filter_report"]
