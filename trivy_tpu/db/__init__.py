from trivy_tpu.db.vulndb import Advisory, VulnDB, build_db, load_db

__all__ = ["Advisory", "VulnDB", "build_db", "load_db"]
