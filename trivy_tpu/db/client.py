"""Vulnerability DB distribution client (pkg/db/db.go analogue).

The reference pulls trivy-db — a BoltDB inside a tar.gz layer of an OCI
artifact — and gates downloads on metadata.json (schema version,
NextUpdate, DownloadedAt).  This client keeps the exact update semantics
(NeedsUpdate, db.go:96; the one-hour throttle, db.go:139 isNewDB; the
skip-update validation) over this framework's DB wire format: a tar.gz of
the JSON source buckets (db/vulndb.py layout) as the OCI layer

    application/vnd.trivy-tpu.db.layer.v1.tar+gzip

The BoltDB wire format itself is a deliberate divergence: the logical
schema (source buckets -> package -> advisories) is preserved, the byte
format is not; fixture DBs build with `build_db_archive` (the pkg/dbtest
pattern).
"""

from __future__ import annotations

import datetime as _dt
import io
import json
import logging
import os
import tarfile
from dataclasses import dataclass, field

SCHEMA_VERSION = 2
MEDIA_TYPE = "application/vnd.trivy-tpu.db.layer.v1.tar+gzip"
DEFAULT_REPOSITORY = "ghcr.io/aquasecurity/trivy-db:2"

logger = logging.getLogger(__name__)


def _parse_time(s: str) -> _dt.datetime:
    if not s:
        return _dt.datetime.fromtimestamp(0, _dt.timezone.utc)
    t = _dt.datetime.fromisoformat(s.replace("Z", "+00:00"))
    if t.tzinfo is None:  # tolerate suffix-less timestamps as UTC
        t = t.replace(tzinfo=_dt.timezone.utc)
    return t


@dataclass
class Metadata:
    """metadata.json (trivy-db metadata.Metadata)."""

    version: int = SCHEMA_VERSION
    next_update: str = ""
    updated_at: str = ""
    downloaded_at: str = ""

    def to_json(self) -> dict:
        return {
            "Version": self.version,
            "NextUpdate": self.next_update,
            "UpdatedAt": self.updated_at,
            "DownloadedAt": self.downloaded_at,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Metadata":
        return cls(
            version=int(d.get("Version", 0)),
            next_update=d.get("NextUpdate", ""),
            updated_at=d.get("UpdatedAt", ""),
            downloaded_at=d.get("DownloadedAt", ""),
        )


class DBError(RuntimeError):
    pass


@dataclass
class DBClient:
    """Update gating + download for the vuln DB directory."""

    db_dir: str
    repository: str = DEFAULT_REPOSITORY
    insecure: bool = False
    clock: object = field(default=None)  # injectable for tests (clock fake)

    def _now(self) -> _dt.datetime:
        if self.clock is not None:
            return self.clock()  # type: ignore[operator]
        return _dt.datetime.now(_dt.timezone.utc)

    @property
    def _meta_path(self) -> str:
        return os.path.join(self.db_dir, "metadata.json")

    def metadata(self) -> Metadata | None:
        try:
            with open(self._meta_path, encoding="utf-8") as f:
                return Metadata.from_json(json.load(f))
        except (OSError, ValueError):
            return None

    def needs_update(self, skip: bool = False) -> bool:
        """db.go:96 NeedsUpdate."""
        meta = self.metadata()
        if meta is None:
            if skip:
                raise DBError(
                    "--skip-db-update cannot be specified on the first run"
                )
            meta = Metadata(version=SCHEMA_VERSION)
        if SCHEMA_VERSION < meta.version:
            raise DBError(
                f"the version of DB schema doesn't match. Local DB: "
                f"{meta.version}, Expected: {SCHEMA_VERSION}"
            )
        if skip:
            if meta.version != SCHEMA_VERSION:
                raise DBError(
                    "--skip-db-update cannot be specified with the old DB "
                    f"schema. Local DB: {meta.version}, Expected: {SCHEMA_VERSION}"
                )
            return False
        if meta.version != SCHEMA_VERSION:
            return True
        return not self._is_new_db(meta)

    def _is_new_db(self, meta: Metadata) -> bool:
        """db.go:139 isNewDB: fresh enough to skip a download."""
        now = self._now()
        if meta.next_update and now < _parse_time(meta.next_update):
            logger.debug("DB update skipped: local DB is the latest")
            return True
        if meta.downloaded_at and now < _parse_time(
            meta.downloaded_at
        ) + _dt.timedelta(hours=1):
            logger.debug("DB update skipped: downloaded within the last hour")
            return True
        return False

    def download(self) -> None:
        """db.go:153 Download: drop stale metadata, pull the OCI layer,
        extract, stamp DownloadedAt."""
        from trivy_tpu.oci import OciArtifact

        try:
            os.unlink(self._meta_path)
        except OSError:
            pass
        os.makedirs(self.db_dir, exist_ok=True)
        art = OciArtifact(self.repository, insecure=self.insecure)
        extracted: set[str] = set()
        with art.download_layer(MEDIA_TYPE) as blob:
            with tarfile.open(fileobj=blob, mode="r:*") as tf:
                for member in tf.getmembers():
                    if not member.isfile() or ".." in member.name:
                        continue
                    name = os.path.basename(member.name)
                    extracted.add(name)
                    with open(os.path.join(self.db_dir, name), "wb") as out:
                        out.write(tf.extractfile(member).read())
        # A pre-existing trivy.db takes priority in load_db; if this
        # artifact did not ship one, drop the stale copy so the fresh
        # bucket files are what scans actually read.
        if "trivy.db" not in extracted:
            try:
                os.unlink(os.path.join(self.db_dir, "trivy.db"))
            except OSError:
                pass
        meta = self.metadata() or Metadata(version=SCHEMA_VERSION)
        meta.downloaded_at = (
            self._now().isoformat().replace("+00:00", "Z")
        )
        with open(self._meta_path, "w", encoding="utf-8") as f:
            json.dump(meta.to_json(), f)

    def ensure(self, skip: bool = False) -> bool:
        """Download when needed; returns True when a download happened."""
        if self.needs_update(skip=skip):
            logger.info("Downloading vulnerability DB from %s", self.repository)
            self.download()
            return True
        return False


def build_db_archive(
    buckets: dict[str, dict], next_update: str = "", updated_at: str = ""
) -> bytes:
    """Build a DB artifact layer from source buckets (the pkg/dbtest
    fixture-DB pattern): {source: {pkg_name: [advisory dicts]}} ->
    tar.gz bytes containing <source>.json files + metadata.json."""
    import gzip

    from trivy_tpu.db.vulndb import _bucket_file

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:

        def add(name: str, data: bytes) -> None:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))

        for source, packages in buckets.items():
            add(_bucket_file(source), json.dumps(packages).encode())
        add(
            "metadata.json",
            json.dumps(
                Metadata(
                    version=SCHEMA_VERSION,
                    next_update=next_update,
                    updated_at=updated_at,
                ).to_json()
            ).encode(),
        )
    return gzip.compress(buf.getvalue())
