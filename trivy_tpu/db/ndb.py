"""rpm ndb database reader (SUSE's Packages.db), read-only, from scratch.

The third and last rpmdb on-disk format (rpm's lib/backend/ndb/rpmpkg.c;
the reference reads it via go-rpmdb's pkg/ndb): SLE 15 and openSUSE
Tumbleweed ship it as /var/lib/rpm/Packages.db.  Layout (little-endian):

* header (32 bytes — two slot widths): magic "RpmP", version,
  generation, slot-page count, next pkg index, pad;
* slot area: from byte 32, `SlotNPages` 4096-byte pages of 16-byte slot
  entries {magic "Slot", pkg index, blk offset, blk count}; EVERY slot
  carries the magic (free slots have pkg index 0) — a slot without it is
  a torn/corrupt database and errors hard, like go-rpmdb;
* blobs: at blk offset * 16 — a 16-byte blob header {magic "BlbS", pkg
  index, generation, blob length} followed by the rpm header blob.

Malformed structure raises NdbError — a package DB that cannot be read
must be loud, never an empty inventory.
"""

from __future__ import annotations

import struct
from typing import Iterator

NDB_HEADER_MAGIC = 0x506D7052  # "RpmP"
NDB_SLOT_MAGIC = 0x746F6C53  # "Slot"
NDB_BLOB_MAGIC = 0x53626C42  # "BlbS"
_SLOT_PAGE = 4096
_BLK = 16


class NdbError(RuntimeError):
    pass


def is_ndb(content: bytes) -> bool:
    return (
        len(content) >= 4
        and struct.unpack_from("<I", content, 0)[0] == NDB_HEADER_MAGIC
    )


class NdbReader:
    def __init__(self, data: bytes):
        if len(data) < 16:
            raise NdbError("ndb: file too small")
        magic, self.version, self.generation, self.slot_npages = (
            struct.unpack_from("<IIII", data, 0)
        )
        if magic != NDB_HEADER_MAGIC:
            raise NdbError("ndb: bad header magic")
        if not 0 < self.slot_npages <= 1 << 20:
            raise NdbError(f"ndb: implausible slot page count {self.slot_npages}")
        self.data = data

    def values(self) -> Iterator[bytes]:
        """Every stored rpm header blob, in slot order."""
        slots_end = self.slot_npages * _SLOT_PAGE
        if slots_end > len(self.data):
            raise NdbError("ndb: slot area beyond EOF")
        # The 32-byte header occupies the first two slot widths of page 0.
        for off in range(32, slots_end, 16):
            smagic, index, blkoff, blkcnt = struct.unpack_from(
                "<IIII", self.data, off
            )
            if smagic != NDB_SLOT_MAGIC:
                raise NdbError(
                    f"ndb: bad slot magic at {off} (torn database?)"
                )
            if index == 0:
                continue  # free slot
            byte0 = blkoff * _BLK
            if byte0 + 16 > len(self.data):
                raise NdbError(f"ndb: slot {index} blob beyond EOF")
            bmagic, bindex, _bgen, blen = struct.unpack_from(
                "<IIII", self.data, byte0
            )
            if bmagic != NDB_BLOB_MAGIC:
                raise NdbError(f"ndb: slot {index}: bad blob magic")
            if bindex != index:
                raise NdbError(
                    f"ndb: slot {index} points at blob of package {bindex}"
                )
            if byte0 + 16 + blen > len(self.data):
                raise NdbError(f"ndb: blob {index} truncated")
            if 16 + blen > blkcnt * _BLK:  # span includes the blob header
                raise NdbError(f"ndb: blob {index} longer than its blocks")
            yield bytes(self.data[byte0 + 16 : byte0 + 16 + blen])
