"""Vulnerability database.

The trivy-db analogue (pkg/db): advisories keyed by (data source, package
name).  The reference ships a BoltDB pulled from an OCI registry; this
framework uses a JSON tree on disk with the same logical schema, built either
from fixture YAML (the pkg/dbtest pattern, §4) or downloaded via the OCI
client (trivy_tpu/db/oci.py) in connected deployments.

Layout: <db_dir>/metadata.json + <db_dir>/<source-bucket>.json where a source
bucket is e.g. "alpine 3.15", "debian 11", "npm", "pip".
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any


@dataclass
class Advisory:
    """db.Advisory (trivy-db types)."""

    vulnerability_id: str
    fixed_version: str = ""
    vulnerable_versions: str = ""  # range expr for language ecosystems
    severity: str = ""
    title: str = ""
    description: str = ""
    references: list[str] = field(default_factory=list)
    cvss_score: float = 0.0
    # source id -> severity string (trivy-db VendorSeverity); consumed by
    # the severity-source precedence resolution (detector/severity.py)
    severity_sources: dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {"VulnerabilityID": self.vulnerability_id}
        if self.fixed_version:
            out["FixedVersion"] = self.fixed_version
        if self.vulnerable_versions:
            out["VulnerableVersions"] = self.vulnerable_versions
        if self.severity:
            out["Severity"] = self.severity
        if self.title:
            out["Title"] = self.title
        if self.description:
            out["Description"] = self.description
        if self.references:
            out["References"] = self.references
        if self.cvss_score:
            out["CVSSScore"] = self.cvss_score
        if self.severity_sources:
            out["VendorSeverity"] = dict(self.severity_sources)
        return out

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Advisory":
        return cls(
            vulnerability_id=d.get("VulnerabilityID", ""),
            fixed_version=d.get("FixedVersion", ""),
            vulnerable_versions=d.get("VulnerableVersions", ""),
            severity=d.get("Severity", ""),
            title=d.get("Title", ""),
            description=d.get("Description", ""),
            severity_sources=dict(d.get("VendorSeverity") or {}),
            references=list(d.get("References") or []),
            cvss_score=d.get("CVSSScore", 0.0),
        )


def _bucket_file(source: str) -> str:
    return source.replace("/", "_").replace(" ", "_") + ".json"


class VulnDB:
    """Get-side interface (trivy-db db.Operation)."""

    def __init__(self, db_dir: str):
        self.db_dir = db_dir
        self._cache: dict[str, dict[str, list[Advisory]]] = {}

    def advisories(self, source: str, pkg_name: str) -> list[Advisory]:
        bucket = self._load(source)
        return bucket.get(pkg_name, [])

    def _load(self, source: str) -> dict[str, list[Advisory]]:
        if source in self._cache:
            return self._cache[source]
        path = os.path.join(self.db_dir, _bucket_file(source))
        bucket: dict[str, list[Advisory]] = {}
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                raw = json.load(f)
            for pkg, advs in raw.items():
                bucket[pkg] = [Advisory.from_json(a) for a in advs]
        self._cache[source] = bucket
        return bucket

    def metadata(self) -> dict[str, Any]:
        path = os.path.join(self.db_dir, "metadata.json")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        return {}


def build_db(
    db_dir: str, data: dict[str, dict[str, list[Advisory | dict]]]
) -> None:
    """Fixture DB builder (the pkg/dbtest InitDB pattern):
    data = {source: {pkg_name: [Advisory|dict, ...]}}."""
    os.makedirs(db_dir, exist_ok=True)
    for source, packages in data.items():
        out = {
            pkg: [
                a.to_json() if isinstance(a, Advisory) else a for a in advs
            ]
            for pkg, advs in packages.items()
        }
        with open(os.path.join(db_dir, _bucket_file(source)), "w") as f:
            json.dump(out, f, indent=1)
    meta = {"Version": 2, "UpdatedAt": "fixture"}
    with open(os.path.join(db_dir, "metadata.json"), "w") as f:
        json.dump(meta, f)


def load_db(db_dir: str) -> VulnDB | None:
    if db_dir and os.path.isdir(db_dir):
        return VulnDB(db_dir)
    return None
