"""Vulnerability database.

The trivy-db analogue (pkg/db): advisories keyed by (data source, package
name).  The reference ships a BoltDB pulled from an OCI registry; this
framework uses a JSON tree on disk with the same logical schema, built either
from fixture YAML (the pkg/dbtest pattern, §4) or downloaded via the OCI
client (trivy_tpu/db/oci.py) in connected deployments.

Layout: <db_dir>/metadata.json + <db_dir>/<source-bucket>.json where a source
bucket is e.g. "alpine 3.15", "debian 11", "npm", "pip".
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any


@dataclass
class Advisory:
    """db.Advisory (trivy-db types)."""

    vulnerability_id: str
    fixed_version: str = ""
    vulnerable_versions: str = ""  # range expr for language ecosystems
    severity: str = ""
    title: str = ""
    description: str = ""
    references: list[str] = field(default_factory=list)
    cvss_score: float = 0.0
    # source id -> severity string (trivy-db VendorSeverity); consumed by
    # the severity-source precedence resolution (detector/severity.py)
    severity_sources: dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {"VulnerabilityID": self.vulnerability_id}
        if self.fixed_version:
            out["FixedVersion"] = self.fixed_version
        if self.vulnerable_versions:
            out["VulnerableVersions"] = self.vulnerable_versions
        if self.severity:
            out["Severity"] = self.severity
        if self.title:
            out["Title"] = self.title
        if self.description:
            out["Description"] = self.description
        if self.references:
            out["References"] = self.references
        if self.cvss_score:
            out["CVSSScore"] = self.cvss_score
        if self.severity_sources:
            out["VendorSeverity"] = dict(self.severity_sources)
        return out

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Advisory":
        return cls(
            vulnerability_id=d.get("VulnerabilityID", ""),
            fixed_version=d.get("FixedVersion", ""),
            vulnerable_versions=d.get("VulnerableVersions", ""),
            severity=d.get("Severity", ""),
            title=d.get("Title", ""),
            description=d.get("Description", ""),
            severity_sources=dict(d.get("VendorSeverity") or {}),
            references=list(d.get("References") or []),
            cvss_score=d.get("CVSSScore", 0.0),
        )


def _bucket_file(source: str) -> str:
    return source.replace("/", "_").replace(" ", "_") + ".json"


class VulnDB:
    """Get-side interface (trivy-db db.Operation)."""

    def __init__(self, db_dir: str):
        self.db_dir = db_dir
        self._cache: dict[str, dict[str, list[Advisory]]] = {}

    def advisories(self, source: str, pkg_name: str) -> list[Advisory]:
        bucket = self._load(source)
        return bucket.get(pkg_name, [])

    def _load(self, source: str) -> dict[str, list[Advisory]]:
        if source in self._cache:
            return self._cache[source]
        path = os.path.join(self.db_dir, _bucket_file(source))
        bucket: dict[str, list[Advisory]] = {}
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                raw = json.load(f)
            for pkg, advs in raw.items():
                bucket[pkg] = [Advisory.from_json(a) for a in advs]
        self._cache[source] = bucket
        return bucket

    def metadata(self) -> dict[str, Any]:
        return _read_metadata(self.db_dir)


def _read_metadata(db_dir: str) -> dict[str, Any]:
    path = os.path.join(db_dir, "metadata.json")
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    return {}


def build_db(
    db_dir: str, data: dict[str, dict[str, list[Advisory | dict]]]
) -> None:
    """Fixture DB builder (the pkg/dbtest InitDB pattern):
    data = {source: {pkg_name: [Advisory|dict, ...]}}."""
    os.makedirs(db_dir, exist_ok=True)
    for source, packages in data.items():
        out = {
            pkg: [
                a.to_json() if isinstance(a, Advisory) else a for a in advs
            ]
            for pkg, advs in packages.items()
        }
        with open(os.path.join(db_dir, _bucket_file(source)), "w") as f:
            json.dump(out, f, indent=1)
    meta = {"Version": 2, "UpdatedAt": "fixture"}
    with open(os.path.join(db_dir, "metadata.json"), "w") as f:
        json.dump(meta, f)


def _sev_str(v: Any) -> str:
    """trivy-db serializes severities as int enums; tolerate strings."""
    from trivy_tpu.result.filter import SEVERITIES

    if isinstance(v, int) and 0 <= v < len(SEVERITIES):
        return SEVERITIES[v]
    if isinstance(v, str):
        return v
    return ""


# Internal detector source prefix -> real trivy-db OS bucket template.
# The detectors build "redhat 8"-style sources (detector/ospkg.py); real
# trivy-db names several OS buckets differently.  Candidates are matched
# case-insensitively, with a ".0"-tolerant prefix (mariner "2" vs
# "CBL-Mariner 2.0").
_OS_BUCKET_ALIASES = {
    "redhat": "Red Hat Enterprise Linux {v}",
    "centos": "CentOS {v}",
    "amazon": "amazon linux {v}",
    "oracle": "Oracle Linux {v}",
    "photon": "Photon OS {v}",
    "cbl-mariner": "CBL-Mariner {v}",
    "suse": "SUSE Linux Enterprise {v}",
    "opensuse-leap": "openSUSE Leap {v}",
}


class BoltVulnDB:
    """Get-side interface over a REAL trivy-db file (`trivy.db`, bbolt).

    Bucket schema (trivy-db v2): <source bucket> -> <package> ->
    {vulnID: advisory JSON}; root "vulnerability" -> {vulnID: detail JSON}
    enriches severity/title/references.  Read through trivy_tpu.db.bolt —
    the artifact the reference downloads drops in unchanged."""

    def __init__(self, db_dir: str):
        from trivy_tpu.db.bolt import Bolt

        self.db_dir = db_dir
        self._bolt = Bolt.open(os.path.join(db_dir, "trivy.db"))
        self._details: dict[str, dict] = {}
        self._vuln_bucket = self._bolt.bucket(b"vulnerability")
        # Language buckets are "<ecosystem>::<data source name>"
        # (trivy-db bucket.go); the detectors query by plain ecosystem, so
        # resolve each source to every matching bucket once.
        self._source_buckets: dict[str, list[bytes]] = {}
        self._top_names: list[bytes] | None = None

    def _buckets_for(self, source: str) -> list[bytes]:
        hit = self._source_buckets.get(source)
        if hit is not None:
            return hit
        if self._top_names is None:
            self._top_names = [name for name, _b in self._bolt.buckets()]
        want = source.encode()
        prefix = want + b"::"
        names = [
            n for n in self._top_names if n == want or n.startswith(prefix)
        ]
        if not names:
            # OS bucket alias pass (exact internal name matched nothing).
            cands = {source.lower()}
            word, _, ver = source.partition(" ")
            tmpl = _OS_BUCKET_ALIASES.get(word)
            if tmpl and ver:
                cands.add(tmpl.format(v=ver).lower())
            names = [
                n
                for n in self._top_names
                if n.decode("utf-8", "replace").lower() in cands
                or any(
                    n.decode("utf-8", "replace").lower() == f"{c}.0"
                    or n.decode("utf-8", "replace").lower().startswith(
                        f"{c}."
                    )
                    for c in cands
                )
            ]
        self._source_buckets[source] = names
        return names

    def _detail(self, vuln_id: str) -> dict:
        if vuln_id in self._details:
            return self._details[vuln_id]
        out: dict = {}
        if self._vuln_bucket is not None:
            raw = self._vuln_bucket.get(vuln_id.encode())
            if raw:
                try:
                    out = json.loads(raw)
                except ValueError:
                    out = {}
        self._details[vuln_id] = out
        return out

    def advisories(self, source: str, pkg_name: str) -> list[Advisory]:
        out: list[Advisory] = []
        for bname in self._buckets_for(source):
            bucket = self._bolt.bucket(bname, pkg_name.encode())
            if bucket is not None:
                self._collect(bucket, out)
        return out

    def _collect(self, bucket, out: list[Advisory]) -> None:
        for vid_b, raw in bucket.items():
            vid = vid_b.decode("utf-8", "replace")
            try:
                d = json.loads(raw)
            except ValueError:
                continue
            det = self._detail(vid)
            fixed = d.get("FixedVersion", "")
            patched = d.get("PatchedVersions") or []
            if not fixed and patched:
                fixed = ", ".join(patched)
            vulnerable = " || ".join(d.get("VulnerableVersions") or [])
            cvss = 0.0
            for src in ("nvd", "redhat", "ghsa"):
                sc = (det.get("CVSS") or {}).get(src) or {}
                if sc.get("V3Score"):
                    cvss = float(sc["V3Score"])
                    break
            out.append(
                Advisory(
                    vulnerability_id=vid,
                    fixed_version=fixed,
                    vulnerable_versions=vulnerable,
                    severity=_sev_str(
                        d.get("Severity", det.get("Severity", 0))
                    ),
                    title=det.get("Title", ""),
                    description=det.get("Description", ""),
                    references=list(det.get("References") or []),
                    cvss_score=cvss,
                    severity_sources={
                        k: _sev_str(v)
                        for k, v in (det.get("VendorSeverity") or {}).items()
                    },
                )
            )

    def metadata(self) -> dict[str, Any]:
        return _read_metadata(self.db_dir)


def load_db(db_dir: str) -> "VulnDB | BoltVulnDB | None":
    if not db_dir or not os.path.isdir(db_dir):
        return None
    if os.path.exists(os.path.join(db_dir, "trivy.db")):
        from trivy_tpu.db.bolt import BoltError

        try:
            return BoltVulnDB(db_dir)
        except (BoltError, OSError) as e:
            # A torn download must degrade with a pointer, not kill every
            # scan with a traceback.
            import logging

            logging.getLogger(__name__).warning(
                "trivy.db unreadable (%s); falling back to JSON buckets — "
                "re-download with --db-repository to repair",
                e,
            )
    return VulnDB(db_dir)
