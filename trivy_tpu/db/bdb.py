"""Berkeley DB hash-database reader (read-only, from scratch).

The legacy rpmdb (`/var/lib/rpm/Packages` on RHEL/CentOS <= 8, Amazon
Linux 2) is a BDB hash database; the reference reads it through
go-rpmdb's pkg/bdb.  This is the same minimal subset, in pure Python:

* metadata page 0: magic 0x00061561 at byte 12 (either endianness — the
  file is written in its creator's byte order), page size at byte 20,
  last page number at byte 32;
* page header (26 bytes): next-page at 16, entry count at 20, free-area
  offset at 22, page type at byte 25;
* hash pages (type 2 unsorted / 13 sorted): entry-count u16 slot indices
  follow the header, alternating key/value entries.  Inline values are
  H_KEYDATA (type byte 1, data to the next-higher slot boundary);
  large values are H_OFFPAGE (type byte 3): a {pgno, tlen} pointer to a
  chain of overflow pages (type 7) whose data regions concatenate to
  tlen bytes.

rpm's Packages db stores one rpm header blob per value; keys are record
numbers and are ignored here.  Soundness bias: malformed structure
raises BdbError — a package DB that cannot be read must be loud, never
an empty inventory.
"""

from __future__ import annotations

import struct
from typing import Iterator

HASH_MAGIC = 0x00061561

_P_OVERFLOW = 7
_P_HASH_UNSORTED = 2
_P_HASH = 13
_H_KEYDATA = 1
_H_OFFPAGE = 3
_PAGE_HEADER = 26


class BdbError(RuntimeError):
    pass


class BdbHashReader:
    def __init__(self, data: bytes):
        self.data = data
        if len(data) < 512:
            raise BdbError("bdb: file too small")
        magic_le = struct.unpack_from("<I", data, 12)[0]
        magic_be = struct.unpack_from(">I", data, 12)[0]
        if magic_le == HASH_MAGIC:
            self._e = "<"
        elif magic_be == HASH_MAGIC:
            self._e = ">"
        else:
            raise BdbError("bdb: not a hash database (bad magic)")
        self.pagesize = struct.unpack_from(self._e + "I", data, 20)[0]
        if not 512 <= self.pagesize <= 65536:
            raise BdbError(f"bdb: implausible page size {self.pagesize}")
        self.last_pgno = self._u32(0, 32)

    # -- field readers (db-endian) -------------------------------------

    def _page(self, pgno: int) -> bytes:
        off = pgno * self.pagesize
        if off + self.pagesize > len(self.data):
            raise BdbError(f"bdb: page {pgno} out of range")
        return self.data[off : off + self.pagesize]

    def _u32(self, pgno: int, off: int) -> int:
        return struct.unpack_from(
            self._e + "I", self.data, pgno * self.pagesize + off
        )[0]

    def _u16(self, page: bytes, off: int) -> int:
        return struct.unpack_from(self._e + "H", page, off)[0]

    # -- value iteration ------------------------------------------------

    def values(self) -> Iterator[bytes]:
        """Every stored value, in page order."""
        npages = min(self.last_pgno + 1, len(self.data) // self.pagesize)
        for pgno in range(1, npages):
            page = self._page(pgno)
            if page[25] not in (_P_HASH_UNSORTED, _P_HASH):
                continue
            n = self._u16(page, 20)
            if _PAGE_HEADER + 2 * n > self.pagesize:
                raise BdbError(f"bdb: page {pgno} entry count {n} overflows")
            slots = [
                self._u16(page, _PAGE_HEADER + 2 * i) for i in range(n)
            ]
            bounds = sorted(o for o in slots if o)
            for vi in slots[1::2]:  # entries alternate key, value
                if not _PAGE_HEADER <= vi < self.pagesize:
                    raise BdbError(f"bdb: page {pgno} slot {vi} out of range")
                etype = page[vi]
                if etype == _H_KEYDATA:
                    nxt = next(
                        (b for b in bounds if b > vi), self.pagesize
                    )
                    yield bytes(page[vi + 1 : nxt])
                elif etype == _H_OFFPAGE:
                    if vi + 12 > self.pagesize:
                        raise BdbError("bdb: truncated H_OFFPAGE entry")
                    opgno = struct.unpack_from(self._e + "I", page, vi + 4)[0]
                    tlen = struct.unpack_from(self._e + "I", page, vi + 8)[0]
                    yield self._overflow(opgno, tlen)
                else:
                    raise BdbError(
                        f"bdb: unsupported entry type {etype} on page {pgno}"
                    )

    def _overflow(self, pgno: int, tlen: int) -> bytes:
        out = bytearray()
        seen: set[int] = set()
        while pgno != 0 and len(out) < tlen:
            if pgno in seen:
                raise BdbError("bdb: overflow chain cycle")
            seen.add(pgno)
            page = self._page(pgno)
            if page[25] != _P_OVERFLOW:
                raise BdbError(
                    f"bdb: page {pgno} in overflow chain is type {page[25]}"
                )
            nxt = struct.unpack_from(self._e + "I", page, 16)[0]
            if nxt:
                out += page[_PAGE_HEADER:]
            else:
                used = self._u16(page, 22)
                out += page[_PAGE_HEADER : _PAGE_HEADER + used]
            pgno = nxt
        if len(out) < tlen:
            raise BdbError("bdb: overflow chain shorter than declared length")
        return bytes(out[:tlen])


def is_bdb_hash(content: bytes) -> bool:
    if len(content) < 16:
        return False
    le, be = struct.unpack_from("<I", content, 12)[0], struct.unpack_from(
        ">I", content, 12
    )[0]
    return HASH_MAGIC in (le, be)
