"""Read-only BoltDB (bbolt) file reader.

The reference's vulnerability DB, Java index DB, and scan cache are bbolt
files (pkg/db/db.go, pkg/javadb/client.go, pkg/fanal/cache/fs.go).  This
module reads that exact on-disk format so a real `trivy.db` artifact drops
in unchanged — pure Python, no bbolt dependency, no write support (the
scanner only ever Gets).

bbolt layout (stable since boltdb v1):

  page      = id(u64) flags(u16) count(u16) overflow(u32) payload...
  meta      = magic(0xED0CDAED u32) version(2 u32) pageSize(u32) flags(u32)
              root{pgid u64, sequence u64} freelist(u64) pgid(u64)
              txid(u64) checksum(u64 = fnv64a of the 56 bytes before it)
  branchElem= pos(u32) ksize(u32) pgid(u64); key at elemOffset+pos
  leafElem  = flags(u32) pos(u32) ksize(u32) vsize(u32); key+value at
              elemOffset+pos; flags&1 -> value is a child bucket
  bucket val= root(u64) sequence(u64) [+ inline leaf page iff root == 0]

Pages 0 and 1 are alternating meta pages; the valid one with the higher
txid wins.  A page spans (1 + overflow) * pageSize bytes.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator

MAGIC = 0xED0CDAED
_PAGE_HDR = struct.Struct("<QHHI")  # id, flags, count, overflow
_META = struct.Struct("<IIIIQQQQQQ")
_BRANCH_ELEM = struct.Struct("<IIQ")
_LEAF_ELEM = struct.Struct("<IIII")

FLAG_BRANCH = 0x01
FLAG_LEAF = 0x02
FLAG_META = 0x04
FLAG_FREELIST = 0x10
BUCKET_LEAF = 0x01


class BoltError(RuntimeError):
    pass


def fnv64a(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class _Page:
    """A view over one (possibly overflowing) page's bytes."""

    __slots__ = ("buf", "flags", "count")

    def __init__(self, buf: memoryview):
        _id, self.flags, self.count, _overflow = _PAGE_HDR.unpack_from(buf, 0)
        self.buf = buf


class Bucket:
    """Read-only bucket: mapping-style access plus sub-bucket traversal."""

    def __init__(self, db: "Bolt", root: int, inline: memoryview | None):
        self._db = db
        self._root = root
        self._inline = inline

    def _root_page(self) -> _Page:
        if self._inline is not None:
            return _Page(self._inline)
        return self._db._page(self._root)

    # -- iteration ---------------------------------------------------------

    def _iter_leaf_elems(
        self, page: _Page
    ) -> Iterator[tuple[int, bytes, memoryview]]:
        for i in range(page.count):
            off = 16 + i * _LEAF_ELEM.size
            flags, pos, ksize, vsize = _LEAF_ELEM.unpack_from(page.buf, off)
            kstart = off + pos
            key = bytes(page.buf[kstart : kstart + ksize])
            val = page.buf[kstart + ksize : kstart + ksize + vsize]
            yield flags, key, val

    def _walk(self, pgid: int) -> Iterator[tuple[int, bytes, memoryview]]:
        page = self._db._page(pgid)
        if page.flags & FLAG_BRANCH:
            for i in range(page.count):
                off = 16 + i * _BRANCH_ELEM.size
                _pos, _ksize, child = _BRANCH_ELEM.unpack_from(page.buf, off)
                yield from self._walk(child)
        elif page.flags & FLAG_LEAF:
            yield from self._iter_leaf_elems(page)
        else:
            raise BoltError(f"page {pgid}: unexpected flags {page.flags:#x}")

    def _items_raw(self) -> Iterator[tuple[int, bytes, memoryview]]:
        if self._inline is not None:
            yield from self._iter_leaf_elems(_Page(self._inline))
        else:
            yield from self._walk(self._root)

    def items(self) -> Iterator[tuple[bytes, bytes]]:
        """Plain key/value pairs (sub-buckets excluded), key order."""
        for flags, key, val in self._items_raw():
            if not flags & BUCKET_LEAF:
                yield key, bytes(val)

    def keys(self) -> list[bytes]:
        return [k for k, _ in self.items()]

    def buckets(self) -> Iterator[tuple[bytes, "Bucket"]]:
        for flags, key, val in self._items_raw():
            if flags & BUCKET_LEAF:
                yield key, self._open_child(val)

    def _open_child(self, val: memoryview) -> "Bucket":
        if len(val) < 16:
            raise BoltError("bucket value shorter than its header")
        root = struct.unpack_from("<Q", val, 0)[0]
        if root == 0:  # inline bucket: header is followed by a leaf page
            return Bucket(self._db, 0, val[16:])
        return Bucket(self._db, root, None)

    # -- point lookups -----------------------------------------------------

    def _seek(self, key: bytes) -> tuple[int, memoryview] | None:
        """(leaf element flags, value) for `key`, descending branch pages
        by last-separator <= key (bbolt cursor semantics)."""
        if self._inline is not None:
            page = _Page(self._inline)
        else:
            page = self._db._page(self._root)
        while page.flags & FLAG_BRANCH:
            child = None
            for i in range(page.count):
                off = 16 + i * _BRANCH_ELEM.size
                pos, ksize, pgid = _BRANCH_ELEM.unpack_from(page.buf, off)
                sep = bytes(page.buf[off + pos : off + pos + ksize])
                if i == 0 or sep <= key:
                    child = pgid
                else:
                    break
            if child is None:
                return None
            page = self._db._page(child)
        for flags, k, val in self._iter_leaf_elems(page):
            if k == key:
                return flags, val
        return None

    def get(self, key: bytes) -> bytes | None:
        hit = self._seek(key)
        if hit is None or hit[0] & BUCKET_LEAF:
            return None
        return bytes(hit[1])

    def bucket(self, key: bytes) -> "Bucket | None":
        hit = self._seek(key)
        if hit is None or not hit[0] & BUCKET_LEAF:
            return None
        return self._open_child(hit[1])


class Bolt:
    """A bbolt database file, opened read-only over one buffer (mmap via
    open(): point lookups fault in only the touched pages)."""

    def __init__(self, data):
        if len(data) < 0x2000:
            raise BoltError("file too small for two meta pages")
        self._data = memoryview(data)
        # Meta 0 is at offset 0; meta 1 is at offset pageSize, which only
        # the metas themselves record.  Meta 0 names the page size when
        # valid; a torn/stale meta 0 is recovered by probing the common
        # sizes for a valid meta 1.
        m0 = self._try_meta(0)
        candidates = (
            [m0[2]] if m0 is not None
            else [4096, 8192, 16384, 32768, 65536]
        )
        m1 = None
        for ps in candidates:
            m1 = self._try_meta(ps)
            if m1 is not None:
                break
        meta = None
        for m in (m0, m1):
            if m is not None and (meta is None or m[5] > meta[5]):
                meta = m
        if meta is None:
            raise BoltError("no valid meta page (not a bbolt file?)")
        (_magic, _version, self.page_size, _flags, self._root_pgid,
         _txid) = meta
        self._root = Bucket(self, self._root_pgid, None)

    @classmethod
    def open(cls, path: str) -> "Bolt":
        import mmap

        with open(path, "rb") as f:
            try:
                return cls(mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ))
            except (ValueError, OSError):  # empty file / no-mmap fs
                return cls(f.read())

    def _try_meta(self, base: int):
        if base + 16 + _META.size > len(self._data):
            return None
        try:
            (magic, version, page_size, flags, root, _seq, _freelist,
             _pgid, txid, checksum) = _META.unpack_from(self._data, base + 16)
        except struct.error:
            return None
        if magic != MAGIC or version != 2:
            return None
        if fnv64a(bytes(self._data[base + 16 : base + 16 + 56])) != checksum:
            return None
        return magic, version, page_size, flags, root, txid

    def _page(self, pgid: int) -> _Page:
        start = pgid * self.page_size
        if start + 16 > len(self._data):
            raise BoltError(f"page {pgid} out of bounds")
        _id, flags, count, overflow = _PAGE_HDR.unpack_from(self._data, start)
        end = start + (1 + overflow) * self.page_size
        return _Page(self._data[start : min(end, len(self._data))])

    # -- root access -------------------------------------------------------

    def bucket(self, *names: bytes) -> Bucket | None:
        b: Bucket | None = self._root
        for name in names:
            if b is None:
                return None
            b = b.bucket(name)
        return b

    def buckets(self) -> Iterator[tuple[bytes, Bucket]]:
        return self._root.buckets()
