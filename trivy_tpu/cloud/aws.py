"""AWS account scanning: enumerate, adapt, evaluate.

Service adapters pull live state (S3 buckets with ACL/encryption/
versioning, EC2 instances with metadata options) through SigV4-signed XML
APIs and synthesize the conftest-style document the terraform AVD checks
already understand:

    {"resource": {"aws_s3_bucket": {...}, "aws_instance": {...}}}

so cloud scans and IaC scans share one policy corpus (the reference's
adapters feed the same rego state model, pkg/iac/adapters/cloud).

AWS_ENDPOINT_URL redirects every service to an S3-compatible/localstack
endpoint, which is also how the tests drive a fake account.
"""

from __future__ import annotations

import logging
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

from trivy_tpu.cache.s3 import S3Client, S3Error

logger = logging.getLogger(__name__)

SUPPORTED_SERVICES = ("s3", "ec2")


class AwsError(RuntimeError):
    pass


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _find(el, name):
    for child in el.iter():
        if _strip_ns(child.tag) == name:
            return child
    return None


def _findall(el, name):
    return [c for c in el.iter() if _strip_ns(c.tag) == name]


class _AwsApi(S3Client):
    """SigV4 requests with query strings + XML replies, riding the cache
    client's generalized signing (service/scope and canonical query are
    parameters of the base _request)."""

    def call(self, method: str, path_and_query: str) -> ET.Element | None:
        path, _, query = path_and_query.partition("?")
        if not path.startswith("/"):
            path = "/" + path
        try:
            status, payload = self._request(method, path, query=query)
        except S3Error as e:
            raise AwsError(str(e)) from e
        if status == 404:
            return None
        if status >= 400:
            raise AwsError(
                f"aws: {method} {path_and_query}: HTTP {status}: "
                f"{payload[:200]!r}"
            )
        if not payload:
            return None
        try:
            return ET.fromstring(payload)
        except ET.ParseError as e:
            raise AwsError(f"aws: bad XML from {path_and_query}: {e}") from e


@dataclass
class AwsScanner:
    services: list[str] = field(default_factory=lambda: ["s3"])
    endpoint: str = ""
    region: str = ""
    errors: list[str] = field(default_factory=list)

    def _api(self, service: str) -> _AwsApi:
        import os

        endpoint = self.endpoint or os.environ.get("AWS_ENDPOINT_URL", "")
        if not endpoint:
            region = self.region or os.environ.get("AWS_REGION", "us-east-1")
            endpoint = f"https://{service}.{region}.amazonaws.com"
        return _AwsApi(
            bucket="", region=self.region, endpoint=endpoint, service=service
        )

    # -- adapters ----------------------------------------------------------

    def adapt_s3(self, api: _AwsApi) -> dict:
        """Buckets + attributes -> aws_s3_bucket/-acl resources."""
        root = api.call("GET", "/")
        buckets: dict[str, dict] = {}
        if root is None:
            return {}
        for b in _findall(root, "Bucket"):
            name_el = _find(b, "Name")
            if name_el is None or not name_el.text:
                continue
            name = name_el.text
            doc: dict = {"bucket": name}
            try:
                acl = api.call("GET", f"/{name}?acl")
                if acl is not None and self._acl_is_public(acl):
                    doc["acl"] = "public-read"
                enc = api.call("GET", f"/{name}?encryption")
                if enc is not None and _find(enc, "SSEAlgorithm") is not None:
                    doc["server_side_encryption_configuration"] = {
                        "rule": {"sse_algorithm": True}
                    }
                ver = api.call("GET", f"/{name}?versioning")
                status = _find(ver, "Status") if ver is not None else None
                if status is not None and (status.text or "") == "Enabled":
                    doc["versioning"] = {"enabled": True}
            except AwsError as e:
                # A bucket whose attributes cannot be read must not pass as
                # private/encrypted; record the degradation for the caller
                # (a degraded scan must not turn CI green).
                logger.warning("s3 bucket %s: %s", name, e)
                self.errors.append(f"s3 bucket {name}: {e}")
            buckets[name] = doc
        return {"aws_s3_bucket": buckets} if buckets else {}

    @staticmethod
    def _acl_is_public(acl: ET.Element) -> bool:
        for grant in _findall(acl, "Grant"):
            uri = _find(grant, "URI")
            if uri is not None and (uri.text or "").endswith(
                ("AllUsers", "AuthenticatedUsers")
            ):
                return True
        return False

    def adapt_ec2(self, api: _AwsApi) -> dict:
        """DescribeInstances -> aws_instance resources.

        Traversal uses DIRECT children only: real responses nest further
        <item>/<instanceId> elements under networkInterfaceSet, and a
        deep .iter() search would let those overwrite the instance doc."""
        root = api.call("GET", "/?Action=DescribeInstances&Version=2016-11-15")
        if root is None:
            return {}

        def children(el, name):
            return [c for c in list(el) if _strip_ns(c.tag) == name]

        def child(el, name):
            got = children(el, name)
            return got[0] if got else None

        instances: dict[str, dict] = {}
        for rset in children(root, "reservationSet"):
            for res_item in children(rset, "item"):
                for iset in children(res_item, "instancesSet"):
                    for item in children(iset, "item"):
                        iid = child(item, "instanceId")
                        if iid is None or not iid.text:
                            continue
                        doc: dict = {}
                        pub = child(item, "ipAddress")
                        if pub is not None and pub.text:
                            doc["associate_public_ip_address"] = True
                        mo = child(item, "metadataOptions")
                        tokens = child(mo, "httpTokens") if mo is not None else None
                        doc["metadata_options"] = {
                            "http_tokens": (tokens.text or "optional")
                            if tokens is not None
                            else "optional"
                        }
                        instances[iid.text] = doc
        return {"aws_instance": instances} if instances else {}

    # -- scan --------------------------------------------------------------

    def scan(self) -> list:
        """Adapt every requested service, evaluate the terraform check
        corpus over the combined resource document, return
        Misconfiguration results per service."""
        from trivy_tpu.iac.engine import shared_scanner

        resources: dict = {}
        for service in self.services:
            if service not in SUPPORTED_SERVICES:
                raise AwsError(
                    f"unsupported service {service!r} "
                    f"(supported: {', '.join(SUPPORTED_SERVICES)})"
                )
            adapter = getattr(self, f"adapt_{service}")
            resources.update(adapter(self._api(service)))
        if not resources:
            return []
        doc = {"resource": resources}
        import json as _json

        mc = shared_scanner().scan("cloud.tf.json", _json.dumps(doc).encode())
        return [mc] if mc is not None else []
