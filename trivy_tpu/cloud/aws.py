"""AWS account scanning: enumerate, adapt, evaluate.

Service adapters pull live state (S3 buckets with ACL/encryption/
versioning, EC2 instances with metadata options) through SigV4-signed XML
APIs and synthesize the conftest-style document the terraform AVD checks
already understand:

    {"resource": {"aws_s3_bucket": {...}, "aws_instance": {...}}}

so cloud scans and IaC scans share one policy corpus (the reference's
adapters feed the same rego state model, pkg/iac/adapters/cloud).

AWS_ENDPOINT_URL redirects every service to an S3-compatible/localstack
endpoint, which is also how the tests drive a fake account.
"""

from __future__ import annotations

import logging
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

from trivy_tpu.cache.s3 import S3Client, S3Error

logger = logging.getLogger(__name__)

SUPPORTED_SERVICES = (
    "s3", "ec2", "rds", "iam", "cloudtrail", "kms",
    "sns", "sqs", "ecr", "eks", "dynamodb", "cloudfront", "efs",
    "kinesis", "logs", "lambda", "redshift", "ecs",
)


class AwsError(RuntimeError):
    pass


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _find(el, name):
    for child in el.iter():
        if _strip_ns(child.tag) == name:
            return child
    return None


def _findall(el, name):
    return [c for c in el.iter() if _strip_ns(c.tag) == name]


class _AwsApi(S3Client):
    """SigV4 requests with query strings + XML replies, riding the cache
    client's generalized signing (service/scope and canonical query are
    parameters of the base _request)."""

    def call(self, method: str, path_and_query: str) -> ET.Element | None:
        path, _, query = path_and_query.partition("?")
        if not path.startswith("/"):
            path = "/" + path
        try:
            status, payload = self._request(method, path, query=query)
        except S3Error as e:
            raise AwsError(str(e)) from e
        if status == 404:
            return None
        if status >= 400:
            raise AwsError(
                f"aws: {method} {path_and_query}: HTTP {status}: "
                f"{payload[:200]!r}"
            )
        if not payload:
            return None
        try:
            return ET.fromstring(payload)
        except ET.ParseError as e:
            raise AwsError(f"aws: bad XML from {path_and_query}: {e}") from e

    @staticmethod
    def _decode_json(status: int, payload: bytes, what: str) -> dict:
        import json as _json

        if status >= 400:
            raise AwsError(f"aws: {what}: HTTP {status}: {payload[:200]!r}")
        try:
            out = _json.loads(payload or b"{}")
        except ValueError as e:
            raise AwsError(f"aws: bad JSON from {what}: {e}") from e
        return out if isinstance(out, dict) else {}

    def call_json(self, target: str, body: dict) -> dict:
        """JSON-protocol service call (CloudTrail/KMS/DynamoDB/Kinesis):
        POST / with the x-amz-target routing header, amz-json-1.1 body."""
        import json as _json

        data = _json.dumps(body).encode()
        try:
            status, payload = self._request(
                "POST",
                "/",
                body=data,
                headers_extra={
                    "x-amz-target": target,
                    "content-type": "application/x-amz-json-1.1",
                },
            )
        except S3Error as e:
            raise AwsError(str(e)) from e
        return self._decode_json(status, payload, target)

    def call_rest_json(self, method: str, path: str) -> dict:
        """REST-JSON service call (EKS/EFS-style GET APIs)."""
        try:
            status, payload = self._request(method, path)
        except S3Error as e:
            raise AwsError(str(e)) from e
        return self._decode_json(status, payload, f"{method} {path}")


@dataclass
class AwsScanner:
    services: list[str] = field(default_factory=lambda: ["s3"])
    endpoint: str = ""
    region: str = ""
    errors: list[str] = field(default_factory=list)

    def _api(self, service: str) -> _AwsApi:
        import os

        endpoint = self.endpoint or os.environ.get("AWS_ENDPOINT_URL", "")
        # SigV4 signing name when it differs from the service key.
        sign = {"efs": "elasticfilesystem"}.get(service, service)
        if not endpoint:
            region = self.region or os.environ.get("AWS_REGION", "us-east-1")
            if service == "cloudfront":
                # Global control plane (no regional hostnames).
                endpoint = "https://cloudfront.amazonaws.com"
            elif service == "ecr":
                endpoint = f"https://api.ecr.{region}.amazonaws.com"
            else:
                endpoint = f"https://{sign}.{region}.amazonaws.com"
        return _AwsApi(
            bucket="", region=self.region, endpoint=endpoint, service=sign
        )

    # -- adapters ----------------------------------------------------------

    def adapt_s3(self, api: _AwsApi) -> dict:
        """Buckets + attributes -> aws_s3_bucket/-acl resources."""
        root = api.call("GET", "/")
        buckets: dict[str, dict] = {}
        if root is None:
            return {}
        for b in _findall(root, "Bucket"):
            name_el = _find(b, "Name")
            if name_el is None or not name_el.text:
                continue
            name = name_el.text
            doc: dict = {"bucket": name}
            try:
                acl = api.call("GET", f"/{name}?acl")
                if acl is not None and self._acl_is_public(acl):
                    doc["acl"] = "public-read"
                enc = api.call("GET", f"/{name}?encryption")
                if enc is not None and _find(enc, "SSEAlgorithm") is not None:
                    doc["server_side_encryption_configuration"] = {
                        "rule": {"sse_algorithm": True}
                    }
                ver = api.call("GET", f"/{name}?versioning")
                status = _find(ver, "Status") if ver is not None else None
                if status is not None and (status.text or "") == "Enabled":
                    doc["versioning"] = {"enabled": True}
            except AwsError as e:
                # A bucket whose attributes cannot be read must not pass as
                # private/encrypted; record the degradation for the caller
                # (a degraded scan must not turn CI green).
                logger.warning("s3 bucket %s: %s", name, e)
                self.errors.append(f"s3 bucket {name}: {e}")
            buckets[name] = doc
        return {"aws_s3_bucket": buckets} if buckets else {}

    @staticmethod
    def _acl_is_public(acl: ET.Element) -> bool:
        for grant in _findall(acl, "Grant"):
            uri = _find(grant, "URI")
            if uri is not None and (uri.text or "").endswith(
                ("AllUsers", "AuthenticatedUsers")
            ):
                return True
        return False

    def adapt_ec2(self, api: _AwsApi) -> dict:
        """DescribeInstances/Volumes/SecurityGroups -> aws_instance /
        aws_ebs_volume / aws_security_group resources.

        Traversal uses DIRECT children only: real responses nest further
        <item>/<instanceId> elements under networkInterfaceSet, and a
        deep .iter() search would let those overwrite the instance doc.
        Each Describe call degrades independently (adapt_s3's contract): a
        role missing one permission still scans what the others return,
        with the gap recorded in self.errors."""

        def children(el, name):
            return [c for c in list(el) if _strip_ns(c.tag) == name]

        def child(el, name):
            got = children(el, name)
            return got[0] if got else None

        def call(action: str):
            try:
                return api.call(
                    "GET", f"/?Action={action}&Version=2016-11-15"
                )
            except AwsError as e:
                logger.warning("ec2 %s: %s", action, e)
                self.errors.append(f"ec2 {action}: {e}")
                return None

        out: dict = {}

        root = call("DescribeInstances")
        instances: dict[str, dict] = {}
        for rset in children(root, "reservationSet") if root is not None else []:
            for res_item in children(rset, "item"):
                for iset in children(res_item, "instancesSet"):
                    for item in children(iset, "item"):
                        iid = child(item, "instanceId")
                        if iid is None or not iid.text:
                            continue
                        doc: dict = {}
                        pub = child(item, "ipAddress")
                        if pub is not None and pub.text:
                            doc["associate_public_ip_address"] = True
                        mo = child(item, "metadataOptions")
                        tokens = child(mo, "httpTokens") if mo is not None else None
                        doc["metadata_options"] = {
                            "http_tokens": (tokens.text or "optional")
                            if tokens is not None
                            else "optional"
                        }
                        instances[iid.text] = doc
        if instances:
            out["aws_instance"] = instances

        vroot = call("DescribeVolumes")
        volumes: dict[str, dict] = {}
        for vset in children(vroot, "volumeSet") if vroot is not None else []:
            for item in children(vset, "item"):
                vid = child(item, "volumeId")
                if vid is None or not vid.text:
                    continue
                enc = child(item, "encrypted")
                volumes[vid.text] = {
                    "encrypted": enc is not None and enc.text == "true"
                }
        if volumes:
            out["aws_ebs_volume"] = volumes

        sroot = call("DescribeSecurityGroups")
        groups: dict[str, dict] = {}
        srets = children(sroot, "securityGroupInfo") if sroot is not None else []
        for gset in srets:
            for item in children(gset, "item"):
                # Explicit None test: leaf Elements are falsy, so
                # `a or b` would discard a found groupId.
                gid = child(item, "groupId")
                if gid is None:
                    gid = child(item, "groupName")
                if gid is None or not gid.text:
                    continue
                ingress = []
                for perms in children(item, "ipPermissions"):
                    for perm in children(perms, "item"):
                        cidrs = []
                        for set_tag, ip_tag in (
                            ("ipRanges", "cidrIp"),
                            ("ipv6Ranges", "cidrIpv6"),
                        ):
                            for rset in children(perm, set_tag):
                                for r in children(rset, "item"):
                                    ip = child(r, ip_tag)
                                    if ip is not None and ip.text:
                                        cidrs.append(ip.text)
                        if cidrs:
                            ingress.append({"cidr_blocks": cidrs})
                groups[gid.text] = {"ingress": ingress}
        if groups:
            out["aws_security_group"] = groups
        return out

    def adapt_rds(self, api: _AwsApi) -> dict:
        """DescribeDBInstances -> aws_db_instance resources (the cloud
        adapter feeds the same fields the terraform corpus checks:
        storage_encrypted, publicly_accessible)."""
        root = api.call("GET", "/?Action=DescribeDBInstances&Version=2014-10-31")
        if root is None:
            return {}
        dbs: dict[str, dict] = {}
        for item in root.iter():
            if _strip_ns(item.tag) != "DBInstance":
                continue
            ident = _find(item, "DBInstanceIdentifier")
            if ident is None or not ident.text:
                continue
            enc = _find(item, "StorageEncrypted")
            pub = _find(item, "PubliclyAccessible")
            dbs[ident.text] = {
                "storage_encrypted": (enc is not None and enc.text == "true"),
                "publicly_accessible": (pub is not None and pub.text == "true"),
            }
        return {"aws_db_instance": dbs} if dbs else {}

    def adapt_iam(self, api: _AwsApi) -> dict:
        """GetAccountPasswordPolicy -> aws_iam_account_password_policy.

        An account with no policy set must FAIL the password-policy check,
        not vanish: AWS answers NoSuchEntity, which adapts to an empty
        policy document (every minimum is unset)."""
        try:
            root = api.call(
                "GET", "/?Action=GetAccountPasswordPolicy&Version=2010-05-08"
            )
        except AwsError as e:
            if "NoSuchEntity" not in str(e):
                raise
            root = None
        policy: dict = {}
        if root is not None:
            for el in root.iter():
                tag = _strip_ns(el.tag)
                if tag == "MinimumPasswordLength" and el.text:
                    policy["minimum_password_length"] = int(el.text)
                elif tag == "RequireSymbols":
                    policy["require_symbols"] = el.text == "true"
                elif tag == "RequireNumbers":
                    policy["require_numbers"] = el.text == "true"
                elif tag == "MaxPasswordAge" and el.text:
                    policy["max_password_age"] = int(el.text)
        return {"aws_iam_account_password_policy": {"account": policy}}

    def adapt_cloudtrail(self, api: _AwsApi) -> dict:
        """DescribeTrails -> aws_cloudtrail resources (multi-region and
        log-validation fields feed the terraform corpus)."""
        out = api.call_json(
            "com.amazonaws.cloudtrail.v20131101.CloudTrail_20131101"
            ".DescribeTrails",
            {},
        )
        trails: dict[str, dict] = {}
        for t in out.get("trailList") or []:
            name = t.get("Name") or t.get("TrailARN", "")
            if not name:
                continue
            trails[name] = {
                "is_multi_region_trail": bool(t.get("IsMultiRegionTrail")),
                "enable_log_file_validation": bool(
                    t.get("LogFileValidationEnabled")
                ),
                "kms_key_id": t.get("KmsKeyId", ""),
            }
        if not trails:
            # No audit logging at all must FAIL the trail checks, not
            # vanish (adapt_iam's absence contract): an empty document
            # fails every per-field requirement.
            trails["account"] = {}
        return {"aws_cloudtrail": trails}

    def adapt_kms(self, api: _AwsApi) -> dict:
        """ListKeys (paginated) + DescribeKey + GetKeyRotationStatus ->
        aws_kms_key resources.  Only customer-managed symmetric keys are
        rotation-checked (rotation is unsupported/meaningless for
        asymmetric and AWS-managed keys); a key whose state cannot be
        read is recorded (self.errors), never assumed rotated."""
        key_ids: list[str] = []
        marker = None
        while True:
            req: dict = {"Marker": marker} if marker else {}
            out = api.call_json("TrentService.ListKeys", req)
            key_ids.extend(
                k.get("KeyId", "") for k in out.get("Keys") or []
            )
            marker = out.get("NextMarker")
            if not out.get("Truncated") or not marker:
                break

        keys: dict[str, dict] = {}
        for key_id in key_ids:
            if not key_id:
                continue
            try:
                meta = (
                    api.call_json(
                        "TrentService.DescribeKey", {"KeyId": key_id}
                    ).get("KeyMetadata")
                    or {}
                )
                if meta.get("KeyManager", "CUSTOMER") != "CUSTOMER":
                    continue
                if meta.get("KeySpec", "SYMMETRIC_DEFAULT") != "SYMMETRIC_DEFAULT":
                    continue
                status = api.call_json(
                    "TrentService.GetKeyRotationStatus", {"KeyId": key_id}
                )
                keys[key_id] = {
                    "enable_key_rotation": bool(status.get("KeyRotationEnabled"))
                }
            except AwsError as e:
                logger.warning("kms key %s: %s", key_id, e)
                self.errors.append(f"kms key {key_id}: {e}")
        return {"aws_kms_key": keys} if keys else {}

    def _query_paged(
        self, api: _AwsApi, base: str, item_tag: str
    ) -> list[str]:
        """Collect `item_tag` texts across NextToken pages of a Query-XML
        list action (a degraded page is an error, never a silent pass)."""
        from urllib.parse import quote

        out: list[str] = []
        token = None
        while True:
            url = base if token is None else (
                f"{base}&NextToken={quote(token, safe='')}"
            )
            root = api.call("GET", url)
            if root is None:
                return out
            out.extend(
                el.text
                for el in root.iter()
                if _strip_ns(el.tag) == item_tag and el.text
            )
            token = next(
                (
                    el.text
                    for el in root.iter()
                    if _strip_ns(el.tag) == "NextToken" and el.text
                ),
                None,
            )
            if not token:
                return out

    def adapt_sns(self, api: _AwsApi) -> dict:
        """ListTopics (paginated) + GetTopicAttributes -> aws_sns_topic."""
        topics: dict[str, dict] = {}
        arns = self._query_paged(
            api, "/?Action=ListTopics&Version=2010-03-31", "TopicArn"
        )
        from urllib.parse import quote

        for arn in arns:
            name = arn.rsplit(":", 1)[-1]
            topics[name] = {"kms_master_key_id": ""}
            try:
                attrs = api.call(
                    "GET",
                    "/?Action=GetTopicAttributes&Version=2010-03-31"
                    f"&TopicArn={quote(arn, safe='')}",
                )
            except AwsError as e:
                self.errors.append(f"sns topic {name}: {e}")
                continue
            for entry in attrs.iter() if attrs is not None else []:
                if _strip_ns(entry.tag) != "entry":
                    continue
                k, v = _find(entry, "key"), _find(entry, "value")
                if k is not None and k.text == "KmsMasterKeyId":
                    topics[name]["kms_master_key_id"] = (
                        v.text if v is not None and v.text else ""
                    )
        return {"aws_sns_topic": topics} if topics else {}

    def adapt_sqs(self, api: _AwsApi) -> dict:
        """ListQueues (paginated) + GetQueueAttributes -> aws_sqs_queue."""
        urls = self._query_paged(
            api, "/?Action=ListQueues&Version=2012-11-05", "QueueUrl"
        )
        from urllib.parse import quote, urlparse

        queues: dict[str, dict] = {}
        for url in urls:
            name = urlparse(url).path.rsplit("/", 1)[-1]
            q = {"kms_master_key_id": "", "sqs_managed_sse_enabled": False}
            queues[name] = q
            try:
                attrs = api.call(
                    "GET",
                    f"/?Action=GetQueueAttributes&Version=2012-11-05"
                    f"&QueueUrl={quote(url, safe='')}&AttributeName.1=All",
                )
            except AwsError as e:
                self.errors.append(f"sqs queue {name}: {e}")
                continue
            for attr in attrs.iter() if attrs is not None else []:
                if _strip_ns(attr.tag) != "Attribute":
                    continue
                k, v = _find(attr, "Name"), _find(attr, "Value")
                if k is None or v is None:
                    continue
                if k.text == "KmsMasterKeyId":
                    q["kms_master_key_id"] = v.text or ""
                elif k.text == "SqsManagedSseEnabled":
                    q["sqs_managed_sse_enabled"] = v.text == "true"
        return {"aws_sqs_queue": queues} if queues else {}

    def adapt_ecr(self, api: _AwsApi) -> dict:
        """DescribeRepositories (paginated) -> aws_ecr_repository."""
        repos: dict[str, dict] = {}
        token = None
        while True:
            req: dict = {"nextToken": token} if token else {}
            out = api.call_json(
                "AmazonEC2ContainerRegistry_V20150921.DescribeRepositories",
                req,
            )
            for r in out.get("repositories") or []:
                name = r.get("repositoryName", "")
                if not name:
                    continue
                enc = r.get("encryptionConfiguration") or {}
                repos[name] = {
                    "image_scanning_configuration": {
                        "scan_on_push": bool(
                            (r.get("imageScanningConfiguration") or {}).get(
                                "scanOnPush"
                            )
                        )
                    },
                    "image_tag_mutability": r.get(
                        "imageTagMutability", "MUTABLE"
                    ),
                    "encryption_configuration": {
                        "encryption_type": enc.get("encryptionType", "AES256")
                    },
                }
            token = out.get("nextToken")
            if not token:
                break
        return {"aws_ecr_repository": repos} if repos else {}

    def adapt_eks(self, api: _AwsApi) -> dict:
        """ListClusters (paginated) + DescribeCluster -> aws_eks_cluster."""
        from urllib.parse import quote

        names: list[str] = []
        token = None
        while True:
            path = "/clusters" if token is None else (
                f"/clusters?nextToken={quote(token, safe='')}"
            )
            out = api.call_rest_json("GET", path)
            names.extend(out.get("clusters") or [])
            token = out.get("nextToken")
            if not token:
                break
        clusters: dict[str, dict] = {}
        for name in names:
            try:
                c = api.call_rest_json("GET", f"/clusters/{name}").get(
                    "cluster"
                ) or {}
            except AwsError as e:
                self.errors.append(f"eks cluster {name}: {e}")
                continue
            vpc = c.get("resourcesVpcConfig") or {}
            log_types: list[str] = []
            for grp in (c.get("logging") or {}).get("clusterLogging") or []:
                if grp.get("enabled"):
                    log_types.extend(grp.get("types") or [])
            clusters[name] = {
                "vpc_config": {
                    "endpoint_public_access": bool(
                        vpc.get("endpointPublicAccess", True)
                    ),
                    "public_access_cidrs": vpc.get("publicAccessCidrs")
                    or ["0.0.0.0/0"],
                },
                "enabled_cluster_log_types": log_types,
            }
        return {"aws_eks_cluster": clusters} if clusters else {}

    def adapt_dynamodb(self, api: _AwsApi) -> dict:
        """ListTables (paginated) + DescribeTable +
        DescribeContinuousBackups -> aws_dynamodb_table resources."""
        names: list[str] = []
        start = None
        while True:
            req: dict = (
                {"ExclusiveStartTableName": start} if start else {}
            )
            out = api.call_json("DynamoDB_20120810.ListTables", req)
            names.extend(out.get("TableNames") or [])
            start = out.get("LastEvaluatedTableName")
            if not start:
                break
        tables: dict[str, dict] = {}
        for name in names:
            t: dict = {
                "server_side_encryption": {"enabled": False, "kms_key_arn": ""},
                "point_in_time_recovery": {"enabled": False},
            }
            tables[name] = t
            try:
                desc = api.call_json(
                    "DynamoDB_20120810.DescribeTable", {"TableName": name}
                ).get("Table") or {}
                sse = desc.get("SSEDescription") or {}
                t["server_side_encryption"] = {
                    "enabled": sse.get("Status") == "ENABLED",
                    "kms_key_arn": sse.get("KMSMasterKeyArn", ""),
                }
                backups = api.call_json(
                    "DynamoDB_20120810.DescribeContinuousBackups",
                    {"TableName": name},
                ).get("ContinuousBackupsDescription") or {}
                pitr = backups.get("PointInTimeRecoveryDescription") or {}
                t["point_in_time_recovery"] = {
                    "enabled": pitr.get("PointInTimeRecoveryStatus")
                    == "ENABLED"
                }
            except AwsError as e:
                self.errors.append(f"dynamodb table {name}: {e}")
        return {"aws_dynamodb_table": tables} if tables else {}

    def adapt_cloudfront(self, api: _AwsApi) -> dict:
        """ListDistributions (Marker-paginated) + GetDistributionConfig ->
        aws_cloudfront_distribution resources."""
        from urllib.parse import quote

        ids: list[str] = []
        marker = None
        while True:
            path = "/2020-05-31/distribution" if marker is None else (
                f"/2020-05-31/distribution?Marker={quote(marker, safe='')}"
            )
            root = api.call("GET", path)
            if root is None:
                break
            ids.extend(
                _find(s, "Id").text
                for s in root.iter()
                if _strip_ns(s.tag) == "DistributionSummary"
                and _find(s, "Id") is not None
            )
            truncated = next(
                (
                    el.text == "true"
                    for el in root.iter()
                    if _strip_ns(el.tag) == "IsTruncated"
                ),
                False,
            )
            marker = next(
                (
                    el.text
                    for el in root.iter()
                    if _strip_ns(el.tag) == "NextMarker" and el.text
                ),
                None,
            )
            if not truncated or not marker:
                break
        dists: dict[str, dict] = {}
        for dist_id in ids:
            try:
                cfg = api.call(
                    "GET", f"/2020-05-31/distribution/{dist_id}/config"
                )
            except AwsError as e:
                self.errors.append(f"cloudfront {dist_id}: {e}")
                continue
            if cfg is None:
                continue
            d: dict = {}
            beh = _find(cfg, "DefaultCacheBehavior")
            if beh is not None:
                vpp = _find(beh, "ViewerProtocolPolicy")
                d["default_cache_behavior"] = {
                    "viewer_protocol_policy": (
                        vpp.text if vpp is not None and vpp.text else "allow-all"
                    )
                }
            cert = _find(cfg, "ViewerCertificate")
            if cert is not None:
                mpv = _find(cert, "MinimumProtocolVersion")
                default_cert = _find(cert, "CloudFrontDefaultCertificate")
                d["viewer_certificate"] = {
                    "minimum_protocol_version": (
                        mpv.text if mpv is not None and mpv.text else "TLSv1"
                    ),
                    "cloudfront_default_certificate": (
                        default_cert is not None
                        and default_cert.text == "true"
                    ),
                }
            logging_el = _find(cfg, "Logging")
            enabled = (
                _find(logging_el, "Enabled") if logging_el is not None else None
            )
            if enabled is not None and enabled.text == "true":
                bucket = _find(logging_el, "Bucket")
                d["logging_config"] = {
                    "bucket": bucket.text if bucket is not None else ""
                }
            dists[dist_id] = d
        return {"aws_cloudfront_distribution": dists} if dists else {}

    def adapt_efs(self, api: _AwsApi) -> dict:
        """DescribeFileSystems (Marker-paginated) -> aws_efs_file_system."""
        from urllib.parse import quote

        systems: dict[str, dict] = {}
        marker = None
        while True:
            path = "/2015-02-01/file-systems" if marker is None else (
                f"/2015-02-01/file-systems?Marker={quote(marker, safe='')}"
            )
            out = api.call_rest_json("GET", path)
            for fs in out.get("FileSystems") or []:
                fsid = fs.get("FileSystemId", "")
                if fsid:
                    systems[fsid] = {"encrypted": bool(fs.get("Encrypted"))}
            marker = out.get("NextMarker")
            if not marker:
                break
        return {"aws_efs_file_system": systems} if systems else {}

    def adapt_kinesis(self, api: _AwsApi) -> dict:
        """ListStreams (paginated) + DescribeStreamSummary ->
        aws_kinesis_stream resources."""
        names: list[str] = []
        start = None
        while True:
            req: dict = (
                {"ExclusiveStartStreamName": start} if start else {}
            )
            out = api.call_json("Kinesis_20131202.ListStreams", req)
            page = out.get("StreamNames") or []
            names.extend(page)
            if not out.get("HasMoreStreams") or not page:
                break
            start = page[-1]
        streams: dict[str, dict] = {}
        for name in names:
            streams[name] = {"encryption_type": "NONE"}
            try:
                desc = api.call_json(
                    "Kinesis_20131202.DescribeStreamSummary",
                    {"StreamName": name},
                ).get("StreamDescriptionSummary") or {}
                streams[name]["encryption_type"] = desc.get(
                    "EncryptionType", "NONE"
                )
            except AwsError as e:
                self.errors.append(f"kinesis stream {name}: {e}")
        return {"aws_kinesis_stream": streams} if streams else {}

    def adapt_logs(self, api: _AwsApi) -> dict:
        """DescribeLogGroups -> aws_cloudwatch_log_group resources."""
        groups: dict[str, dict] = {}
        token = None
        while True:
            req: dict = {"nextToken": token} if token else {}
            out = api.call_json("Logs_20140328.DescribeLogGroups", req)
            for g in out.get("logGroups") or []:
                name = g.get("logGroupName", "")
                if name:
                    groups[name] = {"kms_key_id": g.get("kmsKeyId", "")}
            token = out.get("nextToken")
            if not token:
                break
        return {"aws_cloudwatch_log_group": groups} if groups else {}

    def adapt_lambda(self, api: _AwsApi) -> dict:
        """ListFunctions (REST JSON, Marker-paginated) ->
        aws_lambda_function resources."""
        from urllib.parse import quote

        fns: dict[str, dict] = {}
        marker = None
        while True:
            path = "/2015-03-31/functions/"
            if marker:
                path += f"?Marker={quote(marker, safe='')}"
            out = api.call_rest_json("GET", path)
            for f in out.get("Functions") or []:
                name = f.get("FunctionName", "")
                if not name:
                    continue
                tracing = f.get("TracingConfig") or {}
                fns[name] = {
                    "tracing_config": {
                        "mode": tracing.get("Mode", "PassThrough")
                    }
                }
            marker = out.get("NextMarker")
            if not marker:
                break
        return {"aws_lambda_function": fns} if fns else {}

    def adapt_redshift(self, api: _AwsApi) -> dict:
        """DescribeClusters (Marker-paginated Query XML) ->
        aws_redshift_cluster resources."""
        from urllib.parse import quote

        clusters: dict[str, dict] = {}
        marker = None
        while True:
            url = "/?Action=DescribeClusters&Version=2012-12-01"
            if marker:
                url += f"&Marker={quote(marker, safe='')}"
            root = api.call("GET", url)
            if root is None:
                break
            for item in root.iter():
                if _strip_ns(item.tag) != "Cluster":
                    continue
                ident = _find(item, "ClusterIdentifier")
                if ident is None or not ident.text:
                    continue
                enc = _find(item, "Encrypted")
                clusters[ident.text] = {
                    "encrypted": enc is not None and enc.text == "true"
                }
            nxt = next(
                (
                    el.text
                    for el in root.iter()
                    if _strip_ns(el.tag) == "Marker" and el.text
                ),
                None,
            )
            if not nxt or nxt == marker:
                break
            marker = nxt
        return {"aws_redshift_cluster": clusters} if clusters else {}

    def adapt_ecs(self, api: _AwsApi) -> dict:
        """ListClusters + DescribeClusters (JSON protocol, SETTINGS
        included; 100-ARN describe batches) -> aws_ecs_cluster
        resources.  Per-cluster describe failures are recorded in
        self.errors — a degraded page is an error, never a silent pass."""
        arns: list[str] = []
        token = None
        while True:
            req: dict = {"nextToken": token} if token else {}
            out = api.call_json(
                "AmazonEC2ContainerServiceV20141113.ListClusters", req
            )
            arns.extend(out.get("clusterArns") or [])
            token = out.get("nextToken")
            if not token:
                break
        if not arns:
            return {}
        clusters: dict[str, dict] = {}
        for off in range(0, len(arns), 100):  # DescribeClusters cap
            out = api.call_json(
                "AmazonEC2ContainerServiceV20141113.DescribeClusters",
                {"clusters": arns[off : off + 100], "include": ["SETTINGS"]},
            )
            for fail in out.get("failures") or []:
                self.errors.append(
                    f"ecs cluster {fail.get('arn', '?')}: "
                    f"{fail.get('reason', 'describe failure')}"
                )
            for c in out.get("clusters") or []:
                name = c.get("clusterName", "")
                if not name:
                    continue
                clusters[name] = {
                    "setting": [
                        {
                            "name": s.get("name", ""),
                            "value": s.get("value", ""),
                        }
                        for s in c.get("settings") or []
                    ]
                }
        return {"aws_ecs_cluster": clusters} if clusters else {}

    # -- scan --------------------------------------------------------------

    def scan(self) -> list:
        """Adapt every requested service, evaluate the terraform check
        corpus over the combined resource document, return
        Misconfiguration results per service."""
        from trivy_tpu.iac.engine import shared_scanner

        resources: dict = {}
        for service in self.services:
            if service not in SUPPORTED_SERVICES:
                raise AwsError(
                    f"unsupported service {service!r} "
                    f"(supported: {', '.join(SUPPORTED_SERVICES)})"
                )
            adapter = getattr(self, f"adapt_{service}")
            resources.update(adapter(self._api(service)))
        if not resources:
            return []
        doc = {"resource": resources}
        import json as _json

        mc = shared_scanner().scan("cloud.tf.json", _json.dumps(doc).encode())
        return [mc] if mc is not None else []
