"""AWS account scanning: enumerate, adapt, evaluate.

Service adapters pull live state (S3 buckets with ACL/encryption/
versioning, EC2 instances with metadata options) through SigV4-signed XML
APIs and synthesize the conftest-style document the terraform AVD checks
already understand:

    {"resource": {"aws_s3_bucket": {...}, "aws_instance": {...}}}

so cloud scans and IaC scans share one policy corpus (the reference's
adapters feed the same rego state model, pkg/iac/adapters/cloud).

AWS_ENDPOINT_URL redirects every service to an S3-compatible/localstack
endpoint, which is also how the tests drive a fake account.
"""

from __future__ import annotations

import logging
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

from trivy_tpu.cache.s3 import S3Client, S3Error

logger = logging.getLogger(__name__)

SUPPORTED_SERVICES = ("s3", "ec2", "rds", "iam", "cloudtrail", "kms")


class AwsError(RuntimeError):
    pass


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _find(el, name):
    for child in el.iter():
        if _strip_ns(child.tag) == name:
            return child
    return None


def _findall(el, name):
    return [c for c in el.iter() if _strip_ns(c.tag) == name]


class _AwsApi(S3Client):
    """SigV4 requests with query strings + XML replies, riding the cache
    client's generalized signing (service/scope and canonical query are
    parameters of the base _request)."""

    def call(self, method: str, path_and_query: str) -> ET.Element | None:
        path, _, query = path_and_query.partition("?")
        if not path.startswith("/"):
            path = "/" + path
        try:
            status, payload = self._request(method, path, query=query)
        except S3Error as e:
            raise AwsError(str(e)) from e
        if status == 404:
            return None
        if status >= 400:
            raise AwsError(
                f"aws: {method} {path_and_query}: HTTP {status}: "
                f"{payload[:200]!r}"
            )
        if not payload:
            return None
        try:
            return ET.fromstring(payload)
        except ET.ParseError as e:
            raise AwsError(f"aws: bad XML from {path_and_query}: {e}") from e

    def call_json(self, target: str, body: dict) -> dict:
        """JSON-protocol service call (CloudTrail/KMS): POST / with the
        x-amz-target routing header, amz-json-1.1 body."""
        import json as _json

        data = _json.dumps(body).encode()
        try:
            status, payload = self._request(
                "POST",
                "/",
                body=data,
                headers_extra={
                    "x-amz-target": target,
                    "content-type": "application/x-amz-json-1.1",
                },
            )
        except S3Error as e:
            raise AwsError(str(e)) from e
        if status >= 400:
            raise AwsError(
                f"aws: {target}: HTTP {status}: {payload[:200]!r}"
            )
        try:
            out = _json.loads(payload or b"{}")
        except ValueError as e:
            raise AwsError(f"aws: bad JSON from {target}: {e}") from e
        return out if isinstance(out, dict) else {}


@dataclass
class AwsScanner:
    services: list[str] = field(default_factory=lambda: ["s3"])
    endpoint: str = ""
    region: str = ""
    errors: list[str] = field(default_factory=list)

    def _api(self, service: str) -> _AwsApi:
        import os

        endpoint = self.endpoint or os.environ.get("AWS_ENDPOINT_URL", "")
        if not endpoint:
            region = self.region or os.environ.get("AWS_REGION", "us-east-1")
            endpoint = f"https://{service}.{region}.amazonaws.com"
        return _AwsApi(
            bucket="", region=self.region, endpoint=endpoint, service=service
        )

    # -- adapters ----------------------------------------------------------

    def adapt_s3(self, api: _AwsApi) -> dict:
        """Buckets + attributes -> aws_s3_bucket/-acl resources."""
        root = api.call("GET", "/")
        buckets: dict[str, dict] = {}
        if root is None:
            return {}
        for b in _findall(root, "Bucket"):
            name_el = _find(b, "Name")
            if name_el is None or not name_el.text:
                continue
            name = name_el.text
            doc: dict = {"bucket": name}
            try:
                acl = api.call("GET", f"/{name}?acl")
                if acl is not None and self._acl_is_public(acl):
                    doc["acl"] = "public-read"
                enc = api.call("GET", f"/{name}?encryption")
                if enc is not None and _find(enc, "SSEAlgorithm") is not None:
                    doc["server_side_encryption_configuration"] = {
                        "rule": {"sse_algorithm": True}
                    }
                ver = api.call("GET", f"/{name}?versioning")
                status = _find(ver, "Status") if ver is not None else None
                if status is not None and (status.text or "") == "Enabled":
                    doc["versioning"] = {"enabled": True}
            except AwsError as e:
                # A bucket whose attributes cannot be read must not pass as
                # private/encrypted; record the degradation for the caller
                # (a degraded scan must not turn CI green).
                logger.warning("s3 bucket %s: %s", name, e)
                self.errors.append(f"s3 bucket {name}: {e}")
            buckets[name] = doc
        return {"aws_s3_bucket": buckets} if buckets else {}

    @staticmethod
    def _acl_is_public(acl: ET.Element) -> bool:
        for grant in _findall(acl, "Grant"):
            uri = _find(grant, "URI")
            if uri is not None and (uri.text or "").endswith(
                ("AllUsers", "AuthenticatedUsers")
            ):
                return True
        return False

    def adapt_ec2(self, api: _AwsApi) -> dict:
        """DescribeInstances/Volumes/SecurityGroups -> aws_instance /
        aws_ebs_volume / aws_security_group resources.

        Traversal uses DIRECT children only: real responses nest further
        <item>/<instanceId> elements under networkInterfaceSet, and a
        deep .iter() search would let those overwrite the instance doc.
        Each Describe call degrades independently (adapt_s3's contract): a
        role missing one permission still scans what the others return,
        with the gap recorded in self.errors."""

        def children(el, name):
            return [c for c in list(el) if _strip_ns(c.tag) == name]

        def child(el, name):
            got = children(el, name)
            return got[0] if got else None

        def call(action: str):
            try:
                return api.call(
                    "GET", f"/?Action={action}&Version=2016-11-15"
                )
            except AwsError as e:
                logger.warning("ec2 %s: %s", action, e)
                self.errors.append(f"ec2 {action}: {e}")
                return None

        out: dict = {}

        root = call("DescribeInstances")
        instances: dict[str, dict] = {}
        for rset in children(root, "reservationSet") if root is not None else []:
            for res_item in children(rset, "item"):
                for iset in children(res_item, "instancesSet"):
                    for item in children(iset, "item"):
                        iid = child(item, "instanceId")
                        if iid is None or not iid.text:
                            continue
                        doc: dict = {}
                        pub = child(item, "ipAddress")
                        if pub is not None and pub.text:
                            doc["associate_public_ip_address"] = True
                        mo = child(item, "metadataOptions")
                        tokens = child(mo, "httpTokens") if mo is not None else None
                        doc["metadata_options"] = {
                            "http_tokens": (tokens.text or "optional")
                            if tokens is not None
                            else "optional"
                        }
                        instances[iid.text] = doc
        if instances:
            out["aws_instance"] = instances

        vroot = call("DescribeVolumes")
        volumes: dict[str, dict] = {}
        for vset in children(vroot, "volumeSet") if vroot is not None else []:
            for item in children(vset, "item"):
                vid = child(item, "volumeId")
                if vid is None or not vid.text:
                    continue
                enc = child(item, "encrypted")
                volumes[vid.text] = {
                    "encrypted": enc is not None and enc.text == "true"
                }
        if volumes:
            out["aws_ebs_volume"] = volumes

        sroot = call("DescribeSecurityGroups")
        groups: dict[str, dict] = {}
        srets = children(sroot, "securityGroupInfo") if sroot is not None else []
        for gset in srets:
            for item in children(gset, "item"):
                # Explicit None test: leaf Elements are falsy, so
                # `a or b` would discard a found groupId.
                gid = child(item, "groupId")
                if gid is None:
                    gid = child(item, "groupName")
                if gid is None or not gid.text:
                    continue
                ingress = []
                for perms in children(item, "ipPermissions"):
                    for perm in children(perms, "item"):
                        cidrs = []
                        for set_tag, ip_tag in (
                            ("ipRanges", "cidrIp"),
                            ("ipv6Ranges", "cidrIpv6"),
                        ):
                            for rset in children(perm, set_tag):
                                for r in children(rset, "item"):
                                    ip = child(r, ip_tag)
                                    if ip is not None and ip.text:
                                        cidrs.append(ip.text)
                        if cidrs:
                            ingress.append({"cidr_blocks": cidrs})
                groups[gid.text] = {"ingress": ingress}
        if groups:
            out["aws_security_group"] = groups
        return out

    def adapt_rds(self, api: _AwsApi) -> dict:
        """DescribeDBInstances -> aws_db_instance resources (the cloud
        adapter feeds the same fields the terraform corpus checks:
        storage_encrypted, publicly_accessible)."""
        root = api.call("GET", "/?Action=DescribeDBInstances&Version=2014-10-31")
        if root is None:
            return {}
        dbs: dict[str, dict] = {}
        for item in root.iter():
            if _strip_ns(item.tag) != "DBInstance":
                continue
            ident = _find(item, "DBInstanceIdentifier")
            if ident is None or not ident.text:
                continue
            enc = _find(item, "StorageEncrypted")
            pub = _find(item, "PubliclyAccessible")
            dbs[ident.text] = {
                "storage_encrypted": (enc is not None and enc.text == "true"),
                "publicly_accessible": (pub is not None and pub.text == "true"),
            }
        return {"aws_db_instance": dbs} if dbs else {}

    def adapt_iam(self, api: _AwsApi) -> dict:
        """GetAccountPasswordPolicy -> aws_iam_account_password_policy.

        An account with no policy set must FAIL the password-policy check,
        not vanish: AWS answers NoSuchEntity, which adapts to an empty
        policy document (every minimum is unset)."""
        try:
            root = api.call(
                "GET", "/?Action=GetAccountPasswordPolicy&Version=2010-05-08"
            )
        except AwsError as e:
            if "NoSuchEntity" not in str(e):
                raise
            root = None
        policy: dict = {}
        if root is not None:
            for el in root.iter():
                tag = _strip_ns(el.tag)
                if tag == "MinimumPasswordLength" and el.text:
                    policy["minimum_password_length"] = int(el.text)
                elif tag == "RequireSymbols":
                    policy["require_symbols"] = el.text == "true"
                elif tag == "RequireNumbers":
                    policy["require_numbers"] = el.text == "true"
                elif tag == "MaxPasswordAge" and el.text:
                    policy["max_password_age"] = int(el.text)
        return {"aws_iam_account_password_policy": {"account": policy}}

    def adapt_cloudtrail(self, api: _AwsApi) -> dict:
        """DescribeTrails -> aws_cloudtrail resources (multi-region and
        log-validation fields feed the terraform corpus)."""
        out = api.call_json(
            "com.amazonaws.cloudtrail.v20131101.CloudTrail_20131101"
            ".DescribeTrails",
            {},
        )
        trails: dict[str, dict] = {}
        for t in out.get("trailList") or []:
            name = t.get("Name") or t.get("TrailARN", "")
            if not name:
                continue
            trails[name] = {
                "is_multi_region_trail": bool(t.get("IsMultiRegionTrail")),
                "enable_log_file_validation": bool(
                    t.get("LogFileValidationEnabled")
                ),
            }
        if not trails:
            # No audit logging at all must FAIL the trail checks, not
            # vanish (adapt_iam's absence contract): an empty document
            # fails every per-field requirement.
            trails["account"] = {}
        return {"aws_cloudtrail": trails}

    def adapt_kms(self, api: _AwsApi) -> dict:
        """ListKeys (paginated) + DescribeKey + GetKeyRotationStatus ->
        aws_kms_key resources.  Only customer-managed symmetric keys are
        rotation-checked (rotation is unsupported/meaningless for
        asymmetric and AWS-managed keys); a key whose state cannot be
        read is recorded (self.errors), never assumed rotated."""
        key_ids: list[str] = []
        marker = None
        while True:
            req: dict = {"Marker": marker} if marker else {}
            out = api.call_json("TrentService.ListKeys", req)
            key_ids.extend(
                k.get("KeyId", "") for k in out.get("Keys") or []
            )
            marker = out.get("NextMarker")
            if not out.get("Truncated") or not marker:
                break

        keys: dict[str, dict] = {}
        for key_id in key_ids:
            if not key_id:
                continue
            try:
                meta = (
                    api.call_json(
                        "TrentService.DescribeKey", {"KeyId": key_id}
                    ).get("KeyMetadata")
                    or {}
                )
                if meta.get("KeyManager", "CUSTOMER") != "CUSTOMER":
                    continue
                if meta.get("KeySpec", "SYMMETRIC_DEFAULT") != "SYMMETRIC_DEFAULT":
                    continue
                status = api.call_json(
                    "TrentService.GetKeyRotationStatus", {"KeyId": key_id}
                )
                keys[key_id] = {
                    "enable_key_rotation": bool(status.get("KeyRotationEnabled"))
                }
            except AwsError as e:
                logger.warning("kms key %s: %s", key_id, e)
                self.errors.append(f"kms key {key_id}: {e}")
        return {"aws_kms_key": keys} if keys else {}

    # -- scan --------------------------------------------------------------

    def scan(self) -> list:
        """Adapt every requested service, evaluate the terraform check
        corpus over the combined resource document, return
        Misconfiguration results per service."""
        from trivy_tpu.iac.engine import shared_scanner

        resources: dict = {}
        for service in self.services:
            if service not in SUPPORTED_SERVICES:
                raise AwsError(
                    f"unsupported service {service!r} "
                    f"(supported: {', '.join(SUPPORTED_SERVICES)})"
                )
            adapter = getattr(self, f"adapt_{service}")
            resources.update(adapter(self._api(service)))
        if not resources:
            return []
        doc = {"resource": resources}
        import json as _json

        mc = shared_scanner().scan("cloud.tf.json", _json.dumps(doc).encode())
        return [mc] if mc is not None else []
