"""AWS cloud scanning (pkg/cloud/aws).

Enumerates live account resources over the AWS APIs (SigV4, stdlib HTTP),
adapts them into the same conftest-style resource documents the terraform
checks evaluate, and reports per-service findings — one policy corpus for
IaC and live cloud state, the reference's own design (its cloud scans run
the same AVD checks against adapted state).
"""

from trivy_tpu.cloud.aws import AwsScanner, AwsError

__all__ = ["AwsScanner", "AwsError"]
