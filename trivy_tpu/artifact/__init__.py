from trivy_tpu.artifact.local import LocalArtifact

__all__ = ["LocalArtifact"]
