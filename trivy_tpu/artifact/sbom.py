"""SBOM artifact: decode CycloneDX/SPDX and re-scan the listed packages.

Mirrors pkg/fanal/artifact/sbom/sbom.go + pkg/sbom/sbom.go Decode: format
sniffing, decode to an ArtifactDetail-shaped blob, straight to detectors (no
file walk).
"""

from __future__ import annotations

import hashlib
import json

from trivy_tpu.atypes import ArtifactReference, BlobInfo, PackageInfo
from trivy_tpu.cache.store import ArtifactCache
from trivy_tpu.ftypes import ArtifactType


def detect_format(data: dict) -> str:
    """pkg/sbom/sbom.go Decode format sniff."""
    if data.get("bomFormat") == "CycloneDX":
        return "cyclonedx"
    if str(data.get("spdxVersion", "")).startswith("SPDX-"):
        return "spdx"
    raise ValueError("unrecognized SBOM format (expected CycloneDX or SPDX JSON)")


def build_sbom_reference(
    detail, raw: bytes, cache, name: str, artifact_type: "ArtifactType"
) -> "ArtifactReference":
    """Decoded SBOM detail -> cached blob + artifact reference; the single
    decode-to-reference tail shared by the sbom artifact and the image
    remote-SBOM short-circuit."""
    blob = BlobInfo(
        os=detail.os,
        package_infos=(
            [PackageInfo(file_path="", packages=detail.packages)]
            if detail.packages
            else []
        ),
        applications=list(detail.applications),
    )
    blob_id = "sha256:" + hashlib.sha256(raw).hexdigest()
    cache.put_blob(blob_id, blob)
    return ArtifactReference(
        name=name,
        artifact_type=artifact_type.value,
        id=blob_id,
        blob_ids=[blob_id],
    )


class SbomArtifact:
    """artifact/sbom/sbom.go Artifact."""

    def __init__(self, target: str, cache: ArtifactCache, **_ignored):
        self.target = target
        self.cache = cache

    def inspect(self) -> ArtifactReference:
        with open(self.target, encoding="utf-8") as f:
            raw = f.read()
        from trivy_tpu.sbom.spdx import is_tag_value

        if is_tag_value(raw):
            # SPDX tag-value input (sbom.go's text sniff)
            from trivy_tpu.sbom.spdx import decode_tag_value

            detail = decode_tag_value(raw)
            return build_sbom_reference(
                detail, raw.encode(), self.cache, self.target,
                ArtifactType.SPDX,
            )
        data = json.loads(raw)
        fmt = detect_format(data)
        if fmt == "cyclonedx":
            from trivy_tpu.sbom.cyclonedx import decode

            artifact_type = ArtifactType.CYCLONEDX
        else:
            from trivy_tpu.sbom.spdx import decode

            artifact_type = ArtifactType.SPDX
        detail = decode(data)
        return build_sbom_reference(
            detail, raw.encode(), self.cache, self.target, artifact_type
        )

    def clean(self, ref: ArtifactReference) -> None:
        self.cache.delete_blobs(ref.blob_ids)
