"""SBOM artifact: decode CycloneDX/SPDX and re-scan the listed packages.

Mirrors pkg/fanal/artifact/sbom/sbom.go + pkg/sbom/sbom.go Decode: format
sniffing, decode to an ArtifactDetail-shaped blob, straight to detectors (no
file walk).
"""

from __future__ import annotations

import hashlib
import json

from trivy_tpu.atypes import ArtifactReference, BlobInfo, PackageInfo
from trivy_tpu.cache.store import ArtifactCache
from trivy_tpu.ftypes import ArtifactType


def build_sbom_reference(
    detail, raw: bytes, cache, name: str, artifact_type: "ArtifactType"
) -> "ArtifactReference":
    """Decoded SBOM detail -> cached blob + artifact reference; the single
    decode-to-reference tail shared by the sbom artifact and the image
    remote-SBOM short-circuit."""
    blob = BlobInfo(
        os=detail.os,
        package_infos=(
            [PackageInfo(file_path="", packages=detail.packages)]
            if detail.packages
            else []
        ),
        applications=list(detail.applications),
    )
    blob_id = "sha256:" + hashlib.sha256(raw).hexdigest()
    cache.put_blob(blob_id, blob)
    return ArtifactReference(
        name=name,
        artifact_type=artifact_type.value,
        id=blob_id,
        blob_ids=[blob_id],
    )


class SbomArtifact:
    """artifact/sbom/sbom.go Artifact."""

    def __init__(self, target: str, cache: ArtifactCache, **_ignored):
        self.target = target
        self.cache = cache

    def inspect(self) -> ArtifactReference:
        from trivy_tpu.sbom import decode_sbom

        with open(self.target, encoding="utf-8") as f:
            raw = f.read()
        detail, fmt = decode_sbom(raw)
        artifact_type = (
            ArtifactType.CYCLONEDX if fmt == "cyclonedx" else ArtifactType.SPDX
        )
        return build_sbom_reference(
            detail, raw.encode(), self.cache, self.target, artifact_type
        )

    def clean(self, ref: ArtifactReference) -> None:
        self.cache.delete_blobs(ref.blob_ids)
