"""Container image artifact: docker-save archives and OCI layouts.

Mirrors pkg/fanal/artifact/image/image.go over the archive-input sources
(pkg/fanal/image/{archive.go,oci.go}); daemon/registry sources are a
deployment concern behind the same interface.  Pipeline per image:

  image ID + per-layer diff IDs -> cache keys (sha256 + analyzer versions)
  cache.missing_blobs diff -> only uncached layers are analyzed (image.go:113)
  per missing layer: layer tar walk -> batched analyzer group -> BlobInfo
    with whiteout/opaque dirs (applier resolves overlayfs semantics later)
  image config analysis (history secret scan - imgconf analyzer)

The reference parallelizes layer inspection with a worker pipeline
(image.go:205-227); here each layer's files join the same device batch — the
batch axis absorbs the layer axis.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tarfile
import tempfile
from dataclasses import dataclass

logger = logging.getLogger(__name__)

from trivy_tpu.analyzer.core import AnalyzerGroup, AnalyzerOptions
from trivy_tpu.atypes import ArtifactInfo, ArtifactReference, BlobInfo
from trivy_tpu.cache import stats as cache_stats
from trivy_tpu.cache.store import ArtifactCache
from trivy_tpu.ftypes import ArtifactType
from trivy_tpu.walker.layer_tar import walk_layer_tar


@dataclass
class ImageSource:
    """Parsed archive: config JSON + ordered layer blob readers."""

    config: dict
    config_digest: str  # sha256:... of the raw config bytes
    layers: list  # list of callables -> file object
    repo_tags: list[str]
    repo_digests: list[str]
    # Registry sources attach a callable returning the image's OCI-referrer
    # CycloneDX SBOM (or None) — the remote-SBOM short-circuit input
    # (remote_sbom.go).
    sbom_fetcher: object | None = None
    # Holds a tempfile.TemporaryDirectory for OCI-in-tar extraction; its
    # finalizer removes the extracted blobs when the source is collected.
    _tmpdir: object | None = None

    @property
    def diff_ids(self) -> list[str]:
        return list((self.config.get("rootfs") or {}).get("diff_ids") or [])


def _sha256_hex(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


def load_docker_archive(path: str) -> ImageSource:
    """`docker save` tar: manifest.json lists config + layer paths."""
    tf = tarfile.open(path)
    names = tf.getnames()
    if "manifest.json" in names:
        manifest = json.loads(tf.extractfile("manifest.json").read())[0]
        config_name = manifest["Config"]
        raw_config = tf.extractfile(config_name).read()
        layer_names = manifest.get("Layers") or []
        return ImageSource(
            config=json.loads(raw_config),
            config_digest=_sha256_hex(raw_config),
            layers=[(lambda n=n: tf.extractfile(n)) for n in layer_names],
            repo_tags=list(manifest.get("RepoTags") or []),
            repo_digests=[],
        )
    if "index.json" in names:  # OCI layout packed as tar
        tmp = tempfile.TemporaryDirectory(prefix="trivy-tpu-oci-")
        with tf:
            tf.extractall(tmp.name, filter="data")
        src = load_oci_layout(tmp.name)
        src._tmpdir = tmp
        return src
    raise ValueError(f"unrecognized image archive: {path}")


def load_oci_layout(path: str) -> ImageSource:
    """OCI image layout directory (oci.go)."""

    def blob(digest: str) -> str:
        algo, _, hexd = digest.partition(":")
        return os.path.join(path, "blobs", algo, hexd)

    with open(os.path.join(path, "index.json"), encoding="utf-8") as f:
        index = json.load(f)
    manifest_desc = index["manifests"][0]
    with open(blob(manifest_desc["digest"]), encoding="utf-8") as f:
        manifest = json.load(f)
    with open(blob(manifest["config"]["digest"]), "rb") as f:
        raw_config = f.read()

    layers = [
        (lambda p=blob(l["digest"]): open(p, "rb")) for l in manifest["layers"]
    ]
    return ImageSource(
        config=json.loads(raw_config),
        config_digest=_sha256_hex(raw_config),
        layers=layers,
        repo_tags=[],
        repo_digests=[],
    )


def load_image(target: str) -> ImageSource:
    """Source resolution chain for archive inputs (image.go:26 analogue)."""
    if os.path.isdir(target):
        return load_oci_layout(target)
    return load_docker_archive(target)


def guess_base_image_index(history: list[dict]) -> int:
    """pkg/fanal/image/image.go:111 GuessBaseImageIndex: walk history from
    the bottom, skip the trailing empty layers (ENTRYPOINT/CMD of the built
    image), and treat the next CMD as the end of the base image."""
    base_index = -1
    found_non_empty = False
    for i in range(len(history) - 1, -1, -1):
        h = history[i]
        empty = bool(h.get("empty_layer"))
        if not found_non_empty:
            if empty:
                continue
            found_non_empty = True
        if not empty:
            continue
        created_by = h.get("created_by", "")
        if created_by.startswith("/bin/sh -c #(nop)  CMD") or created_by.startswith("CMD"):
            base_index = i
            break
    return base_index


def guess_base_layers(diff_ids: list[str], config: dict) -> list[str]:
    """image.go:399 guessBaseLayers: diff IDs of the guessed base image
    (empty layers carry no diff ID)."""
    history = list(config.get("history") or [])
    base_index = guess_base_image_index(history)
    out: list[str] = []
    di = 0
    for i, h in enumerate(history):
        if i > base_index:
            break
        if h.get("empty_layer"):
            continue
        if di >= len(diff_ids):
            return []
        out.append(diff_ids[di])
        di += 1
    return out


class ImageArtifact:
    """artifact/image/image.go Artifact."""

    # Class-level default: _layer_key must work on partially-constructed
    # instances too (tests build them via __new__ to probe key math).
    _secret_digest: str | None = None

    def __init__(
        self,
        target: str,
        cache: ArtifactCache,
        analyzer_options: AnalyzerOptions | None = None,
        source: ImageSource | None = None,
    ):
        self.target = target
        self.cache = cache
        self.group = AnalyzerGroup(analyzer_options)
        # `source` lets the daemon/registry chain (trivy_tpu/image) hand in
        # an already-resolved image; plain paths load as archives/layouts.
        self.source = source if source is not None else load_image(target)
        self._secret_digest: str | None = None
        # Hit/miss accounting of the last inspect() (Explain.cache, bench).
        self.last_cache_stats: dict = {}

    def _secret_ruleset_digest(self) -> str:
        """Digest of the ruleset the secret analyzer would scan with —
        derived from config alone (registry/digest.py), never by building
        the engine: on a fully-warm inspect the engine must not be
        constructed at all.  Part of every secret-enabled layer key, so a
        `rules push` invalidates exactly the entries whose verdicts the
        new rules could change."""
        if self._secret_digest is not None:
            return self._secret_digest
        digest = ""
        if any(a.type() == "secret" for a in self.group.analyzers):
            from trivy_tpu.registry.digest import (
                default_ruleset_digest,
                ruleset_digest,
            )

            opt = self.group.options.secret_scanner_option
            config_path = getattr(opt, "config_path", "")
            if config_path:
                from trivy_tpu.rules.model import build_ruleset, load_config

                digest = ruleset_digest(build_ruleset(load_config(config_path)))
            else:
                digest = default_ruleset_digest()
        self._secret_digest = digest
        return digest

    def _layer_key(self, diff_id: str, disabled: tuple[str, ...] = ()) -> str:
        h = hashlib.sha256()
        h.update(diff_id.encode())
        h.update(json.dumps(self.group.analyzer_versions(), sort_keys=True).encode())
        h.update(self.group.options.cache_key_extra.encode())
        # Per-layer disabled analyzers change the blob's contents, so they
        # are part of the key (image.go calcCacheKey includes them).
        h.update(json.dumps(sorted(disabled)).encode())
        if "secret" not in disabled:
            h.update(self._secret_ruleset_digest().encode())
        return "sha256:" + h.hexdigest()

    def _artifact_key(self) -> str:
        h = hashlib.sha256()
        h.update(self.source.config_digest.encode())
        h.update(json.dumps(self.group.analyzer_versions(), sort_keys=True).encode())
        h.update(self.group.options.cache_key_extra.encode())
        return "sha256:" + h.hexdigest()

    def _try_remote_sbom(self) -> ArtifactReference | None:
        """Remote-SBOM short-circuit (image.go:92-98 + remote_sbom.go): a
        CycloneDX SBOM attached via OCI referrers replaces the layer walk
        entirely — packages come from the attestation, not re-analysis."""
        fetcher = getattr(self.source, "sbom_fetcher", None)
        if fetcher is None:
            return None
        doc = fetcher()
        if not doc:
            return None
        from trivy_tpu.sbom.cyclonedx import decode

        try:
            detail = decode(doc)
        except Exception as e:
            logger.warning("OCI-referrer SBOM undecodable: %s", e)
            return None
        logger.info("Found SBOM in the OCI referrers; skipping layer scan")
        from trivy_tpu.artifact.sbom import build_sbom_reference

        return build_sbom_reference(
            detail,
            json.dumps(doc, sort_keys=True).encode(),
            self.cache,
            self.target,
            ArtifactType.CYCLONEDX,
        )

    def inspect(self) -> ArtifactReference:
        if "oci" in (self.group.options.sbom_sources or []):
            ref = self._try_remote_sbom()
            if ref is not None:
                return ref
        src = self.source
        diff_ids = src.diff_ids
        # Base layers skip secret scanning (image.go:100-102, 209-213): the
        # base image's secrets are the base image publisher's problem, and
        # scanning them again in every derived image is pure waste.
        base_diff_ids = set(guess_base_layers(diff_ids, src.config))
        layer_disabled = [
            ("secret",) if d in base_diff_ids else () for d in diff_ids
        ]
        layer_keys = [
            self._layer_key(d, dis)
            for d, dis in zip(diff_ids, layer_disabled)
        ]
        artifact_key = self._artifact_key()

        # The imgconf blob holds a secret scan of the config JSON, so its
        # key carries the ruleset digest too (rules push invalidates it).
        config_key = "sha256:" + hashlib.sha256(
            (artifact_key + ":imgconf:" + self._secret_ruleset_digest()).encode()
        ).hexdigest()
        missing_artifact, missing = self.cache.missing_blobs(
            artifact_key, layer_keys + [config_key]
        )
        total_blobs = len(layer_keys) + 1
        cache_stats.record_request("artifact", "miss", len(missing))
        cache_stats.record_request(
            "artifact", "hit", total_blobs - len(missing)
        )
        self.last_cache_stats = {
            "blobs": total_blobs,
            "hits": total_blobs - len(missing),
            "misses": len(missing),
            "artifact_hit": not missing_artifact,
            "ruleset_digest": self._secret_ruleset_digest(),
        }

        history = [
            h for h in (src.config.get("history") or []) if not h.get("empty_layer")
        ]
        for i, (diff_id, key) in enumerate(zip(diff_ids, layer_keys)):
            if key not in missing:
                continue
            created_by = history[i].get("created_by", "") if i < len(history) else ""
            self._inspect_layer(
                i, diff_id, key, created_by, set(layer_disabled[i])
            )

        if missing_artifact:
            cfg = src.config
            self.cache.put_artifact(
                artifact_key,
                ArtifactInfo(
                    architecture=cfg.get("architecture", ""),
                    created=cfg.get("created", ""),
                    docker_version=cfg.get("docker_version", ""),
                    os_name=cfg.get("os", ""),
                ),
            )

        if config_key in missing:
            self._config_analysis_blob(config_key)
        blob_ids = layer_keys + [config_key]

        return ArtifactReference(
            name=self.target,
            artifact_type=ArtifactType.CONTAINER_IMAGE.value,
            id=artifact_key,
            blob_ids=blob_ids,
            image_metadata={
                "ImageID": src.config_digest,
                "DiffIDs": diff_ids,
                "RepoTags": src.repo_tags,
                "RepoDigests": src.repo_digests,
                "ImageConfig": src.config,
            },
        )

    def _inspect_layer(
        self,
        index: int,
        diff_id: str,
        key: str,
        created_by: str,
        disabled: set[str] | None = None,
    ) -> None:
        """image.go:242 inspectLayer."""
        cache_stats.event("layer_analysis")
        with self.source.layers[index]() as f:
            # Entries read lazily through the open tar; analysis happens
            # inside the `with` so only claimed files materialize.
            layer = walk_layer_tar(f)
            result = self.group.analyze_entries("", layer.entries, disabled)
            result.merge(self.group.post_analyze())
            from trivy_tpu.handler import run_post_handlers

            run_post_handlers(result)
            result.sort()
        blob = BlobInfo(
            diff_id=diff_id,
            created_by=created_by,
            opaque_dirs=layer.opaque_dirs,
            whiteout_files=layer.whiteout_files,
            os=result.os,
            package_infos=list(result.package_infos),
            applications=list(result.applications),
            secrets=list(result.secrets),
            licenses=list(result.licenses),
            misconfigurations=list(result.misconfigs),
            custom_resources=list(result.configs),
            build_info=result.build_info,
        )
        self.cache.put_blob(key, blob)

    def _config_analysis_blob(self, key: str) -> None:
        """Image-config analysis (imgconf analyzers): secrets in the config
        JSON and misconfig over the history-reconstructed Dockerfile, stored
        as one extra blob so it merges through the applier and survives the
        client/server split.  Each sub-analysis only runs when its analyzer
        is enabled; the blob is cache-gated like layer blobs (always put,
        possibly empty, so missing_blobs stays accurate)."""
        cache_stats.event("config_analysis")
        from trivy_tpu.analyzer.imgconf import (
            scan_config_misconfig,
            scan_config_secrets,
        )

        enabled = {a.type() for a in self.group.analyzers}
        secrets = []
        if "secret" in enabled:
            secret_analyzer = next(
                a for a in self.group.analyzers if a.type() == "secret"
            )
            res = scan_config_secrets(self.source.config, secret_analyzer.engine)
            if res is not None:
                secrets.append(res)
        mc = scan_config_misconfig(self.source.config) if "dockerfile" in enabled else None
        if mc is not None:
            # Distinct path so a real /Dockerfile scanned in a layer is never
            # overwritten by the lossy history reconstruction.
            mc.file_path = "Dockerfile (image config)"
        self.cache.put_blob(
            key,
            BlobInfo(
                secrets=secrets,
                misconfigurations=[mc] if mc is not None else [],
            ),
        )

    def clean(self, ref: ArtifactReference) -> None:
        pass  # layer blobs stay cached (content-addressed)
