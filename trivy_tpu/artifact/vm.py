"""VM disk-image artifact (pkg/fanal/artifact/vm/vm.go).

Walks every ext/XFS partition of a raw disk image through the analyzer
group, producing one blob per partition keyed on the image digest +
partition offset + analyzer versions (the content-addressed cache
contract)."""

from __future__ import annotations

import hashlib
import json
import logging
import os

from trivy_tpu.analyzer.core import AnalyzerGroup, AnalyzerOptions
from trivy_tpu.atypes import ArtifactInfo, ArtifactReference, BlobInfo
from trivy_tpu.ftypes import ArtifactType
from trivy_tpu.cache.store import ArtifactCache
from trivy_tpu.handler import run_post_handlers
from trivy_tpu.vm import Ext4Error, Ext4Reader, is_ext, is_lvm, list_partitions
from trivy_tpu.walker.fs import FileEntry

logger = logging.getLogger(__name__)


class VMArtifact:
    def __init__(
        self,
        target: str,
        cache: ArtifactCache,
        analyzer_options: AnalyzerOptions | None = None,
    ):
        self.target = target
        self.cache = cache
        self.group = AnalyzerGroup(analyzer_options)

    def _image_digest(self) -> str:
        if self.target.startswith(("ebs:", "ami:")):
            # Remote snapshots are content-addressed by their immutable id.
            h = hashlib.sha256(self.target.encode())
            return "sha256:" + h.hexdigest()
        h = hashlib.sha256()
        with open(self.target, "rb") as f:
            # Digest head+tail+size: hashing a multi-GB image in full would
            # dominate scan time; partition tables and superblocks pin the
            # identity well enough for cache keying.
            h.update(f.read(1 << 20))
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - (1 << 20)))
            h.update(f.read(1 << 20))
            h.update(str(size).encode())
        return "sha256:" + h.hexdigest()

    def _open_image(self):
        """(file-like, size): local raw image, local VMDK (wrapped into
        its flat view), or a remote EBS snapshot (`ebs:`/`ami:` targets)."""
        from trivy_tpu.vm.ebs import open_vm_target
        from trivy_tpu.vm.vmdk import VmdkFile, is_vmdk

        remote = open_vm_target(self.target)
        if remote is not None:
            return remote, remote.size
        raw = open(self.target, "rb")
        if is_vmdk(raw):
            vmdk = VmdkFile(raw)
            return vmdk, vmdk.size
        return raw, os.path.getsize(self.target)

    def inspect(self) -> ArtifactReference:
        digest = self._image_digest()
        # walker-version component: bump when partition/LV traversal
        # changes what a scan can see (v2: LVM2 linear LV support) —
        # cached empty results from older walkers must not stick.
        versions = (
            json.dumps(self.group.analyzer_versions(), sort_keys=True)
            + self.group.options.cache_key_extra
            + "|vm-walker:4"  # v4: VMDK + EBS/AMI sources
        )
        img, size = self._open_image()
        blob_ids: list[str] = []
        try:
            partitions = list_partitions(img, size)
            keys = []
            for part in partitions:
                key_h = hashlib.sha256()
                key_h.update(digest.encode())
                key_h.update(str(part.offset).encode())
                key_h.update(versions.encode())
                keys.append("sha256:" + key_h.hexdigest())
            blob_ids.extend(keys)
            # One batched round-trip (the image artifact's pattern) instead
            # of a HEAD pair per partition on remote backends.
            _missing_artifact, missing = self.cache.missing_blobs(digest, keys)
            for part, key in zip(partitions, keys):
                if key not in missing:
                    continue
                blob = self._inspect_partition(img, part)
                self.cache.put_blob(key, blob)
        finally:
            close = getattr(img, "close", None)
            if close is not None:
                close()
        self.cache.put_artifact(digest, ArtifactInfo())
        return ArtifactReference(
            name=self.target,
            artifact_type=ArtifactType.VM.value,
            id=digest,
            blob_ids=blob_ids,
        )

    def _inspect_partition(self, img, part) -> BlobInfo:
        if is_lvm(img, part.offset):
            # LVM physical volume: map its linear logical volumes and walk
            # each ext filesystem found inside (vm.go:195 / go-lvm).
            from trivy_tpu.vm.lvm import LVReader, LvmError, logical_volumes

            try:
                lvs = logical_volumes(img, part.offset)
            except LvmError as e:
                logger.warning(
                    "partition %d: unreadable LVM metadata (%s); skipped",
                    part.index, e,
                )
                return BlobInfo()
            merged = BlobInfo()
            scanned = 0
            from trivy_tpu.vm.xfs import is_xfs

            for lv in lvs:
                view = LVReader(img, lv)
                if not (is_ext(view, 0) or is_xfs(view, 0)):
                    logger.info(
                        "LV %s/%s holds no ext/XFS filesystem; skipped",
                        lv.vg_name, lv.name,
                    )
                    continue
                scanned += 1
                merged = self._merge_blob(
                    merged, self._inspect_fs(view, 0, f"LV {lv.name}")
                )
            if not scanned:
                logger.warning(
                    "partition %d: no readable linear LVs", part.index
                )
            return merged
        from trivy_tpu.vm.xfs import is_xfs

        if not (is_ext(img, part.offset) or is_xfs(img, part.offset)):
            logger.info(
                "partition %d holds no ext/XFS filesystem; skipped",
                part.index,
            )
            return BlobInfo()
        return self._inspect_fs(img, part.offset, f"partition {part.index}")

    @staticmethod
    def _merge_blob(into: BlobInfo, other: BlobInfo) -> BlobInfo:
        into.os = into.os or other.os
        into.package_infos.extend(other.package_infos)
        into.applications.extend(other.applications)
        into.secrets.extend(other.secrets)
        into.licenses.extend(other.licenses)
        into.misconfigurations.extend(other.misconfigurations)
        into.custom_resources.extend(other.custom_resources)
        into.build_info = into.build_info or other.build_info
        return into

    def _inspect_fs(self, img, offset: int, what: str) -> BlobInfo:
        """Walk one ext or XFS filesystem through the analyzer group."""
        from trivy_tpu.vm.xfs import XfsError, XfsReader, is_xfs

        try:
            if is_xfs(img, offset):
                reader = XfsReader(img, offset)
            else:
                reader = Ext4Reader(img, offset)
        except (Ext4Error, XfsError) as e:
            logger.warning("%s: %s", what, e)
            return BlobInfo()

        def entries():
            # Structural failures mid-walk (btree dirs, corrupt entries)
            # end THIS filesystem's walk loudly with whatever was already
            # yielded — one bad directory must not abort the disk scan;
            # per-FILE opener failures are handled downstream (OSError
            # tolerance in _read_inputs).
            it = reader.walk()
            while True:
                try:
                    e = next(it)
                except StopIteration:
                    return
                except (Ext4Error, XfsError) as err:
                    logger.warning("%s: walk aborted: %s", what, err)
                    return
                yield FileEntry(
                    path=e.path, size=e.size, mode=e.mode, opener=e.opener
                )

        result = self.group.analyze_entries("", entries())
        result.merge(self.group.post_analyze())
        run_post_handlers(result)
        result.sort()
        return BlobInfo(
            os=result.os,
            package_infos=list(result.package_infos),
            applications=list(result.applications),
            secrets=list(result.secrets),
            licenses=list(result.licenses),
            misconfigurations=list(result.misconfigs),
            custom_resources=list(result.configs),
            build_info=result.build_info,
        )

    def clean(self, ref: ArtifactReference) -> None:
        pass
