"""Local filesystem artifact.

Mirrors pkg/fanal/artifact/local/fs.go: walk the target directory, run the
analyzer group (batched here — the device engine sees the whole walk as one
batch), store the single resulting blob in the cache keyed by
sha256(blob JSON + analyzer versions) (fs.go:174-188), and return an
ArtifactReference whose blob ID the applier later resolves.
"""

from __future__ import annotations

import hashlib
import json
import os

from trivy_tpu.analyzer.core import AnalyzerGroup, AnalyzerOptions
from trivy_tpu.atypes import ArtifactInfo, ArtifactReference, BlobInfo, OS
from trivy_tpu.cache.store import ArtifactCache
from trivy_tpu.ftypes import ArtifactType
from trivy_tpu.walker.fs import FSWalker, WalkOption


class LocalArtifact:
    """artifact/local/fs.go Artifact."""

    def __init__(
        self,
        root: str,
        cache: ArtifactCache,
        analyzer_options: AnalyzerOptions | None = None,
        walk_option: WalkOption | None = None,
        artifact_type: ArtifactType = ArtifactType.FILESYSTEM,
    ):
        self.root = root
        self.cache = cache
        self.group = AnalyzerGroup(analyzer_options)
        self.walker = FSWalker(walk_option)
        self.artifact_type = artifact_type

    def inspect(self) -> ArtifactReference:
        """fs.go:71 Inspect."""
        result = self.group.analyze_entries(self.root, self.walker.walk(self.root))
        # Post-analyzers see their composite FS after the walk (fs.go:120
        # PostAnalyze): cross-file context like lockfile + manifest pairs.
        result.merge(self.group.post_analyze())
        from trivy_tpu.handler import run_post_handlers

        run_post_handlers(result)
        result.sort()

        blob = BlobInfo(
            os=result.os if isinstance(result.os, OS) else None,
            package_infos=list(result.package_infos),
            applications=list(result.applications),
            secrets=list(result.secrets),
            licenses=list(result.licenses),
            misconfigurations=list(result.misconfigs),
            custom_resources=list(result.configs),
            build_info=result.build_info,
        )
        blob_id = self._calc_cache_key(blob)
        self.cache.put_blob(blob_id, blob)

        name = self.root
        if self.artifact_type == ArtifactType.FILESYSTEM:
            name = os.path.abspath(self.root) if self.root == "." else self.root

        return ArtifactReference(
            name=name,
            artifact_type=self.artifact_type.value,
            id=blob_id,
            blob_ids=[blob_id],
        )

    def _calc_cache_key(self, blob: BlobInfo) -> str:
        """fs.go:174-188 calcCacheKey: hash of blob JSON + analyzer versions."""
        h = hashlib.sha256()
        h.update(json.dumps(blob.to_json(), sort_keys=True).encode())
        h.update(
            json.dumps(self.group.analyzer_versions(), sort_keys=True).encode()
        )
        return "sha256:" + h.hexdigest()

    def clean(self, ref: ArtifactReference) -> None:
        self.cache.delete_blobs(ref.blob_ids)
