"""Git repository artifact.

Mirrors pkg/fanal/artifact/repo/git.go: resolve the target (local working
tree, or clone a remote URL to a temp dir with --branch/--tag/--commit), then
delegate to the local filesystem artifact.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import tempfile

from trivy_tpu.analyzer.core import AnalyzerOptions
from trivy_tpu.artifact.local import LocalArtifact
from trivy_tpu.atypes import ArtifactReference
from trivy_tpu.cache.store import ArtifactCache
from trivy_tpu.ftypes import ArtifactType
from trivy_tpu.walker.fs import WalkOption


class RepositoryArtifact:
    """artifact/repo/git.go Artifact."""

    def __init__(
        self,
        target: str,
        cache: ArtifactCache,
        analyzer_options: AnalyzerOptions | None = None,
        walk_option: WalkOption | None = None,
        branch: str = "",
        tag: str = "",
        commit: str = "",
    ):
        self.target = target
        self.branch = branch
        self.tag = tag
        self.commit = commit
        self._tmpdir: str | None = None

        root = self._resolve()
        self._local = LocalArtifact(
            root,
            cache,
            analyzer_options=analyzer_options,
            walk_option=walk_option,
            artifact_type=ArtifactType.REPOSITORY,
        )

    def _resolve(self) -> str:
        if os.path.isdir(self.target):
            return self.target
        # Remote URL: shallow clone like git.go newURL/cloneOptions.
        self._tmpdir = tempfile.mkdtemp(prefix="trivy-tpu-repo-")
        cmd = ["git", "clone", "--depth", "1"]
        if self.branch:
            cmd += ["--branch", self.branch]
        elif self.tag:
            cmd += ["--branch", self.tag]
        cmd += [self.target, self._tmpdir]
        subprocess.run(cmd, check=True, capture_output=True)
        if self.commit:
            subprocess.run(
                ["git", "-C", self._tmpdir, "fetch", "--depth", "1", "origin", self.commit],
                check=True,
                capture_output=True,
            )
            subprocess.run(
                ["git", "-C", self._tmpdir, "checkout", self.commit],
                check=True,
                capture_output=True,
            )
        return self._tmpdir

    def inspect(self) -> ArtifactReference:
        ref = self._local.inspect()
        ref.name = self.target
        ref.artifact_type = ArtifactType.REPOSITORY.value
        return ref

    def clean(self, ref: ArtifactReference) -> None:
        self._local.clean(ref)
        if self._tmpdir:
            shutil.rmtree(self._tmpdir, ignore_errors=True)
