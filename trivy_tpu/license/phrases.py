"""Distinctive-phrase license sieve (the corpus-blind tier).

Mirrors pkg/licensing/classifier.go's keyword classification: each SPDX
id is pinned by a phrase set over normalized text (lowercase, collapsed
whitespace), ALL of which must appear; the first (most specific) match
wins.  Shared verbatim by the host analyzer (analyzer/license.py) and
the device license program (programs/license.py) — the decision code
living in ONE place is what makes the two backends byte-identical.
"""

from __future__ import annotations

import re

from trivy_tpu.ltypes import LicenseFinding

# Each entry: (SPDX id, [phrases — ALL must appear in normalized text]).
_PHRASES: list[tuple[str, list[str]]] = [
    ("Apache-2.0", ["apache license", "version 2.0"]),
    # "remote network interaction" is AGPL-3.0's own section 13 heading;
    # the license NAME appears in GPL-3.0 section 13 and MPL-2.0's
    # Secondary Licenses clause, so it cannot distinguish on its own.
    ("AGPL-3.0", ["gnu affero general public license", "remote network interaction"]),
    ("LGPL-3.0", ["gnu lesser general public license", "version 3"]),
    ("LGPL-2.1", ["gnu lesser general public license", "version 2.1"]),
    ("GPL-3.0", ["gnu general public license", "version 3"]),
    ("GPL-2.0", ["gnu general public license", "version 2"]),
    ("MPL-2.0", ["mozilla public license", "version 2.0"]),
    ("EPL-2.0", ["eclipse public license", "v 2.0"]),
    (
        "BSD-3-Clause",
        [
            "redistribution and use in source and binary forms",
            "neither the name",
        ],
    ),
    (
        "BSD-2-Clause",
        ["redistribution and use in source and binary forms"],
    ),
    (
        "MIT",
        [
            "permission is hereby granted, free of charge",
            "the software is provided \"as is\"",
        ],
    ),
    (
        "ISC",
        [
            "permission to use, copy, modify, and/or distribute this software",
        ],
    ),
    ("Unlicense", ["this is free and unencumbered software"]),
    ("CC0-1.0", ["cc0 1.0"]),
    ("Zlib", ["this software is provided 'as-is'", "zlib"]),
]

# Per-entry anchor tokens for the device sieve: one single-word token
# drawn from each entry's REQUIRED phrases.  Single words only — phrase
# matching runs over whitespace-collapsed text, so a multi-word phrase
# can span a raw line break that a contiguous byte probe would miss,
# while a single token survives normalization verbatim (lowercasing is
# exactly the probe's case fold, and collapsing whitespace never creates
# new intra-word adjacencies).  Every phrase match therefore implies its
# anchor token is present in the raw bytes — the necessary-condition
# contract the gram sieve needs (engine/probes.py epistemics).
_PHRASE_ANCHORS: dict[str, str] = {
    "Apache-2.0": "apache",
    "AGPL-3.0": "affero",
    "LGPL-3.0": "lesser",
    "LGPL-2.1": "lesser",
    "GPL-3.0": "general",
    "GPL-2.0": "general",
    "MPL-2.0": "mozilla",
    "EPL-2.0": "eclipse",
    "BSD-3-Clause": "redistribution",
    "BSD-2-Clause": "redistribution",
    "MIT": "permission",
    "ISC": "permission",
    "Unlicense": "unencumbered",
    "CC0-1.0": "cc0",
    "Zlib": "zlib",
}

# Generic tokens that pin the full-text similarity tier: any text the
# cosine classifier accepts (>= 0.9 against a corpus license) shares the
# overwhelming majority of its trigram mass with that license, and every
# corpus text contains several of these (verified at program compile
# time by programs/license.py).  An adversarially anchor-stripped
# near-verbatim text sits outside this modeled space — the same
# epistemic line the secret sieve draws for its regex factors.
_GENERIC_ANCHORS: tuple[str, ...] = (
    "license",
    "licence",
    "copyright",
    "warranty",
    "warranties",
    "permission",
    "redistribution",
    "public domain",
    "copying",
)


def anchor_tokens() -> list[str]:
    """The deduplicated device-sieve gate vocabulary, stable order."""
    seen: dict[str, None] = {}
    for tok in list(_PHRASE_ANCHORS.values()) + list(_GENERIC_ANCHORS):
        seen.setdefault(tok)
    return list(seen)


def normalize(text: str) -> str:
    return re.sub(r"\s+", " ", text.lower())


def classify_text(text: str) -> list[LicenseFinding]:
    """pkg/licensing/classifier.go Classify, phrase-based."""
    text = normalize(text)
    findings = []
    for spdx_id, phrases in _PHRASES:
        if all(p in text for p in phrases):
            findings.append(LicenseFinding.of(spdx_id, confidence=0.9))
            break  # first (most specific) match wins
    return findings


def classify(content: bytes) -> list[LicenseFinding]:
    return classify_text(content.decode("utf-8", errors="replace"))
