"""The license decision tree, backend-independent.

One function computes the per-text license verdict from the full-text
cosine classifier plus the phrase sieve (fallback + corpus-blind veto).
Both consumers call it on exactly the texts they classify:

- the host analyzer (analyzer/license.py) on every claimed license file;
- the device license program (programs/license.py) on the files the
  anchor-token sieve marked candidates.

Because the decision code is shared and per-text independent (the cosine
matmul scores each row against the fixed corpus matrix, no cross-text
coupling), the two backends are byte-identical on any text they both
evaluate — the program's parity claim reduces to its candidate set
covering every text the host tree would accept, which the anchor tokens
in license/phrases.py are chosen to guarantee for the phrase tier and
programs/license.py verifies against the corpus at compile time for the
cosine tier.
"""

from __future__ import annotations

from trivy_tpu.license.classifier import shared_classifier
from trivy_tpu.license.phrases import classify_text
from trivy_tpu.ltypes import LicenseFinding


def decide_findings(texts: list[str]) -> list[list[LicenseFinding]]:
    """Per-text license findings ([] = no license), one classifier batch."""
    if not texts:
        return []
    clf = shared_classifier()
    matches = clf.classify_batch(texts)
    out: list[list[LicenseFinding]] = []
    for text, match in zip(texts, matches):
        if match is not None and match.confidence >= 0.99:
            # Essentially-exact corpus match: the phrase sieve can
            # add nothing (a verbatim corpus text merely MENTIONING
            # another license must not be vetoed) — skip its pass.
            findings = [
                LicenseFinding.of(match.license, confidence=match.confidence)
            ]
        else:
            phrase = classify_text(text)
            if match is None:
                findings = phrase
            # Corpus-blind veto: licenses absent from the full-text
            # corpus score high against near-identical relatives
            # (AGPL-3.0 vs GPL-3.0 is ~0.98 cosine).  When the phrase
            # sieve names a license the corpus cannot represent, its
            # more specific answer wins.
            elif (
                phrase
                and phrase[0].name != match.license
                and phrase[0].name not in clf.names
            ):
                findings = phrase
            else:
                findings = [
                    LicenseFinding.of(
                        match.license, confidence=match.confidence
                    )
                ]
        out.append(findings)
    return out
