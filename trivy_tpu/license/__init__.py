from trivy_tpu.license.classifier import (  # noqa: F401
    FullTextClassifier,
    shared_classifier,
)
