"""Full-text license classification as one batched similarity matmul.

The reference classifies license files with google/licenseclassifier
(pkg/licensing/classifier.go): normalized text against a canonical
corpus with a confidence threshold.  The TPU-native formulation: every
candidate file becomes a hashed token-trigram histogram (L2-normalized),
the corpus is a [L, D] matrix built once, and classifying a whole scan's
worth of license files is a single [F, D] x [D, L] matmul — MXU work,
batched, static shapes — with cosine scores as confidences.

Corpus sources, in override order: embedded short templates, the PACKAGED
canonical corpus (trivy_tpu/license/corpus/*.txt — 24 SPDX texts shipped
with the framework, so `--license-full` works without any OS-provided
corpus; license texts are freely redistributable), then whatever the
host's /usr/share/common-licenses adds on top.
"""

from __future__ import annotations

import os
import re
import zlib
from dataclasses import dataclass

import numpy as np

DIM = 4096  # histogram buckets; collisions are noise the L2 dot tolerates
DEFAULT_CONFIDENCE = 0.9  # reference classifier's default threshold

_WORD = re.compile(r"[a-z0-9]+")
_COPYRIGHT_LINE = re.compile(r"^.*copyright (\(c\)|©|[0-9]{4}).*$", re.M)

# Short standardized license wordings (public-domain boilerplate).
_EMBEDDED: dict[str, str] = {
    "MIT": """
Permission is hereby granted, free of charge, to any person obtaining a
copy of this software and associated documentation files (the "Software"),
to deal in the Software without restriction, including without limitation
the rights to use, copy, modify, merge, publish, distribute, sublicense,
and/or sell copies of the Software, and to permit persons to whom the
Software is furnished to do so, subject to the following conditions:
The above copyright notice and this permission notice shall be included
in all copies or substantial portions of the Software.
THE SOFTWARE IS PROVIDED "AS IS", WITHOUT WARRANTY OF ANY KIND, EXPRESS
OR IMPLIED, INCLUDING BUT NOT LIMITED TO THE WARRANTIES OF
MERCHANTABILITY, FITNESS FOR A PARTICULAR PURPOSE AND NONINFRINGEMENT.
IN NO EVENT SHALL THE AUTHORS OR COPYRIGHT HOLDERS BE LIABLE FOR ANY
CLAIM, DAMAGES OR OTHER LIABILITY, WHETHER IN AN ACTION OF CONTRACT,
TORT OR OTHERWISE, ARISING FROM, OUT OF OR IN CONNECTION WITH THE
SOFTWARE OR THE USE OR OTHER DEALINGS IN THE SOFTWARE.
""",
    "ISC": """
Permission to use, copy, modify, and/or distribute this software for any
purpose with or without fee is hereby granted, provided that the above
copyright notice and this permission notice appear in all copies.
THE SOFTWARE IS PROVIDED "AS IS" AND THE AUTHOR DISCLAIMS ALL WARRANTIES
WITH REGARD TO THIS SOFTWARE INCLUDING ALL IMPLIED WARRANTIES OF
MERCHANTABILITY AND FITNESS. IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR
ANY SPECIAL, DIRECT, INDIRECT, OR CONSEQUENTIAL DAMAGES OR ANY DAMAGES
WHATSOEVER RESULTING FROM LOSS OF USE, DATA OR PROFITS, WHETHER IN AN
ACTION OF CONTRACT, NEGLIGENCE OR OTHER TORTIOUS ACTION, ARISING OUT OF
OR IN CONNECTION WITH THE USE OR PERFORMANCE OF THIS SOFTWARE.
""",
    "BSD-3-Clause": """
Redistribution and use in source and binary forms, with or without
modification, are permitted provided that the following conditions are met:
1. Redistributions of source code must retain the above copyright notice,
this list of conditions and the following disclaimer.
2. Redistributions in binary form must reproduce the above copyright
notice, this list of conditions and the following disclaimer in the
documentation and/or other materials provided with the distribution.
3. Neither the name of the copyright holder nor the names of its
contributors may be used to endorse or promote products derived from this
software without specific prior written permission.
THIS SOFTWARE IS PROVIDED BY THE COPYRIGHT HOLDERS AND CONTRIBUTORS
"AS IS" AND ANY EXPRESS OR IMPLIED WARRANTIES, INCLUDING, BUT NOT
LIMITED TO, THE IMPLIED WARRANTIES OF MERCHANTABILITY AND FITNESS FOR A
PARTICULAR PURPOSE ARE DISCLAIMED. IN NO EVENT SHALL THE COPYRIGHT
HOLDER OR CONTRIBUTORS BE LIABLE FOR ANY DIRECT, INDIRECT, INCIDENTAL,
SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT NOT LIMITED
TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE, DATA, OR
PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY THEORY OF
LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT (INCLUDING
NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF THIS
SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
""",
    "BSD-2-Clause": """
Redistribution and use in source and binary forms, with or without
modification, are permitted provided that the following conditions are met:
1. Redistributions of source code must retain the above copyright notice,
this list of conditions and the following disclaimer.
2. Redistributions in binary form must reproduce the above copyright
notice, this list of conditions and the following disclaimer in the
documentation and/or other materials provided with the distribution.
THIS SOFTWARE IS PROVIDED BY THE COPYRIGHT HOLDERS AND CONTRIBUTORS
"AS IS" AND ANY EXPRESS OR IMPLIED WARRANTIES, INCLUDING, BUT NOT
LIMITED TO, THE IMPLIED WARRANTIES OF MERCHANTABILITY AND FITNESS FOR A
PARTICULAR PURPOSE ARE DISCLAIMED. IN NO EVENT SHALL THE COPYRIGHT
HOLDER OR CONTRIBUTORS BE LIABLE FOR ANY DIRECT, INDIRECT, INCIDENTAL,
SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT NOT LIMITED
TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE, DATA, OR
PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY THEORY OF
LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT (INCLUDING
NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF THIS
SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
""",
    "Unlicense": """
This is free and unencumbered software released into the public domain.
Anyone is free to copy, modify, publish, use, compile, sell, or
distribute this software, either in source code form or as a compiled
binary, for any purpose, commercial or non-commercial, and by any means.
In jurisdictions that recognize copyright laws, the author or authors of
this software dedicate any and all copyright interest in the software to
the public domain. We make this dedication for the benefit of the public
at large and to the detriment of our heirs and successors. We intend
this dedication to be an overt act of relinquishment in perpetuity of
all present and future rights to this software under copyright law.
THE SOFTWARE IS PROVIDED "AS IS", WITHOUT WARRANTY OF ANY KIND, EXPRESS
OR IMPLIED, INCLUDING BUT NOT LIMITED TO THE WARRANTIES OF
MERCHANTABILITY, FITNESS FOR A PARTICULAR PURPOSE AND NONINFRINGEMENT.
IN NO EVENT SHALL THE AUTHORS BE LIABLE FOR ANY CLAIM, DAMAGES OR OTHER
LIABILITY, WHETHER IN AN ACTION OF CONTRACT, TORT OR OTHERWISE, ARISING
FROM, OUT OF OR IN CONNECTION WITH THE SOFTWARE OR THE USE OR OTHER
DEALINGS IN THE SOFTWARE.
""",
}

# Map /usr/share/common-licenses filenames to SPDX ids.
_SYSTEM_LICENSES = {
    "Apache-2.0": "Apache-2.0",
    "GPL-2": "GPL-2.0",
    "GPL-3": "GPL-3.0",
    "LGPL-2.1": "LGPL-2.1",
    "LGPL-3": "LGPL-3.0",
    "MPL-2.0": "MPL-2.0",
    "CC0-1.0": "CC0-1.0",
    "Artistic": "Artistic-1.0",
}
_SYSTEM_DIR = "/usr/share/common-licenses"


def normalize_tokens(text: str) -> list[str]:
    """licenseclassifier-style normalization: lowercase, copyright lines
    out, words only."""
    text = _COPYRIGHT_LINE.sub(" ", text.lower())
    return _WORD.findall(text)


def _fingerprint(tokens: list[str]) -> np.ndarray:
    """Hashed token-trigram histogram, L2-normalized float32 [DIM]."""
    vec = np.zeros(DIM, dtype=np.float32)
    if len(tokens) < 3:
        return vec
    joined = [" ".join(tokens[i : i + 3]) for i in range(len(tokens) - 2)]
    for gram in joined:
        vec[zlib.crc32(gram.encode()) % DIM] += 1.0
    norm = float(np.linalg.norm(vec))
    if norm > 0:
        vec /= norm
    return vec


@dataclass
class Match:
    license: str
    confidence: float


class FullTextClassifier:
    """Corpus matrix built once; classification is one batched matmul."""

    PACKAGED_DIR = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "corpus"
    )

    def __init__(self, extra: dict[str, str] | None = None):
        corpus: dict[str, str] = dict(_EMBEDDED)
        # Packaged canonical texts (filename = SPDX id): the classifier
        # must work without OS-provided corpora (VERDICT r3 #10).
        if os.path.isdir(self.PACKAGED_DIR):
            for fname in sorted(os.listdir(self.PACKAGED_DIR)):
                if not fname.endswith(".txt"):
                    continue
                try:
                    with open(
                        os.path.join(self.PACKAGED_DIR, fname),
                        encoding="utf-8", errors="replace",
                    ) as f:
                        # embedded templates are the canonical wordings;
                        # packaged files fill in everything they lack
                        corpus.setdefault(fname[:-4], f.read())
                except OSError:
                    continue
        if os.path.isdir(_SYSTEM_DIR):
            for fname, spdx in _SYSTEM_LICENSES.items():
                path = os.path.join(_SYSTEM_DIR, fname)
                try:
                    with open(path, encoding="utf-8", errors="replace") as f:
                        corpus.setdefault(spdx, f.read())
                except OSError:
                    continue
        corpus.update(extra or {})
        self._corpus = corpus
        self.names = sorted(corpus)
        self.matrix = np.stack(
            [_fingerprint(normalize_tokens(corpus[n])) for n in self.names]
        )  # [L, DIM]
        # Stable digest of the corpus contents: cache keys must change
        # when the host's license corpus does.
        digest = 0
        for n in self.names:
            digest = zlib.crc32(corpus[n].encode(), zlib.crc32(n.encode(), digest))
        self.corpus_digest = digest

    def corpus_text(self, name: str) -> str:
        """Raw corpus text for `name` ("" if absent).  The device license
        program audits anchor-token coverage against it at compile time."""
        return self._corpus.get(name, "")

    def classify_batch(
        self,
        texts: list[str],
        confidence: float = DEFAULT_CONFIDENCE,
    ) -> list[Match | None]:
        """All candidate files at once: [F, DIM] x [DIM, L] -> best
        cosine per file.  Runs on the accelerator when one is attached
        (the MXU eats this shape); numpy otherwise."""
        if not texts:
            return []
        fps = np.stack(
            [_fingerprint(normalize_tokens(t)) for t in texts]
        )  # [F, DIM]
        sims = self._matmul(fps)  # [F, L]
        out: list[Match | None] = []
        for row in sims:
            best = int(np.argmax(row))
            score = float(row[best])
            if score >= confidence:
                out.append(Match(self.names[best], round(score, 4)))
            else:
                out.append(None)
        return out

    def _matmul(self, fps: np.ndarray) -> np.ndarray:
        try:
            import jax

            from trivy_tpu.mesh import topology as mesh_topology

            if mesh_topology.platform() not in ("cpu",):
                return np.asarray(
                    _device_dot()(
                        jax.numpy.asarray(fps),
                        jax.numpy.asarray(self.matrix),
                    )
                )
        except Exception:  # no accelerator / jax import issue: numpy path
            pass
        return fps @ self.matrix.T

    def classify(
        self, text: str, confidence: float = DEFAULT_CONFIDENCE
    ) -> Match | None:
        return self.classify_batch([text], confidence)[0]


_DEVICE_DOT = None


def _device_dot():
    """One jitted dot for the process: a fresh lambda per call would make
    every batch a recompile instead of a jit-cache hit."""
    global _DEVICE_DOT
    if _DEVICE_DOT is None:
        import jax
        import jax.numpy as jnp

        _DEVICE_DOT = jax.jit(lambda a, b: jnp.dot(a, b.T))
    return _DEVICE_DOT


_shared: FullTextClassifier | None = None


def shared_classifier() -> FullTextClassifier:
    global _shared
    if _shared is None:
        _shared = FullTextClassifier()
    return _shared
