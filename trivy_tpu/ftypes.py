"""Core result data model.

Mirrors the reference's artifact/result types (pkg/fanal/types/secret.go:1-20,
pkg/fanal/types/artifact.go, pkg/types/report.go:13, pkg/types/result.go) so
findings serialize into the same JSON shape Trivy emits, while staying idiomatic
Python dataclasses internally.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from enum import Enum
from typing import Any


class ResultClass(str, Enum):
    """Mirrors pkg/types/result.go result classes."""

    OS_PKGS = "os-pkgs"
    LANG_PKGS = "lang-pkgs"
    CONFIG = "config"
    SECRET = "secret"
    LICENSE = "license"
    LICENSE_FILE = "license-file"
    CUSTOM = "custom"


class ArtifactType(str, Enum):
    """Mirrors pkg/fanal/types/artifact.go ArtifactType."""

    CONTAINER_IMAGE = "container_image"
    FILESYSTEM = "filesystem"
    REPOSITORY = "repository"
    CYCLONEDX = "cyclonedx"
    SPDX = "spdx"
    VM = "vm"


@dataclass
class Line:
    """One rendered source line (pkg/fanal/types/misconf.go Line)."""

    number: int
    content: str
    is_cause: bool = False
    annotation: str = ""
    truncated: bool = False
    highlighted: str = ""
    first_cause: bool = False
    last_cause: bool = False

    def to_json(self) -> dict[str, Any]:
        return {
            "Number": self.number,
            "Content": self.content,
            "IsCause": self.is_cause,
            "Annotation": self.annotation,
            "Truncated": self.truncated,
            "Highlighted": self.highlighted,
            "FirstCause": self.first_cause,
            "LastCause": self.last_cause,
        }


@dataclass
class Code:
    """Context lines around a finding (pkg/fanal/types/misconf.go Code)."""

    lines: list[Line] = field(default_factory=list)

    def to_json(self) -> dict[str, Any]:
        return {"Lines": [ln.to_json() for ln in self.lines] or None}


@dataclass
class Layer:
    """Origin layer of a finding (pkg/fanal/types/artifact.go Layer)."""

    digest: str = ""
    diff_id: str = ""
    created_by: str = ""

    def empty(self) -> bool:
        return not (self.digest or self.diff_id or self.created_by)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.digest:
            out["Digest"] = self.digest
        if self.diff_id:
            out["DiffID"] = self.diff_id
        if self.created_by:
            out["CreatedBy"] = self.created_by
        return out


@dataclass
class SecretFinding:
    """One secret match (pkg/fanal/types/secret.go:10-20)."""

    rule_id: str
    category: str
    severity: str
    title: str
    start_line: int
    end_line: int
    code: Code
    match: str
    layer: Layer = field(default_factory=Layer)
    # Raw bytes of the match line, used only for Go-compatible bytewise sort
    # ordering (Go sorts the raw string; decoding with errors="replace" first
    # would collapse distinct invalid bytes).  Not serialized.
    match_bytes: bytes = b""

    def to_json(self) -> dict[str, Any]:
        out = {
            "RuleID": self.rule_id,
            "Category": self.category,
            "Severity": self.severity,
            "Title": self.title,
            "StartLine": self.start_line,
            "EndLine": self.end_line,
            "Code": self.code.to_json(),
            "Match": self.match,
        }
        if not self.layer.empty():
            out["Layer"] = self.layer.to_json()
        return out

    def sort_key(self) -> tuple[str, bytes]:
        # Deterministic ordering used by the engine (scanner.go:441-446); Go
        # compares the raw Match bytes.
        return (self.rule_id, self.match_bytes or self.match.encode("utf-8", "replace"))


@dataclass
class Secret:
    """Per-file secret scan result (pkg/fanal/types/secret.go:5-8)."""

    file_path: str = ""
    findings: list[SecretFinding] = field(default_factory=list)


@dataclass
class DetectedVulnerability:
    """pkg/types/vulnerability.go DetectedVulnerability (subset)."""

    vulnerability_id: str
    pkg_name: str
    installed_version: str
    pkg_id: str = ""
    fixed_version: str = ""
    status: str = ""
    severity: str = "UNKNOWN"
    severity_source: str = ""
    primary_url: str = ""
    title: str = ""
    description: str = ""
    references: list[str] = field(default_factory=list)
    layer: "Layer" = field(default_factory=lambda: Layer())

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "VulnerabilityID": self.vulnerability_id,
            "PkgName": self.pkg_name,
            "InstalledVersion": self.installed_version,
        }
        if self.pkg_id:
            out["PkgID"] = self.pkg_id
        if self.fixed_version:
            out["FixedVersion"] = self.fixed_version
        if self.status:
            out["Status"] = self.status
        if not self.layer.empty():
            out["Layer"] = self.layer.to_json()
        if self.primary_url:
            out["PrimaryURL"] = self.primary_url
        if self.title:
            out["Title"] = self.title
        if self.description:
            out["Description"] = self.description
        out["Severity"] = self.severity
        if self.severity_source:
            out["SeveritySource"] = self.severity_source
        if self.references:
            out["References"] = self.references
        return out

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "DetectedVulnerability":
        layer = d.get("Layer") or {}
        return cls(
            vulnerability_id=d.get("VulnerabilityID", ""),
            pkg_name=d.get("PkgName", ""),
            installed_version=d.get("InstalledVersion", ""),
            pkg_id=d.get("PkgID", ""),
            fixed_version=d.get("FixedVersion", ""),
            status=d.get("Status", ""),
            severity=d.get("Severity", "UNKNOWN"),
            severity_source=d.get("SeveritySource", ""),
            primary_url=d.get("PrimaryURL", ""),
            title=d.get("Title", ""),
            description=d.get("Description", ""),
            references=list(d.get("References") or []),
            layer=Layer(
                digest=layer.get("Digest", ""), diff_id=layer.get("DiffID", "")
            ),
        )


@dataclass
class Result:
    """One result block in a report (pkg/types/result.go Result)."""

    target: str
    result_class: ResultClass
    result_type: str = ""
    secrets: list[SecretFinding] = field(default_factory=list)
    vulnerabilities: list[Any] = field(default_factory=list)
    misconfigurations: list[Any] = field(default_factory=list)
    licenses: list[Any] = field(default_factory=list)
    packages: list[Any] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not (
            self.secrets
            or self.vulnerabilities
            or self.misconfigurations
            or self.licenses
        )

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "Target": self.target,
            "Class": self.result_class.value,
        }
        if self.result_type:
            out["Type"] = self.result_type
        if self.packages:
            out["Packages"] = [
                p.to_json() if hasattr(p, "to_json") else p for p in self.packages
            ]
        if self.vulnerabilities:
            out["Vulnerabilities"] = [
                v.to_json() if hasattr(v, "to_json") else v
                for v in self.vulnerabilities
            ]
        if self.misconfigurations:
            out["Misconfigurations"] = [
                m.to_json() if hasattr(m, "to_json") else m
                for m in self.misconfigurations
            ]
        if self.secrets:
            out["Secrets"] = [s.to_json() for s in self.secrets]
        if self.licenses:
            out["Licenses"] = [
                l.to_json() if hasattr(l, "to_json") else l for l in self.licenses
            ]
        return out


@dataclass
class Metadata:
    """Report metadata (pkg/types/report.go Metadata)."""

    image_id: str = ""
    diff_ids: list[str] = field(default_factory=list)
    repo_tags: list[str] = field(default_factory=list)
    repo_digests: list[str] = field(default_factory=list)
    os_family: str = ""
    os_name: str = ""

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.os_family:
            out["OS"] = {"Family": self.os_family, "Name": self.os_name}
        if self.image_id:
            out["ImageID"] = self.image_id
        if self.diff_ids:
            out["DiffIDs"] = self.diff_ids
        if self.repo_tags:
            out["RepoTags"] = self.repo_tags
        if self.repo_digests:
            out["RepoDigests"] = self.repo_digests
        return out


SCHEMA_VERSION = 2  # pkg/types/report.go:11 SchemaVersion


@dataclass
class Report:
    """Top-level scan report (pkg/types/report.go:13)."""

    artifact_name: str
    artifact_type: ArtifactType
    results: list[Result] = field(default_factory=list)
    metadata: Metadata = field(default_factory=Metadata)
    schema_version: int = SCHEMA_VERSION
    created_at: str = ""

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "SchemaVersion": self.schema_version,
        }
        if self.created_at:
            out["CreatedAt"] = self.created_at
        out["ArtifactName"] = self.artifact_name
        out["ArtifactType"] = self.artifact_type.value
        out["Metadata"] = self.metadata.to_json()
        if self.results:
            out["Results"] = [r.to_json() for r in self.results]
        return out


def asdict_shallow(obj: Any) -> dict[str, Any]:
    return {f.name: getattr(obj, f.name) for f in dataclasses.fields(obj)}
