"""Kubernetes manifest misconfiguration checks.

The pkg/iac k8s scanner's role (checks modeled on trivy-checks' KSV-series
policies) over parsed YAML documents.
"""

from __future__ import annotations

import yaml

from trivy_tpu.misconf.types import MisconfFinding, Misconfiguration

_WORKLOAD_KINDS = {
    "Pod", "Deployment", "StatefulSet", "DaemonSet", "ReplicaSet",
    "Job", "CronJob",
}


def is_kubernetes(doc) -> bool:
    return (
        isinstance(doc, dict) and "apiVersion" in doc and "kind" in doc
    )


def _pod_spec(doc: dict) -> dict:
    kind = doc.get("kind")
    spec = doc.get("spec") or {}
    if kind == "Pod":
        return spec
    if kind == "CronJob":
        job_spec = (spec.get("jobTemplate") or {}).get("spec") or {}
        return (job_spec.get("template") or {}).get("spec") or {}
    return (spec.get("template") or {}).get("spec") or {}


def _containers(pod_spec: dict):
    for section in ("initContainers", "containers"):
        for c in pod_spec.get(section) or []:
            if isinstance(c, dict):
                yield c


def _check_privileged(doc, pod_spec):
    for c in _containers(pod_spec):
        sc = c.get("securityContext") or {}
        if sc.get("privileged"):
            yield f"Container '{c.get('name', '?')}' is privileged"


def _check_run_as_nonroot(doc, pod_spec):
    pod_sc = pod_spec.get("securityContext") or {}
    for c in _containers(pod_spec):
        sc = c.get("securityContext") or {}
        if not (sc.get("runAsNonRoot") or pod_sc.get("runAsNonRoot")):
            yield (
                f"Container '{c.get('name', '?')}' should set "
                "securityContext.runAsNonRoot to true"
            )


def _check_host_network(doc, pod_spec):
    if pod_spec.get("hostNetwork"):
        yield "Pod uses the host network namespace"


def _check_host_pid_ipc(doc, pod_spec):
    if pod_spec.get("hostPID"):
        yield "Pod uses the host PID namespace"
    if pod_spec.get("hostIPC"):
        yield "Pod uses the host IPC namespace"


def _check_hostpath(doc, pod_spec):
    for v in pod_spec.get("volumes") or []:
        if isinstance(v, dict) and "hostPath" in v:
            yield f"Volume '{v.get('name', '?')}' mounts a hostPath"


def _check_resource_limits(doc, pod_spec):
    for c in _containers(pod_spec):
        limits = (c.get("resources") or {}).get("limits") or {}
        if "memory" not in limits:
            yield f"Container '{c.get('name', '?')}' has no memory limit"


def _check_allow_privilege_escalation(doc, pod_spec):
    for c in _containers(pod_spec):
        sc = c.get("securityContext") or {}
        if sc.get("allowPrivilegeEscalation", True) and not sc.get("privileged"):
            yield (
                f"Container '{c.get('name', '?')}' should set "
                "securityContext.allowPrivilegeEscalation to false"
            )


_CHECKS = [
    ("KSV017", "Privileged container", "HIGH",
     "Remove securityContext.privileged.", _check_privileged),
    ("KSV012", "Runs as root user", "MEDIUM",
     "Set securityContext.runAsNonRoot: true.", _check_run_as_nonroot),
    ("KSV009", "Access to host network", "HIGH",
     "Remove hostNetwork.", _check_host_network),
    ("KSV010", "Access to host PID/IPC", "HIGH",
     "Remove hostPID/hostIPC.", _check_host_pid_ipc),
    ("KSV023", "hostPath volume mounted", "MEDIUM",
     "Do not mount hostPath volumes.", _check_hostpath),
    ("KSV018", "Memory limit not set", "LOW",
     "Set resources.limits.memory.", _check_resource_limits),
    ("KSV001", "Privilege escalation allowed", "MEDIUM",
     "Set allowPrivilegeEscalation: false.", _check_allow_privilege_escalation),
]


def scan_kubernetes(file_path: str, content: bytes) -> Misconfiguration | None:
    try:
        docs = [d for d in yaml.safe_load_all(content) if is_kubernetes(d)]
    except yaml.YAMLError:
        return None
    workloads = [d for d in docs if d.get("kind") in _WORKLOAD_KINDS]
    if not docs:
        return None

    mc = Misconfiguration(file_type="kubernetes", file_path=file_path)
    for check_id, title, severity, resolution, fn in _CHECKS:
        failed = False
        for doc in workloads:
            pod_spec = _pod_spec(doc)
            if not pod_spec:
                continue
            for message in fn(doc, pod_spec):
                failed = True
                mc.failures.append(
                    MisconfFinding(
                        check_id=check_id,
                        title=title,
                        severity=severity,
                        resolution=resolution,
                        message=f"{doc.get('kind')}/"
                        f"{(doc.get('metadata') or {}).get('name', '?')}: "
                        f"{message}",
                    )
                )
        if workloads and not failed:
            mc.successes.append(
                MisconfFinding(
                    check_id=check_id, title=title, severity=severity,
                    status="PASS",
                )
            )
    return mc
