"""Kubernetes manifest misconfiguration checks.

The pkg/iac k8s scanner's role (checks modeled on trivy-checks' KSV-series
policies) over parsed YAML documents.
"""

from __future__ import annotations


def scan_kubernetes(file_path: str, content: bytes):
    """Rego-driven kubernetes scan (KSV-series checks in
    trivy_tpu/iac/checks); returns None for YAML that is not a k8s
    manifest."""
    from trivy_tpu.iac.engine import shared_scanner

    return shared_scanner().scan(file_path, content)
