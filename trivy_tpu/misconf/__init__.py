from trivy_tpu.misconf.types import Misconfiguration, MisconfFinding

__all__ = ["Misconfiguration", "MisconfFinding"]
