"""Dockerfile parsing + the misconf facade's dockerfile entry point.

The instruction parser feeds both the rego input builder
(trivy_tpu/iac/inputs.py, mirroring the reference's buildkit-parsed
Stages/Commands shape) and the image-history analyzer; the DS-series
checks themselves are .rego policies under trivy_tpu/iac/checks/.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from trivy_tpu.misconf.types import Misconfiguration


@dataclass
class Instruction:
    cmd: str
    value: str
    start_line: int
    end_line: int


def parse_dockerfile(content: bytes) -> list[Instruction]:
    out: list[Instruction] = []
    lines = content.decode("utf-8", errors="replace").splitlines()
    i = 0
    while i < len(lines):
        raw = lines[i].strip()
        start = i + 1
        if not raw or raw.startswith("#"):
            i += 1
            continue
        # continuation lines
        while raw.endswith("\\") and i + 1 < len(lines):
            i += 1
            raw = raw[:-1].rstrip() + " " + lines[i].strip()
        m = re.match(r"(\S+)\s*(.*)", raw)
        if m:
            out.append(
                Instruction(
                    cmd=m.group(1).upper(),
                    value=m.group(2),
                    start_line=start,
                    end_line=i + 1,
                )
            )
        i += 1
    return out


def scan_dockerfile(file_path: str, content: bytes) -> Misconfiguration:
    """Rego-driven dockerfile scan (DS-series checks in trivy_tpu/iac/checks).

    Kept as the misconf facade entry point; the hand-coded Python checks
    this module originally carried are now .rego policies evaluated by
    trivy_tpu/iac (the same engine user checks load into).
    """
    from trivy_tpu.iac.engine import shared_scanner

    mc = shared_scanner().scan(file_path, content)
    if mc is None:
        return Misconfiguration(file_type="dockerfile", file_path=file_path)
    return mc
