"""Dockerfile misconfiguration checks.

The pkg/iac dockerfile scanner's role (checks modeled on trivy-checks'
DS-series Rego policies), as plain Python checks over a parsed instruction
list.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from trivy_tpu.misconf.types import MisconfFinding, Misconfiguration


@dataclass
class Instruction:
    cmd: str
    value: str
    start_line: int
    end_line: int


def parse_dockerfile(content: bytes) -> list[Instruction]:
    out: list[Instruction] = []
    lines = content.decode("utf-8", errors="replace").splitlines()
    i = 0
    while i < len(lines):
        raw = lines[i].strip()
        start = i + 1
        if not raw or raw.startswith("#"):
            i += 1
            continue
        # continuation lines
        while raw.endswith("\\") and i + 1 < len(lines):
            i += 1
            raw = raw[:-1].rstrip() + " " + lines[i].strip()
        m = re.match(r"(\S+)\s*(.*)", raw)
        if m:
            out.append(
                Instruction(
                    cmd=m.group(1).upper(),
                    value=m.group(2),
                    start_line=start,
                    end_line=i + 1,
                )
            )
        i += 1
    return out


def _check_latest_tag(instructions):
    for ins in instructions:
        if ins.cmd != "FROM":
            continue
        image = ins.value.split(" as ")[0].split(" AS ")[0].strip()
        if image.lower() == "scratch" or image.startswith("$"):
            continue
        if ":" not in image.split("/")[-1] and "@" not in image:
            yield ins, f"Specify a tag in the image reference '{image}'"
        elif image.endswith(":latest"):
            yield ins, f"Avoid the ':latest' tag in '{image}'"


def _check_root_user(instructions):
    last_user = None
    for ins in instructions:
        if ins.cmd == "USER":
            last_user = ins
    if last_user is None:
        yield None, "Specify at least one USER command in the Dockerfile"
    elif last_user.value.split(":")[0] in ("root", "0"):
        yield last_user, "Last USER command should not be 'root'"


def _check_add(instructions):
    for ins in instructions:
        if ins.cmd == "ADD" and not re.search(
            r"\.(tar|tar\.\w+|tgz|zip)(\s|$)|^https?://", ins.value
        ):
            yield ins, "Consider using 'COPY' instead of 'ADD'"


def _check_sudo(instructions):
    for ins in instructions:
        if ins.cmd == "RUN" and re.search(r"(^|\s|&&\s*)sudo\s", ins.value):
            yield ins, "Avoid using 'sudo' in RUN commands"


def _check_apt_no_clean(instructions):
    for ins in instructions:
        if (
            ins.cmd == "RUN"
            and re.search(r"apt(-get)?\s+install", ins.value)
            and "rm -rf /var/lib/apt/lists" not in ins.value
        ):
            yield ins, (
                "Remove apt lists after installing "
                "('rm -rf /var/lib/apt/lists/*')"
            )


def _check_healthcheck(instructions):
    if not any(i.cmd == "HEALTHCHECK" for i in instructions):
        yield None, "Add a HEALTHCHECK instruction"


_CHECKS = [
    ("DS001", "':latest' tag used", "HIGH",
     "Use a specific version tag for the image.", _check_latest_tag),
    ("DS002", "Image user should not be 'root'", "HIGH",
     "Add 'USER <non-root>' to the Dockerfile.", _check_root_user),
    ("DS005", "ADD instead of COPY", "LOW",
     "Use COPY for copying local resources.", _check_add),
    ("DS010", "'sudo' usage", "HIGH",
     "Don't use sudo; the build already runs as root.", _check_sudo),
    ("DS017", "apt lists not cleaned up", "LOW",
     "Clean apt cache in the same layer.", _check_apt_no_clean),
    ("DS026", "No HEALTHCHECK defined", "LOW",
     "Add HEALTHCHECK to allow container health monitoring.", _check_healthcheck),
]


def scan_dockerfile(file_path: str, content: bytes) -> Misconfiguration:
    instructions = parse_dockerfile(content)
    mc = Misconfiguration(file_type="dockerfile", file_path=file_path)
    for check_id, title, severity, resolution, fn in _CHECKS:
        failed = False
        for ins, message in fn(instructions):
            failed = True
            mc.failures.append(
                MisconfFinding(
                    check_id=check_id,
                    title=title,
                    severity=severity,
                    resolution=resolution,
                    message=message,
                    start_line=ins.start_line if ins else 0,
                    end_line=ins.end_line if ins else 0,
                )
            )
        if not failed:
            mc.successes.append(
                MisconfFinding(
                    check_id=check_id, title=title, severity=severity,
                    status="PASS",
                )
            )
    return mc
