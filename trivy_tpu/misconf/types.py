"""Misconfiguration types (pkg/fanal/types/misconf.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class MisconfFinding:
    """One check outcome (types.MisconfResult / DetectedMisconfiguration)."""

    check_id: str
    title: str
    description: str = ""
    message: str = ""
    resolution: str = ""
    severity: str = "MEDIUM"
    status: str = "FAIL"  # FAIL | PASS
    start_line: int = 0
    end_line: int = 0
    references: list[str] = field(default_factory=list)
    traces: list[str] = field(default_factory=list)  # --trace rego traces

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "Type": "",
            "ID": self.check_id,
            "Title": self.title,
            "Description": self.description,
            "Message": self.message,
            "Resolution": self.resolution,
            "Severity": self.severity,
            "Status": self.status,
        }
        if self.references:
            out["References"] = self.references
        if self.traces:
            out["Traces"] = self.traces
        if self.start_line:
            out["CauseMetadata"] = {
                "StartLine": self.start_line,
                "EndLine": self.end_line or self.start_line,
            }
        return out

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "MisconfFinding":
        cause = d.get("CauseMetadata") or {}
        return cls(
            check_id=d.get("ID", ""),
            title=d.get("Title", ""),
            description=d.get("Description", ""),
            message=d.get("Message", ""),
            resolution=d.get("Resolution", ""),
            severity=d.get("Severity", "MEDIUM"),
            status=d.get("Status", "FAIL"),
            start_line=cause.get("StartLine", 0),
            end_line=cause.get("EndLine", 0),
            references=list(d.get("References") or []),
            traces=list(d.get("Traces") or []),
        )


@dataclass
class Misconfiguration:
    """types.Misconfiguration — per (file, checker) outcome bundle."""

    file_type: str
    file_path: str
    failures: list[MisconfFinding] = field(default_factory=list)
    successes: list[MisconfFinding] = field(default_factory=list)
    layer: Any = None

    def to_json(self) -> dict[str, Any]:
        return {
            "FileType": self.file_type,
            "FilePath": self.file_path,
            "Failures": [f.to_json() for f in self.failures],
            "Successes": [s.to_json() for s in self.successes],
        }

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Misconfiguration":
        return cls(
            file_type=d.get("FileType", ""),
            file_path=d.get("FilePath", ""),
            failures=[
                MisconfFinding.from_json(f) for f in (d.get("Failures") or [])
            ],
            successes=[
                MisconfFinding.from_json(s) for s in (d.get("Successes") or [])
            ],
        )
