"""Per-distro end-of-life tables (pkg/detector/ospkg/*/: eolDates maps).

Dates are the distros' published EOL dates, as carried by the reference's
per-driver tables (e.g. alpine.go:21, debian.go, ubuntu.go).  Versions not
listed warn "not on the EOL list" and are treated as supported (they may be
newer than this table), mirroring osver.Supported
(pkg/detector/ospkg/version/version.go).
"""

from __future__ import annotations

import datetime as _dt
import logging

logger = logging.getLogger(__name__)


def _d(y: int, m: int, day: int) -> _dt.datetime:
    return _dt.datetime(y, m, day, tzinfo=_dt.timezone.utc)


# family -> version (at the driver's release precision) -> EOL date
EOL_DATES: dict[str, dict[str, _dt.datetime]] = {
    "alpine": {
        "2.7": _d(2015, 11, 1), "3.0": _d(2016, 5, 1), "3.1": _d(2016, 11, 1),
        "3.2": _d(2017, 5, 1), "3.3": _d(2017, 11, 1), "3.4": _d(2018, 5, 1),
        "3.5": _d(2018, 11, 1), "3.6": _d(2019, 5, 1), "3.7": _d(2019, 11, 1),
        "3.8": _d(2020, 5, 1), "3.9": _d(2020, 11, 1), "3.10": _d(2021, 5, 1),
        "3.11": _d(2021, 11, 1), "3.12": _d(2022, 5, 1), "3.13": _d(2022, 11, 1),
        "3.14": _d(2023, 5, 1), "3.15": _d(2023, 11, 1), "3.16": _d(2024, 5, 23),
        "3.17": _d(2024, 11, 22), "3.18": _d(2025, 5, 9),
        "3.19": _d(2025, 11, 1), "3.20": _d(2026, 4, 1), "3.21": _d(2026, 11, 1),
    },
    "debian": {
        "7": _d(2018, 5, 31), "8": _d(2020, 6, 30), "9": _d(2022, 6, 30),
        "10": _d(2024, 6, 30), "11": _d(2026, 8, 31), "12": _d(2028, 6, 30),
    },
    "ubuntu": {
        "14.04": _d(2024, 4, 25), "16.04": _d(2026, 4, 23),
        "18.04": _d(2028, 4, 26), "20.04": _d(2030, 4, 23),
        "21.10": _d(2022, 7, 14), "22.04": _d(2032, 4, 21),
        "22.10": _d(2023, 7, 20), "23.04": _d(2024, 1, 25),
        "23.10": _d(2024, 7, 11), "24.04": _d(2034, 4, 25),
    },
    "centos": {
        "6": _d(2020, 11, 30), "7": _d(2024, 6, 30), "8": _d(2021, 12, 31),
    },
    "redhat": {
        "6": _d(2024, 6, 30), "7": _d(2024, 6, 30), "8": _d(2029, 5, 31),
        "9": _d(2032, 5, 31),
    },
    "amazon": {
        "1": _d(2023, 12, 31), "2": _d(2026, 6, 30), "2022": _d(2026, 6, 30),
        "2023": _d(2028, 3, 15),
    },
    "suse linux enterprise server": {
        "12.5": _d(2024, 10, 31), "15": _d(2019, 12, 31),
        "15.1": _d(2021, 1, 31), "15.2": _d(2021, 12, 31),
        "15.3": _d(2022, 12, 31), "15.4": _d(2023, 12, 31),
        "15.5": _d(2028, 12, 31),
    },
    "opensuse-leap": {
        "15.0": _d(2019, 12, 3), "15.1": _d(2020, 11, 30),
        "15.2": _d(2021, 11, 30), "15.3": _d(2022, 11, 30),
        "15.4": _d(2023, 11, 30), "15.5": _d(2024, 12, 31),
    },
    "fedora": {
        "37": _d(2023, 12, 5), "38": _d(2024, 5, 21), "39": _d(2024, 11, 26),
        "40": _d(2025, 5, 28), "41": _d(2025, 12, 2),
    },
}


def is_supported_version(
    family: str, release: str, now: _dt.datetime | None = None
) -> bool:
    """osver.Supported (version.go): warn + continue for unknown versions,
    warn loudly for EOL ones.  Detection always proceeds either way — the
    reference only logs."""
    if now is None:
        now = _dt.datetime.now(_dt.timezone.utc)
    table = EOL_DATES.get(family)
    if table is None:
        return True
    eol = table.get(release)
    if eol is None:
        logger.warning(
            "This OS version is not on the EOL list: %s %s", family, release
        )
        return True  # can be the latest version
    if now >= eol:
        logger.warning(
            "This OS version is no longer supported by the distribution: "
            "%s %s (EOL %s); the vulnerability results may be incomplete",
            family, release, eol.date(),
        )
        return False
    return True
