"""OS package vulnerability detectors.

Mirrors pkg/detector/ospkg (driver map detect.go:32-49): per-family drivers
that look up advisories by (release bucket, source package name) and compare
the installed version against the fixed version with the family's comparator.
"""

from __future__ import annotations

from dataclasses import dataclass

from trivy_tpu.atypes import OS, Package
from trivy_tpu.db.vulndb import VulnDB
from trivy_tpu.detector.eol import is_supported_version
from trivy_tpu.detector.severity import resolve_severity
from trivy_tpu.detector.version_cmp import COMPARATORS
from trivy_tpu.ftypes import DetectedVulnerability

# family -> (db source prefix, version comparator flavor, release precision)
_DRIVERS: dict[str, tuple[str, str, int]] = {
    "alpine": ("alpine", "apk", 2),  # bucket "alpine 3.15"
    "wolfi": ("wolfi", "apk", 0),
    "chainguard": ("chainguard", "apk", 0),
    "debian": ("debian", "deb", 1),  # bucket "debian 11"
    "ubuntu": ("ubuntu", "deb", 2),  # bucket "ubuntu 22.04"
    "redhat": ("redhat", "rpm", 1),
    "centos": ("centos", "rpm", 1),
    "rocky": ("rocky", "rpm", 1),
    "alma": ("alma", "rpm", 1),
    "oracle": ("oracle", "rpm", 1),
    "amazon": ("amazon", "rpm", 1),
    "photon": ("photon", "rpm", 1),
    "cbl-mariner": ("cbl-mariner", "rpm", 1),
    "fedora": ("fedora", "rpm", 1),
    # SUSE family (detect.go:43-44; trivy-db buckets "SUSE Linux
    # Enterprise 15.4" / "openSUSE Leap 15.4" resolve through the
    # BoltVulnDB alias map)
    "suse linux enterprise server": ("suse", "rpm", 2),
    "opensuse-leap": ("opensuse-leap", "rpm", 2),
}


def _release_bucket(prefix: str, name: str, precision: int) -> str:
    if precision == 0:
        return prefix
    # Codename suffixes ("2 (Karoo)") and 'release N' forms never reach
    # the bucket: the reference strips to the first whitespace field
    # before versioning (amazon driver strings.Fields(osVer)[0]).
    name = name.split()[0] if name.split() else name
    parts = name.split(".")
    return f"{prefix} {'.'.join(parts[:precision])}"


@dataclass
class OSPkgDetector:
    """detector/ospkg Detect (detect.go:52)."""

    db: VulnDB

    def supported(self, family: str) -> bool:
        return family in _DRIVERS

    def detect(
        self, os_info: OS, packages: list[Package]
    ) -> list[DetectedVulnerability]:
        driver = _DRIVERS.get(os_info.family)
        if driver is None:
            return []
        prefix, flavor, precision = driver
        source = _release_bucket(prefix, os_info.name, precision)
        cmp = COMPARATORS[flavor]
        # EOL gate (detect.go:32-49 drivers + osver.Supported): warn on
        # outdated or unknown OS versions; detection proceeds regardless.
        release = source.partition(" ")[2] or os_info.name
        is_supported_version(os_info.family, release)

        out: list[DetectedVulnerability] = []
        for pkg in packages:
            names = {pkg.name, pkg.src_name} - {""}
            seen: set[str] = set()
            for name in sorted(names):
                for adv in self.db.advisories(source, name):
                    if adv.vulnerability_id in seen:
                        continue
                    installed = pkg.version
                    if pkg.release:
                        installed = f"{pkg.version}-{pkg.release}"
                    if pkg.epoch:
                        # utils.FormatVersion includes the epoch; compare_rpm
                        # and compare_deb both parse the N: prefix.
                        installed = f"{pkg.epoch}:{installed}"
                    if adv.fixed_version and cmp(installed, adv.fixed_version) >= 0:
                        continue
                    seen.add(adv.vulnerability_id)
                    severity, severity_source = resolve_severity(adv, prefix)
                    out.append(
                        DetectedVulnerability(
                            vulnerability_id=adv.vulnerability_id,
                            pkg_id=pkg.id,
                            pkg_name=pkg.name,
                            installed_version=installed,
                            fixed_version=adv.fixed_version,
                            severity=severity,
                            severity_source=severity_source,
                            title=adv.title,
                            description=adv.description,
                            references=list(adv.references),
                            layer=pkg.layer,
                            status="fixed" if adv.fixed_version else "affected",
                        )
                    )
        return out
