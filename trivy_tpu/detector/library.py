"""Language-ecosystem vulnerability detectors.

Mirrors pkg/detector/library (driver.go:25-84): per-ecosystem drivers with
their version comparators; advisories carry vulnerable ranges (language DBs)
or fixed versions.
"""

from __future__ import annotations

from dataclasses import dataclass

from trivy_tpu.atypes import Application
from trivy_tpu.db.vulndb import VulnDB
from trivy_tpu.detector.severity import resolve_severity
from trivy_tpu.detector.version_cmp import COMPARATORS, version_in_range
from trivy_tpu.ftypes import DetectedVulnerability

# app type -> (db source, comparator flavor); mirrors driver.go:24-90
_ECOSYSTEMS: dict[str, tuple[str, str]] = {
    "npm": ("npm", "semver"),
    "yarn": ("npm", "semver"),
    "pnpm": ("npm", "semver"),
    "node-pkg": ("npm", "semver"),
    "pip": ("pip", "pep440"),
    "pipenv": ("pip", "pep440"),
    "poetry": ("pip", "pep440"),
    "python-pkg": ("pip", "pep440"),
    "gomod": ("go", "semver"),
    "gobinary": ("go", "semver"),
    "cargo": ("cargo", "semver"),
    "rustbinary": ("cargo", "semver"),
    "composer": ("composer", "semver"),
    "bundler": ("rubygems", "generic"),
    "gemspec": ("rubygems", "generic"),
    "nuget": ("nuget", "semver"),
    "pom": ("maven", "maven"),
    "gradle": ("maven", "maven"),
    "jar": ("maven", "maven"),
    "war": ("maven", "maven"),
    "pub": ("pub", "generic"),
    "hex": ("erlang", "generic"),
    "conan": ("conan", "generic"),
    "swift": ("swift", "generic"),
    "cocoapods": ("cocoapods", "generic"),
    "dotnet-core": ("nuget", "semver"),
    "packages-props": ("nuget", "semver"),
    "julia": ("julia", "semver"),
    # conda-pkg / conda-environment: SBOM-only, no vuln DB (driver.go:75-77)
}


@dataclass
class LibraryDetector:
    db: VulnDB

    def detect_app(self, app: Application) -> list[DetectedVulnerability]:
        eco = _ECOSYSTEMS.get(app.app_type)
        if eco is None:
            return []
        source, flavor = eco
        cmp = COMPARATORS[flavor]

        out: list[DetectedVulnerability] = []
        for pkg in app.packages:
            if not pkg.version:
                # Unversioned packages (unstamped Go '(devel)' main modules,
                # unpinned conda specs) compare below every fixed version and
                # would match every advisory — skip, don't false-positive.
                continue
            for adv in self.db.advisories(source, pkg.name):
                vulnerable = False
                if adv.vulnerable_versions:
                    vulnerable = version_in_range(
                        pkg.version, adv.vulnerable_versions, flavor
                    )
                elif adv.fixed_version:
                    vulnerable = cmp(pkg.version, adv.fixed_version) < 0
                if not vulnerable:
                    continue
                severity, severity_source = resolve_severity(adv, source)
                out.append(
                    DetectedVulnerability(
                        vulnerability_id=adv.vulnerability_id,
                        pkg_id=pkg.id,
                        pkg_name=pkg.name,
                        installed_version=pkg.version,
                        fixed_version=adv.fixed_version,
                        severity=severity,
                        severity_source=severity_source,
                        title=adv.title,
                        description=adv.description,
                        references=list(adv.references),
                        layer=pkg.layer,
                        status="fixed" if adv.fixed_version else "affected",
                    )
                )
        return out
