"""Severity-source precedence (pkg/vulnerability/vulnerability.go:112
getVendorSeverity).

When an advisory carries per-source severities (trivy-db VendorSeverity),
the reported severity prefers: the detection's own data source, then GHSA
for GHSA-* ids, then NVD, then the advisory's bare severity, then UNKNOWN.
The chosen source is reported as SeveritySource, so consumers can see whose
judgment they are trusting.
"""

from __future__ import annotations

from trivy_tpu.db.vulndb import Advisory

# Vendor severity vocabularies normalized to the canonical five levels the
# result filter understands (result/filter.py SEVERITIES); anything unmapped
# degrades to UNKNOWN instead of silently vanishing in the filter.
_CANON = {"UNKNOWN", "LOW", "MEDIUM", "HIGH", "CRITICAL"}
_ALIASES = {
    "MODERATE": "MEDIUM",   # GHSA
    "IMPORTANT": "HIGH",    # Red Hat / SUSE
    "NEGLIGIBLE": "LOW",    # Ubuntu/Debian
    "UNTRIAGED": "UNKNOWN",  # Amazon
    "NONE": "UNKNOWN",
}


def normalize_severity(s: str) -> str:
    up = (s or "").upper()
    if up in _CANON:
        return up
    return _ALIASES.get(up, "UNKNOWN")


def resolve_severity(adv: Advisory, source_id: str) -> tuple[str, str]:
    """Returns (severity, severity_source)."""
    vs = adv.severity_sources
    if source_id and source_id in vs:
        return normalize_severity(vs[source_id]), source_id
    if adv.vulnerability_id.startswith("GHSA-") and "ghsa" in vs:
        return normalize_severity(vs["ghsa"]), "ghsa"
    if "nvd" in vs:
        return normalize_severity(vs["nvd"]), "nvd"
    if adv.severity:
        return normalize_severity(adv.severity), ""
    return "UNKNOWN", ""
