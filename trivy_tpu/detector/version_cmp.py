"""Version comparators per packaging ecosystem.

Mirrors the comparator libraries the reference pulls in (go-deb-version,
go-apk-version, go-npm-version, go-pep440-version — see pkg/detector/ospkg/*
and pkg/detector/library/compare/*).  Each returns <0, 0, >0.
"""

from __future__ import annotations

import re


# ---------------------------------------------------------------------------
# dpkg (Debian policy 5.6.12)
# ---------------------------------------------------------------------------


def _deb_order(c: str) -> int:
    if c == "~":
        return -1
    if c.isalpha():
        return ord(c)
    if not c:
        return 0
    return ord(c) + 256  # non-alphanumeric sorts after letters


def _deb_compare_part(a: str, b: str) -> int:
    ia = ib = 0
    while ia < len(a) or ib < len(b):
        # non-digit run
        while True:
            ca = a[ia] if ia < len(a) and not a[ia].isdigit() else ""
            cb = b[ib] if ib < len(b) and not b[ib].isdigit() else ""
            if not ca and not cb:
                break
            oa, ob = _deb_order(ca), _deb_order(cb)
            if oa != ob:
                return -1 if oa < ob else 1
            ia += bool(ca)
            ib += bool(cb)
        # digit run
        na = nb = 0
        while ia < len(a) and a[ia].isdigit():
            na = na * 10 + int(a[ia])
            ia += 1
        while ib < len(b) and b[ib].isdigit():
            nb = nb * 10 + int(b[ib])
            ib += 1
        if na != nb:
            return -1 if na < nb else 1
    return 0


def _deb_split(v: str) -> tuple[int, str, str]:
    epoch = 0
    if ":" in v:
        e, _, v = v.partition(":")
        if e.isdigit():
            epoch = int(e)
    upstream, _, revision = v.rpartition("-")
    if not upstream:
        upstream, revision = v, ""
    return epoch, upstream, revision


def compare_deb(a: str, b: str) -> int:
    ea, ua, ra = _deb_split(a)
    eb, ub, rb = _deb_split(b)
    if ea != eb:
        return -1 if ea < eb else 1
    c = _deb_compare_part(ua, ub)
    if c:
        return c
    return _deb_compare_part(ra, rb)


# ---------------------------------------------------------------------------
# apk (Alpine)
# ---------------------------------------------------------------------------

_APK_SUFFIXES = {"alpha": -4, "beta": -3, "pre": -2, "rc": -1, "": 0, "cvs": 1,
                 "svn": 2, "git": 3, "hg": 4, "p": 5}
_APK_TOKEN = re.compile(
    r"(\d+)|([a-z])|_(alpha|beta|pre|rc|cvs|svn|git|hg|p)(\d*)|(-r)(\d+)|(.)"
)


def _apk_tokens(v: str):
    out = []
    for m in _APK_TOKEN.finditer(v.lower()):
        if m.group(1) is not None:
            out.append(("num", int(m.group(1))))
        elif m.group(2) is not None:
            out.append(("alpha", m.group(2)))
        elif m.group(3) is not None:
            out.append(("suffix", _APK_SUFFIXES[m.group(3)],
                        int(m.group(4) or 0)))
        elif m.group(5) is not None:
            out.append(("rev", int(m.group(6))))
    return out


def compare_apk(a: str, b: str) -> int:
    ta, tb = _apk_tokens(a), _apk_tokens(b)
    for i in range(max(len(ta), len(tb))):
        xa = ta[i] if i < len(ta) else None
        xb = tb[i] if i < len(tb) else None
        if xa == xb:
            continue
        # missing token: a bare version < one with extra numbers, but a
        # negative suffix (_rc etc.) sorts below a bare version.
        if xa is None:
            return 1 if (xb[0] == "suffix" and xb[1] < 0) else -1
        if xb is None:
            return -1 if (xa[0] == "suffix" and xa[1] < 0) else 1
        if xa[0] != xb[0]:
            order = {"num": 0, "alpha": 1, "suffix": 2, "rev": 3}
            return -1 if order.get(xa[0], 9) < order.get(xb[0], 9) else 1
        return -1 if xa < xb else 1
    return 0


# ---------------------------------------------------------------------------
# semver (npm & friends)
# ---------------------------------------------------------------------------

_SEMVER = re.compile(
    r"^v?(\d+)(?:\.(\d+))?(?:\.(\d+))?(?:-([0-9A-Za-z.-]+))?(?:\+.*)?$"
)


def _semver_key(v: str):
    m = _SEMVER.match(v.strip())
    if not m:
        # Fallback: numeric runs + the raw tail as a pseudo-prerelease, shaped
        # like the regular pre_key so cross-form comparisons never TypeError.
        nums = [int(x) for x in re.findall(r"\d+", v)[:4]]
        return (tuple(nums + [0] * (3 - len(nums))), ((0,), (0.5, v)))
    major, minor, patch = (int(m.group(i) or 0) for i in (1, 2, 3))
    pre = m.group(4)
    if pre is None:
        pre_key = ((1,),)  # release > any prerelease
    else:
        parts = []
        for p in pre.split("."):
            parts.append((0, int(p)) if p.isdigit() else (0.5, p))
        pre_key = ((0,), *parts)
    return ((major, minor, patch), pre_key)


def compare_semver(a: str, b: str) -> int:
    ka, kb = _semver_key(a), _semver_key(b)
    return -1 if ka < kb else (1 if ka > kb else 0)


# ---------------------------------------------------------------------------
# pep440 (PyPI)
# ---------------------------------------------------------------------------


def compare_pep440(a: str, b: str) -> int:
    try:
        from packaging.version import Version

        va, vb = Version(a), Version(b)
        return -1 if va < vb else (1 if va > vb else 0)
    except Exception:
        return compare_semver(a, b)


# ---------------------------------------------------------------------------
# generic / rubygems (close enough to semver with letter segments)
# ---------------------------------------------------------------------------


def compare_generic(a: str, b: str) -> int:
    return _deb_compare_part(a, b)


def _rpm_seg_cmp(a: str, b: str) -> int:
    """librpm rpmvercmp over one version component: alternating digit and
    alpha runs; tilde sorts before everything, caret after release-equal."""
    ia = ib = 0
    while ia < len(a) or ib < len(b):
        ca = a[ia] if ia < len(a) else ""
        cb = b[ib] if ib < len(b) else ""
        if ca == "~" or cb == "~":
            if ca != "~":
                return 1
            if cb != "~":
                return -1
            ia += 1
            ib += 1
            continue
        if ca == "^" or cb == "^":
            if not ca:
                return -1
            if not cb:
                return 1
            if ca != "^":
                return 1
            if cb != "^":
                return -1
            ia += 1
            ib += 1
            continue
        # skip non-alphanumeric separators
        while ia < len(a) and not a[ia].isalnum() and a[ia] not in "~^":
            ia += 1
        while ib < len(b) and not b[ib].isalnum() and b[ib] not in "~^":
            ib += 1
        if ia >= len(a) or ib >= len(b):
            if ia < len(a):
                return 1
            if ib < len(b):
                return -1
            return 0
        if a[ia].isdigit() or b[ib].isdigit():
            ja, jb = ia, ib
            while ja < len(a) and a[ja].isdigit():
                ja += 1
            while jb < len(b) and b[jb].isdigit():
                jb += 1
            da, db_ = a[ia:ja], b[ib:jb]
            if not da:
                return -1  # alpha sorts before digits
            if not db_:
                return 1
            if int(da) != int(db_):
                return 1 if int(da) > int(db_) else -1
            ia, ib = ja, jb
        else:
            ja, jb = ia, ib
            while ja < len(a) and a[ja].isalpha():
                ja += 1
            while jb < len(b) and b[jb].isalpha():
                jb += 1
            sa, sb = a[ia:ja], b[ib:jb]
            if sa != sb:
                return 1 if sa > sb else -1
            ia, ib = ja, jb
    return 0


def _rpm_split(v: str) -> tuple[int, str, str]:
    epoch = 0
    if ":" in v:
        e, _, v = v.partition(":")
        try:
            epoch = int(e)
        except ValueError:
            pass
    ver, _, rel = v.partition("-")
    return epoch, ver, rel


def compare_rpm(a: str, b: str) -> int:
    """Full [epoch:]version[-release] comparison (rpm.go / go-rpm-version)."""
    ea, va, ra = _rpm_split(a)
    eb, vb, rb = _rpm_split(b)
    if ea != eb:
        return 1 if ea > eb else -1
    c = _rpm_seg_cmp(va, vb)
    if c != 0:
        return c
    return _rpm_seg_cmp(ra, rb)


_MAVEN_QUALIFIERS = {
    "alpha": 1, "a": 1, "beta": 2, "b": 2, "milestone": 3, "m": 3,
    "rc": 4, "cr": 4, "snapshot": 5, "": 6, "ga": 6, "final": 6,
    "release": 6, "sp": 7,
}


def _maven_tokens(v: str):
    """org.apache.maven.artifact.versioning.ComparableVersion, abridged:
    dot/dash-separated runs, numbers compare numerically, known qualifiers
    by rank (alpha < beta < milestone < rc < snapshot < release < sp),
    unknown qualifiers lexically after release."""
    for raw in re.split(r"[.\-_]", v.lower()):
        # ComparableVersion splits letter-digit transitions: rc1 -> rc, 1
        for tok in re.findall(r"\d+|[a-z]+", raw):
            if tok.isdigit():
                yield (1, int(tok), "")
            else:
                rank = _MAVEN_QUALIFIERS.get(tok)
                if rank is None:
                    yield (2, 8, tok)
                else:
                    yield (2, rank, "")


def compare_maven(a: str, b: str) -> int:
    ta, tb = list(_maven_tokens(a)), list(_maven_tokens(b))
    # trailing zeros / release qualifiers are neutral padding
    pad = (2, 6, "")
    n = max(len(ta), len(tb))
    for i in range(n):
        xa = ta[i] if i < len(ta) else ((1, 0, "") if (i < len(tb) and tb[i][0] == 1) else pad)
        xb = tb[i] if i < len(tb) else ((1, 0, "") if ta[i][0] == 1 else pad)
        if xa != xb:
            # numeric vs qualifier: numeric sorts after release qualifier
            if xa[0] != xb[0]:
                if xa[0] == 1:  # a numeric vs b qualifier
                    return 1 if xb[1] <= 6 or xa[1] > 0 else -1
                return -1 if xa[1] <= 6 or xb[1] > 0 else 1
            return 1 if xa > xb else -1
    return 0


COMPARATORS = {
    "apk": compare_apk,
    "deb": compare_deb,
    "rpm": compare_rpm,
    "maven": compare_maven,
    "semver": compare_semver,
    "pep440": compare_pep440,
    "generic": compare_generic,
}


# ---------------------------------------------------------------------------
# Range expressions ("<1.2.3", ">=4.0.0, <4.0.14", "a || b")
# ---------------------------------------------------------------------------

_OP = re.compile(r"^(>=|<=|>|<|=|==|!=|\^|~)?\s*(.+)$")


def _check_one(cmp, installed: str, constraint: str) -> bool:
    m = _OP.match(constraint.strip())
    if not m:
        return False
    op, ver = m.group(1) or "=", m.group(2).strip()
    if op == "^":
        # ^X.Y.Z pins the leftmost non-zero component (npm caret semantics):
        # ^1.2.3 => <2.0.0, ^0.2.3 => <0.3.0, ^0.0.3 => <0.0.4.  Partial
        # versions pin at the last specified component when all are zero:
        # ^0 => <1.0.0, ^0.0 => <0.1.0 (node-semver partial-caret rules).
        base = _semver_key(ver)[0]
        inst = _semver_key(installed)[0]
        core = re.split(r"[-+]", ver, maxsplit=1)[0]
        ncomp = min(3, len([c for c in core.split(".") if c not in ("", "x", "X", "*")]))
        ncomp = max(1, ncomp)
        pin = ncomp
        for i in range(ncomp):
            if base[i] != 0:
                pin = i + 1
                break
        return cmp(installed, ver) >= 0 and inst[:pin] == base[:pin]
    if op == "~":
        base = _semver_key(ver)[0]
        inst = _semver_key(installed)[0]
        return cmp(installed, ver) >= 0 and inst[:2] == base[:2]
    c = cmp(installed, ver)
    return {
        ">=": c >= 0,
        "<=": c <= 0,
        ">": c > 0,
        "<": c < 0,
        "=": c == 0,
        "==": c == 0,
        "!=": c != 0,
    }[op]


_CONSTRAINT = re.compile(r"\s*(>=|<=|==|!=|>|<|=|\^|~)?\s*([^\s,]+)")


def version_in_range(installed: str, expr: str, flavor: str = "semver") -> bool:
    """True when `installed` satisfies the vulnerable-range expression.

    Handles both packed (">=4.0.0,<4.0.14") and spaced (">= 4.0.0, < 4.0.14",
    the GHSA style) constraint forms."""
    cmp = COMPARATORS.get(flavor, compare_semver)
    for alternative in expr.split("||"):
        constraints = [
            f"{op or '='}{ver}"
            for op, ver in _CONSTRAINT.findall(alternative)
        ]
        if constraints and all(
            _check_one(cmp, installed, c) for c in constraints
        ):
            return True
    return False
