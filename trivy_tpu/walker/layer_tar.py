"""Container layer tar walker.

Mirrors pkg/fanal/walker/tar.go: stream tar entries, collect overlayfs
whiteout markers — `.wh.<name>` deletes a path, `.wh..wh..opq` marks its
directory opaque — and yield regular files for analysis.
"""

from __future__ import annotations

import os
import tarfile
from dataclasses import dataclass, field
from typing import IO

from trivy_tpu.walker.fs import FileEntry

WHITEOUT_PREFIX = ".wh."
OPAQUE_MARKER = ".wh..wh..opq"


@dataclass
class LayerResult:
    entries: list[FileEntry] = field(default_factory=list)
    opaque_dirs: list[str] = field(default_factory=list)
    whiteout_files: list[str] = field(default_factory=list)


def walk_layer_tar(fileobj: IO[bytes]) -> LayerResult:
    """tar.go:35-103 Walk.

    Openers read lazily through the (seekable) tar, so only files an analyzer
    claims are ever materialized; the caller must keep `fileobj` open until
    analysis of the returned entries finishes.
    """
    result = LayerResult()
    tf = tarfile.open(fileobj=fileobj, mode="r:*")
    for member in tf:
        name = member.name
        if name.startswith("./"):
            name = name[2:]
        dirname, base = os.path.split(name)

        if base == OPAQUE_MARKER:
            result.opaque_dirs.append(dirname)
            continue
        if base.startswith(WHITEOUT_PREFIX):
            result.whiteout_files.append(
                os.path.join(dirname, base[len(WHITEOUT_PREFIX) :])
            )
            continue
        if not member.isreg():
            continue

        def read(m=member) -> bytes:
            f = tf.extractfile(m)
            if f is None:
                raise OSError(f"cannot extract {m.name}")
            with f:
                return f.read()

        result.entries.append(
            FileEntry(
                path=name,
                size=member.size,
                mode=member.mode | 0o100000,
                opener=read,
            )
        )
    return result
