from trivy_tpu.walker.fs import FSWalker, WalkOption, skip_path

__all__ = ["FSWalker", "WalkOption", "skip_path"]
