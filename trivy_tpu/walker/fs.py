"""Filesystem walker with the reference's skip semantics.

Mirrors pkg/fanal/walker/{walk.go,fs.go}: doublestar-style skip patterns
(``**`` crossing separators), default skip dirs, regular-files-only, tolerated
per-file permission errors, and a file-size threshold.  Unlike the reference's
callback-per-file shape, the walker *yields* entries so the analyzer group can
assemble device-sized batches — the TPU-native replacement for the reference's
goroutine-per-file fan-out (pkg/fanal/analyzer/analyzer.go:396-448).
"""

from __future__ import annotations

import fnmatch
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Iterator

DEFAULT_SIZE_THRESHOLD = 100 << 20  # walker/walk.go:15 defaultSizeThreshold

# walker/walk.go:17-22 defaultSkipDirs
DEFAULT_SKIP_DIRS = ["**/.git", "proc", "sys", "dev"]


@dataclass
class WalkOption:
    """walker.Option (walk.go:24-27)."""

    skip_files: list[str] = field(default_factory=list)
    skip_dirs: list[str] = field(default_factory=list)


@dataclass
class FileEntry:
    """One walked file: relative slash path + stat info + lazy opener."""

    path: str  # relative, slash-separated
    size: int
    mode: int
    opener: Callable[[], bytes]


def _doublestar_to_re(pattern: str) -> re.Pattern[str]:
    """Compile a doublestar glob (bmatcuk/doublestar semantics subset) to a regex.

    ``**`` matches any number of path segments (including zero); ``*``/``?``
    never cross ``/``; character classes pass through.
    """
    out = []
    i = 0
    n = len(pattern)
    while i < n:
        c = pattern[i]
        if c == "*":
            if pattern[i : i + 2] == "**":
                # `**/` -> zero or more segments; trailing `**` -> anything
                if pattern[i : i + 3] == "**/":
                    out.append(r"(?:[^/]+/)*")
                    i += 3
                else:
                    out.append(r".*")
                    i += 2
            else:
                out.append(r"[^/]*")
                i += 1
        elif c == "?":
            out.append(r"[^/]")
            i += 1
        elif c == "[":
            j = i + 1
            if j < n and pattern[j] in "!^":
                j += 1
            if j < n and pattern[j] == "]":
                j += 1
            while j < n and pattern[j] != "]":
                j += 1
            if j < n:
                cls = pattern[i + 1 : j]
                if cls.startswith("!"):
                    cls = "^" + cls[1:]
                out.append("[" + cls + "]")
                i = j + 1
            else:
                out.append(re.escape(c))
                i += 1
        else:
            out.append(re.escape(c))
            i += 1
    return re.compile("^" + "".join(out) + "$")


def clean_skip_paths(paths: list[str]) -> list[str]:
    """walker.CleanSkipPaths (walk.go:32-37)."""
    return [os.path.normpath(p).replace(os.sep, "/").lstrip("/") for p in paths]


def compile_skip_patterns(patterns: list[str]) -> list[re.Pattern[str]]:
    out = []
    for pattern in patterns:
        try:
            out.append(_doublestar_to_re(pattern))
        except re.error:
            pass  # bad pattern never matches (walk.go:44-46)
    return out


def skip_path(path: str, skip_patterns: list) -> bool:
    """walker.SkipPath (walk.go:39-53); accepts raw globs or precompiled."""
    path = path.lstrip("/")
    if skip_patterns and isinstance(skip_patterns[0], str):
        skip_patterns = compile_skip_patterns(skip_patterns)
    return any(rx.match(path) for rx in skip_patterns)


class FSWalker:
    """walker.FS (fs.go:17)."""

    def __init__(self, option: WalkOption | None = None):
        self.option = option or WalkOption()

    def walk(self, root: str) -> Iterator[FileEntry]:
        skip_files = compile_skip_patterns(clean_skip_paths(self.option.skip_files))
        skip_dirs = compile_skip_patterns(
            clean_skip_paths(self.option.skip_dirs) + DEFAULT_SKIP_DIRS
        )

        root = os.path.abspath(root)
        if os.path.isfile(root):
            # Single-file target behaves like a one-entry walk.
            st = os.stat(root)
            yield FileEntry(
                path=os.path.basename(root),
                size=st.st_size,
                mode=st.st_mode,
                opener=_opener(root),
            )
            return

        for dirpath, dirnames, filenames in os.walk(root, onerror=None):
            rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
            if rel_dir == ".":
                rel_dir = ""

            kept = []
            for d in dirnames:
                rel = f"{rel_dir}/{d}" if rel_dir else d
                if not skip_path(rel, skip_dirs):
                    kept.append(d)
            dirnames[:] = sorted(kept)

            for fname in sorted(filenames):
                rel = f"{rel_dir}/{fname}" if rel_dir else fname
                if skip_path(rel, skip_files):
                    continue
                full = os.path.join(dirpath, fname)
                try:
                    st = os.lstat(full)
                except OSError:
                    continue  # tolerated like fs.go:104-106 permission skips
                import stat as statmod

                if not statmod.S_ISREG(st.st_mode):
                    continue
                if st.st_size > DEFAULT_SIZE_THRESHOLD:
                    continue  # walk.go:15 defaultSizeThreshold
                yield FileEntry(
                    path=rel, size=st.st_size, mode=st.st_mode, opener=_opener(full)
                )


def _opener(full_path: str) -> Callable[[], bytes]:
    def read() -> bytes:
        with open(full_path, "rb") as f:
            return f.read()

    return read
