"""Java index DB: jar sha1 digest -> (groupId, artifactId, version).

pkg/javadb/client.go analogue: a separate OCI-distributed database the jar
analyzer consults when an archive carries no pom.properties.  Wire format
here is a JSON shard map (sha1 prefix -> {sha1: "g:a:v"}) inside the OCI
layer (media type below); `ensure_javadb` gates re-downloads on the
metadata.json DownloadedAt stamp (the reference's javadb client updates
once per day, client.go).
"""

from __future__ import annotations

import json
import os

JAVA_DB_MEDIA_TYPE = "application/vnd.trivy-tpu.javadb.layer.v1.tar+gzip"
DEFAULT_JAVA_DB_REPOSITORY = "ghcr.io/aquasecurity/trivy-java-db:1"

_default_dir: str = ""


def set_default_javadb_dir(path: str) -> None:
    global _default_dir
    _default_dir = path


def open_default_javadb() -> "JavaDB | SqliteJavaDB | None":
    d = _default_dir or os.environ.get("TRIVY_TPU_JAVA_DB_DIR", "")
    if d and os.path.isdir(d):
        if os.path.exists(os.path.join(d, "trivy-java.db")):
            return SqliteJavaDB(d)
        return JavaDB(d)
    return None


class SqliteJavaDB:
    """Get side over a REAL trivy-java-db file (`trivy-java.db`, SQLite —
    the artifact pkg/javadb/client.go downloads; schema: table
    indices(group_id, artifact_id, version, sha1 BLOB, archive_type)).
    Read with the stdlib sqlite3 module in read-only mode."""

    def __init__(self, db_dir: str):
        import sqlite3

        self.db_dir = db_dir
        path = os.path.join(db_dir, "trivy-java.db")
        self._conn = sqlite3.connect(
            f"file:{path}?mode=ro&immutable=1", uri=True
        )

    def lookup(self, sha1: str) -> tuple[str, str, str] | None:
        """SearchBySHA1 (client.go:135): digest -> (g, a, v).  sha1 is
        stored as a BLOB of raw bytes."""
        try:
            blob = bytes.fromhex(sha1)
        except ValueError:
            return None
        cur = self._conn.execute(
            "SELECT group_id, artifact_id, version FROM indices "
            "WHERE sha1 = ?",
            (blob,),
        )
        row = cur.fetchone()
        if row is None:
            # Some builds store the hex string instead of raw bytes.
            row = self._conn.execute(
                "SELECT group_id, artifact_id, version FROM indices "
                "WHERE sha1 = ?",
                (sha1,),
            ).fetchone()
        if row is None:
            return None
        return str(row[0]), str(row[1]), str(row[2])

    def search_by_artifact_id(
        self, artifact_id: str, version: str
    ) -> str | None:
        """SearchByArtifactID (client.go:149): the most frequent group_id
        among jar-type indices for this artifactId (ties: smallest)."""
        rows = self._conn.execute(
            "SELECT group_id FROM indices "
            "WHERE artifact_id = ? AND version = ? AND archive_type = 'jar' "
            "ORDER BY group_id",
            (artifact_id, version),
        ).fetchall()
        if not rows:
            return None
        counts: dict[str, int] = {}
        for (gid,) in rows:
            counts[gid] = counts.get(gid, 0) + 1
        # Most frequent group wins; the reference leaves ties to Go map
        # order — resolve deterministically to the smallest group id.
        return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[0][0]


class JavaDB:
    """Get side: digest lookup over the shard files."""

    def __init__(self, db_dir: str):
        self.db_dir = db_dir
        self._shards: dict[str, dict] = {}

    def lookup(self, sha1: str) -> tuple[str, str, str] | None:
        shard = sha1[:2]
        if shard not in self._shards:
            path = os.path.join(self.db_dir, f"java-{shard}.json")
            try:
                with open(path, encoding="utf-8") as f:
                    self._shards[shard] = json.load(f)
            except (OSError, ValueError):
                self._shards[shard] = {}
        gav = self._shards[shard].get(sha1)
        if not gav:
            return None
        parts = gav.split(":")
        if len(parts) != 3:
            return None
        return parts[0], parts[1], parts[2]


def build_javadb(db_dir: str, entries: dict[str, str]) -> None:
    """Fixture builder: {sha1: "g:a:v"} -> shard files (the dbtest
    pattern)."""
    os.makedirs(db_dir, exist_ok=True)
    shards: dict[str, dict[str, str]] = {}
    for sha1, gav in entries.items():
        shards.setdefault(sha1[:2], {})[sha1] = gav
    for shard, data in shards.items():
        with open(
            os.path.join(db_dir, f"java-{shard}.json"), "w", encoding="utf-8"
        ) as f:
            json.dump(data, f)


def download_javadb(
    db_dir: str,
    repository: str = DEFAULT_JAVA_DB_REPOSITORY,
    insecure: bool = False,
) -> None:
    """javadb client.go Download: pull the OCI layer and extract shards."""
    import datetime
    import tarfile

    from trivy_tpu.oci import OciArtifact

    os.makedirs(db_dir, exist_ok=True)
    art = OciArtifact(repository, insecure=insecure)
    extracted: set[str] = set()
    with art.download_layer(JAVA_DB_MEDIA_TYPE) as blob:
        with tarfile.open(fileobj=blob, mode="r:*") as tf:
            for member in tf.getmembers():
                if not member.isfile() or ".." in member.name:
                    continue
                name = os.path.basename(member.name)
                extracted.add(name)
                with open(os.path.join(db_dir, name), "wb") as out:
                    out.write(tf.extractfile(member).read())
    # open_default_javadb prefers trivy-java.db; a shard-only refresh must
    # not leave a stale SQLite index shadowing it (db/client.py contract).
    if "trivy-java.db" not in extracted:
        try:
            os.unlink(os.path.join(db_dir, "trivy-java.db"))
        except OSError:
            pass
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
    with open(os.path.join(db_dir, "metadata.json"), "w", encoding="utf-8") as f:
        json.dump({"DownloadedAt": stamp}, f)


def ensure_javadb(
    db_dir: str,
    repository: str = DEFAULT_JAVA_DB_REPOSITORY,
    insecure: bool = False,
    max_age_hours: float = 24.0,
) -> bool:
    """Download unless the local copy is younger than `max_age_hours` (the
    reference's javadb updates once a day).  Returns True on download."""
    import datetime

    from trivy_tpu.db.client import _parse_time

    meta_path = os.path.join(db_dir, "metadata.json")
    try:
        with open(meta_path, encoding="utf-8") as f:
            stamp = json.load(f).get("DownloadedAt", "")
        age = datetime.datetime.now(datetime.timezone.utc) - _parse_time(stamp)
        if stamp and age < datetime.timedelta(hours=max_age_hours):
            return False
    except (OSError, ValueError):
        pass
    download_javadb(db_dir, repository, insecure)
    return True
