"""Stdlib back-compat shims for the oldest supported interpreter.

``tomllib`` landed in Python 3.11; on 3.10 the API-compatible ``tomli``
wheel (already in the image for other tooling) stands in.  Import the
module object from here so every TOML-reading site degrades identically
instead of each carrying its own try/except.
"""

from __future__ import annotations

try:
    import tomllib
except ImportError:  # Python 3.10
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ImportError:  # pragma: no cover - neither parser present
        tomllib = None  # type: ignore[assignment]

__all__ = ["tomllib"]
