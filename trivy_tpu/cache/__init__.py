from trivy_tpu.cache.store import ArtifactCache, FSCache, MemoryCache
from trivy_tpu.cache.tiered import TieredCache
from trivy_tpu.cache.results import ScanResultCache, content_digest, result_key


def build_cache(
    backend: str = "", cache_dir: str = "", ttl_seconds: int = 0
) -> ArtifactCache:
    """Construct the artifact-cache chain a backend spec names — the ONE
    place the CLI scan path and the server agree on what `--cache-backend`
    means.  Remote specs (redis://, s3://) sit behind local tiers (memory
    always, FS when a cache dir is configured): reads promote inward,
    remote writes ride the write-behind thread, and remote errors degrade
    to the local tiers instead of failing the scan.  "" picks FS when a
    cache dir exists, else memory.  Raises ValueError on an unknown spec
    (callers wrap it in their own error type)."""
    if backend.startswith(("redis://", "rediss://")):
        from trivy_tpu.cache.redis import RedisCache

        local: list[ArtifactCache] = [MemoryCache()]
        if cache_dir:
            local.append(FSCache(cache_dir))
        return TieredCache(
            local
            + [RedisCache(backend, ttl_seconds=ttl_seconds, timeout=5.0)]
        )
    if backend.startswith("s3://"):
        from trivy_tpu.cache.s3 import S3Cache

        local = [MemoryCache()]
        if cache_dir:
            local.append(FSCache(cache_dir))
        return TieredCache(local + [S3Cache(backend, timeout=10.0)])
    if backend == "fs":
        if not cache_dir:
            raise ValueError("cache backend 'fs' requires a cache dir")
        return TieredCache([MemoryCache(), FSCache(cache_dir)])
    if backend == "memory":
        return MemoryCache()
    if backend == "":
        return FSCache(cache_dir) if cache_dir else MemoryCache()
    raise ValueError(
        f"unknown cache backend {backend!r} "
        "(memory | fs | redis://... | s3://...)"
    )


__all__ = [
    "ArtifactCache",
    "FSCache",
    "MemoryCache",
    "TieredCache",
    "ScanResultCache",
    "build_cache",
    "content_digest",
    "result_key",
]
