from trivy_tpu.cache.store import ArtifactCache, FSCache, MemoryCache

__all__ = ["ArtifactCache", "FSCache", "MemoryCache"]
