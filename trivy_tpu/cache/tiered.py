"""Tiered cache chain: memory -> FS -> remote, degrade-don't-fail.

The fleet economics (ISSUE 15, ROADMAP open item 3): at registry scale
most image layers are shared, so the dominant throughput metric is cache
hit rate, and the cache must be consultable from a hot serve path
without ever becoming a new failure mode.  This module composes the
existing backends (store.py MemoryCache/FSCache, redis.py, s3.py) into
one ArtifactCache with the production behaviors the single backends
lack:

- **Reads walk the chain** front to back; a hit in a later tier is
  promoted into every earlier tier so the next probe stops sooner.
- **Errors degrade, never fail.**  Each tier carries a retry budget
  (default 8).  A tier that raises is skipped for that operation, its
  budget decremented, and the walk continues with the next tier; a tier
  whose budget is exhausted is taken out of rotation entirely
  (`degraded` in the snapshot).  A full remote outage therefore costs at
  most `error_budget` slow probes process-wide, after which the chain is
  local-only — no scan ever fails because a cache tier did.
- **Writes are tiered too**: local tiers (memory/fs) are written
  synchronously; remote tiers (redis/s3/remote) are fed by an async
  write-behind queue + daemon thread so a slow remote never sits on the
  scan path.  `flush()` drains the queue (tests, close()).
- **Single-flight dedup**: `single_flight(key, fn)` collapses concurrent
  misses on one key into one execution of `fn`; the serve scheduler uses
  it so N simultaneous scans of a novel blob compute once.
- **Negative-entry TTL**: a miss is remembered for `negative_ttl_s`
  (default 30s) and answered locally without re-probing remote tiers —
  registry-scale scans hammer the same novel blob id many times in the
  window before its result lands.
- **Chaos seams**: every tier read crosses ``faults.fire("cache.get")``
  and every tier write ``faults.fire("cache.put")``, so chaos profiles
  (TRIVY_TPU_FAULTS) can prove the degrade-don't-fail contract in CI.

Every probe lands in the process-global tallies (cache/stats.py) as
`trivy_tpu_cache_requests_total{tier,outcome}`.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable

from trivy_tpu import faults, lockcheck
from trivy_tpu.atypes import ArtifactInfo, BlobInfo
from trivy_tpu.cache import stats as cache_stats
from trivy_tpu.cache.store import ArtifactCache

DEFAULT_ERROR_BUDGET = 8
DEFAULT_NEGATIVE_TTL_S = 30.0
REMOTE_TIER_NAMES = ("redis", "s3", "remote")
_WRITE_QUEUE_MAX = 1024


def tier_name(backend: ArtifactCache) -> str:
    """Bounded metric label for a backend (class-name heuristic, with an
    explicit `cache_tier_name` attribute as the override)."""
    explicit = getattr(backend, "cache_tier_name", "")
    if explicit:
        return explicit
    cls = type(backend).__name__.lower()
    for name in ("memory", "fs", "redis", "s3", "remote"):
        if cls.startswith(name):
            return name
    return "remote"


class _Flight:
    """One in-progress single-flight computation."""

    __slots__ = ("done", "result", "ok")

    def __init__(self):
        self.done = threading.Event()
        self.result: object = None
        self.ok = False


class _Tier:
    """One chain link: backend + retry budget (budget/error fields are
    mutated under the owning TieredCache lock).  `io_lock` serializes
    backend calls: the write-behind thread and scan threads would
    otherwise interleave on a remote backend's single socket."""

    __slots__ = ("backend", "name", "budget", "errors", "last_error",
                 "io_lock")

    def __init__(self, backend: ArtifactCache, name: str, budget: int):
        self.backend = backend
        self.name = name
        self.budget = budget
        self.errors = 0
        self.last_error = ""
        self.io_lock = lockcheck.make_lock(f"cache.tier.{name}")

    @property
    def degraded(self) -> bool:
        return self.errors >= self.budget


class TieredCache(ArtifactCache):
    """ArtifactCache over an ordered tier chain (fastest first)."""

    def __init__(
        self,
        tiers: Iterable[ArtifactCache],
        *,
        error_budget: int = DEFAULT_ERROR_BUDGET,
        negative_ttl_s: float = DEFAULT_NEGATIVE_TTL_S,
        write_behind: bool = True,
    ):
        backends = list(tiers)
        if not backends:
            raise ValueError("TieredCache needs at least one tier")
        self._lock = lockcheck.make_lock("cache.tiered")
        self._tiers = [
            _Tier(b, tier_name(b), error_budget) for b in backends
        ]
        self._negative_ttl_s = negative_ttl_s
        self._negative: dict[str, float] = {}  # owner: _lock
        self._inflight: dict[str, _Flight] = {}  # owner: _lock
        self._dedup_hits = 0  # owner: _lock
        self._wb_queue: queue.Queue | None = None
        self._wb_thread: threading.Thread | None = None
        self._wb_dropped = 0  # owner: _lock
        self._closed = False
        if write_behind and any(
            t.name in REMOTE_TIER_NAMES for t in self._tiers
        ):
            self._wb_queue = queue.Queue(maxsize=_WRITE_QUEUE_MAX)
            self._wb_thread = threading.Thread(
                target=self._write_behind_loop,
                name="cache-write-behind",
                daemon=True,
            )
            self._wb_thread.start()

    @property
    def tiers(self) -> list[_Tier]:
        """The ordered tier chain (read-only view for tests and debug
        surfaces; mutating it is not supported)."""
        return list(self._tiers)

    # -- tier walk ---------------------------------------------------------

    def _live_tiers(self) -> list[_Tier]:
        with self._lock:
            return [t for t in self._tiers if not t.degraded]

    def _tier_error(self, tier: _Tier, op: str, e: Exception) -> None:
        cache_stats.record_request(tier.name, "error")
        with self._lock:
            tier.errors += 1
            tier.last_error = f"{op}: {type(e).__name__}: {e}"

    def _get(self, op: str, getter: Callable[[ArtifactCache], object]):
        """Walk tiers for a read; returns (value, hit_tier_index)."""
        hit_val = None
        hit_idx = -1
        tiers = self._live_tiers()
        for i, tier in enumerate(tiers):
            try:
                faults.fire("cache.get")
                with tier.io_lock:
                    val = getter(tier.backend)
            except Exception as e:
                # Degrade to the next tier; the cache must never fail
                # the scan (the whole point of the retry budget).
                self._tier_error(tier, op, e)
                continue
            if val is not None:
                cache_stats.record_request(tier.name, "hit")
                hit_val, hit_idx = val, i
                break
            cache_stats.record_request(tier.name, "miss")
        return hit_val, hit_idx, tiers

    def _promote(
        self,
        tiers: list[_Tier],
        hit_idx: int,
        putter: Callable[[ArtifactCache], None],
    ) -> None:
        """Copy a hit into every tier in front of the one that served it."""
        for tier in tiers[:hit_idx]:
            try:
                faults.fire("cache.put")
                with tier.io_lock:
                    putter(tier.backend)
            except Exception as e:
                self._tier_error(tier, "promote", e)

    def _put(self, key: str, putter: Callable[[ArtifactCache], None]) -> None:
        """Synchronous local writes; remote tiers go through write-behind."""
        with self._lock:
            self._negative.pop(key, None)
        for tier in self._live_tiers():
            if tier.name in REMOTE_TIER_NAMES and self._wb_queue is not None:
                try:
                    self._wb_queue.put_nowait((tier, putter))
                except queue.Full:
                    with self._lock:
                        self._wb_dropped += 1
                continue
            try:
                faults.fire("cache.put")
                with tier.io_lock:
                    putter(tier.backend)
            except Exception as e:
                self._tier_error(tier, "put", e)

    def _write_behind_loop(self) -> None:
        assert self._wb_queue is not None
        while True:
            item = self._wb_queue.get()
            if item is None:  # close() sentinel
                self._wb_queue.task_done()
                return
            tier, putter = item
            if not tier.degraded:
                try:
                    faults.fire("cache.put")
                    with tier.io_lock:
                        putter(tier.backend)
                    cache_stats.event("write_behind_flush")
                except Exception as e:
                    self._tier_error(tier, "write-behind", e)
            self._wb_queue.task_done()

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until queued write-behind work drains (or timeout)."""
        q = self._wb_queue
        if q is None:
            return True
        deadline = time.monotonic() + timeout_s
        while q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.01)
        return not q.unfinished_tasks

    # -- negative entries --------------------------------------------------

    def _negative_hit(self, key: str) -> bool:
        if self._negative_ttl_s <= 0:
            return False
        now = time.monotonic()
        with self._lock:
            exp = self._negative.get(key)
            if exp is None:
                return False
            if now >= exp:
                del self._negative[key]
                expired = True
            else:
                expired = False
        if expired:
            cache_stats.record_eviction("negative-expired")
            return False
        return True

    def _remember_miss(self, key: str) -> None:
        if self._negative_ttl_s <= 0:
            return
        with self._lock:
            self._negative[key] = time.monotonic() + self._negative_ttl_s

    # -- ArtifactCache interface -------------------------------------------

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        self._put("a::" + artifact_id, lambda b: b.put_artifact(artifact_id, info))

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        self._put("b::" + blob_id, lambda b: b.put_blob(blob_id, info))

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        if self._negative_hit("a::" + artifact_id):
            cache_stats.record_request("results", "negative")
            return None
        val, idx, tiers = self._get(
            "get_artifact", lambda b: b.get_artifact(artifact_id)
        )
        if val is None:
            self._remember_miss("a::" + artifact_id)
            return None
        self._promote(tiers, idx, lambda b: b.put_artifact(artifact_id, val))
        return val

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        if self._negative_hit("b::" + blob_id):
            cache_stats.record_request("results", "negative")
            return None
        val, idx, tiers = self._get("get_blob", lambda b: b.get_blob(blob_id))
        if val is None:
            self._remember_miss("b::" + blob_id)
            return None
        self._promote(tiers, idx, lambda b: b.put_blob(blob_id, val))
        return val

    def exists(self, blob_id: str) -> bool:
        if self._negative_hit("b::" + blob_id):
            cache_stats.record_request("results", "negative")
            return False
        # Short-circuit on the first tier that answers: a memory-tier hit
        # must never touch remote tiers — watch-planner novelty probes
        # come in bulk, and letting them fall through to a flaky redis
        # tier burns its error budget on pure existence checks.
        for tier in self._live_tiers():
            try:
                faults.fire("cache.get")
                with tier.io_lock:
                    present = tier.backend.exists(blob_id)
                if present:
                    cache_stats.record_request(tier.name, "hit")
                    return True
                cache_stats.record_request(tier.name, "miss")
            except Exception as e:
                self._tier_error(tier, "exists", e)
        return False

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        ids = list(blob_ids)
        for tier in self._live_tiers():
            try:
                with tier.io_lock:
                    tier.backend.delete_blobs(ids)
            except Exception as e:
                self._tier_error(tier, "delete_blobs", e)

    def clear(self) -> None:
        for tier in self._live_tiers():
            try:
                with tier.io_lock:
                    tier.backend.clear()
            except Exception as e:
                self._tier_error(tier, "clear", e)
        with self._lock:
            self._negative.clear()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.flush()
        if self._wb_queue is not None:
            self._wb_queue.put(None)
            if self._wb_thread is not None:
                self._wb_thread.join(timeout=5.0)
        for tier in self._tiers:
            try:
                tier.backend.close()
            except Exception:
                pass  # already tearing down; backend sockets may be gone

    # -- single-flight -----------------------------------------------------

    def single_flight(self, key: str, fn: Callable[[], object]):
        """Collapse concurrent computations of `key`: the first caller
        (the leader) runs `fn`; callers that arrive while it is in
        flight block and share its result.  A leader that raises
        propagates to itself only — followers see the failed flight and
        compute solo (the retry is theirs to make)."""
        with self._lock:
            flight = self._inflight.get(key)
            if flight is None:
                flight = _Flight()
                self._inflight[key] = flight
                leader = True
            else:
                self._dedup_hits += 1
                leader = False
        if not leader:
            flight.done.wait()
            if flight.ok:
                return flight.result
            return fn()
        try:
            flight.result = fn()
            flight.ok = True
            return flight.result
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            flight.done.set()

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            tiers = [
                {
                    "name": t.name,
                    "errors": t.errors,
                    "budget": t.budget,
                    "degraded": t.degraded,
                    "last_error": t.last_error,
                }
                for t in self._tiers
            ]
            negative = len(self._negative)
            dedup = self._dedup_hits
            dropped = self._wb_dropped
        q = self._wb_queue
        return {
            "tiers": tiers,
            "negative_entries": negative,
            "negative_ttl_s": self._negative_ttl_s,
            "single_flight_dedup": dedup,
            "write_behind": {
                "enabled": q is not None,
                "queued": (q.unfinished_tasks if q is not None else 0),
                "dropped": dropped,
            },
        }
