"""S3 cache backend (pkg/fanal/cache/s3.go).

Cache documents live as S3 objects ``<prefix>/artifact/<id>`` and
``<prefix>/blob/<id>``.  Requests are signed with AWS Signature V4 over
stdlib HTTP — no SDK ships here; the protocol surface the cache needs
(GET/PUT/DELETE/HEAD object) is small and fully specified.

Configuration comes from the backend URL ``s3://bucket/prefix`` plus the
conventional environment: AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY/
AWS_SESSION_TOKEN, AWS_REGION, and AWS_ENDPOINT_URL for S3-compatible
stores (minio/localstack), which is also how the tests drive a fake
endpoint.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterable

from trivy_tpu.atypes import ArtifactInfo, BlobInfo
from trivy_tpu.cache.store import ArtifactCache


class S3Error(RuntimeError):
    pass


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3Client:
    """SigV4-signed object operations."""

    def __init__(
        self,
        bucket: str,
        region: str = "",
        endpoint: str = "",
        access_key: str = "",
        secret_key: str = "",
        session_token: str = "",
        service: str = "s3",
        timeout: float = 60.0,
    ):
        self.bucket = bucket
        self.service = service
        self.timeout = timeout
        self.region = region or os.environ.get("AWS_REGION", "us-east-1")
        self.endpoint = (
            endpoint
            or os.environ.get("AWS_ENDPOINT_URL", "")
            or f"https://s3.{self.region}.amazonaws.com"
        ).rstrip("/")
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", ""
        )
        self.session_token = session_token or os.environ.get(
            "AWS_SESSION_TOKEN", ""
        )

    def _request(
        self,
        method: str,
        key: str,
        body: bytes = b"",
        query: str = "",
        headers_extra: dict[str, str] | None = None,
    ) -> tuple[int, bytes]:
        if key.startswith("/"):
            path = key  # pre-built path (service APIs)
        else:
            path = f"/{self.bucket}/{urllib.parse.quote(key)}"
        url = self.endpoint + path + (f"?{query}" if query else "")
        host = urllib.parse.urlparse(self.endpoint).netloc
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        payload_hash = hashlib.sha256(body).hexdigest()

        headers = {
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        if self.session_token:
            headers["x-amz-security-token"] = self.session_token
        # Extra headers participate in signing (SigV4 requires any present
        # x-amz-* header to be signed; JSON-protocol APIs route on
        # x-amz-target).
        for k, v in (headers_extra or {}).items():
            headers[k.lower()] = v
        signed_headers = ";".join(sorted(headers))
        canonical_query = "&".join(
            sorted(
                part if "=" in part else f"{part}="
                for part in query.split("&")
                if part
            )
        )
        canonical = "\n".join(
            [
                method,
                path,
                canonical_query,
                "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
                signed_headers,
                payload_hash,
            ]
        )
        scope = f"{datestamp}/{self.region}/{self.service}/aws4_request"
        to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        )
        k = _sign(f"AWS4{self.secret_key}".encode(), datestamp)
        k = _sign(k, self.region)
        k = _sign(k, self.service)
        k = _sign(k, "aws4_request")
        signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        )

        req = urllib.request.Request(
            url, data=body if method in ("PUT", "POST") else None,
            headers=headers, method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except urllib.error.URLError as e:
            raise S3Error(f"s3: {method} {key}: {e.reason}") from e

    def put_object(self, key: str, body: bytes) -> None:
        status, payload = self._request("PUT", key, body)
        if status not in (200, 201):
            raise S3Error(f"s3: PUT {key}: HTTP {status}: {payload[:200]!r}")

    def get_object(self, key: str) -> bytes | None:
        status, payload = self._request("GET", key)
        if status == 404:
            return None
        if status != 200:
            raise S3Error(f"s3: GET {key}: HTTP {status}")
        return payload

    def head_object(self, key: str) -> bool:
        status, _ = self._request("HEAD", key)
        return status == 200

    def delete_object(self, key: str) -> None:
        self._request("DELETE", key)


class S3Cache(ArtifactCache):
    """s3.go S3Cache: cache documents as JSON objects."""

    def __init__(self, url: str, **client_kw):
        u = urllib.parse.urlparse(url)
        if u.scheme != "s3" or not u.netloc:
            raise S3Error(f"unsupported s3 URL {url!r}")
        self.prefix = u.path.strip("/") or "fanal"
        self.client = S3Client(bucket=u.netloc, **client_kw)

    def _key(self, bucket: str, item_id: str) -> str:
        return f"{self.prefix}/{bucket}/{item_id}"

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        self.client.put_object(
            self._key("artifact", artifact_id),
            json.dumps(info.to_json()).encode(),
        )

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        self.client.put_object(
            self._key("blob", blob_id), json.dumps(info.to_json()).encode()
        )

    @staticmethod
    def _decode(raw: bytes | None) -> dict | None:
        if not raw:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None  # corrupt object = cache miss, like the redis path

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        doc = self._decode(
            self.client.get_object(self._key("artifact", artifact_id))
        )
        return ArtifactInfo.from_json(doc) if doc else None

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        doc = self._decode(
            self.client.get_object(self._key("blob", blob_id))
        )
        return BlobInfo.from_json(doc) if doc else None

    def exists(self, blob_id: str) -> bool:
        return self.client.head_object(self._key("blob", blob_id))

    def missing_blobs(
        self, artifact_id: str, blob_ids: Iterable[str]
    ) -> tuple[bool, list[str]]:
        missing = [
            bid
            for bid in blob_ids
            if not self.client.head_object(self._key("blob", bid))
        ]
        missing_artifact = not self.client.head_object(
            self._key("artifact", artifact_id)
        )
        return missing_artifact, missing

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        for bid in blob_ids:
            self.client.delete_object(self._key("blob", bid))

    def clear(self) -> None:
        # Bucket listing/deletion is an operator action in the reference
        # too (s3.go implements Clear as a no-op for shared buckets).
        pass

    def close(self) -> None:
        pass
