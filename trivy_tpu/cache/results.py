"""Per-blob scan-result cache: the fleet's scan-once-per-layer plane.

At registry scale most image layers are shared, so millions of scans
collapse to a small set of novel blobs (ISSUE 15 / ROADMAP open item 3,
mirroring the economics of Trivy's ArtifactCache split in
`pkg/fanal/cache/`).  This module stores the *device scan verdict* for a
single content blob, keyed by everything that could change it:

    result key = sha256(blob_digest \\x00 ruleset_digest \\x00 schema
                        \\x00 program_id)

- `blob_digest` is sha256 over the exact bytes the engine scanned, so
  identical content hits regardless of path or image;
- `ruleset_digest` comes from the PR 4 registry (registry/digest.py) —
  a `rules push` changes the digest and naturally invalidates exactly
  the entries scanned under the old rules, nothing else;
- `engine_schema_version` (RESULT_SCHEMA_VERSION here) versions the
  finding encoding itself, so a wire-format change never rehydrates
  garbage;
- `program_id` names which scan program's verdict this is
  (programs/base.py): one device pass now yields several per-blob
  verdicts, and a license verdict must never answer a secret lookup.
  For license entries pass the program's `verdict_digest()` as
  `ruleset_digest` — the classifier corpus is part of the verdict
  identity there, not just the anchor ruleset.

Values ride the existing BlobInfo JSON document (atypes.py secret
round-trip) through any ArtifactCache backend — memory, FS, Redis, S3,
or the TieredCache chain — with the cached Secret's path stripped at
put time and the *requester's* path restored at hit time, so a hit is
byte-identical to a cold scan of the same bytes under any name.
"""

from __future__ import annotations

import hashlib
import threading

from trivy_tpu.atypes import BlobInfo
from trivy_tpu.cache import stats as cache_stats
from trivy_tpu.cache.store import ArtifactCache
from trivy_tpu.cache.tiered import TieredCache
from trivy_tpu.ftypes import Secret

# Version of the cached-finding encoding (the third key component).
# Bump on any change to SecretFinding/Code/Layer JSON shape — and on any
# change to the key derivation itself (v2 added the program_id
# component; v1 keys must never alias v2 entries).
RESULT_SCHEMA_VERSION = 2


def content_digest(data: bytes) -> str:
    """Canonical digest of the exact bytes handed to the engine."""
    return "sha256:" + hashlib.sha256(data).hexdigest()


def result_key(
    blob_digest: str,
    ruleset_digest: str,
    schema_version: int = RESULT_SCHEMA_VERSION,
    program_id: str = "secret",
) -> str:
    """The composite content-addressed key (itself `sha256:<hex>` so the
    FS backend files it under the plain hex digest)."""
    h = hashlib.sha256()
    h.update(blob_digest.encode("utf-8"))
    h.update(b"\x00")
    h.update(ruleset_digest.encode("utf-8"))
    h.update(b"\x00")
    h.update(str(schema_version).encode("ascii"))
    h.update(b"\x00")
    h.update(program_id.encode("utf-8"))
    return "sha256:" + h.hexdigest()


# Marker distinguishing an index document from a verdict document; also
# the CustomResources entry kind the index rides under.
INDEX_KIND = "trivy-tpu/result-index"


def index_key(
    ruleset_digest: str,
    program_id: str = "secret",
    schema_version: int = RESULT_SCHEMA_VERSION,
) -> str:
    """Key of the per-(ruleset digest, program id) reverse index — the
    set of blob digests holding cached verdicts under that digest.  The
    leading INDEX_KIND component keeps it disjoint from every
    result_key (those start with a blob digest, never the marker)."""
    h = hashlib.sha256()
    h.update(INDEX_KIND.encode("utf-8"))
    h.update(b"\x00")
    h.update(ruleset_digest.encode("utf-8"))
    h.update(b"\x00")
    h.update(str(schema_version).encode("ascii"))
    h.update(b"\x00")
    h.update(program_id.encode("utf-8"))
    return "sha256:" + h.hexdigest()


class ScanResultCache:
    """Get/put of per-blob Secret verdicts over an ArtifactCache backend.

    The backend is typically a TieredCache; any ArtifactCache works
    (tests use MemoryCache).  A *hit with zero findings* is a first-class
    verdict — clean blobs are the common case and exactly what the warm
    path must not re-scan.
    """

    def __init__(self, backend: ArtifactCache):
        self.backend = backend
        # Reverse-index write path: _indexed mirrors (index key, blob
        # digest) pairs already persisted so the steady state (same blob
        # re-verdicted under the same digest) skips the read-merge-write.
        self._index_lock = threading.Lock()
        self._indexed: set[tuple[str, str]] = set()

    def get(
        self,
        blob_digest: str,
        ruleset_digest: str,
        path: str = "",
        program_id: str = "secret",
    ) -> Secret | None:
        """The cached verdict rehydrated under `path`, or None on miss.
        A non-None return with empty findings means "scanned clean"."""
        if not ruleset_digest:
            # No digest, no key: an engine that can't identify its rules
            # must not serve stale verdicts.
            cache_stats.record_request("results", "miss")
            return None
        key = result_key(blob_digest, ruleset_digest, program_id=program_id)
        blob = self.backend.get_blob(key)
        if blob is None:
            cache_stats.record_request("results", "miss")
            return None
        cache_stats.record_request("results", "hit")
        findings = list(blob.secrets[0].findings) if blob.secrets else []
        return Secret(file_path=path, findings=findings)

    def put(
        self,
        blob_digest: str,
        ruleset_digest: str,
        secret: Secret,
        program_id: str = "secret",
    ) -> None:
        """Store the verdict for one blob (path stripped: the key is the
        content, not the name it was scanned under)."""
        if not ruleset_digest:
            return
        key = result_key(blob_digest, ruleset_digest, program_id=program_id)
        secrets = (
            [Secret(file_path="", findings=list(secret.findings))]
            if secret.findings
            else []
        )
        self.backend.put_blob(key, BlobInfo(secrets=secrets))
        self._index_add(blob_digest, ruleset_digest, program_id)

    def exists(
        self,
        blob_digest: str,
        ruleset_digest: str,
        program_id: str = "secret",
    ) -> bool:
        """Pure existence probe (no rehydration): does a cached verdict
        for this (blob, ruleset, program) exist in any tier?  The watch
        planner's novelty test — cheap by design (FS backends stat, the
        tiered chain short-circuits on its first hit)."""
        if not ruleset_digest:
            return False
        key = result_key(blob_digest, ruleset_digest, program_id=program_id)
        return self.backend.exists(key)

    def indexed_blobs(
        self,
        ruleset_digest: str,
        program_id: str = "secret",
    ) -> list[str]:
        """Blob digests holding cached verdicts under (ruleset digest,
        program id), from the persisted reverse index.  This is what lets
        the re-verification sweeper enumerate exactly the entries an old
        ruleset digest invalidated without a full tier walk."""
        if not ruleset_digest:
            return []
        blob = self.backend.get_blob(index_key(ruleset_digest, program_id))
        return sorted(self._index_entries(blob))

    def remove(
        self,
        blob_digest: str,
        ruleset_digest: str,
        program_id: str = "secret",
    ) -> None:
        """Drop one verdict and its reverse-index entry (sweeper cleanup
        after re-verdicting a blob under a new digest)."""
        if not ruleset_digest:
            return
        key = result_key(blob_digest, ruleset_digest, program_id=program_id)
        self.backend.delete_blobs([key])
        ikey = index_key(ruleset_digest, program_id)
        with self._index_lock:
            self._indexed.discard((ikey, blob_digest))
            entries = self._index_entries(self.backend.get_blob(ikey))
            if blob_digest not in entries:
                return
            entries.discard(blob_digest)
            if entries:
                self.backend.put_blob(ikey, self._index_doc(entries))
            else:
                self.backend.delete_blobs([ikey])

    def _index_add(
        self, blob_digest: str, ruleset_digest: str, program_id: str
    ) -> None:
        """Read-merge-write the reverse index under the instance lock.
        Persisting through put_blob means a TieredCache backend pops any
        negative entry for the index key on write, so a fresh verdict is
        always enumerable by the next sweep — a remembered miss never
        masks a re-scan."""
        ikey = index_key(ruleset_digest, program_id)
        pair = (ikey, blob_digest)
        with self._index_lock:
            if pair in self._indexed:
                return
            entries = self._index_entries(self.backend.get_blob(ikey))
            if blob_digest not in entries:
                entries.add(blob_digest)
                self.backend.put_blob(ikey, self._index_doc(entries))
            self._indexed.add(pair)

    @staticmethod
    def _index_doc(entries: set[str]) -> BlobInfo:
        return BlobInfo(
            custom_resources=[
                {"Kind": INDEX_KIND, "Blobs": sorted(entries)}
            ]
        )

    @staticmethod
    def _index_entries(blob: BlobInfo | None) -> set[str]:
        if blob is None:
            return set()
        for res in blob.custom_resources:
            if isinstance(res, dict) and res.get("Kind") == INDEX_KIND:
                return {str(b) for b in res.get("Blobs") or []}
        return set()

    def get_or_scan(
        self,
        blob_digest: str,
        ruleset_digest: str,
        path: str,
        scan_fn,
        program_id: str = "secret",
    ) -> Secret:
        """Hit path, or run `scan_fn()` exactly once per key across
        concurrent callers (single-flight when the backend is tiered)
        and remember its verdict."""
        hit = self.get(blob_digest, ruleset_digest, path, program_id)
        if hit is not None:
            return hit

        def _miss() -> Secret:
            verdict = scan_fn()
            self.put(blob_digest, ruleset_digest, verdict, program_id)
            return verdict

        if isinstance(self.backend, TieredCache):
            key = result_key(blob_digest, ruleset_digest, program_id=program_id)
            result = self.backend.single_flight(key, _miss)
            # The leader's verdict carries the leader's path; re-serve
            # under ours if they differ (shared findings are immutable).
            if isinstance(result, Secret) and result.file_path != path:
                return Secret(file_path=path, findings=list(result.findings))
            return result  # type: ignore[return-value]
        return _miss()

    def snapshot(self) -> dict:
        inner = getattr(self.backend, "snapshot", None)
        return {
            "schema_version": RESULT_SCHEMA_VERSION,
            "backend": type(self.backend).__name__,
            "tiers": inner() if callable(inner) else None,
        }

    def close(self) -> None:
        self.backend.close()
