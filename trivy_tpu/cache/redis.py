"""Redis cache backend (pkg/fanal/cache/redis.go).

Speaks RESP (the Redis serialization protocol) directly over a stdlib
socket — no client library ships in this environment, and the cache needs
only GET/SET/DEL/EXISTS/SCAN/PING.  Key layout matches the reference:
``fanal::artifact::<id>`` and ``fanal::blob::<id>`` (redis.go key scheme),
values are the same JSON documents the FS cache writes.

TLS (rediss://) wraps the socket with ssl; AUTH comes from the URL
userinfo.  The backend selects with ``--cache-backend redis://host:port``.
"""

from __future__ import annotations

import json
import socket
import ssl
import urllib.parse
from typing import Iterable

from trivy_tpu.atypes import ArtifactInfo, BlobInfo
from trivy_tpu.cache.store import ArtifactCache

ARTIFACT_PREFIX = "fanal::artifact::"
BLOB_PREFIX = "fanal::blob::"


class RedisError(RuntimeError):
    pass


class RespClient:
    """Minimal RESP2 client: one connection, request/response."""

    def __init__(self, url: str, timeout: float = 30.0):
        u = urllib.parse.urlparse(url)
        if u.scheme not in ("redis", "rediss"):
            raise RedisError(f"unsupported redis URL {url!r}")
        host = u.hostname or "localhost"
        port = u.port or 6379
        self._sock = socket.create_connection((host, port), timeout=timeout)
        if u.scheme == "rediss":
            ctx = ssl.create_default_context()
            self._sock = ctx.wrap_socket(self._sock, server_hostname=host)
        self._buf = b""
        if u.password:
            password = urllib.parse.unquote(u.password)
            if u.username:
                self.command(
                    "AUTH", urllib.parse.unquote(u.username), password
                )
            else:
                self.command("AUTH", password)
        db = (u.path or "/").lstrip("/")
        if db:
            self.command("SELECT", db)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    # -- wire format -------------------------------------------------------

    @staticmethod
    def _encode(parts: tuple[str | bytes, ...]) -> bytes:
        out = [b"*%d\r\n" % len(parts)]
        for p in parts:
            b = p if isinstance(p, bytes) else str(p).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(out)

    def command(self, *parts: str | bytes):
        self._sock.sendall(self._encode(parts))
        return self._read_reply()

    def pipeline(self, commands: list[tuple[str | bytes, ...]]) -> list:
        """Send N commands in one write, then read N replies — one
        network round trip instead of N (the MissingBlobs diff probes
        every layer of an image with EXISTS)."""
        if not commands:
            return []
        self._sock.sendall(b"".join(self._encode(c) for c in commands))
        return [self._read_reply() for _ in commands]

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisError("redis: connection closed")
            self._buf += chunk
        line, _, self._buf = self._buf.partition(b"\r\n")
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisError("redis: connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            return self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RedisError(f"redis: bad reply {line!r}")


class RedisCache(ArtifactCache):
    """redis.go RedisCache over the RESP client."""

    def __init__(self, url: str, ttl_seconds: int = 0, timeout: float = 30.0):
        self._client = RespClient(url, timeout=timeout)
        self._ttl = ttl_seconds
        self._client.command("PING")

    def _set(self, key: str, value: dict) -> None:
        data = json.dumps(value)
        if self._ttl > 0:
            self._client.command("SET", key, data, "EX", str(self._ttl))
        else:
            self._client.command("SET", key, data)

    def _get(self, key: str) -> dict | None:
        raw = self._client.command("GET", key)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        self._set(ARTIFACT_PREFIX + artifact_id, info.to_json())

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        self._set(BLOB_PREFIX + blob_id, info.to_json())

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        doc = self._get(ARTIFACT_PREFIX + artifact_id)
        return ArtifactInfo.from_json(doc) if doc else None

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        doc = self._get(BLOB_PREFIX + blob_id)
        return BlobInfo.from_json(doc) if doc else None

    def exists(self, blob_id: str) -> bool:
        return bool(self._client.command("EXISTS", BLOB_PREFIX + blob_id))

    def missing_blobs(
        self, artifact_id: str, blob_ids: Iterable[str]
    ) -> tuple[bool, list[str]]:
        # One pipelined round trip: N blob EXISTS + the artifact EXISTS.
        ids = list(blob_ids)
        replies = self._client.pipeline(
            [("EXISTS", BLOB_PREFIX + bid) for bid in ids]
            + [("EXISTS", ARTIFACT_PREFIX + artifact_id)]
        )
        missing = [bid for bid, present in zip(ids, replies) if not present]
        return not replies[-1], missing

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        ids = [BLOB_PREFIX + b for b in blob_ids]
        if ids:
            self._client.command("DEL", *ids)

    def clear(self) -> None:
        cursor = "0"
        while True:
            reply = self._client.command(
                "SCAN", cursor, "MATCH", "fanal::*", "COUNT", "512"
            )
            cursor = (
                reply[0].decode()
                if isinstance(reply[0], bytes)
                else str(reply[0])
            )
            keys = reply[1] or []
            if keys:
                self._client.command("DEL", *keys)
            if cursor == "0":
                break

    def close(self) -> None:
        self._client.close()
