"""Process-global cache accounting: requests per tier, evictions, events.

The result cache is consulted from CLI scans (ImageArtifact.inspect),
the serve scheduler (pre-ticket hit demux), and background write-behind
threads alike, so the question "what is THIS process's hit rate" is
per-process, not per-cache-instance — the gatelog pattern (obs/gatelog.py).
Consumers:

- `GET /debug/cache` serves :func:`snapshot`;
- the server's collect hook folds :func:`request_tallies` into
  `trivy_tpu_cache_requests_total{tier,outcome}` and
  :func:`eviction_tallies` into
  `trivy_tpu_cache_evictions_total{reason}`;
- the flight recorder embeds :func:`snapshot` in captures;
- bench/cache-smoke assert warm-pass deltas (miss == 0,
  `layer_analysis` == 0, `device_dispatch` == 0) from before/after
  snapshots.

Labels are bounded enums (metric-safe).  Tiers: `memory`, `fs`,
`redis`, `s3`, `remote`, `results` (the aggregated ScanResultCache
verdict), `artifact` (the MissingBlobs diff in the image walk).
Outcomes: `hit`, `miss`, `error` (tier degraded, scan continued),
`negative` (served from a negative entry inside its TTL).  Eviction
reasons: `corrupt` (undecodable JSON self-healed off disk),
`stale-schema` (BLOB_JSON_SCHEMA_VERSION mismatch), `ttl`,
`negative-expired`, `capacity`.

Counts are monotonic since process start — safe to export as counter
families via delta collect hooks.
"""

from __future__ import annotations

from trivy_tpu import lockcheck

_LOCK = lockcheck.make_lock("cache.stats")
_REQUESTS: dict[tuple[str, str], int] = {}  # owner: _LOCK
_EVICTIONS: dict[str, int] = {}  # owner: _LOCK
_EVENTS: dict[str, int] = {}  # owner: _LOCK

TIERS = ("memory", "fs", "redis", "s3", "remote", "results", "artifact")
OUTCOMES = ("hit", "miss", "error", "negative")
EVICTION_REASONS = (
    "corrupt", "stale-schema", "ttl", "negative-expired", "capacity",
)


def record_request(tier: str, outcome: str, n: int = 1) -> None:
    """Count one (or n) cache lookups against a tier with its outcome."""
    if n <= 0:
        return
    key = (tier, outcome)
    with _LOCK:
        _REQUESTS[key] = _REQUESTS.get(key, 0) + n


def record_eviction(reason: str, n: int = 1) -> None:
    """Count a self-heal/expiry eviction by bounded reason."""
    if n <= 0:
        return
    with _LOCK:
        _EVICTIONS[reason] = _EVICTIONS.get(reason, 0) + n


def event(name: str, n: int = 1) -> None:
    """Generic monotonic event counter (`layer_analysis`,
    `device_dispatch`, `write_behind_flush`...) — the signals the
    cold-vs-warm assertions in bench_cache / cache-smoke diff."""
    if n <= 0:
        return
    with _LOCK:
        _EVENTS[name] = _EVENTS.get(name, 0) + n


def request_tallies() -> dict[tuple[str, str], int]:
    """(tier, outcome) -> count since process start (monotonic)."""
    with _LOCK:
        return dict(_REQUESTS)


def eviction_tallies() -> dict[str, int]:
    """reason -> count since process start (monotonic)."""
    with _LOCK:
        return dict(_EVICTIONS)


def events() -> dict[str, int]:
    with _LOCK:
        return dict(_EVENTS)


def snapshot() -> dict:
    """JSON-shaped view for /debug/cache and flight captures."""
    with _LOCK:
        requests = [
            {"tier": t, "outcome": o, "count": c}
            for (t, o), c in sorted(_REQUESTS.items())
        ]
        evictions = [
            {"reason": r, "count": c} for r, c in sorted(_EVICTIONS.items())
        ]
        ev = dict(_EVENTS)
    hits = sum(r["count"] for r in requests if r["outcome"] == "hit")
    misses = sum(r["count"] for r in requests if r["outcome"] == "miss")
    total = hits + misses
    return {
        "requests": requests,
        "evictions": evictions,
        "events": ev,
        "hits": hits,
        "misses": misses,
        "hit_rate": (hits / total) if total else None,
    }


def clear() -> None:
    """Reset all tallies (tests/bench isolation)."""
    with _LOCK:
        _REQUESTS.clear()
        _EVICTIONS.clear()
        _EVENTS.clear()
