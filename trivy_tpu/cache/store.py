"""Content-addressed scan cache.

Mirrors pkg/fanal/cache/cache.go — the ArtifactCache (Put side) /
LocalArtifactCache (Get side) interface pair and the checkpoint/resume role the
cache plays in the reference (SURVEY §5): analysis results keyed by
sha256(content + analyzer versions), so unchanged blobs are never re-analyzed
(`MissingBlobs` diffing, pkg/fanal/artifact/image/image.go:113).

Backends: in-memory dict and a JSON-files-on-disk store (the BoltDB FS cache
analogue, pkg/fanal/cache/fs.go:17).  Both sides of the interface are one
class here — the split only matters at the RPC seam, where RemoteCache
implements the Put side over HTTP (trivy_tpu/rpc/).
"""

from __future__ import annotations

import json
import os
from typing import Iterable

from trivy_tpu.atypes import BLOB_JSON_SCHEMA_VERSION, ArtifactInfo, BlobInfo

SCHEMA_VERSION = 2  # cache.go schemaVersion


class BlobNotFoundError(KeyError):
    """Requested blob IDs are not in the cache (deterministic client error)."""


class ArtifactCache:
    """Interface: cache.ArtifactCache + cache.LocalArtifactCache."""

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        raise NotImplementedError

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        raise NotImplementedError

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        raise NotImplementedError

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        raise NotImplementedError

    def missing_blobs(
        self, artifact_id: str, blob_ids: Iterable[str]
    ) -> tuple[bool, list[str]]:
        """cache.MissingBlobs: (artifact missing?, missing blob ids)."""
        missing = [b for b in blob_ids if self.get_blob(b) is None]
        return self.get_artifact(artifact_id) is None, missing

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryCache(ArtifactCache):
    """cache.NewMemoryCache analogue; also the NopCache replacement for tests."""

    def __init__(self) -> None:
        self._artifacts: dict[str, ArtifactInfo] = {}
        self._blobs: dict[str, BlobInfo] = {}

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        self._artifacts[artifact_id] = info

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        self._blobs[blob_id] = info

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        return self._artifacts.get(artifact_id)

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        return self._blobs.get(blob_id)

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        for b in blob_ids:
            self._blobs.pop(b, None)

    def clear(self) -> None:
        self._artifacts.clear()
        self._blobs.clear()


def _safe_key(key: str) -> str:
    return key.replace("/", "_").replace(":", "_")


class FSCache(ArtifactCache):
    """JSON-on-disk content-addressed cache (the BoltDB fscache analogue)."""

    def __init__(self, cache_dir: str):
        self.root = os.path.join(cache_dir, "fanal")
        os.makedirs(os.path.join(self.root, "artifact"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "blob"), exist_ok=True)

    def _path(self, bucket: str, key: str) -> str:
        return os.path.join(self.root, bucket, _safe_key(key) + ".json")

    def _write(self, bucket: str, key: str, value: dict) -> None:
        path = self._path(bucket, key)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(value, f)
        os.replace(tmp, path)

    def _read(self, bucket: str, key: str) -> dict | None:
        try:
            with open(self._path(bucket, key), encoding="utf-8") as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        self._write("artifact", artifact_id, info.to_json())

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        self._write("blob", blob_id, info.to_json())

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        d = self._read("artifact", artifact_id)
        return ArtifactInfo.from_json(d) if d is not None else None

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        d = self._read("blob", blob_id)
        if d is None:
            return None
        info = BlobInfo.from_json(d)
        # Schema-version gating like cache.go: stale schema = cache miss.
        if info.schema_version != BLOB_JSON_SCHEMA_VERSION:
            return None
        return info

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        for b in blob_ids:
            try:
                os.remove(self._path("blob", b))
            except OSError:
                pass

    def clear(self) -> None:
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)
        os.makedirs(os.path.join(self.root, "artifact"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "blob"), exist_ok=True)
