"""Content-addressed scan cache.

Mirrors pkg/fanal/cache/cache.go — the ArtifactCache (Put side) /
LocalArtifactCache (Get side) interface pair and the checkpoint/resume role the
cache plays in the reference (SURVEY §5): analysis results keyed by
sha256(content + analyzer versions), so unchanged blobs are never re-analyzed
(`MissingBlobs` diffing, pkg/fanal/artifact/image/image.go:113).

Backends: in-memory dict and a JSON-files-on-disk store (the BoltDB FS cache
analogue, pkg/fanal/cache/fs.go:17).  Both sides of the interface are one
class here — the split only matters at the RPC seam, where RemoteCache
implements the Put side over HTTP (trivy_tpu/rpc/).
"""

from __future__ import annotations

import base64
import json
import os
import string
from typing import Iterable

from trivy_tpu.atypes import BLOB_JSON_SCHEMA_VERSION, ArtifactInfo, BlobInfo
from trivy_tpu.cache import stats as cache_stats

SCHEMA_VERSION = 2  # cache.go schemaVersion


class BlobNotFoundError(KeyError):
    """Requested blob IDs are not in the cache (deterministic client error)."""


class ArtifactCache:
    """Interface: cache.ArtifactCache + cache.LocalArtifactCache."""

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        raise NotImplementedError

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        raise NotImplementedError

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        raise NotImplementedError

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        raise NotImplementedError

    def exists(self, blob_id: str) -> bool:
        """Presence probe without decoding the entry.  The base form is
        a full get (always correct); backends override with a cheap
        existence check (`os.path.exists`, pipelined Redis `EXISTS`) —
        the MissingBlobs diff is O(layers) probes per image, and on warm
        fleets nearly every probe is a hit."""
        return self.get_blob(blob_id) is not None

    def missing_blobs(
        self, artifact_id: str, blob_ids: Iterable[str]
    ) -> tuple[bool, list[str]]:
        """cache.MissingBlobs: (artifact missing?, missing blob ids)."""
        missing = [b for b in blob_ids if not self.exists(b)]
        return self.get_artifact(artifact_id) is None, missing

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryCache(ArtifactCache):
    """cache.NewMemoryCache analogue; also the NopCache replacement for tests."""

    def __init__(self) -> None:
        self._artifacts: dict[str, ArtifactInfo] = {}
        self._blobs: dict[str, BlobInfo] = {}

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        self._artifacts[artifact_id] = info

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        self._blobs[blob_id] = info

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        return self._artifacts.get(artifact_id)

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        return self._blobs.get(blob_id)

    def exists(self, blob_id: str) -> bool:
        return blob_id in self._blobs

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        for b in blob_ids:
            self._blobs.pop(b, None)

    def clear(self) -> None:
        self._artifacts.clear()
        self._blobs.clear()


_HEX = set(string.hexdigits.lower())


def _safe_key(key: str) -> str:
    """Injective filename for a cache key.

    The dominant key shape is `sha256:<64 hex>` — keep the bare hex
    digest as the filename (readable, fixed-length).  Anything else gets
    unpadded urlsafe-base64 of the full key.  Both mappings are
    injective, so distinct keys can no longer collide on one file (the
    old replace('/','_').replace(':','_') folded `a/b` and `a:b` into
    the same entry, silently cross-contaminating results).
    """
    algo, sep, digest = key.partition(":")
    if sep and algo == "sha256" and len(digest) == 64 and set(digest) <= _HEX:
        return digest
    return base64.urlsafe_b64encode(key.encode("utf-8")).decode("ascii").rstrip("=")


def _legacy_safe_key(key: str) -> str:
    """Pre-collision-fix filename; kept for migration-free fallback reads
    of entries written by older processes."""
    return key.replace("/", "_").replace(":", "_")


class FSCache(ArtifactCache):
    """JSON-on-disk content-addressed cache (the BoltDB fscache analogue)."""

    def __init__(self, cache_dir: str):
        self.root = os.path.join(cache_dir, "fanal")
        os.makedirs(os.path.join(self.root, "artifact"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "blob"), exist_ok=True)

    def _path(self, bucket: str, key: str) -> str:
        return os.path.join(self.root, bucket, _safe_key(key) + ".json")

    def _legacy_path(self, bucket: str, key: str) -> str:
        return os.path.join(self.root, bucket, _legacy_safe_key(key) + ".json")

    def _write(self, bucket: str, key: str, value: dict) -> None:
        path = self._path(bucket, key)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(value, f)
        os.replace(tmp, path)

    def _evict(self, path: str, reason: str) -> None:
        """Self-heal: a corrupt/stale entry left on disk is a permanent
        re-miss (and, for stale schemas, a poisoned exists() probe) —
        delete on detection and account for it."""
        try:
            os.remove(path)
        except OSError:
            return
        cache_stats.record_eviction(reason)

    def _read(self, bucket: str, key: str) -> dict | None:
        path = self._path(bucket, key)
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except json.JSONDecodeError:
            self._evict(path, "corrupt")
            return None
        except OSError:
            pass
        # Migration-free fallback: entries written before the injective
        # _safe_key fix live under the old flattened name.
        legacy = self._legacy_path(bucket, key)
        if legacy == path:
            return None
        try:
            with open(legacy, encoding="utf-8") as f:
                return json.load(f)
        except json.JSONDecodeError:
            self._evict(legacy, "corrupt")
            return None
        except OSError:
            return None

    def put_artifact(self, artifact_id: str, info: ArtifactInfo) -> None:
        self._write("artifact", artifact_id, info.to_json())

    def put_blob(self, blob_id: str, info: BlobInfo) -> None:
        self._write("blob", blob_id, info.to_json())

    def get_artifact(self, artifact_id: str) -> ArtifactInfo | None:
        d = self._read("artifact", artifact_id)
        return ArtifactInfo.from_json(d) if d is not None else None

    def get_blob(self, blob_id: str) -> BlobInfo | None:
        d = self._read("blob", blob_id)
        if d is None:
            return None
        info = BlobInfo.from_json(d)
        # Schema-version gating like cache.go: stale schema = cache miss,
        # and the dead file is reaped so exists() stops vouching for it.
        if info.schema_version != BLOB_JSON_SCHEMA_VERSION:
            for path in (self._path("blob", blob_id),
                         self._legacy_path("blob", blob_id)):
                if os.path.exists(path):
                    self._evict(path, "stale-schema")
                    break
            return None
        return info

    def exists(self, blob_id: str) -> bool:
        """O(1) presence probe: stat instead of a full JSON read.  A
        corrupt or stale-schema file can answer True until its first
        get_blob self-heals it off disk — the same window the reference
        BoltDB cache has."""
        return os.path.exists(self._path("blob", blob_id)) or os.path.exists(
            self._legacy_path("blob", blob_id)
        )

    def delete_blobs(self, blob_ids: Iterable[str]) -> None:
        for b in blob_ids:
            for path in (self._path("blob", b), self._legacy_path("blob", b)):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def clear(self) -> None:
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)
        os.makedirs(os.path.join(self.root, "artifact"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "blob"), exist_ok=True)
