"""Per-method SLOs tracked as multi-window burn rates (SRE-workbook style).

A single latency histogram answers "how bad is the tail right now"; an SLO
answers "are we spending our error budget faster than we can afford".  This
module layers declarative objectives over the request stream the server
already observes into `trivy_tpu_request_seconds`:

  * An `Objective` per RPC method: a latency threshold + target fraction
    (e.g. 99% of requests under 1s) and an error target (e.g. 99.9% of
    requests not 5xx/408).  Defaults apply to every method; a YAML file
    (`--slo-config`) overrides per method.
  * Burn rates over three windows (5m/1h/6h): burn = bad_fraction /
    (1 - target).  Burn 1.0 means "spending budget exactly as provisioned";
    14.4 over 5m is the classic page-now threshold.  Multi-window reporting
    distinguishes a blip (5m hot, 6h calm) from a slow leak (all hot).
  * Budget remaining over the longest window: 1 - burn_6h (can go
    negative — the operator should know *how far* over budget they are).

Request outcomes land in a ring of fixed 10s time slots per method (max
6h/10s = 2160 slots), so window sums are O(slots) at scrape time and O(1)
at observe time.  The latency threshold is snapped DOWN to the nearest
`LATENCY_BUCKETS` bound so every burn number is exactly derivable from the
exported `request_seconds` histogram — the SLO layer never claims precision
the histogram cannot back.

Classification: 5xx and 408 (deadline expired server-side) burn the error
budget; 429 does NOT — a QoS rejection is the server protecting itself,
not failing the tenant — but it still triggers flight-recorder capture
(see obs/flight.py) because the tenant experienced it as a failure.

All clock inputs are injectable (`now=`) so tests are deterministic.
"""

from __future__ import annotations

from bisect import bisect_right
from collections import deque
from dataclasses import dataclass
from time import monotonic

from trivy_tpu import lockcheck
from trivy_tpu.obs import metrics as obs_metrics

# (label, seconds) — ordered short to long; the last window funds the
# budget-remaining number.
WINDOWS = (("5m", 300.0), ("1h", 3600.0), ("6h", 21600.0))
SLOT_SECONDS = 10.0


def snap_threshold(
    threshold_s: float,
    buckets: tuple[float, ...] = obs_metrics.LATENCY_BUCKETS,
) -> float:
    """Largest histogram bucket bound <= threshold (or the smallest bound
    if the threshold sits below all of them), so "slow" is exactly the
    histogram's count above that bound."""
    i = bisect_right(buckets, float(threshold_s))
    return buckets[i - 1] if i > 0 else buckets[0]


@dataclass(frozen=True)
class Objective:
    """One method's SLO: latency_target of requests under
    latency_threshold_s, error_target of requests not an error."""

    latency_threshold_s: float = 1.0
    latency_target: float = 0.99
    error_target: float = 0.999

    def validate(self) -> None:
        if self.latency_threshold_s <= 0:
            raise ValueError(
                f"latency_threshold_s must be > 0, got {self.latency_threshold_s}"
            )
        for name in ("latency_target", "error_target"):
            v = getattr(self, name)
            if not 0.0 < v < 1.0:
                raise ValueError(f"{name} must be in (0, 1), got {v}")


def load_slo_config(path: str) -> tuple[Objective, dict[str, Objective]]:
    """Parse an --slo-config YAML file:

        default:
          latency_threshold_s: 1.0
          latency_target: 0.99
          error_target: 0.999
        methods:
          scan_secrets: {latency_threshold_s: 0.25}

    Method entries inherit unset fields from `default`, which itself
    inherits from the built-in Objective defaults."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: SLO config must be a mapping")

    def build(raw: object, base: Objective) -> Objective:
        if raw is None:
            raw = {}
        if not isinstance(raw, dict):
            raise ValueError(f"{path}: objective entries must be mappings")
        obj = Objective(
            latency_threshold_s=float(
                raw.get("latency_threshold_s", base.latency_threshold_s)
            ),
            latency_target=float(
                raw.get("latency_target", base.latency_target)
            ),
            error_target=float(raw.get("error_target", base.error_target)),
        )
        obj.validate()
        return obj

    default = build(doc.get("default"), Objective())
    methods = {
        str(m): build(raw, default)
        for m, raw in (doc.get("methods") or {}).items()
    }
    return default, methods


class _Slot:
    """One SLOT_SECONDS bucket of request outcomes for one method."""

    __slots__ = ("t0", "total", "slow", "errors")

    def __init__(self, t0: float):
        self.t0 = t0
        self.total = 0
        self.slow = 0
        self.errors = 0


class SloTracker:
    """Classifies every (method, code, elapsed) observation against its
    objective, keeps the per-window slot rings, and exposes the
    trivy_tpu_slo_* families plus the /debug/slo report."""

    def __init__(
        self,
        registry: obs_metrics.Registry,
        default: Objective | None = None,
        per_method: dict[str, Objective] | None = None,
        slot_s: float = SLOT_SECONDS,
        now=monotonic,
    ):
        self._now = now
        self._slot_s = float(slot_s)
        self._max_window = max(w for _, w in WINDOWS)
        self._default = self._snap(default or Objective())
        self._per_method = {
            m: self._snap(o) for m, o in (per_method or {}).items()
        }
        self._lock = lockcheck.make_lock("obs.slo")
        self._methods: dict[str, deque[_Slot]] = {}  # owner: _lock

        self._m_burn = registry.gauge(
            "trivy_tpu_slo_burn_rate",
            "error-budget burn rate (1.0 = spending exactly as provisioned)",
            ("method", "slo", "window"),
        )
        self._m_budget = registry.gauge(
            "trivy_tpu_slo_budget_remaining",
            "fraction of the error budget left over the longest window "
            "(negative = over budget)",
            ("method", "slo"),
        )
        self._m_breaches = registry.counter(
            "trivy_tpu_slo_breaches_total",
            "individual requests that breached an objective",
            ("method", "slo"),
        )
        self._m_threshold = registry.gauge(
            "trivy_tpu_slo_latency_threshold_seconds",
            "latency objective threshold (snapped to a histogram bound)",
            ("method",),
        )
        registry.add_collect_hook(self._collect)

    @staticmethod
    def _snap(obj: Objective) -> Objective:
        obj.validate()
        return Objective(
            latency_threshold_s=snap_threshold(obj.latency_threshold_s),
            latency_target=obj.latency_target,
            error_target=obj.error_target,
        )

    def objective(self, method: str) -> Objective:
        return self._per_method.get(method, self._default)

    # -- observe (request threads) ----------------------------------------

    def observe(
        self, method: str, code: int, elapsed_s: float
    ) -> tuple[str, ...]:
        """Record one request outcome.  Returns the objectives it breached
        (() / ("latency",) / ("error",) / ("latency", "error")) so the
        caller can decide whether to promote the request into the flight
        ring.  429 never appears here — see the module docstring."""
        obj = self.objective(method)
        slow = elapsed_s > obj.latency_threshold_s
        err = code == 408 or code >= 500
        now = self._now()
        t0 = now - (now % self._slot_s)
        with self._lock:
            slots = self._methods.setdefault(method, deque())
            if not slots or slots[-1].t0 != t0:
                slots.append(_Slot(t0))
                horizon = now - self._max_window - self._slot_s
                while slots and slots[0].t0 < horizon:
                    slots.popleft()
            slot = slots[-1]
            slot.total += 1
            if slow:
                slot.slow += 1
            if err:
                slot.errors += 1
        breached = []
        if slow:
            breached.append("latency")
            self._m_breaches.labels(method=method, slo="latency").inc()
        if err:
            breached.append("error")
            self._m_breaches.labels(method=method, slo="error").inc()
        return tuple(breached)

    # -- report (scrape / debug endpoint) ----------------------------------

    def _window_sums(
        self, slots: list[_Slot], now: float
    ) -> dict[str, tuple[int, int, int]]:
        out = {}
        for label, width in WINDOWS:
            total = slow = errors = 0
            for s in slots:
                # A slot counts toward a window while any part of it
                # overlaps [now - width, now].
                if s.t0 + self._slot_s >= now - width:
                    total += s.total
                    slow += s.slow
                    errors += s.errors
            out[label] = (total, slow, errors)
        return out

    @staticmethod
    def _burn(bad: int, total: int, target: float) -> float:
        if total <= 0:
            return 0.0
        return (bad / total) / max(1.0 - target, 1e-9)

    def report(self) -> dict:
        """The /debug/slo payload: per method, the (snapped) objective,
        window sums, burn per window, and budget remaining over the
        longest window."""
        now = self._now()
        with self._lock:
            snap = {m: list(slots) for m, slots in self._methods.items()}
        budget_label = WINDOWS[-1][0]
        methods = {}
        for m, slots in sorted(snap.items()):
            obj = self.objective(m)
            sums = self._window_sums(slots, now)
            windows = {}
            for label, _ in WINDOWS:
                total, slow, errors = sums[label]
                windows[label] = {
                    "total": total,
                    "slow": slow,
                    "errors": errors,
                    "latency_burn": round(
                        self._burn(slow, total, obj.latency_target), 4
                    ),
                    "error_burn": round(
                        self._burn(errors, total, obj.error_target), 4
                    ),
                }
            long = windows[budget_label]
            methods[m] = {
                "objective": {
                    "latency_threshold_s": obj.latency_threshold_s,
                    "latency_target": obj.latency_target,
                    "error_target": obj.error_target,
                },
                "windows": windows,
                "latency_budget_remaining": round(
                    1.0 - long["latency_burn"], 4
                ),
                "error_budget_remaining": round(1.0 - long["error_burn"], 4),
            }
        return {
            "slot_seconds": self._slot_s,
            "windows": {label: width for label, width in WINDOWS},
            "budget_window": budget_label,
            "methods": methods,
        }

    def _collect(self) -> None:
        """Scrape-time mirror of report() into the gauge families.  Must
        never raise and never do work a scrape shouldn't trigger — it only
        sums slots already recorded."""
        now = self._now()
        with self._lock:
            snap = {m: list(slots) for m, slots in self._methods.items()}
        budget_label = WINDOWS[-1][0]
        for m, slots in snap.items():
            obj = self.objective(m)
            sums = self._window_sums(slots, now)
            self._m_threshold.labels(method=m).set(obj.latency_threshold_s)
            for label, _ in WINDOWS:
                total, slow, errors = sums[label]
                self._m_burn.labels(method=m, slo="latency", window=label).set(
                    self._burn(slow, total, obj.latency_target)
                )
                self._m_burn.labels(method=m, slo="error", window=label).set(
                    self._burn(errors, total, obj.error_target)
                )
            total, slow, errors = sums[budget_label]
            self._m_budget.labels(method=m, slo="latency").set(
                1.0 - self._burn(slow, total, obj.latency_target)
            )
            self._m_budget.labels(method=m, slo="error").set(
                1.0 - self._burn(errors, total, obj.error_target)
            )
