"""Flight recorder: cheap always-on tracing, breach-promoted incidents.

The trace ring (obs/trace.py) already records every span at deque-append
cost, but it is a *global* ring: by the time an operator asks "why was
that scan slow", the interesting spans have been pushed out by ten
thousand boring ones.  The flight recorder closes that gap the Dapper
way — keep tracing cheap and unconditional, and at the moment a request
*breaches* (latency over its SLO threshold, 408/5xx, a QoS 429, or a
deadline expiry inside the scheduler) promote everything we know about it
into a small bounded incident ring:

  * the request's full span tree, filtered out of the trace ring by
    trace id (queue wait, batch execution, engine phases — whatever the
    request touched);
  * a scheduler snapshot taken at breach time: lane depths, resident
    pool contents, QoS bucket levels — the context that explains *why*
    the request waited;
  * a device-memory snapshot (obs/memwatch.py) so an `hbm-pressure`
    incident names who held the bytes when the watermark tripped.

Incidents are served newest-first by `GET /debug/flight?limit=N` and,
when `--flight-out` is set, appended to a JSONL file as they are captured
so they survive the process.  The file is size-capped
(`--flight-out-max-mb`, default 64): when the active file would exceed
the cap it rotates to `<path>.1` (one backup generation), and records
lost with the overwritten backup count into
`trivy_tpu_flight_dropped_total` — a long-running server cannot fill the
disk with incidents.

Capture runs on request/handler threads and must never raise: an
observability feature that can turn a breach into an outage is worse
than no feature.  The snapshot callback, the gate callback, the memory
callback, and the file append are each individually guarded.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Callable

from trivy_tpu import lockcheck
from trivy_tpu.obs import trace as obs_trace

DEFAULT_CAPACITY = 64
DEFAULT_OUT_MAX_MB = 64.0


class FlightRecorder:
    """Bounded incident ring.  `snapshot_fn` is injected (the server
    passes BatchScheduler.snapshot) so this module needs no dependency on
    trivy_tpu.serve; `gate_fn` likewise (the server passes a
    gatelog.records thunk) so a capture embeds the hybrid-gate decisions
    that routed the breached request."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        snapshot_fn: Callable[[], dict] | None = None,
        out_path: str = "",
        out_max_mb: float = DEFAULT_OUT_MAX_MB,
        gate_fn: Callable[[], list] | None = None,
        registry=None,
        memory_fn: Callable[[], dict] | None = None,
        cache_fn: Callable[[], dict] | None = None,
        fleet_fn: Callable[[], dict] | None = None,
    ):
        self._lock = lockcheck.make_lock("obs.flight")
        self._ring: deque[dict] = deque(maxlen=max(1, int(capacity)))  # owner: _lock
        self._seq = 0  # owner: _lock
        self._snapshot_fn = snapshot_fn
        self._gate_fn = gate_fn
        self._memory_fn = memory_fn
        self._cache_fn = cache_fn
        self._fleet_fn = fleet_fn
        self.out_path = out_path
        # 0 disables the cap; the bookkeeping below is all owner: _lock.
        self.out_max_bytes = int(max(0.0, out_max_mb) * (1 << 20))
        self._out_bytes = 0
        self._out_records = 0  # records this process wrote to the active file
        self._backup_records = 0  # records this process rotated into .1
        self.dropped = 0  # records lost to rotation (this process's writes)
        if out_path:
            try:
                self._out_bytes = os.path.getsize(out_path)
            except OSError:
                self._out_bytes = 0
        self._m_captured = None
        self._m_dropped = None
        if registry is not None:
            self._m_captured = registry.counter(
                "trivy_tpu_flight_records_total",
                "breach incidents captured into the flight ring",
                ("reason",),
            )
            self._m_dropped = registry.counter(
                "trivy_tpu_flight_dropped_total",
                "flight-out JSONL records lost to size-capped rotation",
            )

    @property
    def captured(self) -> int:
        with self._lock:
            return self._seq

    # -- capture (request / owner threads) ---------------------------------

    def _span_tree(self, trace_id: str) -> list[dict]:
        if not trace_id:
            return []
        spans = [s for s in obs_trace.snapshot() if s.trace_id == trace_id]
        spans.sort(key=lambda s: (s.start, s.span_id))
        t0 = spans[0].start if spans else 0.0
        return [
            {
                "name": s.name,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "start_ms": round((s.start - t0) * 1e3, 3),
                "dur_ms": round(s.dur * 1e3, 3),
                "tid": s.tid,
                "attrs": dict(s.attrs),
            }
            for s in spans
        ]

    def _scheduler_state(self) -> dict:
        if self._snapshot_fn is None:
            return {}
        try:
            return self._snapshot_fn()
        except Exception as e:
            # Breach context is best-effort; the record (with spans) still
            # lands even when the scheduler is mid-teardown.
            return {"error": f"{type(e).__name__}: {e}"}

    def _gate_state(self) -> list:
        if self._gate_fn is None:
            return []
        try:
            return list(self._gate_fn())
        except Exception as e:
            return [{"error": f"{type(e).__name__}: {e}"}]

    def _memory_state(self) -> dict:
        if self._memory_fn is None:
            return {}
        try:
            return dict(self._memory_fn())
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _cache_state(self) -> dict:
        if self._cache_fn is None:
            return {}
        try:
            return dict(self._cache_fn())
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _fleet_state(self) -> dict:
        if self._fleet_fn is None:
            return {}
        try:
            return dict(self._fleet_fn())
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def capture(
        self,
        *,
        trace_id: str = "",
        method: str = "",
        tenant: str = "",
        code: int = 0,
        elapsed_s: float = 0.0,
        reason: str = "",
    ) -> dict:
        """Promote one breached request into the incident ring and return
        the record (callers may enrich their logs with it)."""
        rec = {
            "seq": 0,
            "captured_at": time.time(),
            "reason": reason,
            "method": method,
            "tenant": tenant,
            "trace_id": trace_id,
            "code": int(code),
            "elapsed_s": round(float(elapsed_s), 6),
            "spans": self._span_tree(trace_id),
            "scheduler": self._scheduler_state(),
            "gate": self._gate_state(),
            "memory": self._memory_state(),
            "cache": self._cache_state(),
        }
        fleet = self._fleet_state()
        if fleet:
            # Fleeted hosts only: which member this was and its affinity
            # posture at breach time (omitted entirely when unfleeted,
            # keeping existing record shapes byte-stable).
            rec["fleet"] = fleet
        dropped = 0
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            if self.out_path:
                try:
                    line = json.dumps(rec, default=str) + "\n"
                    if (
                        self.out_max_bytes
                        and self._out_bytes
                        and self._out_bytes + len(line) > self.out_max_bytes
                    ):
                        # One backup generation: the active file demotes to
                        # .1 (still on disk), whatever .1 held is gone —
                        # that loss is what the dropped counter measures.
                        os.replace(self.out_path, self.out_path + ".1")
                        dropped = self._backup_records
                        self.dropped += dropped
                        self._backup_records = self._out_records
                        self._out_records = 0
                        self._out_bytes = 0
                    with open(self.out_path, "a") as f:
                        f.write(line)
                    self._out_bytes += len(line)
                    self._out_records += 1
                except OSError:
                    pass
        if self._m_captured is not None:
            self._m_captured.labels(reason=reason or "unknown").inc()
        if dropped and self._m_dropped is not None:
            self._m_dropped.inc(dropped)
        return rec

    # -- read side (debug endpoint, tests) ---------------------------------

    def records(self, limit: int | None = None) -> list[dict]:
        """Newest-first incident list, optionally truncated to `limit`."""
        with self._lock:
            items = list(self._ring)
        items.reverse()
        if limit is not None:
            items = items[: max(0, int(limit))]
        return items
