"""Ring-buffer span collector with Chrome-trace export.

Spans are wall-clock intervals named after pipeline stages ("rpc.scan_secrets",
"queue.wait", "batch", "chunk.h2d", "confirm", ...), linked into trees by a
contextvar carrying (trace_id, span_id): a span opened inside another on the
same thread becomes its child, and a trace_id minted on a scanning client
(rpc/client.py RemoteSecretEngine) rides the `X-Trivy-Trace-Id` header so
server-side spans join the same tree.

Granularity discipline: spans mark per-request / per-batch / per-chunk work,
never per-file or per-row — the collector is a deque append under a lock, but
nothing is free at row rates.  When tracing is disabled (the default),
`span()` returns a shared no-op context manager after one predicate, so
instrumented hot paths stay within noise (bench.py BENCH_OBS pins this at
<2% on the smoke corpus).

Export is the Chrome trace-event format (`"X"` complete events, microsecond
timestamps), which chrome://tracing and ui.perfetto.dev load directly; spans
record `time.perf_counter()` and export anchors them to the wall clock via a
process-start epoch so they align with the JAX profiler's device timeline
when both land in one --profile-dir.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field

from trivy_tpu import lockcheck

# perf_counter -> wall-clock anchor, fixed at import so every span in the
# process (and its chrome export) shares one timebase.
_EPOCH_S = time.time() - time.perf_counter()

DEFAULT_RING = 8192

_lock = lockcheck.make_lock("obs.trace.ring")
_ring: deque = deque(maxlen=DEFAULT_RING)  # owner: _lock
_enabled = os.environ.get("TRIVY_TPU_TRACE", "") not in ("", "0", "false", "off")
_next_id = 0  # owner: _lock

# (trace_id, span_id) of the innermost open span on this thread/context.
_ctx: contextvars.ContextVar[tuple[str, int] | None] = contextvars.ContextVar(
    "trivy_tpu_trace", default=None
)


@dataclass
class SpanRecord:
    """One closed span: [start, start+dur) in perf_counter seconds."""

    name: str
    trace_id: str
    span_id: int
    parent_id: int
    start: float
    dur: float
    tid: int
    attrs: dict = field(default_factory=dict)


def enabled() -> bool:
    return _enabled


def enable(ring: int | None = None) -> None:
    """Turn span collection on (idempotent); `ring` bounds retained spans."""
    global _enabled, _ring
    with _lock:
        if ring is not None and ring != _ring.maxlen:
            _ring = deque(_ring, maxlen=max(1, ring))
        _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def clear() -> None:
    with _lock:
        _ring.clear()


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace_id() -> str:
    """trace_id of the innermost open span on this thread ("" when none) —
    the correlation key JSON logging and the RPC client header read."""
    cur = _ctx.get()
    return cur[0] if cur else ""


def _alloc_id() -> int:  # graftlint: holds(_lock)
    global _next_id
    _next_id += 1
    return _next_id


class _NoopSpan:
    """Shared do-nothing span: the disabled path's entire cost."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "trace_id", "attrs", "span_id", "parent_id", "_tok", "_t0")

    def __init__(self, name: str, trace_id: str | None, attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.attrs = attrs

    def __enter__(self):
        parent = _ctx.get()
        if not self.trace_id:
            self.trace_id = parent[0] if parent else new_trace_id()
        self.parent_id = parent[1] if parent else 0
        with _lock:
            self.span_id = _alloc_id()
        self._tok = _ctx.set((self.trace_id, self.span_id))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        _ctx.reset(self._tok)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        with _lock:
            _ring.append(
                SpanRecord(
                    self.name, self.trace_id, self.span_id, self.parent_id,
                    self._t0, dur, threading.get_ident(), self.attrs,
                )
            )
        return False

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)


def span(name: str, trace_id: str | None = None, **attrs):
    """Context manager timing one pipeline stage.  `trace_id` pins the
    span to a specific trace (the RPC boundary); otherwise it inherits the
    enclosing span's, minting a fresh one at tree roots."""
    if not _enabled:
        return _NOOP
    return _Span(name, trace_id, attrs)


def add_span(
    name: str,
    start: float,
    dur: float,
    trace_id: str = "",
    parent_id: int = 0,
    **attrs,
) -> None:
    """Record an interval measured after the fact (queue wait: the
    scheduler only learns a ticket's wait at dispatch).  `start` is in
    perf_counter seconds (derive past instants as perf_counter() - age)."""
    if not _enabled:
        return
    with _lock:
        _ring.append(
            SpanRecord(
                name, trace_id or new_trace_id(), _alloc_id(), parent_id,
                start, max(0.0, dur), threading.get_ident(), attrs,
            )
        )


def adopt(trace_id: str):
    """Context manager adopting `trace_id` as the ambient trace without
    opening a timed span (the scheduler's owner thread stamps a batch's
    lead trace onto engine spans this way)."""
    return _Adopt(trace_id)


class _Adopt:
    __slots__ = ("trace_id", "_tok")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id

    def __enter__(self):
        cur = _ctx.get()
        self._tok = _ctx.set((self.trace_id, cur[1] if cur else 0))
        return self

    def __exit__(self, *exc):
        _ctx.reset(self._tok)
        return False


def snapshot() -> list[SpanRecord]:
    with _lock:
        return list(_ring)


def to_chrome(spans: list[SpanRecord] | None = None) -> dict:
    """Chrome trace-event JSON (the format chrome://tracing and Perfetto
    load): one "X" complete event per span, µs timestamps on the wall
    clock, thread id preserved, span linkage in args."""
    if spans is None:
        spans = snapshot()
    pid = os.getpid()
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": "trivy-tpu host"},
        }
    ]
    for s in spans:
        args = {
            "trace_id": s.trace_id,
            "span_id": s.span_id,
            "parent_id": s.parent_id,
        }
        args.update(s.attrs)
        events.append(
            {
                "name": s.name,
                "ph": "X",
                "ts": (_EPOCH_S + s.start) * 1e6,
                "dur": s.dur * 1e6,
                "pid": pid,
                "tid": s.tid,
                "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump(path: str, spans: list[SpanRecord] | None = None) -> str:
    """Write the chrome-trace JSON to `path` (creating parent dirs);
    returns the path written."""
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_chrome(spans), f)
    return path


def dump_into_profile_dir(profile_dir: str) -> str | None:
    """Host spans into a JAX --profile-dir so Perfetto shows host stages
    against the device timeline; no-op (None) when tracing is off or the
    ring is empty."""
    if not _enabled:
        return None
    spans = snapshot()
    if not spans:
        return None
    return dump(
        os.path.join(profile_dir, f"host_trace.{os.getpid()}.json"), spans
    )
