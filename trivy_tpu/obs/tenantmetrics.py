"""Per-tenant/per-digest metric dimensions behind a cardinality governor.

Label values drawn from the wire (client IDs, ruleset digests) are
unbounded: one misbehaving client minting a fresh ClientID per request
would otherwise grow a metric series per request until the scrape — and
the process — falls over.  The governor bounds that: the top-K keys by
request volume get their own series, everything else collapses into the
`_other` rollup, and K is an operator knob (`--max-tenant-series`).

Mechanics:

  * Admission is immediate while fewer than K keys are resident (the
    first K distinct keys each get a series — no warmup cliff).
  * Every `cadence` observations the governor re-ranks all keys by
    (-count, key) and installs the new top-K.  The sort key makes
    promotion/demotion a pure function of the observation sequence —
    deterministic, testable, no timestamps.
  * Demotion *folds* the key's existing series into `_other` via
    `_Family.fold_label`, so totals are conserved (sum over tenants +
    `_other` always equals the untenanted family) and the scrape shrinks
    instead of accumulating dead series.
  * Counts halve at each rebalance (so an ancient burst cannot pin
    residency forever) and keys that decay to zero are dropped (so the
    counts table is bounded too, not just the label space).

A key named literally `_other` aliases into the rollup; conservation is
unaffected, the tenant just cannot be distinguished from the tail.

graftlint GL007 enforces the contract repo-wide: a `.labels()` value for
an unbounded label name must be a literal or routed through
`resolve()`/`lookup()` here.
"""

from __future__ import annotations

from typing import Callable

from trivy_tpu import lockcheck
from trivy_tpu.obs import metrics as obs_metrics

OTHER = "_other"
# Rebalance cadence: cheap enough to amortize (one O(n log n) sort per
# 256 events over a table bounded by decay), frequent enough that a
# traffic shift re-ranks within seconds under load.
REBALANCE_CADENCE = 256


class CardinalityGovernor:
    """Top-K label admission.  `resolve` counts the key's volume and
    returns the label value to use (the key itself while resident, else
    OTHER); `lookup` returns the same mapping without counting — use it
    for follow-up observations of an already-admitted request so one
    request is one unit of volume no matter how many families it lands
    in."""

    def __init__(
        self,
        max_series: int = 16,
        cadence: int = REBALANCE_CADENCE,
        on_demote: Callable[[str], None] | None = None,
        name: str = "obs.tenant.governor",
    ):
        self.max_series = max(0, int(max_series))
        self.cadence = max(1, int(cadence))
        self.on_demote = on_demote
        self._lock = lockcheck.make_lock(name)
        self._counts: dict[str, int] = {}  # owner: _lock
        self._resident: dict[str, bool] = {}  # owner: _lock
        self._seen = 0  # owner: _lock

    def resolve(self, key: str) -> str:
        key = str(key)
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._seen += 1
            if (
                key not in self._resident
                and len(self._resident) < self.max_series
            ):
                self._resident[key] = True
            if self._seen % self.cadence == 0:
                self._rebalance()
            return key if key in self._resident else OTHER

    def lookup(self, key: str) -> str:
        with self._lock:
            return str(key) if str(key) in self._resident else OTHER

    def resident(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._resident))

    def _rebalance(self) -> None:  # graftlint: holds(_lock)
        ranked = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        new = {k: True for k, _ in ranked[: self.max_series]}
        demoted = [k for k in self._resident if k not in new]
        self._resident = new
        self._counts = {
            k: v // 2
            for k, v in self._counts.items()
            if v // 2 > 0 or k in new
        }
        for k in demoted:
            if self.on_demote is not None:
                self.on_demote(k)


class TenantMetrics:
    """The tenant/digest-labelled families the scheduler feeds, each
    behind its own governor.  Tenant keys are client IDs ("" = anonymous,
    kept as its own key: the anonymous crowd is usually the biggest
    tenant and the operator should see it).  Digest "" (the builtin
    ruleset lane) maps to "default"."""

    def __init__(
        self,
        registry: obs_metrics.Registry,
        max_tenant_series: int = 16,
        max_digest_series: int | None = None,
        cadence: int = REBALANCE_CADENCE,
    ):
        self._m_requests = registry.counter(
            "trivy_tpu_tenant_requests_total",
            "admitted tickets by tenant and ruleset digest "
            '(long tail rolls up into tenant="_other")',
            ("tenant", "digest"),
        )
        self._m_rejected = registry.counter(
            "trivy_tpu_tenant_rejected_total",
            "admission rejections by tenant and reason",
            ("tenant", "reason"),
        )
        self._m_wait = registry.histogram(
            "trivy_tpu_tenant_ticket_wait_seconds",
            "queue wait (submit to dispatch) by tenant",
            ("tenant",),
            buckets=obs_metrics.LATENCY_BUCKETS,
        )
        self._m_phase = registry.histogram(
            "trivy_tpu_tenant_batch_phase_seconds",
            "per-dispatch engine phase time by ruleset digest",
            ("digest", "phase"),
            buckets=obs_metrics.LATENCY_BUCKETS,
        )
        self.tenants = CardinalityGovernor(
            max_series=max_tenant_series,
            cadence=cadence,
            on_demote=self._demote_tenant,
            name="obs.tenant.governor",
        )
        # Digests are additionally bounded upstream by pool residency, but
        # UnknownRulesetError paths still see arbitrary wire digests.
        self.digests = CardinalityGovernor(
            max_series=(
                max(4, max_tenant_series)
                if max_digest_series is None
                else max_digest_series
            ),
            cadence=cadence,
            on_demote=self._demote_digest,
            name="obs.digest.governor",
        )

    @staticmethod
    def _digest_key(digest: str) -> str:
        return digest or "default"

    def _demote_tenant(self, key: str) -> None:
        self._m_requests.fold_label("tenant", key, OTHER)
        self._m_rejected.fold_label("tenant", key, OTHER)
        self._m_wait.fold_label("tenant", key, OTHER)

    def _demote_digest(self, key: str) -> None:
        self._m_requests.fold_label("digest", key, OTHER)
        self._m_phase.fold_label("digest", key, OTHER)

    # -- event seats (scheduler paths) ------------------------------------

    def admit(self, tenant: str, digest: str) -> None:
        """One admitted ticket: the volume signal both governors rank by."""
        self._m_requests.labels(
            tenant=self.tenants.resolve(tenant),
            digest=self.digests.resolve(self._digest_key(digest)),
        ).inc()

    def reject(self, tenant: str, reason: str) -> None:
        self._m_rejected.labels(
            tenant=self.tenants.resolve(tenant), reason=reason
        ).inc()

    def wait(self, tenant: str, seconds: float) -> None:
        self._m_wait.labels(tenant=self.tenants.lookup(tenant)).observe(
            seconds
        )

    def phase(self, digest: str, phase: str, seconds: float) -> None:
        self._m_phase.labels(
            digest=self.digests.lookup(self._digest_key(digest)), phase=phase
        ).observe(seconds)
