"""Process-global device-memory ledger: raw HBM truth + attributed truth.

Every top ROADMAP item (device-resident hit rows, mesh sharding, resident
ruleset/content caches) turns on one question the observatory could not
answer before this module: *what is in HBM right now, who put it there,
and how close are we to the edge?*  Two complementary truths:

  raw         per-device usage/peak/limit sampled from JAX's
              ``device.memory_stats()``.  Guarded — CPU backends have no
              allocator stats, so the ledger keeps working from
              registrations alone and ``pressure()`` reports its source.
  attributed  a registration ledger: every long-lived device allocation
              (resident ruleset slots, `ResidentChunkCache` entries,
              pipeline staging buffers, verify-stream tensor sets,
              compiled-ruleset NFA tensors) calls
              ``track(component, nbytes)`` and holds the returned handle;
              ``release()``/GC of the owner removes the bytes.  The
              per-device, per-component sums are exact by construction —
              `/debug/memory` reports both sides and their residual.

The ledger is process-global for the same reason the device-phase sample
queue is (obs/metrics.py): allocations happen in engine code that owns no
registry, while exposition is per-server.  Servers bridge the two with
``register_collectors(registry)``, which exports

  trivy_tpu_device_hbm_bytes{device,component}   attributed bytes (plus a
                                                 ``_unattributed`` series
                                                 for the raw residual)
  trivy_tpu_device_hbm_peak_bytes{device}        raw peak when the backend
                                                 reports one, else the
                                                 attributed high-water mark
  trivy_tpu_hbm_pressure                         used/limit fraction the
                                                 admission watermarks act on

Tracking is off by default and costs one predicate + a shared no-op
handle when off — the same pattern as ``device_phase`` — so the BENCH_OBS
<2% disabled-path overhead gate holds with memwatch compiled in.  Servers
call ``enable()``; ``TRIVY_TPU_MEMWATCH=1`` forces it on for ad-hoc runs.

Thread-safety: one leaf lock guards the ledger; the stats provider is
always called *outside* it (a test provider may legitimately read the
ledger back).  ``ruleset_digest(digest)`` is a contextvar scope: track()
calls inside it inherit the digest tag, which is how the resident pool
reconciles its manifest byte *estimates* against measured engine
allocations without threading a digest through every engine layer.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import weakref
from typing import Callable

from trivy_tpu import lockcheck

_LOCK = lockcheck.make_lock("obs.memwatch")
_enabled = os.environ.get("TRIVY_TPU_MEMWATCH", "") == "1"
_seq = 0  # owner: _LOCK
_allocs: dict[int, "_Allocation"] = {}  # owner: _LOCK
# Attributed high-water mark per device, maintained incrementally so the
# peak survives releases.  owner: _LOCK
_attr_peak: dict[str, int] = {}
# Injected in tests/bench to fake a TPU allocator; None = the real
# jax.devices() sampler below.
_stats_provider: Callable[[], dict] | None = None
# When the backend reports no bytes_limit (CPU), pressure() can still run
# in attributed mode against this explicit budget (0 = no budget known).
_attr_limit = 0
_default_device: str | None = None  # lazily resolved, cached


class _NoopHandle:
    """Shared do-nothing handle returned while tracking is off (the
    `_NOOP_PHASE` pattern: one predicate, zero allocation, on the hot
    path)."""

    __slots__ = ()
    nbytes = 0
    component = ""
    device = ""
    digest = ""

    def resize(self, nbytes: int) -> None:
        pass

    def release(self) -> None:
        pass


NOOP_HANDLE = _NoopHandle()

_digest_ctx: contextvars.ContextVar[str] = contextvars.ContextVar(
    "trivy_tpu_memwatch_digest", default=""
)


class _Allocation:
    """One tracked long-lived device allocation; release is idempotent."""

    __slots__ = ("seq", "component", "device", "digest", "nbytes",
                 "released", "__weakref__")

    def __init__(self, seq: int, component: str, device: str, digest: str,
                 nbytes: int):
        self.seq = seq
        self.component = component
        self.device = device
        self.digest = digest
        self.nbytes = int(nbytes)
        self.released = False

    def resize(self, nbytes: int) -> None:
        with _LOCK:
            if self.released:
                return
            self.nbytes = int(nbytes)
            _bump_peak_locked(self.device)

    def release(self) -> None:
        with _LOCK:
            if self.released:
                return
            self.released = True
            _allocs.pop(self.seq, None)


def _bump_peak_locked(device: str) -> None:  # graftlint: holds(_LOCK)
    total = sum(a.nbytes for a in _allocs.values() if a.device == device)
    if total > _attr_peak.get(device, 0):
        _attr_peak[device] = total


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn tracking on (idempotent).  Servers call this at construction;
    already-live allocations made while off are simply not in the ledger."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop the whole ledger + peaks + injected provider (tests/bench)."""
    global _attr_limit, _stats_provider, _default_device
    with _LOCK:
        for a in _allocs.values():
            a.released = True
        _allocs.clear()
        _attr_peak.clear()
    _stats_provider = None
    _attr_limit = 0
    _default_device = None


def _device_name() -> str:
    """Default device tag for untagged registrations: the backend's first
    device as "platform:id", matching the raw-sampler keys so attributed
    and raw rows join in snapshot().  Falls back to "host" when no JAX
    backend can initialise."""
    global _default_device
    if _default_device is None:
        try:
            import jax

            d = jax.devices()[0]
            _default_device = f"{d.platform}:{getattr(d, 'id', 0)}"
        except Exception:
            _default_device = "host"
    return _default_device


def track(component: str, nbytes: int, device: str = "", digest: str = "",
          owner=None):
    """Register `nbytes` of long-lived device memory under `component`.

    Returns a handle: ``resize(nbytes)`` for allocations that grow,
    ``release()`` when freed.  Pass ``owner=`` to auto-release when that
    object is garbage-collected (the safety net for engine-held tensors
    dropped by pool eviction).  With an empty `digest`, the ambient
    ``ruleset_digest(...)`` scope tags the allocation, which is what lets
    the resident pool measure per-ruleset bytes.  No-op (shared handle)
    while tracking is off.
    """
    if not _enabled:
        return NOOP_HANDLE
    global _seq
    dev = device or _device_name()
    dig = digest or _digest_ctx.get()
    with _LOCK:
        _seq += 1
        alloc = _Allocation(_seq, component, dev, dig, int(nbytes))
        _allocs[alloc.seq] = alloc
        _bump_peak_locked(dev)
    if owner is not None:
        weakref.finalize(owner, alloc.release)
    return alloc


@contextlib.contextmanager
def ruleset_digest(digest: str):
    """Scope within which untagged track() calls inherit `digest`."""
    tok = _digest_ctx.set(digest or "")
    try:
        yield
    finally:
        _digest_ctx.reset(tok)


def nbytes_of(value) -> int:
    """Best-effort byte size of a cached value: .nbytes, or the sum over
    a tuple/list of such (the shapes engines actually cache)."""
    n = getattr(value, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(value, (tuple, list)):
        return sum(nbytes_of(v) for v in value)
    return 0


# -- read side -------------------------------------------------------------


def total_bytes() -> int:
    with _LOCK:
        return sum(a.nbytes for a in _allocs.values())


def allocation_count() -> int:
    with _LOCK:
        return len(_allocs)


def bytes_for_digest(digest: str, exclude: tuple[str, ...] = ()) -> int:
    """Attributed bytes tagged with `digest` (the resident pool's measured
    side), minus any components in `exclude`."""
    if not digest:
        return 0
    with _LOCK:
        return sum(
            a.nbytes
            for a in _allocs.values()
            if a.digest == digest and a.component not in exclude
        )


def set_stats_provider(fn: Callable[[], dict] | None) -> None:
    """Inject (or with None, restore) the raw per-device stats source.
    The provider returns ``{device: {"bytes_in_use": int,
    "peak_bytes_in_use": int, "bytes_limit": int}}`` and is always called
    outside the ledger lock, so a fake may read the ledger back."""
    global _stats_provider
    _stats_provider = fn


def set_attributed_limit(nbytes: int) -> None:
    """Byte budget pressure() falls back to when the backend reports no
    bytes_limit (CPU dev boxes) — attributed_total/limit."""
    global _attr_limit
    _attr_limit = max(0, int(nbytes))


def _jax_stats() -> dict:
    """Default raw sampler.  ``memory_stats`` is absent or None on CPU
    backends — those devices are simply omitted, and the ledger carries
    on from registrations alone."""
    out: dict[str, dict] = {}
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return out
    for d in devices:
        fn = getattr(d, "memory_stats", None)
        if fn is None:
            continue
        try:
            ms = fn()
        except Exception:
            ms = None
        if not ms:
            continue
        in_use = int(ms.get("bytes_in_use", 0))
        out[f"{d.platform}:{getattr(d, 'id', 0)}"] = {
            "bytes_in_use": in_use,
            "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", in_use)),
            "bytes_limit": int(ms.get("bytes_limit", 0)),
        }
    return out


def raw_stats() -> dict:
    """Per-device raw allocator stats ({} on backends without them)."""
    fn = _stats_provider or _jax_stats
    try:
        return dict(fn())
    except Exception:
        return {}


def pressure() -> dict:
    """How close to the edge: ``fraction`` in [0, 1] with its ``source``.

    "measured": max over devices of raw bytes_in_use/bytes_limit.
    "attributed": ledger total / set_attributed_limit() budget (no raw
    limits available).  "none": no limit known from either side —
    fraction 0.0, watermarks can't act.
    """
    raw = raw_stats()
    best = {"fraction": 0.0, "source": "none", "device": None,
            "bytes_in_use": 0, "bytes_limit": 0}
    for dev, ms in raw.items():
        limit = ms.get("bytes_limit", 0)
        if limit and limit > 0:
            frac = ms.get("bytes_in_use", 0) / limit
            if best["source"] == "none" or frac > best["fraction"]:
                best = {
                    "fraction": frac, "source": "measured", "device": dev,
                    "bytes_in_use": ms.get("bytes_in_use", 0),
                    "bytes_limit": limit,
                }
    if best["source"] == "none" and _attr_limit > 0:
        used = total_bytes()
        best = {
            "fraction": used / _attr_limit, "source": "attributed",
            "device": None, "bytes_in_use": used, "bytes_limit": _attr_limit,
        }
    return best


def snapshot(top: int = 10) -> dict:
    """The `/debug/memory` body: per-device raw + attributed breakdown,
    residuals, watermark-ready pressure, and the `top` largest live
    allocations.  Attributed sums equal the live ledger exactly (zero
    tolerance by construction); the raw residual is the backend's
    unattributed remainder."""
    raw = raw_stats()
    with _LOCK:
        allocs = [
            (a.component, a.device, a.digest, a.nbytes)
            for a in _allocs.values()
        ]
        peaks = dict(_attr_peak)
    devices: dict[str, dict] = {}
    for comp, dev, _dig, nb in allocs:
        d = devices.setdefault(dev, {"attributed": {}, "attributed_bytes": 0})
        d["attributed"][comp] = d["attributed"].get(comp, 0) + nb
        d["attributed_bytes"] += nb
    for dev in raw:
        devices.setdefault(dev, {"attributed": {}, "attributed_bytes": 0})
    for dev, d in devices.items():
        d["attributed_peak_bytes"] = peaks.get(dev, 0)
        ms = raw.get(dev)
        d["raw"] = ms
        d["residual_bytes"] = (
            ms["bytes_in_use"] - d["attributed_bytes"] if ms else None
        )
    allocs.sort(key=lambda t: t[3], reverse=True)
    return {
        "enabled": _enabled,
        "devices": devices,
        "attributed_total_bytes": sum(nb for *_x, nb in allocs),
        "registered_allocations": len(allocs),
        "top": [
            {"component": c, "device": d, "digest": g, "nbytes": n}
            for c, d, g, n in allocs[: max(0, int(top))]
        ],
        "pressure": pressure(),
    }


def explain_block() -> dict:
    """The small `Explain.memory` dict attached to --explain responses."""
    p = pressure()
    return {
        "pressure": round(p["fraction"], 4),
        "source": p["source"],
        "attributed_bytes": total_bytes(),
        "allocations": allocation_count(),
    }


def register_collectors(registry) -> None:
    """Create the HBM gauge families on `registry` and add the collect
    hook that rebuilds them from live ledger + raw stats each scrape
    (clear + re-set, the build_info pattern, so released components stop
    scraping instead of pinning stale samples)."""
    g_bytes = registry.gauge(
        "trivy_tpu_device_hbm_bytes",
        "device bytes by attributed component "
        '(component="_unattributed" = raw in-use minus the ledger)',
        labelnames=("device", "component"),
    )
    g_peak = registry.gauge(
        "trivy_tpu_device_hbm_peak_bytes",
        "peak device bytes (backend allocator peak when reported, else "
        "the attributed high-water mark)",
        labelnames=("device",),
    )
    g_pressure = registry.gauge(
        "trivy_tpu_hbm_pressure",
        "max used/limit fraction across devices (0 = no limit known); "
        "the --hbm-soft-pct/--hbm-hard-pct watermarks act on this",
    )

    def _collect() -> None:
        snap = snapshot(top=0)
        g_bytes.clear()
        g_peak.clear()
        for dev, d in snap["devices"].items():
            for comp, nb in d["attributed"].items():
                g_bytes.labels(device=dev, component=comp).set(nb)
            residual = d.get("residual_bytes")
            if residual is not None and residual > 0:
                g_bytes.labels(device=dev, component="_unattributed").set(
                    residual
                )
            ms = d.get("raw")
            peak = (
                ms["peak_bytes_in_use"] if ms else d["attributed_peak_bytes"]
            )
            g_peak.labels(device=dev).set(peak)
        g_pressure.set(snap["pressure"]["fraction"])

    registry.add_collect_hook(_collect)
