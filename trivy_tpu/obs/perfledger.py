"""Bench ledger: append-only performance history with a regression gate.

bench.py's contract is ONE JSON line per run — great for the harness's
stdout tail, useless for trajectory: by the next run the previous line is
gone and "did the pipeline get slower since the codec landed?" needs an
archaeologist.  The ledger closes that gap the cheapest way that works:
every bench run appends one JSONL entry — the same compact payload the
bench printed, wrapped with the provenance that makes runs comparable
(git sha, device platform, ruleset digest, exit status, timestamp).  The
file is append-only; nothing in this module ever rewrites or truncates
it.

Three consumers, all via `trivy-tpu perf`:

  report  render the recent trajectory of the headline metrics;
  diff    per-metric deltas between two runs (dotted paths into the
          bench payload, numeric leaves only);
  gate    compare the latest run against a checked-in baseline
          (tools/perfgate/baseline.json) and exit non-zero when any
          metric regresses past its per-metric tolerance — the CI hook
          (`make perf-gate`) that turns the ledger from a diary into a
          tripwire.

Ledger writes must never break the bench: append() is called from
bench._emit after the stdout line is flushed, swallows OSError, and
prints nothing (the single-line stdout contract is bench.py's, not
ours to spoil).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

SCHEMA = 1
DEFAULT_LEDGER = "BENCH_LEDGER.jsonl"


def ledger_path(explicit: str = "") -> str:
    """Resolve the ledger file: explicit arg > BENCH_LEDGER_FILE env >
    the default.  An explicitly-empty env var disables the ledger."""
    if explicit:
        return explicit
    return os.environ.get("BENCH_LEDGER_FILE", DEFAULT_LEDGER)


def _git_sha() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return out.stdout.strip() if out.returncode == 0 else ""
    except Exception:
        return ""


def _platform() -> str:
    try:
        from trivy_tpu.mesh import topology as mesh_topology

        return mesh_topology.platform()
    except Exception:
        return sys.platform


def append(payload: dict, *, rc: int = 0, path: str = "") -> dict | None:
    """Append one run to the ledger; returns the entry, or None when the
    ledger is disabled or unwritable.  Never raises, never prints."""
    try:
        p = ledger_path(path)
        if not p:
            return None
        entry = {
            "schema": SCHEMA,
            "ts": time.time(),
            "git_sha": _git_sha(),
            "platform": _platform(),
            "ruleset_digest": (payload or {}).get("ruleset_digest", ""),
            "rc": int(rc),
            "bench": payload or {},
        }
        line = json.dumps(entry, separators=(",", ":"), default=str)
        with open(p, "a") as f:
            f.write(line + "\n")
        return entry
    except Exception:
        return None


def read(path: str = "") -> list[dict]:
    """All ledger entries, oldest first.  Malformed lines are skipped
    (a truncated tail from a killed run must not poison history)."""
    p = ledger_path(path)
    entries: list[dict] = []
    try:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(obj, dict) and "bench" in obj:
                    entries.append(obj)
    except OSError:
        pass
    return entries


def flatten(entry: dict) -> dict[str, float]:
    """Numeric leaves of the entry's bench payload as dotted paths
    ("detail.files_per_sec" -> 1234.5).  Bools and strings are skipped;
    lists are skipped (their per-element identity is not stable run to
    run)."""
    out: dict[str, float] = {}

    def walk(prefix: str, node) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        elif isinstance(node, bool):
            return
        elif isinstance(node, (int, float)):
            out[prefix] = float(node)

    walk("", entry.get("bench") or {})
    return out


def diff(base: dict, head: dict) -> list[dict]:
    """Per-metric deltas between two ledger entries, sorted by |pct|
    descending so the biggest movers lead.  Metrics present in only one
    run are reported with the other side null."""
    b, h = flatten(base), flatten(head)
    rows: list[dict] = []
    for metric in sorted(set(b) | set(h)):
        bv, hv = b.get(metric), h.get(metric)
        row: dict = {"metric": metric, "base": bv, "head": hv}
        if bv is not None and hv is not None:
            row["delta"] = round(hv - bv, 6)
            if bv:
                row["pct"] = round((hv - bv) / abs(bv) * 100.0, 2)
        rows.append(row)
    rows.sort(key=lambda r: abs(r.get("pct") or 0.0), reverse=True)
    return rows


def load_baseline(path: str) -> dict:
    """Baseline JSON: {"schema": 1, "metrics": {"<dotted.path>":
    {"baseline": X, "tolerance": 0.5, "direction": "higher"|"lower"}}}.
    direction names which way is GOOD: "higher" gates on drops below
    baseline*(1-tolerance), "lower" on rises above
    baseline*(1+tolerance)."""
    with open(path) as f:
        base = json.load(f)
    if not isinstance(base, dict) or "metrics" not in base:
        raise ValueError(f"{path}: not a perf baseline (no 'metrics' key)")
    return base


def gate(entry: dict, baseline: dict) -> tuple[list[dict], list[dict]]:
    """Check one ledger entry against a baseline; returns (failures,
    checked).  A metric absent from the run is skipped, not failed —
    sections are env-gated and a baseline must not force every section
    on.  A non-zero bench rc is itself a failure: a crashed run proves
    nothing about performance."""
    failures: list[dict] = []
    checked: list[dict] = []
    if entry.get("rc"):
        failures.append({
            "metric": "rc",
            "value": entry.get("rc"),
            "reason": "bench run exited non-zero",
            "error": (entry.get("bench") or {}).get("error", ""),
        })
    values = flatten(entry)
    for metric, spec in sorted((baseline.get("metrics") or {}).items()):
        value = values.get(metric)
        if value is None:
            continue
        base = float(spec["baseline"])
        tol = float(spec.get("tolerance", 0.25))
        direction = spec.get("direction", "higher")
        if direction == "higher":
            bound = base * (1.0 - tol)
            ok = value >= bound
        else:
            bound = base * (1.0 + tol)
            ok = value <= bound
        row = {
            "metric": metric, "value": round(value, 6),
            "baseline": base, "bound": round(bound, 6),
            "direction": direction,
        }
        checked.append(row)
        if not ok:
            failures.append({**row, "reason": "outside tolerance"})
    return failures, checked
