"""Observability substrate: span tracing (obs/trace.py), the
counter/gauge/histogram metrics registry (obs/metrics.py), per-method
SLO burn-rate tracking (obs/slo.py), tenant-labelled families behind a
cardinality governor (obs/tenantmetrics.py), the breach-triggered
flight recorder (obs/flight.py), and the device-memory ledger
(obs/memwatch.py: raw HBM stats + per-component attributed bytes, the
pressure signal the admission watermarks act on).

One trace from RPC ticket to TPU kernel: `RemoteSecretEngine` mints a
trace_id, ships it as `X-Trivy-Trace-Id`, the server stamps it onto the
scheduler ticket, and every pipeline stage (queue wait, batch fill,
per-chunk encode/h2d/exec/fetch, host confirm) opens a span carrying it.
Spans land in a bounded ring buffer and export as Chrome-trace JSON
(`trivy-tpu scan --trace-out`, server `GET /debug/traces`), which Perfetto
merges with the JAX profiler's device timeline when both write into one
`--profile-dir`.  When a request breaches its SLO, its span tree plus a
scheduler snapshot are promoted into the flight ring (`GET /debug/flight`).

Everything is off by default: `span()` returns a no-op singleton unless
tracing was enabled (`TRIVY_TPU_TRACE=1` or `trace.enable()`), so the
scan path pays one predicate per call site.
"""

from trivy_tpu.obs import (
    flight,
    memwatch,
    metrics,
    slo,
    tenantmetrics,
    trace,
)

__all__ = ["flight", "memwatch", "metrics", "slo", "tenantmetrics", "trace"]
