"""Counter/gauge/histogram families with one registry + exposition renderer.

The server's and scheduler's hand-rolled Prometheus text lines grew the same
code three times (rpc/server.py _Metrics, serve/scheduler.py metrics_text and
_engine_metric_lines); this module is the single exposition path they all
render through.  Families register on a `Registry` (per-server, never
process-global — tests boot many servers in one process), samples update at
event time, and `render()` emits text-format 0.0.4: HELP + TYPE per family,
histogram `_bucket{le=...}` series cumulative and `le="+Inf"`-terminated,
`_sum`/`_count` alongside.

Latency lives in fixed-bucket histograms, not totals: a `*_seconds_total`
counter answers "how much", a histogram answers "how bad is the tail", and
the tail is what an admission queue tunes against.

Collect hooks run at scrape time for values owned elsewhere (queue depth,
ruleset epoch, engine link gauges) — a hook must never do work a scrape
shouldn't trigger (the scheduler's hook reads the non-building
`RulesetManager.active`, exactly like the render path it replaces).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Callable, Iterable

from trivy_tpu import lockcheck

# Request/wait latency buckets: 1ms..60s, roughly log-spaced.  The scan
# server's floor is a batch window of a few ms and its ceiling a deadline
# of minutes; these cover both tails.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
# Batch fill ratio is bounded [0, 1]; resolution matters near empty
# (window expired) and near full (bytes-capped dispatch).
RATIO_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
# Per-batch byte volumes: 4 KiB .. 256 MiB, x4 steps.
BYTES_BUCKETS = tuple(float(4096 * 4**i) for i in range(9))


def _fmt(v: float | int) -> str:
    """Exposition value: ints stay ints, floats trim trailing zeros."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    if v != v or v in (float("inf"), float("-inf")):
        return {float("inf"): "+Inf", float("-inf"): "-Inf"}.get(v, "NaN")
    out = f"{v:.9f}".rstrip("0").rstrip(".")
    return out or "0"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape(str(v))}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Family:
    kind = ""

    def __init__(
        self, name: str, help_text: str, labelnames: tuple[str, ...],
        lock: threading.Lock,
    ):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._children: dict[tuple[str, ...], object] = {}
        if not self.labelnames:
            # Label-less families expose their zero sample immediately
            # (a gauge that has never been set must still scrape as 0).
            self._child(())

    def _new_child(self):
        raise NotImplementedError

    def _child(self, key: tuple[str, ...]):
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def clear(self) -> None:
        """Drop every child series.  The seat for collect hooks that
        rebuild a family from live state each scrape (e.g. one
        trivy_tpu_build_info series per *resident* ruleset): without the
        reset, series for evicted residents would keep scraping stale 1s.
        Label-less families re-expose their zero sample immediately."""
        with self._lock:
            self._children.clear()
        if not self.labelnames:
            self._child(())

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kw)} != {sorted(self.labelnames)}"
            )
        return self._child(tuple(str(kw[n]) for n in self.labelnames))

    def fold_label(self, labelname: str, value: str, into: str) -> None:
        """Fold every series whose `labelname` equals `value` into the
        series with that label replaced by `into`, then drop the source.

        The cardinality governor's demotion primitive: totals are conserved
        (each event was counted exactly once, folding moves samples rather
        than duplicating them), the destination stays monotonic (a fold
        only adds), and the demoted series disappears from the next scrape
        instead of pinning a stale sample forever."""
        if labelname not in self.labelnames:
            raise ValueError(
                f"{self.name}: no label {labelname!r} in {self.labelnames}"
            )
        i = self.labelnames.index(labelname)
        with self._lock:
            keys = [k for k in self._children if k[i] == str(value)]
            for key in keys:
                src = self._children.pop(key)
                dkey = key[:i] + (str(into),) + key[i + 1 :]
                dst = self._children.get(dkey)
                if dst is None:
                    dst = self._children[dkey] = self._new_child()
                self._fold_child(src, dst)

    def _fold_child(self, src, dst) -> None:
        """Merge src's samples into dst; runs under the family lock, so it
        must touch child fields directly (inc()/observe() would deadlock
        on the same non-reentrant lock)."""
        raise NotImplementedError

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            lines.extend(self._render_child(key, child))
        return lines

    def _render_child(self, key, child) -> list[str]:
        raise NotImplementedError


class _Value:
    __slots__ = ("v", "_lock")

    def __init__(self, lock: threading.Lock):
        self.v = 0.0
        self._lock = lock


class _CounterChild(_Value):
    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.v += amount

    def set_total(self, value: float) -> None:
        """Collect-hook seat: adopt a monotonic total owned elsewhere
        (e.g. RulesetManager.reloads) instead of double-counting events."""
        with self._lock:
            self.v = value


class Counter(_Family):
    kind = "counter"

    def _new_child(self):
        return _CounterChild(self._lock)

    def _fold_child(self, src, dst) -> None:
        dst.v += src.v

    def inc(self, amount: float = 1.0) -> None:
        self._child(()).inc(amount)

    def set_total(self, value: float) -> None:
        self._child(()).set_total(value)

    def _render_child(self, key, child) -> list[str]:
        return [
            f"{self.name}{_label_str(self.labelnames, key)} {_fmt(_as_num(child.v))}"
        ]


class _GaugeChild(_Value):
    def set(self, value: float) -> None:
        with self._lock:
            self.v = value

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.v += amount

    def dec(self, amount: float = 1.0, floor: float | None = None) -> None:
        with self._lock:
            self.v -= amount
            if floor is not None and self.v < floor:
                self.v = floor


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild(self._lock)

    def _fold_child(self, src, dst) -> None:
        # Gauges fold additively: the governor only demotes counting-style
        # gauges, where "combined level" is the only meaningful rollup.
        dst.v += src.v

    def set(self, value: float) -> None:
        self._child(()).set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._child(()).inc(amount)

    def dec(self, amount: float = 1.0, floor: float | None = None) -> None:
        self._child(()).dec(amount, floor)

    def _render_child(self, key, child) -> list[str]:
        return [
            f"{self.name}{_label_str(self.labelnames, key)} {_fmt(_as_num(child.v))}"
        ]


class _HistogramChild:
    __slots__ = ("counts", "sum", "count", "_buckets", "_lock")

    def __init__(self, buckets: tuple[float, ...], lock: threading.Lock):
        self._buckets = buckets
        self.counts = [0] * len(buckets)  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0
        self._lock = lock

    def observe(self, value: float) -> None:
        i = bisect_left(self._buckets, value)
        with self._lock:
            if i < len(self.counts):
                self.counts[i] += 1
            self.sum += value
            self.count += 1


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name, help_text, labelnames, lock, buckets):
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"{name}: histogram needs >= 1 bucket bound")
        self.buckets = b
        super().__init__(name, help_text, labelnames, lock)

    def _new_child(self):
        return _HistogramChild(self.buckets, self._lock)

    def _fold_child(self, src, dst) -> None:
        for i, n in enumerate(src.counts):
            dst.counts[i] += n
        dst.sum += src.sum
        dst.count += src.count

    def observe(self, value: float) -> None:
        self._child(()).observe(value)

    def _render_child(self, key, child) -> list[str]:
        lines = []
        cum = 0
        for bound, n in zip(self.buckets, child.counts):
            cum += n
            labels = _label_str(
                self.labelnames + ("le",), key + (_fmt(bound),)
            )
            lines.append(f"{self.name}_bucket{labels} {cum}")
        inf_labels = _label_str(self.labelnames + ("le",), key + ("+Inf",))
        lines.append(f"{self.name}_bucket{inf_labels} {child.count}")
        plain = _label_str(self.labelnames, key)
        lines.append(f"{self.name}_sum{plain} {_fmt(child.sum)}")
        lines.append(f"{self.name}_count{plain} {child.count}")
        return lines


def _as_num(v: float):
    """Render-friendly: whole floats print as ints (counters that only
    ever inc(1) must expose `3`, not `3.0`)."""
    return int(v) if isinstance(v, float) and v.is_integer() else v


class Registry:
    """One scrape surface: ordered families + collect hooks."""

    def __init__(self):
        self._lock = lockcheck.make_lock("obs.metrics.registry")
        self._families: dict[str, _Family] = {}  # owner: _lock
        self._hooks: list[Callable[[], None]] = []  # owner: _lock

    def _register(self, cls, name: str, help_text: str, labelnames, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type or label set"
                    )
                return fam
            fam = cls(name, help_text, tuple(labelnames),
                      lockcheck.make_lock("obs.metrics.family"), **kw)
            self._families[name] = fam
            return fam

    def counter(
        self, name: str, help_text: str, labelnames: Iterable[str] = ()
    ) -> Counter:
        return self._register(Counter, name, help_text, labelnames)

    def gauge(
        self, name: str, help_text: str, labelnames: Iterable[str] = ()
    ) -> Gauge:
        return self._register(Gauge, name, help_text, labelnames)

    def histogram(
        self,
        name: str,
        help_text: str,
        labelnames: Iterable[str] = (),
        buckets: Iterable[float] = LATENCY_BUCKETS,
    ) -> Histogram:
        return self._register(
            Histogram, name, help_text, labelnames, buckets=buckets
        )

    def add_collect_hook(self, fn: Callable[[], None]) -> None:
        """`fn()` runs at every render(), before lines are built — the seat
        for gauges mirroring live state (queue depth, engine stats).  Hooks
        must be cheap and must never build what is not already built."""
        with self._lock:
            self._hooks.append(fn)

    def render(self) -> str:
        with self._lock:
            hooks = list(self._hooks)
            fams = list(self._families.values())
        for fn in hooks:
            try:
                fn()
            except Exception:
                # A scrape must never 500 because one hook's source object
                # is mid-teardown; the stale sample is the lesser evil.
                pass
        lines: list[str] = []
        for fam in fams:
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Device-phase attribution: per-kernel fenced timings from the engines
# ---------------------------------------------------------------------------
#
# The engines' coarse wall timings (dispatch_s / fetch_map_s) lump every
# kernel behind one async dispatch boundary; these helpers split them into
# honest per-kernel sections.  `device_phase(kernel)` opens a span nested
# under the ambient chunk span and, at `.done(*arrays)`, blocks on the
# section's output arrays before reading the clock — the fence is what makes
# an async dispatch's timing attributable to ITS kernel rather than to
# whoever synchronizes next.  Fences run ONLY when tracing is enabled: the
# disabled path returns a shared no-op handle and costs one predicate (the
# BENCH_OBS <2% overhead contract), and the pipelined engine's overlap is
# never serialized outside an observation window.
#
# Samples queue process-globally (engines don't own a registry); a server's
# collect hook drains them into its per-server
# `trivy_tpu_device_phase_seconds{kernel}` histogram at scrape time.

# The per-kernel section names the engines report (bounded label set).
DEVICE_PHASE_KERNELS = (
    "encode", "unpack", "sieve-step", "compact", "verify-stream",
)

# Kernel sections are sub-millisecond to a few seconds (relay dispatch):
# 50us .. 2.5s, roughly log-spaced.
DEVICE_PHASE_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

_DEVICE_PHASE_LOCK = lockcheck.make_lock("obs.metrics.device_phase")
_DEVICE_PHASE_PENDING: list[tuple[str, str, float]] = []  # owner: _DEVICE_PHASE_LOCK
# Tracing on with nothing scraping (CLI scans) must not grow unbounded:
# beyond the cap the oldest samples drop — the scrape path is best-effort
# by design, the span tree keeps the full record.
_DEVICE_PHASE_MAX_PENDING = 4096


def record_device_phase(kernel: str, seconds: float, device: str = "") -> None:
    """Queue one per-kernel fenced timing for the next scrape drain.

    `device` is the bounded device label ("cpu:0", "tpu:3", "mesh[8]" for
    a sharded dispatch, "" when unknown) — bounded by construction: values
    come only from the mesh topology's device tags plus the one mesh[N]
    aggregate, the cardinality-governor shape.  Positional callers predate
    the label and land in the "" series."""
    with _DEVICE_PHASE_LOCK:
        _DEVICE_PHASE_PENDING.append((kernel, device, seconds))
        overflow = len(_DEVICE_PHASE_PENDING) - _DEVICE_PHASE_MAX_PENDING
        if overflow > 0:
            del _DEVICE_PHASE_PENDING[:overflow]


def drain_device_phases() -> list[tuple[str, str, float]]:
    """Take every pending (kernel, device, seconds) sample (collect-hook
    seat)."""
    with _DEVICE_PHASE_LOCK:
        out = list(_DEVICE_PHASE_PENDING)
        _DEVICE_PHASE_PENDING.clear()
    return out


class _NoopPhase:
    __slots__ = ()

    def done(self, *arrays) -> float:
        return 0.0


_NOOP_PHASE = _NoopPhase()


def _phase_device_label(arrays) -> str:
    """Device label for a fenced section, from its first output that
    knows where it lives: one device -> its "platform:id" tag, a sharded
    array -> "mesh[N]" (one aggregate series per mesh size, never one per
    device-set permutation — that keeps the label bounded)."""
    for a in arrays:
        devs = getattr(a, "devices", None)
        if devs is None:
            continue
        try:
            ds = list(devs()) if callable(devs) else list(devs)
        except Exception:  # graftlint: swallow(labeling never degrades the scan)
            continue
        if len(ds) == 1:
            d = ds[0]
            return f"{d.platform}:{d.id}"
        if len(ds) > 1:
            return f"mesh[{len(ds)}]"
    return ""


class _DevicePhase:
    __slots__ = ("kernel", "_t0", "_span")

    def __init__(self, kernel: str):
        from trivy_tpu.obs import trace as obs_trace

        self.kernel = kernel
        # Deliberate handle pattern: begin/done brackets an async dispatch
        # across statements, which `with` cannot.  If done() is skipped by
        # an unwinding exception the span misses its ring append but the
        # ambient context heals: the enclosing chunk span's token reset
        # restores the contextvar.
        self._span = obs_trace.span(  # graftlint: ignore[GL006]
            f"kernel.{kernel}", kernel=kernel
        )
        self._span.__enter__()
        self._t0 = time.perf_counter()

    def done(self, *arrays) -> float:
        flat: list = []
        for a in arrays:
            if isinstance(a, (tuple, list)):
                flat.extend(a)
            else:
                flat.append(a)
        for a in flat:
            bur = getattr(a, "block_until_ready", None)
            if bur is not None:
                try:
                    bur()
                except Exception:
                    # a failed fence degrades the timing, never the scan
                    pass
        dt = time.perf_counter() - self._t0
        record_device_phase(self.kernel, dt, device=_phase_device_label(flat))
        self._span.__exit__(None, None, None)
        return dt


def device_phase(kernel: str):
    """Begin a per-kernel timed section; no-op unless tracing is enabled.

    Usage in engine code::

        ph = obs_metrics.device_phase("sieve-step")
        out = step(dev_rows)          # async dispatch
        ph.done(out)                  # fence + record + close span

    `.done(*arrays)` blocks on each array that has `block_until_ready`
    (host-side sections pass none and just read the clock)."""
    from trivy_tpu.obs import trace as obs_trace

    if not obs_trace.enabled():
        return _NOOP_PHASE
    return _DevicePhase(kernel)
