"""Hybrid-gate decision audit: why did this process verify on DFA/device?

Every hybrid-gate resolution (engine/hybrid.py) records a structured
decision here — the measured link terms (`probe_link`), the post-codec
effective rate the cost model priced (`effective_link_rate`), the
thresholds it was held against, the chosen backend and the margin by
which it won — so "why did auto resolve to dfa" is answerable from a
running process instead of re-derived by hand from bench output.

The log is process-global on purpose: engines are constructed from CLI
scans, server scheduler lanes, and reload threads alike, and the
question ("what did the gate see on THIS host") is per-process, not
per-registry.  Consumers:

- `GET /debug/gate` serves `records()` newest-first;
- the server's collect hook folds `tallies()` into
  `trivy_tpu_hybrid_gate_decision_total{backend,reason}` and the latest
  margin into `trivy_tpu_hybrid_gate_margin`;
- the flight recorder embeds `records()` in breach captures, so an
  incident shows the gate state that routed it.

Reasons are a bounded enum (metric-label safe): `link-wide`,
`link-narrow`, `mesh-wide` (the multi-device mesh profile cleared the
bar — the record carries `profile`/`devices` so the aggregate-rate
pricing is auditable), `no-device`, `forced`, `fallback`, `breaker` (a
runtime circuit-breaker transition re-routing batches — see
engine/breaker.py and the serve scheduler's failure domains).

Backends are likewise bounded: `dfa`, `device` (legacy flag-map
stream), `fused` (device-resident verify — lane verdicts resolve
on-device and only a packed keep-mask crosses the link; see
engine/nfa_device.py), `none`, `auto`.  A `fused` record whose terms
carry `profile: "fused"` was priced against the fused cost model
(zero re-upload, FUSED_GATE_RTT_S bar — engine/hybrid.py gate_terms);
the serve scheduler's degraded ladder steps fused -> legacy-device ->
host-DFA, each rung visible here and in `/debug/gate`.
"""

from __future__ import annotations

import time
from collections import deque

from trivy_tpu import lockcheck

DEFAULT_CAPACITY = 256

_LOCK = lockcheck.make_lock("obs.gatelog")
_RING: deque = deque(maxlen=DEFAULT_CAPACITY)  # owner: _LOCK
_TALLIES: dict[tuple[str, str], int] = {}  # owner: _LOCK (survives eviction)
_SEQ = 0  # owner: _LOCK


def record(
    *,
    requested: str,
    backend: str,
    reason: str,
    profile: str | None = None,
    devices: int | None = None,
    link_mb_per_sec: float | None = None,
    link_rtt_s: float | None = None,
    h2d_ratio: float | None = None,
    d2h_ratio: float | None = None,
    eff_mb_per_sec: float | None = None,
    eff_threshold_mb_per_sec: float | None = None,
    rtt_threshold_s: float | None = None,
    codec: str | None = None,
    margin: float | None = None,
    error: str = "",
) -> dict:
    """Append one gate decision; returns the stored record.

    `margin` is signed distance from the flip point (positive = the link
    cleared the device bar); None when the decision never priced the link
    (no device, forced mode).
    """
    global _SEQ
    rec: dict = {
        "captured_at": time.time(),  # wall timestamp, not a duration
        "requested": requested,
        "backend": backend,
        "reason": reason,
        "margin": margin,
    }
    if profile is not None:
        rec["profile"] = profile
    if devices is not None:
        rec["devices"] = devices
    if link_mb_per_sec is not None:
        rec["link"] = {
            "mb_per_sec": link_mb_per_sec,
            "rtt_s": link_rtt_s,
            "h2d_ratio": h2d_ratio,
            "d2h_ratio": d2h_ratio,
            "eff_mb_per_sec": eff_mb_per_sec,
            "codec": codec,
        }
    if eff_threshold_mb_per_sec is not None:
        rec["thresholds"] = {
            "eff_mb_per_sec": eff_threshold_mb_per_sec,
            "rtt_s": rtt_threshold_s,
        }
    if error:
        rec["error"] = error
    with _LOCK:
        _SEQ += 1
        rec["seq"] = _SEQ
        _RING.append(rec)
        key = (backend, reason)
        _TALLIES[key] = _TALLIES.get(key, 0) + 1
    return rec


def records(limit: int | None = None) -> list[dict]:
    """Newest-first decision records (shallow copies)."""
    with _LOCK:
        out = [dict(r) for r in reversed(_RING)]
    return out[:limit] if limit is not None else out


def last() -> dict | None:
    with _LOCK:
        return dict(_RING[-1]) if _RING else None


def tallies() -> dict[tuple[str, str], int]:
    """(backend, reason) -> decision count since process start.  Counts
    are monotonic and survive ring eviction — safe to export as a
    counter family."""
    with _LOCK:
        return dict(_TALLIES)


def last_margin() -> float | None:
    """Margin of the newest decision that priced the link, or None."""
    with _LOCK:
        for rec in reversed(_RING):
            if rec.get("margin") is not None:
                return rec["margin"]
    return None


def clear() -> None:
    """Reset ring, tallies, and sequence (tests)."""
    global _SEQ
    with _LOCK:
        _RING.clear()
        _TALLIES.clear()
        _SEQ = 0
