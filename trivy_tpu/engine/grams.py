"""Masked-gram compilation: probes -> (mask, value) uint32 compare constants.

The TPU-shaped reformulation of the probe sieve (engine/probes.py).  The
gather-LUT shift-AND sieve is correct but gather-bound on TPU (byte-table
gathers don't vectorize onto the VPU).  Instead, each probe is compiled to a
small set of **masked 4-gram variants**: the device case-folds the content,
packs every 4-byte window into a uint32, and tests

    (window & mask_g) == val_g

for all grams at once — pure elementwise compare/AND/OR that XLA fuses into
one VPU kernel with no gathers (ops/gram_sieve.py).

Soundness: a gram is derived from a window of the probe's byte-class sequence;
positions with wide classes are masked out, small classes (<= MAX_CLASS_EXPAND
members after case folding) are expanded into variants.  Every true probe
occurrence therefore fires at least one of its grams ("no gram hit" soundly
proves "no probe occurrence").  Probes whose best window is below the
selectivity floor get no grams and are treated as always-hit (they stop
filtering but never drop matches).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from trivy_tpu.engine.ir import bs_members
from trivy_tpu.engine.probes import _FREQ, ProbeSet

GRAM_LEN = 4
MAX_CLASS_EXPAND = 4  # class wider than this (folded) is masked out
MAX_VARIANTS = 8  # max expanded (mask, val) pairs per probe
MIN_GRAM_BITS = 9.0  # selectivity floor (bits) for a usable gram


def fold_byte(b: int) -> int:
    return b + 32 if 0x41 <= b <= 0x5A else b


def fold_members(bs: int) -> list[int]:
    return sorted({fold_byte(b) for b in bs_members(bs)})


def _class_bits(members: list[int]) -> float:
    p = float(sum(_FREQ[b] for b in members))
    return -math.log2(max(p, 1e-12))


@dataclass
class _Position:
    members: list[int]  # folded byte values
    keep: bool  # participates in the mask
    bits: float


def _plan_window(classes: tuple[int, ...]) -> tuple[float, list[_Position]]:
    """Score one window; greedily mask out wide / low-value positions until
    the variant product fits MAX_VARIANTS."""
    positions = []
    for bs in classes:
        members = fold_members(bs)
        keep = 0 < len(members) <= MAX_CLASS_EXPAND and 0 not in members
        positions.append(
            _Position(members=members, keep=keep, bits=_class_bits(members))
        )

    def product() -> int:
        p = 1
        for pos in positions:
            if pos.keep and len(pos.members) > 1:
                p *= len(pos.members)
        return p

    while product() > MAX_VARIANTS:
        # Drop the kept multi-member position with the least selectivity.
        worst = min(
            (p for p in positions if p.keep and len(p.members) > 1),
            key=lambda p: p.bits,
        )
        worst.keep = False

    score = sum(p.bits for p in positions if p.keep)
    return score, positions


def _window_variants(plan: list[_Position]) -> list[tuple[int, int]]:
    variants: list[tuple[int, int]] = [(0, 0)]
    for j, pos in enumerate(plan):
        if not pos.keep:
            continue
        shift = 8 * j
        variants = [
            (mask | (0xFF << shift), val | (member << shift))
            for mask, val in variants
            for member in pos.members
        ]
    return variants


def probe_gram_windows(
    classes: tuple[int, ...], max_windows: int = 2
) -> list[tuple[int, list[tuple[int, int]]]]:
    """Select up to `max_windows` windows of the probe; each returns its
    (start, variants): the window's byte offset within the probe's class
    sequence (the per-hit probe-class confirm in the C scan aligns with
    it) and its (mask, val) uint32 variants.  A probe occurrence fires
    EVERY selected window (AND semantics across windows, OR across a
    window's variants).

    Single-window selection by letter-frequency score alone is fragile: the
    best-scored window of "atlassian" is "lass", a substring of "class",
    which fires on essentially all source code.  Requiring two well-separated
    windows ("atla" AND "sian") keeps soundness (both are necessary
    conditions) while multiplying selectivities.
    """
    wlen = min(GRAM_LEN, len(classes))
    scored: list[tuple[float, int, list[_Position]]] = []
    for start in range(len(classes) - wlen + 1):
        score, plan = _plan_window(tuple(classes[start : start + wlen]))
        if score >= MIN_GRAM_BITS:
            scored.append((score, start, plan))
    if not scored:
        return []

    best = max(scored, key=lambda t: t[0])
    chosen = [best]
    if max_windows >= 2 and len(scored) > 1:
        # Farthest usable window from the best one (ties: higher score);
        # require enough separation that one common word can't contain both.
        far = max(
            (t for t in scored if t != best),
            key=lambda t: (abs(t[1] - best[1]), t[0]),
        )
        if abs(far[1] - best[1]) >= 2:
            chosen.append(far)
        else:
            # No window sits >= 2 from the best one (6-byte factors have
            # starts 0..2 only), but a pair across the whole span may:
            # "twitch" -> "twit" AND "itch", which a containing word like
            # "switch" cannot satisfy — its best-scored window "witc" alone
            # fires on essentially every C file.  Overlap keeps soundness
            # (every factor occurrence contains all its sub-windows).
            pairs = [
                (a, b)
                for i, a in enumerate(scored)
                for b in scored[i + 1 :]
                if abs(a[1] - b[1]) >= 2
            ]
            if pairs:
                chosen = list(
                    max(
                        pairs,
                        key=lambda ab: (
                            abs(ab[0][1] - ab[1][1]),
                            ab[0][0] + ab[1][0],
                        ),
                    )
                )

    return [(start, _window_variants(plan)) for _score, start, plan in chosen]


def probe_grams(classes: tuple[int, ...]) -> list[tuple[int, int]]:
    """Backward-compatible single-window form: the best window's variants."""
    windows = probe_gram_windows(classes, max_windows=1)
    return windows[0][1] if windows else []


@dataclass
class GramSet:
    """Compiled gram constants + probe attribution.

    Grams group into *windows* (a window's variants are case/class
    expansions of one probe window; OR semantics) and windows group into
    probes (a probe occurrence fires every one of its windows; AND
    semantics — see probe_gram_windows)."""

    masks: np.ndarray  # [G] uint32
    vals: np.ndarray  # [G] uint32
    gram_probe: np.ndarray  # [G] int32 — owning probe index
    gram_window: np.ndarray  # [G] int32 — owning window index
    window_probe: np.ndarray  # [W] int32 — window's probe index
    window_start: np.ndarray  # [W] int32 — window offset within its probe
    probe_has_gram: np.ndarray  # [P] bool
    num_probes: int
    _wmember: np.ndarray = field(init=False, repr=False)  # [G, W] f32 0/1
    _pmember: np.ndarray = field(init=False, repr=False)  # [W, P] f32 0/1
    _pwindows: np.ndarray = field(init=False, repr=False)  # [P] f32 counts
    _bit_weights: np.ndarray = field(init=False, repr=False)  # [P-pad] uint32

    def __post_init__(self) -> None:
        w = self.num_windows
        self._wmember = np.zeros((self.num_grams, w), dtype=np.float32)
        if self.num_grams:
            self._wmember[np.arange(self.num_grams), self.gram_window] = 1.0
        self._pmember = np.zeros((w, self.num_probes), dtype=np.float32)
        if w:
            self._pmember[np.arange(w), self.window_probe] = 1.0
        self._pwindows = self._pmember.sum(axis=0)
        pw = (self.num_probes + 31) // 32
        self._bit_weights = (
            np.uint32(1) << (np.arange(pw * 32, dtype=np.uint32) % 32)
        )

    @property
    def num_grams(self) -> int:
        return len(self.masks)

    @property
    def num_windows(self) -> int:
        return len(self.window_probe)

    def probe_hits_bool(self, gram_hits: np.ndarray) -> np.ndarray:
        """[F, G] bool gram hits -> [F, P] bool probe hits.

        Probes without grams are always-hit (sound over-approximation)."""
        window_hit = gram_hits.astype(np.float32) @ self._wmember > 0  # [F, W]
        probe_hit = (
            window_hit.astype(np.float32) @ self._pmember
        ) >= self._pwindows[None, :]  # all windows present
        probe_hit[:, ~self.probe_has_gram] = True
        return probe_hit

    def probe_hits(self, gram_hits: np.ndarray) -> np.ndarray:
        """[F, G] bool gram hits -> [F, Pw] packed uint32 probe bitmaps."""
        probe_hit = self.probe_hits_bool(gram_hits)
        f = len(probe_hit)
        pw = (self.num_probes + 31) // 32
        padded = np.zeros((f, pw * 32), dtype=np.uint32)
        padded[:, : self.num_probes] = probe_hit
        return (
            (padded * self._bit_weights[None, :])
            .reshape(f, pw, 32)
            .sum(axis=-1, dtype=np.uint32)
        )


def build_gram_set(pset: ProbeSet) -> GramSet:
    masks: list[int] = []
    vals: list[int] = []
    gram_probe: list[int] = []
    gram_window: list[int] = []
    window_probe: list[int] = []
    window_start: list[int] = []
    has = np.zeros(len(pset.probes), dtype=bool)

    for p, probe in enumerate(pset.probes):
        windows = probe_gram_windows(probe.classes)
        if not windows:
            continue
        has[p] = True
        for wstart, variants in windows:
            wid = len(window_probe)
            window_probe.append(p)
            window_start.append(wstart)
            for mask, val in variants:
                masks.append(mask)
                vals.append(val)
                gram_probe.append(p)
                gram_window.append(wid)

    masks_a = np.array(masks, dtype=np.uint32)
    vals_a = np.array(vals, dtype=np.uint32)
    gram_probe_a = np.array(gram_probe, dtype=np.int32)
    gram_window_a = np.array(gram_window, dtype=np.int32)
    # Sort grams by (mask, val) for a deterministic layout; per-gram arrays
    # permute together, so attribution is unaffected.  (Kernels no longer
    # require this order — PallasGramSieve re-sorts via dedupe_grams — but
    # the numpy/native sieves and tests rely on a stable, reproducible
    # gram order across processes.)
    if len(masks_a):
        perm = np.lexsort((vals_a, masks_a))
        masks_a, vals_a = masks_a[perm], vals_a[perm]
        gram_probe_a, gram_window_a = gram_probe_a[perm], gram_window_a[perm]

    return GramSet(
        masks=masks_a,
        vals=vals_a,
        gram_probe=gram_probe_a,
        gram_window=gram_window_a,
        window_probe=np.array(window_probe, dtype=np.int32),
        window_start=np.array(window_start, dtype=np.int32),
        probe_has_gram=has,
        num_probes=len(pset.probes),
    )
