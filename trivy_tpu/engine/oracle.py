"""CPU oracle secret engine.

An exact re-implementation of the reference scan algorithm
(pkg/fanal/secret/scanner.go:371-537) in Python.  This is the differential-test
oracle for the TPU engine and the CPU fallback path — it must produce
byte-identical findings to Trivy's Go engine.

Algorithm per (file, ruleset), mirroring Scan (scanner.go:371-452):
  1. global allow-path check (:375-380)
  2. per rule: path match (:391), allow-path (:397), keyword prefilter (:403)
  3. FindLocations (:97-121) / FindSubmatchLocations for named groups (:123-143)
  4. allow-regex suppression of matched text (:145-148)
  5. exclude-block suppression (:417)
  6. cumulative censoring of match spans into a copied buffer (:425-430, :454-462)
  7. finding assembly with line numbers, truncated match line, +-2 context
     lines (:464-537)
  8. deterministic sort by (RuleID, Match) (:441-446)
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from trivy_tpu.ftypes import Code, Line, Secret, SecretFinding
from trivy_tpu.rules.model import (
    ExcludeBlock,
    Rule,
    RuleSet,
    SecretConfig,
    build_ruleset,
)

SECRET_HIGHLIGHT_RADIUS = 2  # scanner.go:479


@dataclass(frozen=True)
class Location:
    """Byte-offset span (scanner.go:223-226)."""

    start: int
    end: int

    def contains(self, other: "Location") -> bool:
        # scanner.go:228-230 Location.Match
        return self.start <= other.start and other.end <= self.end


class _Blocks:
    """Lazy exclude-region materialization (scanner.go:232-270)."""

    def __init__(self, content: bytes, regexes: list[re.Pattern[bytes]]):
        self._content = content
        self._regexes = regexes
        self._locs: list[Location] | None = None

    def match(self, loc: Location) -> bool:
        if self._locs is None:
            self._locs = [
                Location(m.start(), m.end())
                for rx in self._regexes
                for m in rx.finditer(self._content)
            ]
        return any(l.contains(loc) for l in self._locs)


class OracleScanner:
    """Mirrors secret.Scanner (scanner.go:23-26) on top of a RuleSet."""

    def __init__(self, ruleset: RuleSet | None = None, config: SecretConfig | None = None):
        self.ruleset = ruleset if ruleset is not None else build_ruleset(config)

    # -- scanner.go:50-58 Global helpers --
    def allow(self, match: bytes) -> bool:
        return self.ruleset.allow(match)

    def allow_path(self, path: str) -> bool:
        return self.ruleset.allow_path(path)

    # -- scanner.go:97-121 --
    def find_locations(self, rule: Rule, content: bytes) -> list[Location]:
        if rule.regex is None:
            return []
        if rule.secret_group_name:
            return self.find_submatch_locations(rule, content)
        locs = []
        for m in rule.regex.finditer(content):
            loc = Location(m.start(), m.end())
            if self.allow_location(rule, content, loc):
                continue
            locs.append(loc)
        return locs

    # -- scanner.go:123-143 --
    def find_submatch_locations(self, rule: Rule, content: bytes) -> list[Location]:
        assert rule.regex is not None
        out: list[Location] = []
        for m in rule.regex.finditer(content):
            whole = Location(m.start(), m.end())
            if self.allow_location(rule, content, whole):
                continue
            # getMatchSubgroupsLocations (scanner.go:150-163): spans of every
            # group whose name equals SecretGroupName.  Go allows duplicate
            # group names; the translator renames repeats and records the
            # renames (goregex.translate), which Rule.original_group_name
            # consults so user-authored lookalike names are never stripped.
            # Deliberate divergence: a group that did not participate in the
            # match (span -1) is skipped — the reference appends Location{-1,-1}
            # and would panic slicing it (latent bug, unreachable via builtins).
            for name in rule.regex.groupindex:
                if rule.original_group_name(name) == rule.secret_group_name:
                    if m.start(name) < 0:
                        continue
                    out.append(Location(m.start(name), m.end(name)))
        return out

    # -- scanner.go:145-148 --
    def allow_location(self, rule: Rule, content: bytes, loc: Location) -> bool:
        match = content[loc.start : loc.end]
        return self.allow(match) or rule.allow(match)

    # -- scanner.go:371-452 --
    def scan(
        self, file_path: str, content: bytes, rule_indices: list[int] | None = None
    ) -> Secret:
        """Scan content.  `rule_indices` optionally restricts the rule loop to a
        subset (in original order); findings are identical to a full scan as
        long as the subset contains every rule that actually matches — this is
        how device-sieve candidates are confirmed without re-running all rules.
        """
        if self.allow_path(file_path):
            return Secret(file_path=file_path)

        censored: bytearray | None = None
        matched: list[tuple[Rule, Location]] = []
        global_excluded = _Blocks(content, self.ruleset.exclude_block.regexes)
        lowered = content.lower()  # shared keyword-prefilter buffer

        rules = (
            self.ruleset.rules
            if rule_indices is None
            else [self.ruleset.rules[i] for i in rule_indices]
        )
        for rule in rules:
            if not rule.match_path(file_path):
                continue
            if rule.allow_path(file_path):
                continue
            if not rule.match_keywords(content, lowered):
                continue

            locs = self.find_locations(rule, content)
            if not locs:
                continue

            local_excluded = _Blocks(content, rule.exclude_block.regexes)
            for loc in locs:
                if global_excluded.match(loc) or local_excluded.match(loc):
                    continue
                matched.append((rule, loc))
                if censored is None:
                    censored = bytearray(content)
                censored[loc.start : loc.end] = b"*" * (loc.end - loc.start)

        if not matched:
            return Secret()

        final = bytes(censored) if censored is not None else content
        findings = [to_finding(rule, loc, final) for rule, loc in matched]
        findings.sort(key=SecretFinding.sort_key)
        return Secret(file_path=file_path, findings=findings)


def to_finding(rule: Rule, loc: Location, content: bytes) -> SecretFinding:
    """scanner.go:464-477."""
    start_line, end_line, code, match_line = find_location(loc.start, loc.end, content)
    return SecretFinding(
        rule_id=rule.id,
        category=rule.category,
        severity=rule.severity if rule.severity else "UNKNOWN",
        title=rule.title,
        match=match_line.decode("utf-8", errors="replace"),
        match_bytes=match_line,
        start_line=start_line,
        end_line=end_line,
        code=code,
    )


def find_location(start: int, end: int, content: bytes) -> tuple[int, int, Code, bytes]:
    """scanner.go:481-537 — line numbers, truncated match line, context code.

    The match line is returned as raw bytes (Go keeps it as a string over the
    original bytes); callers decode for display but sort on the bytes."""
    start_line_num = content.count(b"\n", 0, start)

    line_start = content.rfind(b"\n", 0, start)
    if line_start == -1:
        line_start = 0
    else:
        line_start += 1

    line_end = content.find(b"\n", start)
    if line_end == -1:
        line_end = len(content)

    if line_end - line_start > 100:
        line_start = 0 if start - 30 < 0 else start - 30
        line_end = len(content) if end + 20 > len(content) else end + 20
    match_line = content[line_start:line_end]
    end_line_num = start_line_num + content.count(b"\n", start, end)

    code = Code()
    lines = content.split(b"\n")
    code_start = max(start_line_num - SECRET_HIGHLIGHT_RADIUS, 0)
    code_end = min(end_line_num + SECRET_HIGHLIGHT_RADIUS, len(lines))

    raw_lines = lines[code_start:code_end]
    found_first = False
    for i, raw in enumerate(raw_lines):
        text = raw.decode("utf-8", errors="replace")
        real_line = code_start + i
        in_cause = start_line_num <= real_line <= end_line_num
        code.lines.append(
            Line(
                number=code_start + i + 1,
                content=text,
                is_cause=in_cause,
                highlighted=text,
                first_cause=(not found_first) and in_cause,
                last_cause=False,
            )
        )
        found_first = found_first or in_cause
    for ln in reversed(code.lines):
        if ln.is_cause:
            ln.last_cause = True
            break

    return start_line_num + 1, end_line_num + 1, code, match_line
