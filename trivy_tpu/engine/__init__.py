"""Secret-scan engines: goregex translation, CPU oracle, NFA compiler, device engine."""
