r"""Translate Go (RE2) regex patterns to Python `re` patterns over bytes.

The reference engine compiles rules with Go's ``regexp`` package (RE2 syntax,
pkg/fanal/secret/scanner.go:61-82).  To reproduce its matches byte-for-byte with
Python's ``re`` on bytes, a few dialect differences must be bridged:

1. **Inline flag scope.** In Go, a mid-pattern ``(?i)`` applies from that point
   to the end of the *enclosing group*; Python only allows global inline flags
   at the very start of a pattern.  We rewrite ``X(?i)Y`` → ``X(?i:Y)`` with the
   correct lexical scope (used by e.g. the `adobe-client-secret` rule
   ``(p8e-)(?i)[a-z0-9]{32}``, builtin-rules.go:293).

2. **``$`` semantics.** Without ``(?m)``, Go's ``$`` matches only at the end of
   the text; Python's ``$`` also matches before a trailing newline.  We rewrite
   ``$`` → ``\Z`` outside multiline scope.  Similarly Go ``\z`` → Python ``\Z``.

3. **``\s`` class.** RE2's ``\s`` is ``[\t\n\f\r ]``; Python's bytes ``\s`` also
   includes ``\v`` (0x0b).  We expand ``\s``/``\S`` explicitly.

Known, documented divergences (irrelevant for the builtin corpus, which is
pure-ASCII, and for content that passes the binary sniff):
  * Go does full Unicode case folding under ``(?i)``; Python bytes patterns
    fold ASCII only.
  * Go treats invalid UTF-8 bytes as U+FFFD for ``.`` and negated classes.
"""

from __future__ import annotations

import re

# RE2 \s (https://github.com/google/re2/wiki/Syntax): [\t\n\f\r ]
_RE2_SPACE = r"\t\n\f\r "
_RE2_NOT_SPACE_CLASS = r"[^\t\n\f\r ]"
_RE2_SPACE_CLASS = r"[\t\n\f\r ]"

_FLAG_CHARS = set("imsU")


class GoRegexError(ValueError):
    pass


def _parse_inline_flags(s: str, i: int) -> tuple[str, str, int] | None:
    """If s[i:] starts an inline-flag construct ``(?flags)`` or ``(?flags:``,
    return (set_flags, clear_flags, end_index_after_construct_open).

    Returns None if this is not a flag construct.
    """
    if not s.startswith("(?", i):
        return None
    j = i + 2
    set_flags = ""
    clear_flags = ""
    clearing = False
    while j < len(s):
        c = s[j]
        if c in _FLAG_CHARS:
            if clearing:
                clear_flags += c
            else:
                set_flags += c
            j += 1
        elif c == "-" and not clearing:
            clearing = True
            j += 1
        elif c in ":)":
            if not set_flags and not clear_flags:
                return None  # e.g. "(?:" plain non-capturing, or "(?P<"
            return set_flags, clear_flags, j
        else:
            return None
    return None


def _apply_flags(flags: frozenset[str], set_f: str, clear_f: str) -> frozenset[str]:
    out = set(flags)
    out.update(set_f)
    out.difference_update(clear_f)
    return frozenset(out)


def _flag_group_prefix(set_f: str, clear_f: str) -> str:
    if "U" in set_f or "U" in clear_f:
        raise GoRegexError("ungreedy flag (?U) is not supported")
    if clear_f:
        return f"(?{set_f}-{clear_f}:" if set_f else f"(?-{clear_f}:"
    return f"(?{set_f}:"


def _translate_class(s: str, i: int) -> tuple[str, int]:
    """Translate a character class starting at s[i] == '['. Returns (text, next_i)."""
    out = ["["]
    j = i + 1
    if j < len(s) and s[j] == "^":
        out.append("^")
        j += 1
    if j < len(s) and s[j] == "]":
        out.append("\\]")  # leading ']' is a literal in Go and Python alike; escape for safety
        j += 1
    while j < len(s):
        c = s[j]
        if c == "]":
            out.append("]")
            return "".join(out), j + 1
        if c == "\\":
            if j + 1 >= len(s):
                raise GoRegexError("trailing backslash in class")
            nxt = s[j + 1]
            if nxt == "s":
                out.append(_RE2_SPACE)
            elif nxt == "S":
                raise GoRegexError(r"\S inside a character class is not supported")
            elif nxt == "d":
                out.append("0-9")
            elif nxt == "w":
                out.append("0-9A-Za-z_")
            elif nxt in ("D", "W"):
                raise GoRegexError(rf"\{nxt} inside a character class is not supported")
            elif nxt == "p" or nxt == "P":
                raise GoRegexError("unicode classes \\p are not supported")
            else:
                out.append("\\" + nxt)
            j += 2
            continue
        if c == "[" and s.startswith("[:", j):
            raise GoRegexError("POSIX classes [:...:] are not supported")
        out.append(c)
        j += 1
    raise GoRegexError("unterminated character class")


_DUP_SEP = "__dup"


_DUP_SUFFIX_RE = re.compile(rf"{_DUP_SEP}\d+$")


def base_group_name(name: str) -> str:
    """Heuristic original Go group name for a deduplicated Python group name.

    Prefer the explicit rename map from :func:`translate` — this suffix
    stripping cannot distinguish a renamed repeat from a user-authored group
    literally named e.g. ``secret__dup2``.  Kept for callers without access
    to the translation's rename map.
    """
    return _DUP_SUFFIX_RE.sub("", name)


def _translate(
    s: str,
    i: int,
    flags: frozenset[str],
    seen_names: dict[str, int],
    renames: dict[str, str],
) -> tuple[str, int]:
    """Translate until an unmatched ')' (not consumed) or end of string."""
    out: list[str] = []
    while i < len(s):
        c = s[i]
        if c == ")":
            return "".join(out), i
        if c == "\\":
            if i + 1 >= len(s):
                raise GoRegexError("trailing backslash")
            nxt = s[i + 1]
            if nxt == "s":
                out.append(_RE2_SPACE_CLASS)
            elif nxt == "S":
                out.append(_RE2_NOT_SPACE_CLASS)
            elif nxt == "z":
                out.append(r"\Z")
            elif nxt in ("p", "P"):
                raise GoRegexError("unicode classes \\p are not supported")
            elif nxt == "Q":
                raise GoRegexError(r"\Q...\E quoting is not supported")
            else:
                out.append("\\" + nxt)
            i += 2
            continue
        if c == "[":
            text, i = _translate_class(s, i)
            out.append(text)
            continue
        if c == "$":
            out.append("$" if "m" in flags else r"\Z")
            i += 1
            continue
        if c == "(":
            fl = _parse_inline_flags(s, i)
            if fl is not None:
                set_f, clear_f, j = fl
                new_flags = _apply_flags(flags, set_f, clear_f)
                prefix = _flag_group_prefix(set_f, clear_f)
                if s[j] == ")":
                    # Scoped to remainder of the enclosing group: wrap the rest.
                    rest, k = _translate(s, j + 1, new_flags, seen_names, renames)
                    out.append(prefix + rest + ")")
                    return "".join(out), k
                # "(?flags: ... )" group
                body, k = _translate(s, j + 1, new_flags, seen_names, renames)
                if k >= len(s) or s[k] != ")":
                    raise GoRegexError("unterminated group")
                out.append(prefix + body + ")")
                i = k + 1
                continue
            # Other group forms: "(?:", "(?P<name>", "(?P=name" (unsupported), "("
            if s.startswith("(?:", i):
                prefix, body_start = "(?:", i + 3
            elif s.startswith("(?P<", i):
                end = s.index(">", i)
                name = s[i + 4 : end]
                n = seen_names.get(name, 0)
                seen_names[name] = n + 1
                if n:
                    # Go RE2 allows duplicate group names; Python re does
                    # not.  Pick an unused dedup name (a user-authored group
                    # may already occupy name__dupN) and record the rename.
                    cand = f"{name}{_DUP_SEP}{n}"
                    while cand in seen_names:
                        n += 1
                        cand = f"{name}{_DUP_SEP}{n}"
                    seen_names[cand] = 1
                    renames[cand] = name
                    name = cand
                prefix, body_start = f"(?P<{name}>", end + 1
            elif s.startswith("(?<", i) or s.startswith("(?'", i):
                raise GoRegexError("unsupported group syntax")
            elif s.startswith("(?P=", i) or s.startswith("(?=", i) or s.startswith("(?!", i):
                raise GoRegexError("lookaround/backreference not in RE2")
            else:
                prefix, body_start = "(", i + 1
            body, k = _translate(s, body_start, flags, seen_names, renames)
            if k >= len(s) or s[k] != ")":
                raise GoRegexError("unterminated group")
            out.append(prefix + body + ")")
            i = k + 1
            continue
        out.append(c)
        i += 1
    return "".join(out), i


def translate(pattern: str) -> tuple[str, dict[str, str]]:
    """Translate a Go RE2 pattern; returns (python pattern, rename map).

    The rename map sends each deduplicated Python group name back to its
    original Go name (duplicate names are legal in RE2, illegal in `re`);
    user-authored names are never entries in the map.
    """
    renames: dict[str, str] = {}
    text, i = _translate(pattern, 0, frozenset(), {}, renames)
    if i != len(pattern):
        raise GoRegexError(f"unbalanced ')' at {i} in {pattern!r}")
    return text, renames


def go_to_python(pattern: str) -> str:
    """Translate a Go RE2 pattern into an equivalent Python re pattern (str form)."""
    return translate(pattern)[0]


def compile_bytes(pattern: str) -> re.Pattern[bytes]:
    """Compile a Go RE2 pattern for matching over bytes content."""
    return re.compile(go_to_python(pattern).encode("utf-8"))


def compile_bytes_renamed(
    pattern: str,
) -> tuple[re.Pattern[bytes], dict[str, str]]:
    """compile_bytes plus the duplicate-group rename map (see translate)."""
    text, renames = translate(pattern)
    return re.compile(text.encode("utf-8")), renames


def compile_str(pattern: str) -> re.Pattern[str]:
    """Compile a Go RE2 pattern for matching over str (file paths)."""
    return re.compile(go_to_python(pattern))
