"""Device NFA verification of candidate (file, rule) pairs.

The TPU seat of the hybrid engine's verify stage (engine/hybrid.py step 3):
each rule's 64-position Glushkov search automaton (the same compilation
redfa.py uses for its bit-parallel fallback) becomes dense tensors, and a
batch of candidate pairs advances through `lax.scan` over byte positions:

    S'[b] = (step(S[b] @ F[rule_b]) | first[rule_b]) & accept[rule_b, c_t]

— boolean matmuls on the MXU, one scan step per byte, every pair in the
batch in parallel.  Rule count is absorbed by batching (each lane carries
its own rule's tensors, gathered once per call), which is what makes the
500-rule configuration scale: the device does the per-rule regex work the
reference runs as a host loop.

Only candidate bytes cross the link (class ids, one byte each), so the
stage pays for itself exactly when candidates are sparse — the common
case after the gram sieve.  Pairs whose rule has no 64-position automaton
or whose file exceeds the length cap pass through unverified (the host
oracle confirms them exactly, as always).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from trivy_tpu.engine.redfa import compile_search_nfa64

MAX_LEN = 1 << 15  # files above this verify on host
LEN_BUCKETS = (2048, 8192, MAX_LEN)
BATCH_BUCKETS = (64, 512, 2048)


class NfaVerifier:
    def __init__(self, rules, mesh=None):
        self.mesh = mesh  # single-program path; mesh reserved for sharding
        self.num_rules = len(rules)
        nfas = [compile_search_nfa64(r) for r in rules]
        # The dense accept tensor holds 64 classes; rules needing more fall
        # back to host confirmation (out-of-range class ids would clip and
        # silently corrupt matching).
        nfas = [
            n if (n is not None and n.num_classes <= 64) else None
            for n in nfas
        ]
        self.has_nfa = np.array([n is not None for n in nfas], dtype=bool)
        r = self.num_rules
        # Dense per-rule tensors, padded to 64 positions / 64 classes.
        self.follow = np.zeros((r, 64, 64), dtype=np.float32)
        self.accept = np.zeros((r, 64, 64), dtype=np.float32)  # [R, C, S]
        self.first = np.zeros((r, 64), dtype=np.float32)
        self.last = np.zeros((r, 64), dtype=np.float32)
        self.luts = np.zeros((r, 256), dtype=np.uint8)
        for i, nfa in enumerate(nfas):
            if nfa is None:
                continue
            m = len(nfa.follow)
            for p in range(m):
                word = int(nfa.follow[p])
                for q in range(m):
                    if word >> q & 1:
                        self.follow[i, p, q] = 1.0
            for c in range(nfa.num_classes):
                word = int(nfa.classmask[c])
                for q in range(m):
                    if word >> q & 1:
                        self.accept[i, c, q] = 1.0
            for q in range(m):
                if nfa.first >> q & 1:
                    self.first[i, q] = 1.0
                if nfa.last >> q & 1:
                    self.last[i, q] = 1.0
            self.luts[i] = nfa.byte_class
        self._tensors_on_device = None

    # ------------------------------------------------------------------

    def _device_tensors(self):
        if self._tensors_on_device is None:
            self._tensors_on_device = (
                jnp.asarray(self.follow),
                jnp.asarray(self.accept),
                jnp.asarray(self.first),
                jnp.asarray(self.last),
            )
        return self._tensors_on_device

    def warmup(self) -> None:
        self._device_tensors()

    @staticmethod
    @functools.partial(jax.jit, static_argnames=("length",))
    def _run(classes, rule_ids, follow, accept, first, last, length):
        """classes [B, L] uint8, rule_ids [B] int32 -> matched [B] bool."""
        f = follow[rule_ids]  # [B, 64, 64]
        a = accept[rule_ids]  # [B, 64, 64]  (class, state)
        fst = first[rule_ids]  # [B, 64]
        lst = last[rule_ids]  # [B, 64]

        def step(carry, t):
            state, matched = carry  # [B, 64] f32, [B] bool
            c = classes[:, t]  # [B]
            cmask = jnp.take_along_axis(
                a, c[:, None, None].astype(jnp.int32), axis=1
            )[:, 0, :]  # [B, 64]
            reach = jnp.einsum("bp,bpq->bq", state, f)
            nxt = jnp.minimum(reach + fst, 1.0) * cmask
            nxt = jnp.minimum(nxt, 1.0)
            hit = (nxt * lst).sum(axis=1) > 0
            return (nxt, matched | hit), None

        init = (jnp.zeros(classes.shape[0:1] + (64,), jnp.float32),
                jnp.zeros(classes.shape[:1], bool))
        (state, matched), _ = jax.lax.scan(
            step, init, jnp.arange(length), unroll=4
        )
        return matched

    # ------------------------------------------------------------------

    def verify(self, contents, pairs):
        """contents[i] is the bytes for pairs[i] = (fi, rule_idxs).  Flattens
        into (file, rule) lanes, drops lanes the device refutes, returns the
        surviving pairs in the same structure."""
        flat: list[tuple[int, int, bytes]] = []
        passthrough: dict[int, set[int]] = {}
        for (fi, idxs), content in zip(pairs, contents):
            for r in np.asarray(idxs).tolist():
                if not self.has_nfa[r] or len(content) > MAX_LEN:
                    passthrough.setdefault(fi, set()).add(int(r))
                else:
                    flat.append((fi, int(r), content))
        verdicts: dict[int, set[int]] = {
            fi: set(rs) for fi, rs in passthrough.items()
        }
        if flat:
            follow, accept, first, last = self._device_tensors()
            # Lanes group per length bucket (the jit specializes on the
            # static length): one 30KB candidate among thousands of small
            # ones must not pad every batch to 32768 scan steps.  A file
            # with k candidate rules still ships k class rows — per-rule
            # byte classes differ, and candidate multiplicity is small
            # after the gram sieve.
            by_len: dict[int, list] = {}
            for lane in flat:
                bucket = next(b for b in LEN_BUCKETS if len(lane[2]) <= b)
                by_len.setdefault(bucket, []).append(lane)
            for length, lanes in sorted(by_len.items()):
                batch_cap = next(
                    (b for b in BATCH_BUCKETS if len(lanes) <= b),
                    BATCH_BUCKETS[-1],
                )
                for off in range(0, len(lanes), batch_cap):
                    chunk = lanes[off : off + batch_cap]
                    b = len(chunk)
                    classes = np.zeros((batch_cap, length), dtype=np.uint8)
                    rule_ids = np.zeros(batch_cap, dtype=np.int32)
                    for k, (_fi, r, content) in enumerate(chunk):
                        data = np.frombuffer(content, dtype=np.uint8)
                        classes[k, : len(data)] = self.luts[r][data]
                        rule_ids[k] = r
                    matched = np.asarray(
                        self._run(
                            jnp.asarray(classes),
                            jnp.asarray(rule_ids),
                            follow, accept, first, last,
                            length,
                        )
                    )[:b]
                    for (fi, r, _c), hit in zip(chunk, matched):
                        if hit:
                            verdicts.setdefault(fi, set()).add(r)
        out = []
        for fi, _idxs in pairs:
            if fi in verdicts and verdicts[fi]:
                out.append(
                    (fi, np.array(sorted(verdicts[fi]), dtype=np.int64))
                )
        return out
