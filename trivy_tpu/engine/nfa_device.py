"""Device NFA verification of candidate (file, rule) pairs.

The TPU seat of the hybrid engine's verify stage (engine/hybrid.py step 3):
each rule's 64-position Glushkov search automaton (the same compilation
redfa.py uses for its bit-parallel fallback) becomes dense tensors, and a
batch of candidate lanes advances through `lax.scan` over byte positions:

    S'[g,b] = (S[g,b] @ F[g] | first[g]) & accept[g, class(byte_t)]

Kernel design notes (all measured on the bench host's TPU v5e):

* Lanes are grouped BY RULE into [G, Bg] so the follow/accept tensors are
  per-GROUP ([G, 64, 64]) rather than per-lane ([B, 64, 64]).  The per-lane
  layout made every scan step re-read a 16MB gathered accept tensor from
  HBM (~45us/step); grouped, the step's working set is ~1MB and the step
  cost drops to ~5us regardless of batch width.
* The class-mask lookup is a one-hot matmul (`onehot(c) @ accept[g]`), not
  a take_along_axis gather — the gather materialized a [B, 64, 64] repeat
  per step; the matmul reads the resident [G, 64, 64] tensor and runs on
  the MXU.
* Byte classes are fed as the scan's `xs` ([L, G, Bg], leading axis
  consumed per step) so each step reads a contiguous [G, Bg] slab instead
  of a strided minor-dimension slice.
* Rule tensors live resident on the device ([R, 64, 64], ~1MB) and are
  gathered per dispatch by group-rule ids — per-call transfer is the
  packed class bytes only.
* All arithmetic is exact in bf16 (0/1 tensors, dot products bounded by
  64 positions < 256, min-clamped to 1), so TPU dispatches use the MXU's
  native precision; CPU keeps f32.

With ``mesh`` set, the GROUP axis is sharded over all mesh axes (groups
are independent: each carries its own rule tensors, so the partitioned
program needs no collectives — the scaling-book data-parallel shape with
rule tensors as the replicated "model state").

Economics: only candidate bytes cross the link, so the stage pays for
itself exactly when verify work dominates AND the link is wide.  The
bench host's tunnel-attached chip measures ~50 MB/s host->device and
~100ms round-trip, while the host C verifier walks 300-900 MB/s (NFA
mode) to 37 GB/s (DFA mode) — on such relay links the cost gate in
engine/hybrid.py keeps verification on the host; on PCIe/ICI-attached
parts (10+ GB/s, ~100us dispatch) the same gate routes the C-slow
NFA-mode lanes here.  bench.py's verify_backend section records both the
forced-device measurement and the link probe that justifies the gate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from trivy_tpu.engine.redfa import compile_search_nfa64, compute_prefix_bounds

MAX_LEN = 1 << 15  # lanes whose walk window exceeds this verify on host
LEN_BUCKETS = (512, 1024, 2048, 4096, 8192, 16384, MAX_LEN)
GROUP_BUCKETS = (8, 16, 32, 64, 128)  # all divisible by the 8-device mesh
LANES_PER_GROUP = 64
_NO_TRIM = np.iinfo(np.int32).max


class NfaVerifier:
    def __init__(self, rules, mesh=None, trimmable=None, prefix_bounds=None):
        self.mesh = mesh
        self.num_rules = len(rules)
        # Walk-window trim bound, shared with the host DfaVerifier (the
        # dfa_verify_pairs clip [first - bound, last + bound + 8]) —
        # refutation soundness requires both verifiers to clip identically,
        # so the engine passes one compute_prefix_bounds array to both.
        self.prefix_bound = np.asarray(
            prefix_bounds
            if prefix_bounds is not None
            else compute_prefix_bounds(rules, trimmable),
            dtype=np.int64,
        )
        nfas = [compile_search_nfa64(r) for r in rules]
        # The dense accept tensor holds 64 classes; rules needing more fall
        # back to host confirmation (out-of-range class ids would clip and
        # silently corrupt matching).
        nfas = [
            n if (n is not None and n.num_classes <= 64) else None
            for n in nfas
        ]
        self.has_nfa = np.array([n is not None for n in nfas], dtype=bool)
        r = self.num_rules
        # Dense per-rule tensors, padded to 64 positions / 64 classes.
        self.follow = np.zeros((r, 64, 64), dtype=np.float32)
        self.accept = np.zeros((r, 64, 64), dtype=np.float32)  # [R, C, S]
        self.first = np.zeros((r, 64), dtype=np.float32)
        self.last = np.zeros((r, 64), dtype=np.float32)
        self.luts = np.zeros((r, 256), dtype=np.uint8)
        for i, nfa in enumerate(nfas):
            if nfa is None:
                continue
            m = len(nfa.follow)
            for p in range(m):
                word = int(nfa.follow[p])
                for q in range(m):
                    if word >> q & 1:
                        self.follow[i, p, q] = 1.0
            for c in range(nfa.num_classes):
                word = int(nfa.classmask[c])
                for q in range(m):
                    if word >> q & 1:
                        self.accept[i, c, q] = 1.0
            for q in range(m):
                if nfa.first >> q & 1:
                    self.first[i, q] = 1.0
                if nfa.last >> q & 1:
                    self.last[i, q] = 1.0
            self.luts[i] = nfa.byte_class
        self._tensors_on_device = None

    # ------------------------------------------------------------------

    def _shardings(self):
        """(group-sharded [L,G,Bg], gid-sharded [G], replicated) specs, or
        Nones without a mesh."""
        if self.mesh is None:
            return None, None, None
        from jax.sharding import NamedSharding, PartitionSpec as P

        axes = tuple(self.mesh.axis_names)
        return (
            NamedSharding(self.mesh, P(None, axes, None)),
            NamedSharding(self.mesh, P(axes)),
            NamedSharding(self.mesh, P()),
        )

    def _compute_dtype(self):
        return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32

    def _device_tensors(self):
        if self._tensors_on_device is None:
            dt = self._compute_dtype()
            arrs = (
                self.follow.astype(dt),
                self.accept.astype(dt),
                self.first.astype(dt),
                self.last.astype(dt),
            )
            _, _, rep = self._shardings()
            if rep is not None:
                self._tensors_on_device = tuple(
                    jax.device_put(a, rep) for a in arrs
                )
            else:
                self._tensors_on_device = tuple(jnp.asarray(a) for a in arrs)
        return self._tensors_on_device

    def _put(self, classes_t: np.ndarray, gids: np.ndarray):
        cls_sh, gid_sh, _ = self._shardings()
        if cls_sh is None:
            return jnp.asarray(classes_t), jnp.asarray(gids)
        return jax.device_put(classes_t, cls_sh), jax.device_put(gids, gid_sh)

    def warmup(self, compile_buckets: bool = False) -> None:
        """Ship rule tensors; with ``compile_buckets`` also pre-compile the
        jit specializations bulk work actually hits: every length bucket at
        the largest group count (big batches ride max-G dispatches) plus
        small-G tails for the short lengths.  Rare shapes (small-G tails of
        long buckets) still compile on first use."""
        tensors = self._device_tensors()
        if not compile_buckets:
            return
        combos = [(ln, GROUP_BUCKETS[-1]) for ln in LEN_BUCKETS]
        combos += [
            (ln, g) for ln in LEN_BUCKETS[:2] for g in GROUP_BUCKETS[:-1]
        ]
        for length, g in combos:
            classes_t, gids = self._put(
                np.zeros((length, g, LANES_PER_GROUP), dtype=np.uint8),
                np.zeros(g, dtype=np.int32),
            )
            self._run(classes_t, gids, *tensors).block_until_ready()

    @staticmethod
    @jax.jit
    def _run(classes_t, gids, follow, accept, first, last):
        """classes_t [L, G, Bg] uint8, gids [G] int32 -> matched [G, Bg].

        Rule tensors are resident [R, ...]; per-group tensors gather once
        outside the scan.  The step body is two small batched matmuls
        (one-hot class mask, follow reachability) plus elementwise ops —
        per-step HBM traffic is the [G, Bg] byte slab and the [G, 64, 64]
        group tensors."""
        dt = follow.dtype
        f = follow[gids]  # [G, 64, 64]
        a = accept[gids]  # [G, C=64, S=64]
        fst = first[gids][:, None, :]  # [G, 1, 64]
        lst = last[gids][:, None, :]  # [G, 1, 64]
        one = dt.type(1)

        def step(carry, c):
            state, matched = carry  # [G, Bg, 64] dt, [G, Bg] bool
            oh = jax.nn.one_hot(c, 64, dtype=dt)  # [G, Bg, 64]
            cmask = jnp.einsum(
                "gbc,gcs->gbs", oh, a, preferred_element_type=dt
            )
            reach = jnp.einsum(
                "gbp,gpq->gbq", state, f, preferred_element_type=dt
            )
            nxt = jnp.minimum(jnp.minimum(reach + fst, one) * cmask, one)
            hit = (nxt * lst).sum(axis=2) > 0
            return (nxt, matched | hit), None

        init = (
            jnp.zeros(classes_t.shape[1:3] + (64,), dt),
            jnp.zeros(classes_t.shape[1:3], bool),
        )
        (_state, matched), _ = jax.lax.scan(
            step, init, classes_t, unroll=8
        )
        return matched

    # ------------------------------------------------------------------

    def _windows(self, pairs: np.ndarray, lens: np.ndarray):
        """Per-lane walk windows [start, stop) over pairs [N, 4] columns
        (file, rule, first_hint, last_hint) — the dfa_verify_pairs clip:
        trimmable rules walk [first - bound, last + bound + 8], untrimmable
        walk the whole file."""
        flen = lens[pairs[:, 0]]
        bound = self.prefix_bound[pairs[:, 1]]
        trim = bound != _NO_TRIM
        start = np.where(
            trim, np.maximum(pairs[:, 2].astype(np.int64) - bound, 0), 0
        )
        stop = np.where(
            trim,
            np.minimum(pairs[:, 3].astype(np.int64) + bound + 8, flen),
            flen,
        )
        return start, np.maximum(stop, start)

    def device_eligible(self, pairs: np.ndarray, lens: np.ndarray):
        """bool[N]: the lane's rule has a 64-position automaton and its
        trim-clipped walk window fits the device length cap.  Trimming is
        what makes big files eligible: a 1MB file whose gram hits sit in
        one region still verifies as a few-hundred-byte lane."""
        if not len(pairs):
            return np.zeros(0, dtype=bool)
        start, stop = self._windows(pairs, lens)
        return self.has_nfa[pairs[:, 1]] & (stop - start <= MAX_LEN)

    def verify_lanes(
        self, contents: list[bytes], pairs: np.ndarray, lens: np.ndarray
    ) -> np.ndarray:
        """bool[N] keep-mask for device-eligible lanes.  contents[i] is the
        full file bytes for pairs[i, 0]; the lane ships only its clipped
        walk window.  Lanes sort by (window bucket, rule), pack into
        [G, LANES_PER_GROUP] groups per length bucket, and dispatch once
        per (bucket, group-chunk) — dispatch count stays O(buckets), not
        O(lanes), which matters when the link round-trip is the fixed
        cost."""
        n = len(pairs)
        keep = np.zeros(n, dtype=bool)
        if n == 0:
            return keep
        start, stop = self._windows(pairs, lens)
        wlen = stop - start
        bucket = np.searchsorted(np.array(LEN_BUCKETS), wlen, side="left")
        order = np.lexsort((pairs[:, 1], bucket))
        tensors = self._device_tensors()
        # Phase 1: assemble + dispatch every (bucket, group-chunk) — JAX
        # dispatch is async, so transfers and executions of later chunks
        # overlap earlier ones.  Phase 2: fetch verdicts.
        in_flight: list[tuple[list[np.ndarray], object]] = []
        pos = 0
        while pos < len(order):
            bk = bucket[order[pos]]
            end = int(
                np.searchsorted(bucket[order], bk, side="right")
            )
            lanes = order[pos:end]
            pos = end
            length = LEN_BUCKETS[bk]
            # split the bucket's lanes into per-rule groups of Bg
            groups: list[np.ndarray] = []
            gstart = 0
            rules = pairs[lanes, 1]
            for i in range(1, len(lanes) + 1):
                if i == len(lanes) or rules[i] != rules[gstart]:
                    for off in range(gstart, i, LANES_PER_GROUP):
                        groups.append(lanes[off : min(off + LANES_PER_GROUP, i)])
                    gstart = i
            gi = 0
            while gi < len(groups):
                gcap = next(
                    (g for g in GROUP_BUCKETS if len(groups) - gi <= g),
                    GROUP_BUCKETS[-1],
                )
                chunk = groups[gi : gi + gcap]
                gi += gcap
                classes = np.zeros(
                    (gcap, LANES_PER_GROUP, length), dtype=np.uint8
                )
                gids = np.zeros(gcap, dtype=np.int32)
                for g, lane_idx in enumerate(chunk):
                    r = int(pairs[lane_idx[0], 1])
                    gids[g] = r
                    lut = self.luts[r]
                    for b, li in enumerate(lane_idx):
                        data = np.frombuffer(contents[li], dtype=np.uint8)[
                            start[li] : stop[li]
                        ]
                        classes[g, b, : len(data)] = lut[data]
                classes_t = np.ascontiguousarray(classes.transpose(2, 0, 1))
                cd, gd = self._put(classes_t, gids)
                in_flight.append((chunk, self._run(cd, gd, *tensors)))
        for chunk, out in in_flight:
            matched = np.asarray(out)
            for g, lane_idx in enumerate(chunk):
                keep[lane_idx] = matched[g, : len(lane_idx)]
        return keep
