"""Device NFA verification of candidate (file, rule) pairs.

The TPU seat of the hybrid engine's verify stage (engine/hybrid.py step 3):
each rule's 64-position Glushkov search automaton (the same compilation
redfa.py uses for its bit-parallel fallback) becomes dense tensors, and a
batch of candidate lanes advances through `lax.scan` over byte positions:

    S'[g,b] = (S[g,b] @ F[g] | first[g]) & accept[g, class(byte_t)]

Kernel design notes (all measured on the bench host's TPU v5e):

* Lanes are grouped BY RULE into [G, Bg] so the follow/accept tensors are
  per-GROUP ([G, 64, 64]) rather than per-lane ([B, 64, 64]).  The per-lane
  layout made every scan step re-read a 16MB gathered accept tensor from
  HBM (~45us/step); grouped, the step's working set is ~1MB and the step
  cost drops to ~5us regardless of batch width.
* The class-mask lookup is a one-hot matmul (`onehot(c) @ accept[g]`), not
  a take_along_axis gather — the gather materialized a [B, 64, 64] repeat
  per step; the matmul reads the resident [G, 64, 64] tensor and runs on
  the MXU.
* Byte classes are fed as the scan's `xs` ([L, G, Bg], leading axis
  consumed per step) so each step reads a contiguous [G, Bg] slab instead
  of a strided minor-dimension slice.
* Rule tensors live resident on the device ([R, 64, 64], ~1MB) and are
  gathered per dispatch by group-rule ids — per-call transfer is the
  packed class bytes only.
* All arithmetic is exact in bf16 (0/1 tensors, dot products bounded by
  64 positions < 256, min-clamped to 1), so TPU dispatches use the MXU's
  native precision; CPU keeps f32.

With ``mesh`` set, the GROUP axis is sharded over all mesh axes (groups
are independent: each carries its own rule tensors, so the partitioned
program needs no collectives — the scaling-book data-parallel shape with
rule tensors as the replicated "model state").

Economics: only candidate bytes cross the link, so the stage pays for
itself exactly when verify work dominates AND the link is wide.  The
bench host's tunnel-attached chip measures ~50 MB/s host->device and
~100ms round-trip, while the host C verifier walks 300-900 MB/s (NFA
mode) to 37 GB/s (DFA mode) — on such relay links the cost gate in
engine/hybrid.py keeps verification on the host; on PCIe/ICI-attached
parts (10+ GB/s, ~100us dispatch) the same gate routes the C-slow
NFA-mode lanes here.  bench.py's verify_backend section records both the
forced-device measurement and the link probe that justifies the gate.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from trivy_tpu import faults
from trivy_tpu.engine.redfa import compile_search_nfa64, compute_prefix_bounds
from trivy_tpu.obs import memwatch
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import trace as obs_trace

MAX_LEN = 1 << 15  # lanes whose walk window exceeds this verify on host
LEN_BUCKETS = (512, 1024, 2048, 4096, 8192, 16384, MAX_LEN)
GROUP_BUCKETS = (8, 16, 32, 64, 128)  # all divisible by the 8-device mesh
LANES_PER_GROUP = 64
_NO_TRIM = np.iinfo(np.int32).max

# Dense multi-rule stream (the production dispatch shape).  The link is
# the scarce resource, device exec is nearly free, so: each FILE's span
# (the union of its candidate pairs' walk windows) crosses the link
# exactly ONCE, packed back-to-back with one 0x00 separator into fixed
# rows of RAW BYTES, and every distinct candidate rule's automaton runs
# sequentially over the same resident rows inside a single dispatch
# (lax.map over stacked per-rule tensors).  Per-rule accept tensors are
# per-BYTE ([256, 64], class translation folded in at build, byte 0
# forced dead), so the host never class-translates lanes in Python.
# Output is one hit flag per rule per 32-position block (device-side OR
# over the block), keeping d2h at R/32nd of h2d.  Spans containing a
# real 0x00 byte, spans longer than the jumbo row, and rules beyond the
# per-dispatch rule stack take the padded class-bucket path.
STREAM_TIERS = (512, 4096, 16384)  # row lengths; spans take the smallest fit
STREAM_ROW_LEN = STREAM_TIERS[1]  # compat alias (tests, docs)
JUMBO_ROW_LEN = STREAM_TIERS[2]
STREAM_BLOCK = 32  # positions OR-compressed into one output flag
RULE_STACK_BUCKETS = (4, 8, 16, 32)  # jit-stable per-dispatch rule counts
# Small batches dispatch narrow G without a mesh; meshed runs keep the
# 8-divisible buckets.
STREAM_GROUP_BUCKETS = (1, 2, 4) + GROUP_BUCKETS
PAD_CLASS = 63

# Fused path (verify="fused", engine/hybrid.py): the SAME packed rows,
# but lane verdicts resolve ON-DEVICE — the dispatch carries the lane
# table (row, rule slot, first/last block) alongside the bytes, and the
# only d2h is one packed keep-mask bit per lane (link.fetch_mask_packed)
# instead of the [ceil(R/8), Lo, G, Bg] flag map.  The block walk can
# additionally run as an associative scan over per-block affine
# summaries (SURVEY §7.4's fused shape) — O(log Lo) depth at 64x the
# per-block state, so "auto" only picks it when the summary tensors fit
# the budget below.
FUSED_ASSOC_BUDGET_BYTES = 64 << 20


def fused_scan_mode() -> str:
    """Fused-kernel block-walk strategy: "seq" carries NFA state across
    32-byte blocks with the sequential lax.scan the legacy stream uses;
    "assoc" folds each block into one affine summary ([64, 64] transfer
    matrix + offset vector) and combines summaries with
    jax.lax.associative_scan.  "auto" (default) picks assoc only when the
    dispatch's summary tensors fit FUSED_ASSOC_BUDGET_BYTES.
    TRIVY_TPU_FUSED_SCAN overrides."""
    mode = os.environ.get("TRIVY_TPU_FUSED_SCAN", "auto").strip().lower()
    return mode if mode in ("auto", "assoc", "seq") else "auto"


class NfaVerifier:
    def __init__(self, rules, mesh=None, trimmable=None, prefix_bounds=None,
                 fused=False, rule_stack=None, sieve_kernel_id=""):
        self.mesh = mesh
        self.num_rules = len(rules)
        # Fused mode: resolve lane verdicts on-device and fetch only the
        # packed keep-mask.  Mutable — the serve scheduler's degraded
        # ladder flips it off for a legacy-stream retry (see
        # HybridSecretEngine.scan_batch_device_legacy).
        self.fused = bool(fused)
        # Provenance label for the sieve program that produced the
        # candidate lanes this verifier walks (the megakernel's kernel id
        # when the one-dispatch fused sieve fed them — ops/megakernel.py;
        # empty for host/native sieves).  Surfaced in stream_stats so
        # /debug and merged profiles attribute verify work to the kernel
        # generation that routed it; registry aot_warmup threads it from
        # the engine's built program.
        self.sieve_kernel_id = str(sieve_kernel_id)
        # Walk-window trim bound, shared with the host DfaVerifier (the
        # dfa_verify_pairs clip [first - bound, last + bound + 8]) —
        # refutation soundness requires both verifiers to clip identically,
        # so the engine passes one compute_prefix_bounds array to both.
        self.prefix_bound = np.asarray(
            prefix_bounds
            if prefix_bounds is not None
            else compute_prefix_bounds(rules, trimmable),
            dtype=np.int64,
        )
        nfas = [compile_search_nfa64(r) for r in rules]
        # The dense accept tensor holds 64 classes; rules needing more fall
        # back to host confirmation (out-of-range class ids would clip and
        # silently corrupt matching).
        nfas = [
            n if (n is not None and n.num_classes <= 64) else None
            for n in nfas
        ]
        self.has_nfa = np.array([n is not None for n in nfas], dtype=bool)
        # Stream machinery: per-rule raw-byte tensors build lazily and
        # cache for the process lifetime.
        self._nfas = nfas
        self._byte_tensor_cache: dict[int, tuple] = {}
        r = self.num_rules
        # Dense per-rule tensors, padded to 64 positions / 64 classes.
        self.follow = np.zeros((r, 64, 64), dtype=np.float32)
        self.accept = np.zeros((r, 64, 64), dtype=np.float32)  # [R, C, S]
        self.first = np.zeros((r, 64), dtype=np.float32)
        self.last = np.zeros((r, 64), dtype=np.float32)
        self.luts = np.zeros((r, 256), dtype=np.uint8)
        for i, nfa in enumerate(nfas):
            if nfa is None:
                continue
            m = len(nfa.follow)
            for p in range(m):
                word = int(nfa.follow[p])
                for q in range(m):
                    if word >> q & 1:
                        self.follow[i, p, q] = 1.0
            for c in range(nfa.num_classes):
                word = int(nfa.classmask[c])
                for q in range(m):
                    if word >> q & 1:
                        self.accept[i, c, q] = 1.0
            for q in range(m):
                if nfa.first >> q & 1:
                    self.first[i, q] = 1.0
                if nfa.last >> q & 1:
                    self.last[i, q] = 1.0
            self.luts[i] = nfa.byte_class
        self._tensors_on_device = None
        if rule_stack is not None:
            self._seed_rule_stack(rule_stack)

    def _seed_rule_stack(self, stack) -> None:
        """Pre-seed the per-rule byte-tensor cache from a registry
        artifact's stacked uint8 rule tensors (registry/store.py schema 3
        `vstack_*` arrays, built by `build_rule_stack`), so warm starts
        skip the per-rule Python tensor build.  A stack whose rule count
        mismatches is ignored — the lazy per-rule path stays correct."""
        try:
            has = np.asarray(stack["vstack_has"]).astype(bool)
            fol = np.asarray(stack["vstack_follow"], dtype=np.float32)
            acc = np.asarray(stack["vstack_accept_b"], dtype=np.float32)
            fst = np.asarray(stack["vstack_first"], dtype=np.float32)
            lst = np.asarray(stack["vstack_last"], dtype=np.float32)
        except (KeyError, TypeError):
            return
        if len(has) != self.num_rules:
            return
        for r in range(self.num_rules):
            if has[r] and self._nfas[r] is not None:
                self._byte_tensor_cache[r] = (fol[r], acc[r], fst[r], lst[r])

    # ------------------------------------------------------------------

    def _shardings(self):
        """(group-sharded [L,G,Bg], gid-sharded [G], replicated) specs
        from the partition plan (mesh/plan.py), or Nones without a mesh."""
        if self.mesh is None:
            return None, None, None
        from trivy_tpu.mesh import plan as mesh_plan

        return (
            mesh_plan.sharding_for(self.mesh, "padded_classes"),
            mesh_plan.sharding_for(self.mesh, "lane_tables"),
            mesh_plan.sharding_for(self.mesh, "vstack_rules"),
        )

    def _compute_dtype(self):
        from trivy_tpu.mesh import topology as mesh_topology

        return jnp.bfloat16 if mesh_topology.backend_is_tpu() else jnp.float32

    def _device_tensors(self):
        if self._tensors_on_device is None:
            dt = self._compute_dtype()
            arrs = (
                self.follow.astype(dt),
                self.accept.astype(dt),
                self.first.astype(dt),
                self.last.astype(dt),
            )
            _, _, rep = self._shardings()
            if rep is not None:
                self._tensors_on_device = tuple(
                    jax.device_put(a, rep) for a in arrs
                )
            else:
                self._tensors_on_device = tuple(jnp.asarray(a) for a in arrs)
            # Compiled-ruleset NFA tensors are the canonical long-lived
            # device allocation: ledger them for the verifier's lifetime
            # (the pool's measured-byte accounting reads this back via
            # the ambient ruleset-digest tag).
            memwatch.track(
                "nfa-tensors",
                memwatch.nbytes_of(self._tensors_on_device),
                owner=self,
            )
        return self._tensors_on_device

    def _put(self, classes_t: np.ndarray, gids: np.ndarray):
        cls_sh, gid_sh, _ = self._shardings()
        if cls_sh is None:
            return jnp.asarray(classes_t), jnp.asarray(gids)
        return jax.device_put(classes_t, cls_sh), jax.device_put(gids, gid_sh)

    def warmup(self, compile_buckets: bool = False) -> None:  # graftlint: fetch-boundary
        """Ship rule tensors; with ``compile_buckets`` also pre-compile the
        jit specializations bulk work actually hits: every length bucket at
        the largest group count (big batches ride max-G dispatches) plus
        small-G tails for the short lengths.  Rare shapes (small-G tails of
        long buckets) still compile on first use."""
        tensors = self._device_tensors()
        if not compile_buckets:
            return
        combos = [(ln, GROUP_BUCKETS[-1]) for ln in LEN_BUCKETS]
        combos += [
            (ln, g) for ln in LEN_BUCKETS[:2] for g in GROUP_BUCKETS[:-1]
        ]
        for length, g in combos:
            classes_t, gids = self._put(
                np.zeros((length, g, LANES_PER_GROUP), dtype=np.uint8),
                np.zeros(g, dtype=np.int32),
            )
            self._run(classes_t, gids, *tensors).block_until_ready()
        # multi-rule stream shapes: the two big row tiers at the largest
        # group chunk, a mid-size rule stack (TPU path only — the CPU
        # gather variant compiles in milliseconds on first use)
        jdt = self._compute_dtype()
        if jdt == jnp.bfloat16:
            rb = RULE_STACK_BUCKETS[1]
            zt = lambda *s: jnp.zeros(s, jdt)
            for length in STREAM_TIERS[1:]:
                bd = self._put_stream(
                    np.zeros(
                        (
                            length // STREAM_BLOCK, STREAM_BLOCK,
                            GROUP_BUCKETS[-1], LANES_PER_GROUP,
                        ),
                        dtype=np.uint8,
                    )
                )
                self._run_stream_multi(
                    bd, zt(rb, 64, 64), zt(rb, 256, 64), zt(rb, 64),
                    zt(rb, 64),
                ).block_until_ready()
            if self.fused:
                # the fused verdict shape big batches actually hit: large
                # row tier, max group chunk, minimal lane table (lane
                # counts pad to powers of two, so other widths are cheap
                # incremental compiles); lane tables take their plan
                # placement so the meshed specialization is the one
                # production dispatches hit
                bd = self._put_stream(
                    np.zeros(
                        (
                            STREAM_TIERS[1] // STREAM_BLOCK, STREAM_BLOCK,
                            GROUP_BUCKETS[-1], LANES_PER_GROUP,
                        ),
                        dtype=np.uint8,
                    )
                )
                lane = self._put_lanes(np.zeros(8, np.int32))
                self._run_fused(
                    bd, zt(rb, 64, 64), zt(rb, 256, 64), zt(rb, 64),
                    zt(rb, 64), lane, lane, lane, lane,
                    onehot=True, assoc=False,
                ).block_until_ready()

    @staticmethod
    @jax.jit
    def _run(classes_t, gids, follow, accept, first, last):
        """classes_t [L, G, Bg] uint8, gids [G] int32 -> matched [G, Bg].

        Rule tensors are resident [R, ...]; per-group tensors gather once
        outside the scan.  The step body is two small batched matmuls
        (one-hot class mask, follow reachability) plus elementwise ops —
        per-step HBM traffic is the [G, Bg] byte slab and the [G, 64, 64]
        group tensors."""
        dt = follow.dtype
        f = follow[gids]  # [G, 64, 64]
        a = accept[gids]  # [G, C=64, S=64]
        fst = first[gids][:, None, :]  # [G, 1, 64]
        lst = last[gids][:, None, :]  # [G, 1, 64]
        one = dt.type(1)

        def step(carry, c):
            state, matched = carry  # [G, Bg, 64] dt, [G, Bg] bool
            oh = jax.nn.one_hot(c, 64, dtype=dt)  # [G, Bg, 64]
            cmask = jnp.einsum(
                "gbc,gcs->gbs", oh, a, preferred_element_type=dt
            )
            reach = jnp.einsum(
                "gbp,gpq->gbq", state, f, preferred_element_type=dt
            )
            nxt = jnp.minimum(jnp.minimum(reach + fst, one) * cmask, one)
            hit = (nxt * lst).sum(axis=2) > 0
            return (nxt, matched | hit), None

        init = (
            jnp.zeros(classes_t.shape[1:3] + (64,), dt),
            jnp.zeros(classes_t.shape[1:3], bool),
        )
        (_state, matched), _ = jax.lax.scan(
            step, init, classes_t, unroll=8
        )
        return matched

    @staticmethod
    @jax.jit
    def _run_stream_multi(bytes_t, follow, accept_b, first, last):
        """bytes_t [Lo, 32, G, Bg] uint8 RAW BYTES x per-rule tensors
        stacked on a leading R axis -> hit flags [R, Lo, G, Bg] uint8:
        1 iff a match of rule slot r ends in positions [32j, 32j+32) of
        that row.

        Every rule's automaton scans the SAME resident byte rows
        (lax.map over the rule stack) — the bytes cross the link once no
        matter how many rules claim a file, which is the whole economics
        of the stream path (exec is cheap, transfers are not).  The
        automaton consumes raw bytes through the per-byte accept tensor
        (accept_b[r, byte, state] — class translation folded in at
        build), state carries across 32-blocks, and byte 0x00 is forced
        dead so the one-byte span separators reset matching."""
        return NfaVerifier._stream_multi_impl(
            bytes_t, follow, accept_b, first, last, onehot=True
        )

    @staticmethod
    @jax.jit
    def _run_stream_multi_gather(bytes_t, follow, accept_b, first, last):
        """CPU variant of _run_stream_multi: the per-byte accept lookup
        is a gather (fast on CPU) instead of the one-hot matmul the MXU
        wants; results are identical."""
        return NfaVerifier._stream_multi_impl(
            bytes_t, follow, accept_b, first, last, onehot=False
        )

    @staticmethod
    def _stream_multi_impl(bytes_t, follow, accept_b, first, last, onehot):
        dt = follow.dtype
        one = dt.type(1)

        def per_rule(tens):
            f, a, fs, ls = tens  # [64,64] [256,64] [64] [64]
            fsb = fs[None, None, :]
            lsb = ls[None, None, :]

            def blk_step(state, blk):  # blk [32, G, Bg]
                hit0 = jnp.zeros(state.shape[:2], dtype=bool)

                def inner(i, sh):
                    st, hit = sh
                    if onehot:
                        oh = jax.nn.one_hot(blk[i], 256, dtype=dt)
                        cmask = jnp.einsum(
                            "gbc,cs->gbs", oh, a,
                            preferred_element_type=dt,
                        )
                    else:
                        cmask = a[blk[i]]  # [G, Bg, 64] gather
                    reach = jnp.einsum(
                        "gbp,pq->gbq", st, f, preferred_element_type=dt
                    )
                    nxt = jnp.minimum(
                        jnp.minimum(reach + fsb, one) * cmask, one
                    )
                    return nxt, hit | ((nxt * lsb).sum(-1) > 0)

                st, hit = jax.lax.fori_loop(
                    0, blk.shape[0], inner, (state, hit0)
                )
                return st, hit.astype(jnp.uint8)

            init = jnp.zeros(bytes_t.shape[2:4] + (64,), dt)
            _st, ys = jax.lax.scan(blk_step, init, bytes_t)
            return ys  # [Lo, G, Bg] uint8

        flags = jax.lax.map(per_rule, (follow, accept_b, first, last))
        return NfaVerifier._pack_rule_flags(flags)

    @staticmethod
    def _pack_rule_flags(flags):
        """[R, Lo, G, Bg] uint8 -> [ceil(R/8), Lo, G, Bg] uint8, 8 rule
        slots per byte: d2h shrinks R/ceil(R/8)-fold."""
        r = flags.shape[0]
        rp = -(-r // 8)
        pad = jnp.zeros((rp * 8 - r,) + flags.shape[1:], flags.dtype)
        grouped = jnp.concatenate([flags, pad]).reshape(
            (rp, 8) + flags.shape[1:]
        )
        w8 = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
        return jnp.einsum(
            "pk...,k->p...", grouped, w8,
            preferred_element_type=jnp.int32,
        ).astype(jnp.uint8)  # [ceil(R/8), Lo, G, Bg]

    @staticmethod
    def _stream_assoc_impl(bytes_t, follow, accept_b, first, last, onehot):
        """Associative-scan variant of `_stream_multi_impl`: per 32-byte
        block, fold the byte steps into one affine summary — transfer
        matrix M [64, 64], offset v [64] (state contribution born inside
        the block), plus hit detectors a [64] / b [] — then combine
        summaries across the row's blocks with
        ``jax.lax.associative_scan`` instead of a sequential carry.

        Soundness: the per-byte step S' = min(min(S@F + first, 1) * cmask,
        1) is affine in S over min-clamped {0,1} tensors (clamping is pure
        normalization — positivity is what carries meaning), so byte maps
        compose as (M, v) pairs and a block's hit test reduces to
        (S_enter . a) + b > 0.  Byte-exact vs the sequential path in bf16:
        every matmul partial sum is an integer bounded by 65 < 256.
        Memory: one rule's summaries are [Lo, G, Bg, 64, 64] — 64x the
        sequential block state — so dispatch sites budget-gate this path
        (FUSED_ASSOC_BUDGET_BYTES)."""
        dt = follow.dtype
        one = dt.type(1)
        _, _, g, bg = bytes_t.shape

        def per_rule(tens):
            f, a, fs, ls = tens  # [64,64] [256,64] [64] [64]
            fsb = fs[None, None, :]

            def block_summary(blk):  # [32, G, Bg]
                m0 = jnp.broadcast_to(
                    jnp.eye(64, dtype=dt), (g, bg, 64, 64)
                )
                v0 = jnp.zeros((g, bg, 64), dt)
                a0 = jnp.zeros((g, bg, 64), dt)
                b0 = jnp.zeros((g, bg), dt)

                def inner(i, carry):
                    m, v, av, bv = carry
                    if onehot:
                        oh = jax.nn.one_hot(blk[i], 256, dtype=dt)
                        cmask = jnp.einsum(
                            "gbc,cs->gbs", oh, a,
                            preferred_element_type=dt,
                        )
                    else:
                        cmask = a[blk[i]]  # [G, Bg, 64] gather
                    m2 = jnp.minimum(
                        jnp.einsum(
                            "gbpr,rq->gbpq", m, f,
                            preferred_element_type=dt,
                        ) * cmask[:, :, None, :],
                        one,
                    )
                    v2 = jnp.minimum(
                        (jnp.einsum(
                            "gbp,pq->gbq", v, f,
                            preferred_element_type=dt,
                        ) + fsb) * cmask,
                        one,
                    )
                    av2 = jnp.minimum(
                        av + jnp.einsum(
                            "gbpq,q->gbp", m2, ls,
                            preferred_element_type=dt,
                        ),
                        one,
                    )
                    bv2 = jnp.minimum(
                        bv + jnp.einsum(
                            "gbq,q->gb", v2, ls,
                            preferred_element_type=dt,
                        ),
                        one,
                    )
                    return m2, v2, av2, bv2

                return jax.lax.fori_loop(
                    0, blk.shape[0], inner, (m0, v0, a0, b0)
                )

            summ_m, summ_v, det_a, det_b = jax.vmap(block_summary)(bytes_t)

            def compose(x, y):
                m1, v1 = x
                m2, v2 = y
                return (
                    jnp.minimum(
                        jnp.einsum(
                            "...pr,...rq->...pq", m1, m2,
                            preferred_element_type=dt,
                        ),
                        one,
                    ),
                    jnp.minimum(
                        jnp.einsum(
                            "...p,...pq->...q", v1, m2,
                            preferred_element_type=dt,
                        ) + v2,
                        one,
                    ),
                )

            _m_incl, v_incl = jax.lax.associative_scan(
                compose, (summ_m, summ_v), axis=0
            )
            # entering state of block j = composed offset of blocks < j
            # applied to the zero init state (exclusive shift)
            enter = jnp.concatenate(
                [jnp.zeros_like(v_incl[:1]), v_incl[:-1]], axis=0
            )
            hit = (
                jnp.einsum(
                    "lgbp,lgbp->lgb", enter, det_a,
                    preferred_element_type=dt,
                ) + det_b
            ) > 0
            return hit.astype(jnp.uint8)  # [Lo, G, Bg]

        flags = jax.lax.map(per_rule, (follow, accept_b, first, last))
        return NfaVerifier._pack_rule_flags(flags)

    @staticmethod
    @functools.partial(jax.jit, static_argnames=("onehot", "assoc"))
    def _run_fused(bytes_t, follow, accept_b, first, last,
                   lane_row, lane_slot, lane_b0, lane_b1,
                   onehot, assoc):
        """The fused verify dispatch: bytes_t [Lo, 32, G, Bg] raw bytes x
        stacked rule tensors x a lane table (lane_* [N] int32: packed row,
        rule slot, first/exclusive-last 32-block of the lane's window) ->
        packed keep-mask uint8 [N/8].

        Block flags compute exactly as the legacy stream kernel (or its
        associative-scan variant), but the per-lane verdict — "any hit
        block in [b0, b1)" — resolves HERE, on device, via a cumulative
        block-sum gather, so the only d2h is one bit per lane.  Padded
        lane-table entries (row = slot = b0 = b1 = 0) resolve False by
        construction (empty block range)."""
        if assoc:
            flags = NfaVerifier._stream_assoc_impl(
                bytes_t, follow, accept_b, first, last, onehot
            )
        else:
            flags = NfaVerifier._stream_multi_impl(
                bytes_t, follow, accept_b, first, last, onehot
            )
        rp, lo, g, bg = flags.shape
        # [P, Lo, G, Bg] -> [P, rows, Lo]; per lane: its slot's bit plane
        # of its row, block-cumsum, then the [b0, b1) interval test
        h = flags.transpose(0, 2, 3, 1).reshape(rp, g * bg, lo)
        bits = (
            h[lane_slot // 8, lane_row].astype(jnp.int32)
            >> (lane_slot % 8)[:, None]
        ) & 1  # [N, Lo]
        cs = jnp.cumsum(bits, axis=1)
        csz = jnp.pad(cs, ((0, 0), (1, 0)))  # [N, Lo+1], csz[:, 0] = 0
        ar = jnp.arange(lane_row.shape[0])
        keep = csz[ar, lane_b1] > csz[ar, lane_b0]
        return jnp.packbits(keep)

    # ------------------------------------------------------------------

    def _windows(self, pairs: np.ndarray, lens: np.ndarray):
        """Per-lane walk windows [start, stop) over pairs [N, 4] columns
        (file, rule, first_hint, last_hint) — the dfa_verify_pairs clip:
        trimmable rules walk [first - bound, last + bound + 8], untrimmable
        walk the whole file."""
        flen = lens[pairs[:, 0]]
        bound = self.prefix_bound[pairs[:, 1]]
        trim = bound != _NO_TRIM
        start = np.where(
            trim, np.maximum(pairs[:, 2].astype(np.int64) - bound, 0), 0
        )
        stop = np.where(
            trim,
            np.minimum(pairs[:, 3].astype(np.int64) + bound + 8, flen),
            flen,
        )
        return start, np.maximum(stop, start)

    def device_eligible(self, pairs: np.ndarray, lens: np.ndarray):
        """bool[N]: the lane's rule has a 64-position automaton and its
        trim-clipped walk window fits the device length cap.  Trimming is
        what makes big files eligible: a 1MB file whose gram hits sit in
        one region still verifies as a few-hundred-byte lane."""
        if not len(pairs):
            return np.zeros(0, dtype=bool)
        start, stop = self._windows(pairs, lens)
        return self.has_nfa[pairs[:, 1]] & (stop - start <= MAX_LEN)

    def verify_lanes(
        self, contents: list[bytes], pairs: np.ndarray, lens: np.ndarray
    ) -> np.ndarray:
        """bool[N] keep-mask for device-eligible lanes.  contents[i] is the
        full file bytes for pairs[i, 0]; the lane ships only its clipped
        walk window.

        Production path (stream): windows <= STREAM_ROW_LEN pack densely
        into fixed rows per rule — link bytes track the actual window
        bytes, the whole batch rides O(1) fixed-shape dispatches, and the
        device returns per-position hit bitmaps the host maps back to
        lanes.  Jumbo windows and all-64-class rules take the padded
        bucket path."""
        n = len(pairs)
        keep = np.zeros(n, dtype=bool)
        if n == 0:
            return keep
        start, stop = self._windows(pairs, lens)
        wlen = stop - start
        stream = self.has_nfa[pairs[:, 1]] & (wlen <= STREAM_TIERS[-1])
        s_idx = np.flatnonzero(stream)
        if len(s_idx):
            self._verify_stream(contents, pairs, start, stop, s_idx, keep)
        rest = np.flatnonzero(~stream)
        if len(rest):
            self._verify_padded(contents, pairs, start, stop, rest, keep)
        return keep

    def _rule_byte_tensors(self, r: int):
        """(follow [64,64], accept_b [256,64], first [64], last [64]) f32
        for rule r, raw-byte accept (class translation folded in), byte 0
        forced dead; cached per rule."""
        cached = self._byte_tensor_cache.get(r)
        if cached is not None:
            return cached
        nfa = self._nfas[r]
        m = len(nfa.follow)
        follow = np.zeros((64, 64), np.float32)
        for p in range(m):
            w = int(nfa.follow[p])
            q = 0
            while w:
                if w & 1:
                    follow[p, q] = 1.0
                w >>= 1
                q += 1
        byte_masks = nfa.classmask[nfa.byte_class]  # [256] uint64
        accept_b = np.zeros((256, 64), np.float32)
        for q in range(m):
            accept_b[:, q] = (
                (byte_masks >> np.uint64(q)) & np.uint64(1)
            ).astype(np.float32)
        accept_b[0, :] = 0.0  # 0x00 = the universal dead separator
        first = np.zeros(64, np.float32)
        last = np.zeros(64, np.float32)
        fw, lw = int(nfa.first), int(nfa.last)
        for q in range(m):
            if fw >> q & 1:
                first[q] = 1.0
            if lw >> q & 1:
                last[q] = 1.0
        out = (follow, accept_b, first, last)
        self._byte_tensor_cache[r] = out
        return out

    def _verify_stream(
        self, contents, pairs, start, stop, s_idx, keep
    ) -> None:
        """Exception-safe shell around the stream dispatch: the per-call
        stacked rule tensors are ledgered ("verify-stream") for exactly
        the duration of the call, even when a dispatch raises."""
        mw: list = []
        try:
            self._verify_stream_impl(
                contents, pairs, start, stop, s_idx, keep, mw
            )
        finally:
            for h in mw:
                h.release()

    def _verify_stream_impl(
        self, contents, pairs, start, stop, s_idx, keep, mw
    ) -> None:
        """Multi-rule stream dispatch: pairs group by FILE, each file's
        single SPAN of raw bytes (covering every candidate pair's window)
        packs into fixed rows, and every distinct candidate rule scans
        the same resident rows inside one dispatch.  Verdict: pair (f, r)
        survives iff rule r's flag is set for any 32-position block
        overlapping the pair's own window inside the span
        (block-granular over-approx; the oracle confirm is exact)."""
        import time as _time
        from collections import deque

        from trivy_tpu.engine.pipeline import default_depth

        depth = default_depth()
        tiers = STREAM_TIERS
        # Fused mode resolves lane verdicts on-device (one keep-mask bit
        # per lane crosses the link).  Meshed runs fuse too: lane tables
        # shard row-wise per the plan, the verdict gather crosses the
        # sharded G axis under GSPMD's inserted collectives, and the d2h
        # is one packed keep-mask per shard (link.fetch_mask_packed's
        # host demux reassembles them in lane order).
        fused = bool(self.fused)
        scan_mode = fused_scan_mode() if fused else "seq"
        st = self.stream_stats = {
            "lanes": int(len(s_idx)), "span_bytes": 0,
            "rows": [0] * len(tiers),
            "rules": 0, "dispatches": 0, "overflow_lanes": 0,
            "assemble_s": 0.0, "dispatch_s": 0.0, "fetch_map_s": 0.0,
            "pipeline_depth": depth, "h2d_overlap_s": 0.0,
            "fetch_bytes_raw": 0, "fetch_bytes": 0,
            "backend": "fused" if fused else "stream",
            "sieve_kernel": self.sieve_kernel_id,
        }
        # D2H compaction (engine/link.py): the packed flag tensor is
        # almost entirely zero lanes (r05: 400 real pairs in 60k lanes,
        # 1.48s of fetch_map_s pure d2h), so the device reduces to a
        # nonzero-lane bitmap and ships only the lanes that hit.
        from trivy_tpu.engine import link as link_mod

        compact_fetch = link_mod.d2h_compaction_enabled()
        t0 = _time.perf_counter()
        # assemble_s is timed DIRECTLY: the assembly clock pauses while a
        # flush (dispatch + bounded fetch) runs and resumes after — the
        # old end-minus-dispatch subtraction went negative whenever a
        # dispatch overlapped assembly under pipeline_depth >= 2.
        asm_mark = t0
        overflow: list[int] = []  # lanes for the padded path

        # distinct rules on the stream, most-claimed first; rules beyond
        # the largest jit-stable stack fall back to the padded path
        rvals, rcounts = np.unique(pairs[s_idx, 1], return_counts=True)
        if len(rvals) > RULE_STACK_BUCKETS[-1]:
            keep_rules = rvals[
                np.argsort(-rcounts)[: RULE_STACK_BUCKETS[-1]]
            ]
        else:
            keep_rules = rvals
        rule_slot = {int(r): i for i, r in enumerate(np.sort(keep_rules))}
        st["rules"] = len(rule_slot)

        order = s_idx[np.argsort(pairs[s_idx, 0], kind="stable")]
        rows_buf: list[list[np.ndarray]] = [[] for _ in tiers]
        flushed = [0] * len(tiers)

        # Pipelined dispatch machinery: a full max-group block of rows
        # dispatches DURING assembly (same dispatch granularity as before,
        # so the per-dispatch fixed relay cost is unchanged — only the
        # serialization goes away), and fetches run bounded-depth so d2h
        # of dispatch N-1 overlaps exec/transfer of N.  The r05 stream
        # serialized assemble (0.39s) -> dispatch (1.89s) -> fetch_map
        # (1.48s); these stages now overlap.
        jdt = self._compute_dtype()
        run = (
            self._run_stream_multi
            if jdt == jnp.bfloat16
            else self._run_stream_multi_gather
        )
        gbuckets = (
            GROUP_BUCKETS if self.mesh is not None else STREAM_GROUP_BUCKETS
        )
        flush_rows = gbuckets[-1] * LANES_PER_GROUP
        tens = None
        in_flight: deque = deque()
        fetched: list[tuple] = []

        def _build_tensors():
            # stack per-rule byte tensors (shared by all row tiers)
            nonlocal tens
            rb = next(
                (b for b in RULE_STACK_BUCKETS if len(rule_slot) <= b),
                RULE_STACK_BUCKETS[-1],
            )
            fol = np.zeros((rb, 64, 64), np.float32)
            acc = np.zeros((rb, 256, 64), np.float32)
            fst = np.zeros((rb, 64), np.float32)
            lst = np.zeros((rb, 64), np.float32)
            for r, slot in rule_slot.items():
                f_, a_, s_, l_ = self._rule_byte_tensors(r)
                fol[slot], acc[slot], fst[slot], lst[slot] = f_, a_, s_, l_
            _, _, rep = self._shardings()
            tens = tuple(
                jax.device_put(jnp.asarray(t, jdt), rep)
                if rep is not None
                else jnp.asarray(t, jdt)
                for t in (fol, acc, fst, lst)
            )
            mw.append(
                memwatch.track("verify-stream", memwatch.nbytes_of(tens))
            )

        def _fetch_one():  # graftlint: fetch-boundary
            ent = in_flight.popleft()
            tf = _time.perf_counter()
            if ent[0] == "fused":
                # fused dispatch: the d2h is the packed keep-mask; lane
                # verdicts apply immediately (no host remap pass)
                _, lane_ids, out, raw_b = ent
                with obs_trace.span("verify.fetch", lanes=len(lane_ids)):
                    faults.fire("nfa.fetch")
                    mask, raw_b, got_b = link_mod.fetch_mask_packed(
                        out, raw_b
                    )
                keep[lane_ids[mask[: len(lane_ids)]]] = True
                st["fetch_bytes_raw"] += raw_b
                st["fetch_bytes"] += got_b
            else:
                _, tier_, lo_, hi_, out = ent
                with obs_trace.span("verify.fetch", rows=hi_ - lo_):
                    faults.fire("nfa.fetch")
                    if compact_fetch:
                        packed, raw_b, got_b = link_mod.fetch_stream_packed(
                            out
                        )
                    else:
                        packed = np.asarray(out)
                        raw_b = got_b = packed.nbytes
                st["fetch_bytes_raw"] += raw_b
                st["fetch_bytes"] += got_b
                fetched.append((tier_, lo_, hi_, packed))
            dtf = _time.perf_counter() - tf
            st["fetch_map_s"] += dtf
            if in_flight:  # later dispatches were in flight while we waited
                st["h2d_overlap_s"] += dtf

        def _flush_range(tier, row_lo, row_hi):
            """Dispatch rows [row_lo, row_hi) of `tier` in group-bucket
            chunks, fetching oldest results once `depth` are in flight.
            Fused mode attaches each chunk's lane table to the dispatch so
            verdicts resolve on-device."""
            nonlocal asm_mark
            st["assemble_s"] += _time.perf_counter() - asm_mark
            td = _time.perf_counter()
            with obs_trace.span(
                "verify.dispatch", tier=tier, rows=row_hi - row_lo
            ):
                if tens is None:
                    _build_tensors()
                length = tiers[tier]
                lo_blocks = length // STREAM_BLOCK
                gi = row_lo
                while gi < row_hi:
                    remaining = -(-(row_hi - gi) // LANES_PER_GROUP)
                    gcap = next(
                        (g for g in gbuckets if remaining <= g), gbuckets[-1]
                    )
                    lo = gi
                    hi = min(lo + gcap * LANES_PER_GROUP, row_hi)
                    gi = hi
                    rows_arr = np.zeros(
                        (gcap * LANES_PER_GROUP, length), dtype=np.uint8
                    )
                    for k, row in enumerate(range(lo, hi)):
                        rows_arr[k] = rows_buf[tier][row]
                    # [G*Bg, L] -> [Lo, 32, G, Bg]
                    bytes_t = np.ascontiguousarray(
                        rows_arr.reshape(
                            gcap, LANES_PER_GROUP, length // STREAM_BLOCK,
                            STREAM_BLOCK,
                        ).transpose(2, 3, 0, 1)
                    )
                    if fused:
                        # this chunk's lanes: rows append in order, so a
                        # monotone cursor per tier suffices
                        fb = fl_buf[tier]
                        p = fl_ptr[tier]
                        q = p
                        while q < len(fb[1]) and fb[1][q] < hi:
                            q += 1
                        fl_ptr[tier] = q
                        if q == p:
                            continue  # rows carried no lanes
                        n_l = q - p
                        npad = max(8, 1 << (n_l - 1).bit_length())
                        lrow = np.zeros(npad, np.int32)
                        lslot = np.zeros(npad, np.int32)
                        lb0 = np.zeros(npad, np.int32)
                        lb1 = np.zeros(npad, np.int32)
                        lrow[:n_l] = np.asarray(fb[1][p:q], np.int32) - lo
                        lslot[:n_l] = fb[2][p:q]
                        lb0[:n_l] = fb[3][p:q]
                        lb1[:n_l] = fb[4][p:q]
                        lane_ids = np.asarray(fb[0][p:q], np.int64)
                        itemsize = jnp.dtype(jdt).itemsize
                        est = (
                            lo_blocks * gcap * LANES_PER_GROUP
                            * 64 * 64 * itemsize
                        )
                        assoc = scan_mode == "assoc" or (
                            scan_mode == "auto"
                            and est <= FUSED_ASSOC_BUDGET_BYTES
                        )
                        faults.fire("nfa.dispatch")
                        bd = self._put_stream(bytes_t)
                        ph = obs_metrics.device_phase("verify.fused")
                        out = self._run_fused(
                            bd, *tens,
                            self._put_lanes(lrow), self._put_lanes(lslot),
                            self._put_lanes(lb0), self._put_lanes(lb1),
                            onehot=(jdt == jnp.bfloat16), assoc=assoc,
                        )
                        ph.done(out)
                        # what the legacy flag-map fetch would have moved
                        raw_b = (
                            -(-tens[0].shape[0] // 8)
                            * lo_blocks * gcap * LANES_PER_GROUP
                        )
                        in_flight.append(("fused", lane_ids, out, raw_b))
                    else:
                        faults.fire("nfa.dispatch")
                        bd = self._put_stream(bytes_t)
                        # traced runs fence each dispatch (per-kernel
                        # verify-stream attribution); untraced dispatch
                        # stays async and overlaps with the bounded
                        # fetch queue
                        ph = obs_metrics.device_phase("verify-stream")
                        out = run(bd, *tens)
                        ph.done(out)
                        in_flight.append(("stream", tier, lo, hi, out))
                    st["dispatches"] += 1
                    while len(in_flight) > depth:
                        _fetch_one()
            st["dispatch_s"] += _time.perf_counter() - td
            asm_mark = _time.perf_counter()

        # flat per-lane placement (vectorized verdict resolution):
        # lane id, tier, row, rule slot, first/last 32-block of its window
        lv_lane: list[int] = []
        lv_tier: list[int] = []
        lv_row: list[int] = []
        lv_slot: list[int] = []
        lv_b0: list[int] = []
        lv_b1: list[int] = []
        # fused mode keeps per-TIER lane tables instead (lane, row, slot,
        # b0, b1) — consumed chunk-by-chunk via fl_ptr in _flush_range,
        # shipped with the dispatch, never resolved on host
        fl_buf: list[tuple[list, list, list, list, list]] = [
            ([], [], [], [], []) for _ in tiers
        ]
        fl_ptr = [0] * len(tiers)
        open_row = [(-1, ln + 1) for ln in tiers]
        pos = 0
        while pos < len(order):
            end = pos
            f0 = pairs[order[pos], 0]
            while end < len(order) and pairs[order[end], 0] == f0:
                end += 1
            lanes_f = [
                int(li)
                for li in order[pos:end]
                if int(pairs[li, 1]) in rule_slot
            ]
            overflow.extend(
                int(li)
                for li in order[pos:end]
                if int(pairs[li, 1]) not in rule_slot
            )
            pos = end
            if not lanes_f:
                continue
            content = np.frombuffer(
                contents[int(pairs[lanes_f[0], 0])], dtype=np.uint8
            )
            s = int(min(start[li] for li in lanes_f))
            e = int(max(stop[li] for li in lanes_f))
            span = content[s:e]
            tier = next(
                (t for t, ln in enumerate(tiers) if len(span) <= ln), -1
            )
            if tier < 0 or (span == 0).any():
                # oversize span, or contains the dead separator byte:
                # the padded class path verifies these exactly
                overflow.extend(lanes_f)
                continue
            length = tiers[tier]
            cur, cpos = open_row[tier]
            if cur < 0 or cpos + len(span) > length:
                rows_buf[tier].append(np.zeros(length, np.uint8))
                cur, cpos = len(rows_buf[tier]) - 1, 0
            rows_buf[tier][cur][cpos : cpos + len(span)] = span
            for li in lanes_f:
                rs0 = cpos + int(start[li]) - s
                rs1 = cpos + int(stop[li]) - s
                if fused:
                    fb = fl_buf[tier]
                    fb[0].append(li)
                    fb[1].append(cur)
                    fb[2].append(rule_slot[int(pairs[li, 1])])
                    fb[3].append(rs0 // STREAM_BLOCK)
                    fb[4].append(-(-rs1 // STREAM_BLOCK))
                else:
                    lv_lane.append(li)
                    lv_tier.append(tier)
                    lv_row.append(cur)
                    lv_slot.append(rule_slot[int(pairs[li, 1])])
                    lv_b0.append(rs0 // STREAM_BLOCK)
                    lv_b1.append(-(-rs1 // STREAM_BLOCK))
            # one 0x00 separator byte between spans
            open_row[tier] = (cur, cpos + len(span) + 1)
            st["span_bytes"] += len(span)
            # Rows strictly before `cur` are closed; once a full max-group
            # block of them has accumulated, dispatch it now so the device
            # chews on it while assembly continues.
            if cur - flushed[tier] >= flush_rows:
                _flush_range(tier, flushed[tier], flushed[tier] + flush_rows)
                flushed[tier] += flush_rows
        st["rows"] = [len(b) for b in rows_buf]
        st["overflow_lanes"] = len(overflow)
        # close the final assembly segment (flushes paused the clock)
        st["assemble_s"] += _time.perf_counter() - asm_mark
        asm_mark = _time.perf_counter()

        if not any(rows_buf) and not overflow:
            return
        if not any(rows_buf):
            # only overflow lanes: padded path handles everything
            self._verify_padded(
                contents, pairs, start, stop,
                np.asarray(overflow, dtype=np.int64), keep,
            )
            return
        # remainder rows (below the flush threshold) per tier
        for tier in range(len(tiers)):
            if flushed[tier] < len(rows_buf[tier]):
                _flush_range(tier, flushed[tier], len(rows_buf[tier]))

        # Overflow lanes run on the padded path WHILE the stream
        # dispatches above are in flight (they were issued async), so the
        # two device phases overlap instead of serializing round-trips.
        if overflow:
            self._verify_padded(
                contents, pairs, start, stop,
                np.asarray(overflow, dtype=np.int64), keep,
            )

        while in_flight:
            _fetch_one()

        t0 = _time.perf_counter()
        la_lane = np.asarray(lv_lane, dtype=np.int64)
        la_tier = np.asarray(lv_tier, dtype=np.int8)
        la_row = np.asarray(lv_row, dtype=np.int64)
        la_slot = np.asarray(lv_slot, dtype=np.int32)
        la_b0 = np.asarray(lv_b0, dtype=np.int64)
        la_b1 = np.asarray(lv_b1, dtype=np.int64)
        for tier, row_lo, row_hi, packed in fetched:
            # packed: [ceil(R/8), Lo, gcap, Bg] uint8
            rp_, lo_, g_, bg_ = packed.shape
            m = (
                (la_tier == tier)
                & (la_row >= row_lo)
                & (la_row < row_hi)
            )
            if not m.any():
                continue
            # [P, Lo, G, Bg] -> [P, rows, Lo]; per used rule slot, extract
            # its bit plane and cumsum blocks so "any hit block in
            # [b0, b1)" is one vectorized compare per slot
            h = packed.transpose(0, 2, 3, 1).reshape(rp_, g_ * bg_, lo_)
            rows_rel = la_row[m] - row_lo
            mslot = la_slot[m]
            mlane = la_lane[m]
            mb0 = la_b0[m]
            mb1 = la_b1[m]
            cs = np.zeros((g_ * bg_, lo_ + 1), dtype=np.uint16)
            for slot in np.unique(mslot):
                sm = mslot == slot
                bits = (h[slot // 8] >> (slot % 8)) & 1
                np.cumsum(bits, axis=1, dtype=np.uint16, out=cs[:, 1:])
                rr = rows_rel[sm]
                hit = cs[rr, mb1[sm]] > cs[rr, mb0[sm]]
                keep[mlane[sm][hit]] = True
        st["fetch_map_s"] += _time.perf_counter() - t0

    def _put_stream(self, bytes_t: np.ndarray):
        """Device placement for the 4D stream operand ([Lo, 32, G, Bg]:
        G is the sharded axis per the plan)."""
        if self.mesh is None:
            return jnp.asarray(bytes_t)
        from trivy_tpu.mesh import plan as mesh_plan

        return jax.device_put(
            bytes_t, mesh_plan.sharding_for(self.mesh, "stream_bytes")
        )

    def _put_lanes(self, arr: np.ndarray):
        """Fused lane-table placement: the lane axis shards row-wise per
        the plan (lane counts pad to powers of two >= 8, so any mesh up
        to 8 devices divides them)."""
        if self.mesh is None:
            return jnp.asarray(arr)
        from trivy_tpu.mesh import plan as mesh_plan

        return jax.device_put(
            arr, mesh_plan.sharding_for(self.mesh, "lane_tables")
        )

    def _verify_padded(
        self, contents, pairs, start, stop, lane_idx, keep
    ) -> None:
        """Bucket-padded dispatch for jumbo windows / 64-class rules:
        lanes sort by (window bucket, rule), pack into
        [G, LANES_PER_GROUP] groups per length bucket, one dispatch per
        (bucket, group-chunk)."""
        wlen = stop - start
        bucket = np.searchsorted(np.array(LEN_BUCKETS), wlen, side="left")
        order = lane_idx[
            np.lexsort((pairs[lane_idx, 1], bucket[lane_idx]))
        ]
        tensors = self._device_tensors()
        # Phase 1: assemble + dispatch every (bucket, group-chunk) — JAX
        # dispatch is async, so transfers and executions of later chunks
        # overlap earlier ones.  Phase 2: fetch verdicts.
        in_flight: list[tuple[list[np.ndarray], object]] = []
        pos = 0
        while pos < len(order):
            bk = bucket[order[pos]]
            end = pos
            while end < len(order) and bucket[order[end]] == bk:
                end += 1
            lanes = order[pos:end]
            pos = end
            length = LEN_BUCKETS[bk]
            # split the bucket's lanes into per-rule groups of Bg
            groups: list[np.ndarray] = []
            gstart = 0
            rules = pairs[lanes, 1]
            for i in range(1, len(lanes) + 1):
                if i == len(lanes) or rules[i] != rules[gstart]:
                    for off in range(gstart, i, LANES_PER_GROUP):
                        groups.append(lanes[off : min(off + LANES_PER_GROUP, i)])
                    gstart = i
            gi = 0
            while gi < len(groups):
                gcap = next(
                    (g for g in GROUP_BUCKETS if len(groups) - gi <= g),
                    GROUP_BUCKETS[-1],
                )
                chunk = groups[gi : gi + gcap]
                gi += gcap
                classes = np.zeros(
                    (gcap, LANES_PER_GROUP, length), dtype=np.uint8
                )
                gids = np.zeros(gcap, dtype=np.int32)
                for g, lane_arr in enumerate(chunk):
                    r = int(pairs[lane_arr[0], 1])
                    gids[g] = r
                    lut = self.luts[r]
                    for b, li in enumerate(lane_arr):
                        data = np.frombuffer(
                            contents[int(pairs[li, 0])], dtype=np.uint8
                        )[start[li] : stop[li]]
                        classes[g, b, : len(data)] = lut[data]
                classes_t = np.ascontiguousarray(classes.transpose(2, 0, 1))
                cd, gd = self._put(classes_t, gids)
                in_flight.append((chunk, self._run(cd, gd, *tensors)))
        for chunk, out in in_flight:
            matched = np.asarray(out)
            for g, lane_arr in enumerate(chunk):
                keep[lane_arr] = matched[g, : len(lane_arr)]


def build_rule_stack(verifier: NfaVerifier) -> dict[str, np.ndarray]:
    """Stacked uint8 per-rule byte tensors for the registry artifact
    (registry/store.py schema 3 `vstack_*` arrays): every stream-eligible
    rule's raw-byte automaton in one dense stack, so warm starts seed
    `NfaVerifier(rule_stack=...)` and skip the per-rule Python tensor
    build, and `aot_warmup` can pre-lower the fused verify shapes against
    real tensor shapes.  All values are {0, 1}; `vstack_accept_b[:, 0, :]`
    is all-zero (byte 0x00 is the stream's dead separator) — the unpack
    side validates both."""
    r = verifier.num_rules
    out = {
        "vstack_has": np.zeros(r, np.uint8),
        "vstack_follow": np.zeros((r, 64, 64), np.uint8),
        "vstack_accept_b": np.zeros((r, 256, 64), np.uint8),
        "vstack_first": np.zeros((r, 64), np.uint8),
        "vstack_last": np.zeros((r, 64), np.uint8),
    }
    for i in range(r):
        if verifier._nfas[i] is None:
            continue
        fol, acc, fst, lst = verifier._rule_byte_tensors(i)
        out["vstack_has"][i] = 1
        out["vstack_follow"][i] = fol.astype(np.uint8)
        out["vstack_accept_b"][i] = acc.astype(np.uint8)
        out["vstack_first"][i] = fst.astype(np.uint8)
        out["vstack_last"][i] = lst.astype(np.uint8)
    return out
