"""The TPU secret engine: device sieve -> candidate rules -> exact host confirm.

Pipeline (the TPU-native reformulation of pkg/fanal/secret/scanner.go Scan):

  1. Host packs blobs into overlapping tiles (scanner/packing.py).
  2. Device runs the packed shift-AND sieve (ops/sieve.py) over every byte,
     producing per-tile probe-hit bitmaps; tile axis shards over the mesh.
  3. Host ORs bitmaps per file, resolves per-file candidate rule sets via the
     precompiled gate/anchor masks (vectorized; typically empty).
  4. Host confirms candidates byte-exactly with the oracle restricted to the
     candidate subset — findings are byte-identical to the reference engine by
     construction (probes are necessary conditions; see engine/probes.py).

Per-file path gating (AllowPath etc.) happens in the oracle exactly as the
reference does it, so gating order is preserved.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from trivy_tpu.ftypes import Secret
from trivy_tpu.engine.oracle import OracleScanner
from trivy_tpu.engine.probes import ProbeSet, build_probe_set
from trivy_tpu.rules.model import RuleSet, SecretConfig, build_ruleset
from trivy_tpu.scanner.packing import DEFAULT_OVERLAP, DEFAULT_TILE_LEN, pack


# Fixed tile-batch shapes.  Every device call uses one of these row counts, so
# XLA compiles each bucket exactly once per process; larger scans are chunked
# into max-bucket-row batches (static shapes — SURVEY §1 XLA semantics).
TILE_BUCKETS = (512, 4096)


@dataclass
class SieveStats:
    files: int = 0
    bytes: int = 0
    tiles: int = 0
    candidate_pairs: int = 0
    confirmed_findings: int = 0


class TpuSecretEngine:
    """Drop-in engine with the oracle's Scan semantics, device-accelerated."""

    def __init__(
        self,
        ruleset: RuleSet | None = None,
        config: SecretConfig | None = None,
        tile_len: int = DEFAULT_TILE_LEN,
        mesh=None,
        max_batch_tiles: int = 4096,
    ):
        self.ruleset = ruleset if ruleset is not None else build_ruleset(config)
        self.oracle = OracleScanner(self.ruleset)
        self.pset: ProbeSet = build_probe_set(self.ruleset.rules)
        self.tile_len = tile_len
        self.overlap = max(DEFAULT_OVERLAP, self.pset.jmax)
        self.max_batch_tiles = max_batch_tiles
        self.stats = SieveStats()

        self._gate, self._gate_any, self._conj, self._conj_any = self.pset.gate_masks()

        import jax.numpy as jnp

        self._lut = jnp.asarray(self.pset.build_lut())
        if mesh is not None:
            from trivy_tpu.ops.sieve import make_sharded_sieve

            self._mesh = mesh
            self._sieve_fn = make_sharded_sieve(mesh)
            self._tile_align = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        else:
            from trivy_tpu.ops import sieve as sieve_mod

            self._mesh = None
            self._sieve_fn = lambda tiles, lut: sieve_mod._sieve_jit(
                tiles, lut, tiles.shape[1]
            )
            self._tile_align = 1

    # ------------------------------------------------------------------

    def _buckets(self) -> list[int]:
        """Tile-row batch shapes: TILE_BUCKETS capped by max_batch_tiles,
        rounded up to the mesh-device multiple."""
        align = self._tile_align
        caps = [b for b in TILE_BUCKETS if b <= self.max_batch_tiles]
        if not caps or caps[-1] != self.max_batch_tiles:
            caps.append(self.max_batch_tiles)
        return [-(-b // align) * align for b in caps]

    def warmup(self) -> None:
        """Compile every tile-bucket shape ahead of timed scanning."""
        import jax
        import jax.numpy as jnp

        for rows in self._buckets():
            tiles = jnp.zeros((rows, self.tile_len), dtype=jnp.uint8)
            jax.block_until_ready(self._sieve_fn(tiles, self._lut))

    def candidate_matrix(self, file_hits: np.ndarray) -> np.ndarray:
        """[F, R] bool candidate matrix from per-file probe bitmaps."""
        h = file_hits[:, None, :]  # [F, 1, Pw]
        gate_ok = ~self._gate_any[None, :] | (h & self._gate[None]).any(-1)
        conj_hit = (file_hits[:, None, None, :] & self._conj[None]).any(-1)  # [F,R,K]
        conj_ok = (~self._conj_any[None] | conj_hit).all(-1)
        return gate_ok & conj_ok

    def _run_sieve(self, contents: list[bytes]) -> np.ndarray:
        import jax.numpy as jnp

        from trivy_tpu.scanner.packing import count_tiles

        buckets = self._buckets()
        max_rows = buckets[-1]
        total = count_tiles(contents, self.tile_len, self.overlap)
        self.stats.tiles += total
        fit = next((b for b in buckets if total <= b), None)
        if fit is not None:
            batch = pack(contents, self.tile_len, self.overlap, pad_tiles_to=fit)
            tile_hits = np.asarray(self._sieve_fn(jnp.asarray(batch.tiles), self._lut))
        else:
            # Chunk into fixed max-bucket-row batches: one compiled shape,
            # pipelined h2d/compute across chunks (dispatch is async; we only
            # materialize results at the end).
            batch = pack(contents, self.tile_len, self.overlap)
            chunks = []
            for off in range(0, len(batch.tiles), max_rows):
                part = batch.tiles[off : off + max_rows]
                if len(part) < max_rows:
                    part = np.concatenate(
                        [part, np.zeros((max_rows - len(part), part.shape[1]), np.uint8)]
                    )
                chunks.append(self._sieve_fn(jnp.asarray(part), self._lut))
            tile_hits = np.concatenate([np.asarray(c) for c in chunks])[
                : len(batch.tiles)
            ]
        return batch.file_hits(tile_hits)

    def scan_batch(self, items: list[tuple[str, bytes]]) -> list[Secret]:
        """Scan (path, content) blobs; returns per-file Secret results."""
        if not items:
            return []
        self.stats.files += len(items)
        self.stats.bytes += sum(len(c) for _, c in items)

        file_hits = self._run_sieve([c for _, c in items])
        cand = self.candidate_matrix(file_hits)

        results: list[Secret] = []
        for fi, (path, content) in enumerate(items):
            idxs = np.flatnonzero(cand[fi])
            if len(idxs) == 0:
                # Preserve the reference's allow-path result shape
                # (scanner.go:375-380 returns Secret{FilePath} for allowed
                # paths, empty Secret otherwise) even when the sieve lets us
                # skip the oracle entirely.
                if self.oracle.allow_path(path):
                    results.append(Secret(file_path=path))
                else:
                    results.append(Secret())
                continue
            self.stats.candidate_pairs += len(idxs)
            res = self.oracle.scan(path, content, rule_indices=idxs.tolist())
            self.stats.confirmed_findings += len(res.findings)
            results.append(res)
        return results

    def scan(self, file_path: str, content: bytes) -> Secret:
        return self.scan_batch([(file_path, content)])[0]
