"""The TPU secret engine: device sieve -> candidate rules -> exact host confirm.

Pipeline (the TPU-native reformulation of pkg/fanal/secret/scanner.go Scan):

  1. Host packs blobs densely into overlapping rows (scanner/packing.py
     pack_dense — zero padding waste, h2d is the wall through the host link).
  2. Device runs the masked 4-gram sieve (ops/gram_sieve.py) over every byte,
     producing per-row gram-hit bitmaps; the row axis shards over the mesh.
  3. Host ORs bitmaps per file, maps grams -> probes (engine/grams.py), and
     resolves per-file candidate rule sets via the precompiled gate/anchor
     masks (vectorized; typically empty).
  4. Host confirms candidates byte-exactly with the oracle restricted to the
     candidate subset — findings are byte-identical to the reference engine by
     construction (grams are necessary conditions; see engine/probes.py and
     engine/grams.py).

Per-file path gating (AllowPath etc.) happens in the oracle exactly as the
reference does it, so gating order is preserved.

The gather-LUT shift-AND sieve (ops/sieve.py) is kept as `sieve="lut"` — it is
the bit-exact keyword semantics but gather-bound on TPU; the gram sieve is the
production path (~5x faster exec, no gathers).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from trivy_tpu import faults
from trivy_tpu.cache import stats as cache_stats
from trivy_tpu.ftypes import Secret
from trivy_tpu.engine.grams import GramSet, build_gram_set
from trivy_tpu.engine.oracle import OracleScanner
from trivy_tpu.obs import memwatch
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import trace as obs_trace
from trivy_tpu.engine.probes import ProbeSet, build_probe_set
from trivy_tpu.rules.model import RuleSet, SecretConfig, build_ruleset
from trivy_tpu.scanner.packing import (
    DEFAULT_OVERLAP,
    DEFAULT_TILE_LEN,
    dedupe_blobs,
    pack,
    pack_dense,
)

# Fixed row-batch shapes.  Every device call uses one of these row counts, so
# XLA compiles each bucket exactly once per process; larger scans are chunked
# into max-bucket-row batches (static shapes — SURVEY §1 XLA semantics).
TILE_BUCKETS = (512, 4096)
# The TPU link has a large fixed per-call latency (~100ms through the axon
# relay); the Pallas path uses few, huge calls so the fixed cost amortizes.
# Granular buckets matter on narrow links: padding a 7k-row batch up to a
# 32k-row bucket would quadruple the bytes crossing the link (each bucket
# shape compiles once per process; warmup covers them all).
TILE_BUCKETS_PALLAS = (4096, 8192, 16384, 32768)

GRAM_OVERLAP = 3  # gram window (4) - 1


@dataclass
class SieveStats:
    files: int = 0
    bytes: int = 0
    tiles: int = 0
    candidate_pairs: int = 0
    device_pairs: int = 0  # candidate lanes verified on the device NFA
    confirmed_findings: int = 0
    # Wall-clock per phase (seconds), accumulated across scan_batch calls:
    # host pack, sieve (device dispatch+execute+fetch, or native host scan),
    # gram->probe->rule candidate resolution, optional device NFA verify,
    # exact host confirm.  Overlapped pipelines (engine/hybrid.py) make the
    # sum exceed wall-clock — that is the point.
    pack_s: float = 0.0
    sieve_s: float = 0.0
    candidate_s: float = 0.0
    verify_s: float = 0.0
    confirm_s: float = 0.0
    # Device dispatch count for the sieve phase (link-floor accounting:
    # each dispatch pays the link round-trip on relay-attached chips).
    device_dispatches: int = 0
    # Populated only under TRIVY_TPU_SYNC_TIMING=1 (bench decomposition):
    # measured h2d transfer vs on-device exec+fetch, separated by a forced
    # sync between them.  Production keeps transfers/exec pipelined.
    h2d_s: float = 0.0
    exec_s: float = 0.0
    # Chunk-pipeline accounting (engine/pipeline.py): finish-stage wall
    # that ran while later chunks were staged/executing (transfer hidden
    # behind compute), content-digest dedupe savings, resident-LRU chunk
    # hits, and the depth the run used.
    h2d_overlap_s: float = 0.0
    dedupe_saved_bytes: int = 0
    resident_hits: int = 0
    pipeline_depth: int = 0
    # Link-codec accounting (engine/link.py).  bytes_on_link_raw counts
    # padded chunk bytes at ACTUAL staging time — resident-LRU hits and
    # dedupe-skipped blobs never ship, so they never count (the pre-codec
    # bench derived this from tiles * tile_len, which overstated
    # steady-state traffic).  bytes_on_link_coded is what device_put
    # really moved (== raw when no codec applies); encode_s is the host
    # transcode+pack cost.  d2h_bytes_raw/d2h_bytes are the fetch-side
    # pair (full result size vs bitmap+compacted rows actually moved).
    bytes_on_link_raw: int = 0
    bytes_on_link_coded: int = 0
    encode_s: float = 0.0
    d2h_bytes_raw: int = 0
    d2h_bytes: int = 0

    def phases(self) -> dict:
        out = {
            "pack_s": round(self.pack_s, 4),
            "sieve_s": round(self.sieve_s, 4),
            "candidate_s": round(self.candidate_s, 4),
            "confirm_s": round(self.confirm_s, 4),
        }
        if self.verify_s:
            out["verify_s"] = round(self.verify_s, 4)
        if self.pipeline_depth:
            out["pipeline_depth"] = self.pipeline_depth
            out["h2d_overlap_s"] = round(self.h2d_overlap_s, 4)
        if self.dedupe_saved_bytes:
            out["dedupe_saved_bytes"] = self.dedupe_saved_bytes
        if self.resident_hits:
            out["resident_hits"] = self.resident_hits
        if self.bytes_on_link_raw:
            out["bytes_on_link_raw"] = self.bytes_on_link_raw
            out["bytes_on_link_coded"] = self.bytes_on_link_coded
            out["codec_ratio"] = round(
                self.bytes_on_link_coded / self.bytes_on_link_raw, 4
            )
        if self.encode_s:
            out["encode_s"] = round(self.encode_s, 4)
        if self.d2h_bytes_raw:
            out["d2h_bytes_raw"] = self.d2h_bytes_raw
            out["d2h_bytes"] = self.d2h_bytes
            out["d2h_ratio"] = round(
                self.d2h_bytes / self.d2h_bytes_raw, 4
            )
        return out


class TpuSecretEngine:
    """Drop-in engine with the oracle's Scan semantics, device-accelerated."""

    DEFAULT_MAX_BATCH_TILES = 4096

    def __init__(
        self,
        ruleset: RuleSet | None = None,
        config: SecretConfig | None = None,
        tile_len: int = DEFAULT_TILE_LEN,
        mesh=None,
        max_batch_tiles: int | None = None,
        sieve: str = "gram",
        kernel: str = "auto",
        pipeline_depth: int | None = None,
        dedupe: bool = True,
        resident_chunks: int | None = None,
        compiled=None,
        fused: bool | None = None,
        megakernel: bool | None = None,
        aot_cache_dir: str | None = None,
        program_table=None,
    ):
        from trivy_tpu.engine.pipeline import (
            ResidentChunkCache,
            default_depth,
        )

        self._max_tiles_explicit = max_batch_tiles is not None
        if max_batch_tiles is None:
            max_batch_tiles = self.DEFAULT_MAX_BATCH_TILES
        self.ruleset = ruleset if ruleset is not None else build_ruleset(config)
        self.oracle = OracleScanner(self.ruleset)
        # Warm start: a registry CompiledArtifact (already digest-matched to
        # this ruleset by the loader) supplies the probe/gram tensors, so
        # construction skips the whole compile pipeline.
        self._compiled = compiled
        self._ruleset_digest = compiled.digest if compiled is not None else None
        self.pset: ProbeSet = (
            compiled.pset if compiled is not None
            else build_probe_set(self.ruleset.rules)
        )
        self.tile_len = tile_len
        self.max_batch_tiles = max_batch_tiles
        self.sieve = sieve
        self.stats = SieveStats()
        self.pipeline_depth = (
            pipeline_depth if pipeline_depth is not None else default_depth()
        )
        self.dedupe = dedupe
        # Multi-program demux (programs/base.py): when set, `ruleset` is
        # the table's merged ruleset and scan_programs slices the shared
        # candidate matrix per program.  scan_batch stays the secret-only
        # facade (it routes through the table so one engine serves both).
        self.program_table = program_table
        self.program_stats: dict[str, dict] = {}
        self._resident = ResidentChunkCache(resident_chunks)
        # Fused sieve->verify residency (this PR's tentpole): staged rows
        # and their hit words stay device-resident for the batch lifetime
        # and candidate lanes derive ON-DEVICE (no d2h of the full hit
        # matrix).  Resolved on the gram jax path below; native/lut keep
        # the host derivation.
        self._fused = False
        self._fused_requested = fused
        self._row_store = None
        self._sieve_donated = None
        # Megakernel state (ops/megakernel.py): the one-dispatch fusion of
        # unpack->sieve->derive->verdict.  Built on the Pallas gram path
        # below; `_mega_on` is the runtime switch the gate pricing and the
        # scheduler's step-down rung flip without rebuilding the program.
        self._mega = None
        self._mega_on = False
        self._mega_requested = megakernel
        self._mega_fn = None  # meshed fused callable (shard_map + psum)
        self._kernel_tag = ""
        self._aot_dir = aot_cache_dir or os.environ.get(
            "TRIVY_TPU_AOT_CACHE"
        ) or None
        self._mesh = mesh
        self._tile_buckets = TILE_BUCKETS
        # Resolved against the unified topology below (native never
        # touches a device, so it keeps the trivial alignment).
        self._tile_align = 1

        self._gate, self._gate_any, self._conj, self._conj_any = self.pset.gate_masks()
        self._build_member_matrices()

        # Link-codec state (engine/link.py): filled in on the device gram
        # path below; native/lut paths stay raw.
        self._link = None
        self._d2h_compact = False
        self._staged_cols = tile_len
        self._codec_tag = ":raw"

        if sieve == "native":
            # C++ host sieve (native/gram_sieve.cpp): no JAX, for CPU-only
            # hosts; NumPy reference as last resort.
            self.gset = (
                compiled.gset if compiled is not None
                else build_gram_set(self.pset)
            )
            self._masks_np, self._vals_np = self.gset.masks, self.gset.vals
            self.overlap = GRAM_OVERLAP
            self._sieve_fn = None
            return

        from trivy_tpu.ops import enable_compilation_cache

        enable_compilation_cache()

        import jax.numpy as jnp

        from trivy_tpu.mesh import topology as mesh_topology

        if mesh is None:
            # Unified mesh selection (mesh/topology.py): sieve, lane
            # derive, and fused verify all see this one mesh instead of
            # probing jax.devices() per site.  None on single-device
            # hosts — every consumer takes its unsharded path.
            mesh = mesh_topology.get_mesh()
            self._mesh = mesh
        # Batches pad to devices x TILE_BUCKET so every shard gets whole
        # rows (the Pallas branch further multiplies by block_rows).
        self._tile_align = mesh_topology.mesh_device_count(mesh)

        if sieve == "gram":
            import jax

            from trivy_tpu.ops import gram_sieve as gs_mod

            self.gset: GramSet = (
                compiled.gset if compiled is not None
                else build_gram_set(self.pset)
            )
            self.overlap = GRAM_OVERLAP

            # Link codec (engine/link.py): when the ruleset's kept-value
            # alphabet fits a 4/6-bit width, rows transcode to packed
            # class ids on the host and unpack on-device ahead of the
            # match kernel; gram constants are rewritten into class space
            # so the same kernels run unchanged.  Wide alphabets fall
            # back to raw uint8 transparently (self._link stays None).
            from trivy_tpu.engine import link as link_mod

            _mode = link_mod.codec_mode()
            self._d2h_compact = _mode != "off"
            if _mode != "off":
                _alpha = (
                    getattr(compiled, "alphabet", None)
                    if compiled is not None
                    else None
                )
                if _alpha is None:
                    _alpha = link_mod.derive_alphabet(self.gset)
                self._link = link_mod.select_codec(_alpha, _mode, self.gset)
            if self._link is not None:
                self._staged_cols = self._link.coded_len(tile_len)
                self._codec_tag = ":" + self._link.codec_id
                cmasks, cvals = self._link.encode_grams(
                    self.gset.masks, self.gset.vals
                )
                unpack = self._link.make_unpack(tile_len)
            else:
                cmasks, cvals = self.gset.masks, self.gset.vals
                unpack = None

            on_tpu = mesh_topology.is_tpu()
            # Fused default: on for TPU hosts (where killing the d2h of
            # the hit matrix pays), opt-in elsewhere — explicit `fused=`
            # or TRIVY_TPU_FUSED=1/0 overrides either way.  CPU CI holds
            # the path to byte-parity via the fused-vs-legacy tests
            # rather than running it by default.
            _fenv = os.environ.get("TRIVY_TPU_FUSED", "")
            if self._fused_requested is not None:
                self._fused = bool(self._fused_requested)
            elif _fenv:
                self._fused = _fenv != "0"
            else:
                self._fused = on_tpu
            use_pallas = kernel == "pallas" or (kernel == "auto" and on_tpu)
            if use_pallas:
                # Pallas kernel (production path): gram constants baked into
                # the program, ~10x the XLA formulation.  With a mesh, the
                # same kernel runs per shard under shard_map (the round-2
                # review's "Pallas and the mesh are mutually exclusive" gap).
                from trivy_tpu.ops.gram_sieve_pallas import (
                    PallasGramSieve,
                    make_sharded_pallas_sieve,
                )

                sieve_obj = PallasGramSieve(cmasks, cvals)
                # Kernel output bits are over distinct (mask, val) pairs;
                # _candidates expands them back to gset gram order.  (In
                # class space a merged codec can collapse more pairs than
                # the raw constants would — the expansion handles both.)
                self._pallas_obj = sieve_obj
                if mesh is not None:
                    self._sieve_fn = make_sharded_pallas_sieve(
                        mesh, sieve_obj, pre=unpack
                    )
                    # Every shard must tile into whole Pallas blocks.
                    self._tile_align = self._tile_align * sieve_obj.block_rows
                elif unpack is not None:
                    self._sieve_fn = lambda rows: sieve_obj(unpack(rows))
                    # split handles for per-kernel attribution (the traced
                    # path times unpack apart from the match kernel)
                    self._unpack_fn = unpack
                    self._sieve_core = sieve_obj
                else:
                    self._sieve_fn = sieve_obj
                self._tile_buckets = TILE_BUCKETS_PALLAS
                if (
                    not self._max_tiles_explicit
                    and self.max_batch_tiles < self._tile_buckets[-1]
                ):
                    # Default cap tuned for the XLA path; the Pallas path
                    # amortizes per-call link latency with bigger batches.
                    # An explicit caller cap (memory bound) is respected:
                    # buckets are min-capped in _buckets().
                    self.max_batch_tiles = self._tile_buckets[-1]
                # Megakernel: same opt-in ladder as fused (explicit ctor
                # arg > TRIVY_TPU_MEGAKERNEL env > on-TPU default); rides
                # on the fused contract (it produces what the fused path
                # produces, one dispatch earlier), so fused-off disables
                # it outright.  Auto-mode TPU starts additionally pass
                # through the measured-rate gate in warmup().
                _menv = os.environ.get("TRIVY_TPU_MEGAKERNEL", "")
                if self._mega_requested is not None:
                    want_mega = bool(self._mega_requested)
                elif _menv:
                    want_mega = _menv != "0"
                else:
                    want_mega = self._fused and on_tpu
                if (
                    want_mega
                    and self._fused
                    and self.gset.num_grams > 0
                    and tile_len >= 256
                    and tile_len & (tile_len - 1) == 0
                ):
                    from trivy_tpu.ops.megakernel import (
                        MegaGramSieve,
                        make_sharded_megakernel,
                    )

                    self._mega = MegaGramSieve(
                        cmasks, cvals,
                        wmember=self.gset._wmember,
                        pmember=self.gset._pmember,
                        pwindows=self.gset._pwindows,
                        probe_has_gram=self.gset.probe_has_gram,
                        gate_member=self._gate_member,
                        gate_any=self._gate_any,
                        conj_member=self._conj_member,
                        conj_any=self._conj_any,
                        num_conjuncts=self._num_conjuncts,
                        row_len=tile_len,
                        sym_bits=(
                            self._link.sym_bits
                            if self._link is not None else None
                        ),
                    )
                    # Resident-row store keys carry the kernel id: a
                    # ruleset/codec change re-bakes the constants, and a
                    # stale fused verdict must never alias the new program.
                    self._kernel_tag = ":" + self._mega.kernel_id
                    if mesh is not None:
                        self._mega_fn = make_sharded_megakernel(
                            mesh, self._mega
                        )
                    self._mega_on = True
            else:
                masks, vals = gs_mod.pad_grams(cmasks, cvals)
                self._masks = jnp.asarray(masks)
                self._vals = jnp.asarray(vals)
                if mesh is not None:
                    fn = gs_mod.make_sharded_gram_sieve(mesh, unpack=unpack)
                elif unpack is not None:
                    fn = jax.jit(
                        lambda rows, m, v: gs_mod.gram_sieve_rows(
                            unpack(rows), m, v
                        )
                    )
                    self._unpack_fn = unpack
                    self._sieve_core = lambda rows: gs_mod._gram_sieve_jit(
                        rows, self._masks, self._vals
                    )
                else:
                    fn = gs_mod._gram_sieve_jit
                self._sieve_fn = lambda rows: fn(rows, self._masks, self._vals)
                self._tile_buckets = TILE_BUCKETS
        elif sieve == "lut":
            from trivy_tpu.engine import link as link_mod

            # No transcoder here (the LUT sieve's byte semantics are the
            # contract), but the d2h compaction is lossless and applies.
            self._d2h_compact = link_mod.d2h_compaction_enabled()
            self._lut = jnp.asarray(self.pset.build_lut())
            self.overlap = max(DEFAULT_OVERLAP, self.pset.jmax)
            if mesh is not None:
                from trivy_tpu.ops.sieve import make_sharded_sieve

                fn = make_sharded_sieve(mesh)
                self._sieve_fn = lambda tiles: fn(tiles, self._lut)
            else:
                from trivy_tpu.ops import sieve as sieve_mod

                self._sieve_fn = lambda tiles: sieve_mod._sieve_jit(
                    tiles, self._lut, tiles.shape[1]
                )
        else:
            raise ValueError(f"unknown sieve: {sieve}")

    # ------------------------------------------------------------------

    @property
    def ruleset_digest(self) -> str:
        """Content digest of the active rule material (registry/digest.py);
        seeded by a warm-start artifact, else computed lazily on first use
        (response headers, /metrics, bench)."""
        if self._ruleset_digest is None:
            from trivy_tpu.registry.digest import ruleset_digest

            self._ruleset_digest = ruleset_digest(self.ruleset)
        return self._ruleset_digest

    def _buckets(self) -> list[int]:
        """Row batch shapes: TILE_BUCKETS capped by max_batch_tiles, rounded
        up to the mesh-device multiple."""
        align = self._tile_align
        caps = [b for b in self._tile_buckets if b <= self.max_batch_tiles]
        if not caps or caps[-1] != self.max_batch_tiles:
            caps.append(self.max_batch_tiles)
        return [-(-b // align) * align for b in caps]

    def warmup(self) -> None:  # graftlint: fetch-boundary
        """Compile every row-bucket shape and build the host verifier
        ahead of timed scanning (the DFA table build costs ~0.7s and must
        not land inside the first scan)."""
        self._host_verifier()
        if self.sieve == "native":
            from trivy_tpu.native import load_native

            load_native()
            return
        import jax
        import jax.numpy as jnp

        for rows in self._buckets():
            # Staged width: the codec ships packed class ids, so every
            # bucket's compiled shape is the CODED row width.
            batch = jnp.zeros((rows, self._staged_cols), dtype=jnp.uint8)
            jax.block_until_ready(self._sieve_fn(batch))
        if self._mega is not None and self._mega_on:
            # Compile (or AOT-load) the megakernel at the smallest
            # bucket x minimum file pad — the shape the gate pricing
            # dispatch uses; other (rows, fp) shapes compile on first
            # use and land in the same AOT store.
            rows0 = self._buckets()[0]
            fn = (
                self._mega_fn if self._mega_fn is not None
                else self._mega_exec(rows0, 8)
            )
            args = (
                jnp.zeros((rows0, self._staged_cols), jnp.uint8),
                jnp.zeros((1, 8), jnp.int32),
                jnp.full((1, 8), -1, jnp.int32),
                jnp.zeros((8, 1), jnp.int8),
            )
            jax.block_until_ready(fn(*args))
            if self._mega_requested is None and not os.environ.get(
                "TRIVY_TPU_MEGAKERNEL", ""
            ):
                # Auto mode only: explicit ctor/env choices are never
                # second-guessed by the gate (tests and operators pin).
                self._price_mega_gate(fn, args, rows0)

    def _price_mega_gate(self, fn, args, rows: int) -> None:
        """Price the megakernel gate from a MEASURED warm dispatch: the
        fused program must clear both the fused link bar and an absolute
        exec-rate floor (hybrid.MEGA_GATE_EXEC_MB_S) — a chip whose fused
        dispatch crawls should keep the staged path, whose stages pipeline
        across chunks.  Records the decision in the gate audit log."""
        import time as _time

        import jax

        from trivy_tpu.engine import link as link_mod
        from trivy_tpu.engine.hybrid import gate_terms
        from trivy_tpu.mesh import topology as mesh_topology
        from trivy_tpu.obs import gatelog

        t0 = _time.perf_counter()
        jax.block_until_ready(fn(*args))
        dt = max(_time.perf_counter() - t0, 1e-9)
        rate = rows * self.tile_len / dt / 1e6  # raw MB/s through the sieve
        terms = gate_terms(
            h2d_ratio=self._link.ratio if self._link is not None else 1.0,
            d2h_ratio=link_mod.FUSED_MASK_D2H_RATIO,
            profile="mega",
            devices=mesh_topology.mesh_device_count(self._mesh),
            exec_mb_s=rate,
        )
        self._mega_on = bool(terms["wide"])
        gatelog.record(
            requested="auto",
            backend="fused",
            reason="mega-wide" if self._mega_on else "mega-narrow",
            profile=terms["profile"],
            devices=terms["devices"],
            link_mb_per_sec=terms["link_mb_per_sec"],
            link_rtt_s=terms["link_rtt_s"],
            h2d_ratio=terms["h2d_ratio"],
            d2h_ratio=terms["d2h_ratio"],
            eff_mb_per_sec=terms["eff_mb_per_sec"],
            eff_threshold_mb_per_sec=terms["eff_threshold_mb_per_sec"],
            rtt_threshold_s=terms["rtt_threshold_s"],
            codec=terms["codec"],
            margin=terms["margin"],
        )

    def _build_member_matrices(self) -> None:
        """Dense probe->rule membership for the matmul-form candidate
        resolution (fast path for bool probe hits)."""
        from trivy_tpu.engine.probes import MAX_CONJUNCTS

        p = len(self.pset.probes)
        r = len(self.pset.plans)
        self._gate_member = np.zeros((p, r), dtype=np.float32)
        self._conj_member = np.zeros((p, r * MAX_CONJUNCTS), dtype=np.float32)
        self._num_conjuncts = MAX_CONJUNCTS
        for i, plan in enumerate(self.pset.plans):
            for pid in plan.gate_probe_ids:
                self._gate_member[pid, i] = 1.0
            for k, conjunct in enumerate(plan.anchor_conjuncts):
                for pid in conjunct:
                    self._conj_member[pid, i * MAX_CONJUNCTS + k] = 1.0

    def candidate_matrix_bool(self, probe_bool: np.ndarray) -> np.ndarray:
        """[F, P] bool probe hits -> [F, R] bool candidates (matmul form)."""
        f = len(probe_bool)
        r = len(self.pset.plans)
        ph = probe_bool.astype(np.float32)
        gate_ok = ~self._gate_any[None, :] | (ph @ self._gate_member > 0)
        conj_hit = (ph @ self._conj_member).reshape(
            f, r, self._num_conjuncts
        ) > 0
        conj_ok = (~self._conj_any[None] | conj_hit).all(-1)
        return gate_ok & conj_ok

    def candidate_matrix(self, file_hits: np.ndarray) -> np.ndarray:
        """[F, R] bool candidate matrix from per-file probe bitmaps [F, Pw]."""
        h = file_hits[:, None, :]  # [F, 1, Pw]
        gate_ok = ~self._gate_any[None, :] | (h & self._gate[None]).any(-1)
        conj_hit = (file_hits[:, None, None, :] & self._conj[None]).any(-1)  # [F,R,K]
        conj_ok = (~self._conj_any[None] | conj_hit).all(-1)
        return gate_ok & conj_ok

    @staticmethod
    def _pad_chunk(rows: np.ndarray, off: int, max_rows: int) -> np.ndarray:
        part = rows[off : off + max_rows]
        if len(part) < max_rows:
            part = np.concatenate(
                [part, np.zeros((max_rows - len(part), part.shape[1]), np.uint8)]
            )
        return np.ascontiguousarray(part)

    def _exec_fn(self):
        """Sieve callable for pipelined dispatch.  On TPU the row buffer is
        donated so XLA reuses the staging allocation in place of an extra
        device-side copy; on other backends donation is a silent no-op
        warning, so the plain callable runs."""
        if self._sieve_donated is None:
            import jax

            from trivy_tpu.mesh import topology as mesh_topology

            fn = self._sieve_fn
            if mesh_topology.backend_is_tpu():
                fn = jax.jit(lambda r: self._sieve_fn(r), donate_argnums=0)
            self._sieve_donated = fn
        return self._sieve_donated

    def _encode_chunk(self, part: np.ndarray) -> tuple[np.ndarray, int]:
        """(staged buffer, raw padded bytes): the link codec transcodes
        the padded chunk to packed class ids; without one the chunk
        ships as-is.  Callers account bytes_on_link_* at actual staging
        time, so resident hits and dedupe skips never count."""
        if self._link is None:
            return part, part.nbytes
        import time as _time

        t0 = _time.perf_counter()
        with obs_trace.span("chunk.encode", bytes=part.nbytes):
            ph = obs_metrics.device_phase("encode")
            coded = self._link.encode_rows(part)
            ph.done(coded)
        self.stats.encode_s += _time.perf_counter() - t0
        return coded, part.nbytes

    def _count_link(self, raw_nbytes: int, coded_nbytes: int) -> None:
        self.stats.bytes_on_link_raw += raw_nbytes
        self.stats.bytes_on_link_coded += coded_nbytes

    def _note_dispatch(self) -> None:
        # Per-engine stats plus the process-global event the result
        # cache's cold-vs-warm assertions diff (cache-smoke / BENCH_CACHE
        # prove the warm pass dispatches nothing to the device).
        self.stats.device_dispatches += 1
        cache_stats.event("device_dispatch")

    def _fetch_hits(self, out) -> np.ndarray:  # graftlint: fetch-boundary
        """D2H of one chunk's hit words.  With compaction on, the device
        reduces to a nonzero-row bitmap and ships only the hit rows
        (engine/link.py); either way the raw/actual byte pair lands in
        stats."""
        if self._d2h_compact:
            from trivy_tpu.engine import link as link_mod

            arr, raw_b, got_b = link_mod.fetch_rows_compact(out)
        else:
            arr = np.asarray(out)
            raw_b = got_b = arr.nbytes
        self.stats.d2h_bytes_raw += raw_b
        self.stats.d2h_bytes += got_b
        return arr

    def _resident_dispatch(
        self, part: np.ndarray, real_rows: int | None = None
    ) -> np.ndarray:
        """One synchronous dispatch through the resident-chunk LRU: a
        digest-identical chunk never re-crosses the link.  The digest is
        taken over the CODED buffer and suffixed with the codec id, so a
        codec change (env flip, ruleset reload) can never alias a raw
        chunk's cached hit words."""
        from trivy_tpu.engine.pipeline import chunk_digest

        buf, raw_n = self._encode_chunk(part)
        digest = None
        # Sync-timing passes measure the raw link; a resident hit would
        # skip the transfer being measured.
        if self._resident.capacity and not os.environ.get(
            "TRIVY_TPU_SYNC_TIMING"
        ):
            digest = chunk_digest(buf) + self._codec_tag
            hit = self._resident.get(digest)
            if hit is not None:
                self.stats.resident_hits += 1
                return hit
        self._note_dispatch()
        self._count_link(raw_n, buf.nbytes)
        out = self._dispatch_rows(buf, real_rows=real_rows)
        if digest is not None:
            self._resident.put(digest, out)
        return out

    def _sieve_rows(self, rows: np.ndarray) -> np.ndarray:
        """Run the device sieve over fixed-shape row chunks; returns the
        per-row packed hit words [T, W]."""
        from trivy_tpu.engine.pipeline import (
            ChunkPipeline,
            chunk_digest,
            stage_rows,
        )

        buckets = self._buckets()
        max_rows = buckets[-1]
        total = len(rows)
        fit = next((b for b in buckets if total <= b), None)
        if fit is not None:
            return self._resident_dispatch(
                self._pad_chunk(rows, 0, fit), real_rows=total
            )[:total]
        if os.environ.get("TRIVY_TPU_SYNC_TIMING"):
            # Forced-sync decomposition (bench's h2d/exec split): serial by
            # design so the phase boundary stays measurable.
            chunks = []
            for off in range(0, total, max_rows):
                buf, raw_n = self._encode_chunk(
                    self._pad_chunk(rows, off, max_rows)
                )
                self._note_dispatch()
                self._count_link(raw_n, buf.nbytes)
                chunks.append(self._dispatch_rows(buf))
            return np.concatenate(chunks)[:total]

        # Chunked pipeline (engine/pipeline.py): h2d staging of chunk N+1
        # (async device_put) overlaps exec of chunk N (donated buffer on
        # TPU) and the d2h fetch of chunk N-1, bounded at pipeline_depth
        # chunks in flight; digest-unchanged chunks come from the resident
        # LRU without touching the link at all.
        n_chunks = -(-total // max_rows)
        outs: list = [None] * n_chunks
        exec_fn = self._exec_fn()

        def stage(ci):
            part = self._pad_chunk(rows, ci * max_rows, max_rows)
            buf, raw_n = self._encode_chunk(part)
            digest = None
            if self._resident.capacity:
                digest = chunk_digest(buf) + self._codec_tag
                hit = self._resident.get(digest)
                if hit is not None:
                    return (digest, hit, True, memwatch.NOOP_HANDLE)
            self._count_link(raw_n, buf.nbytes)
            with obs_trace.span("chunk.h2d", chunk=ci, bytes=buf.nbytes):
                faults.fire("device.put")
                # Staging buffers live device-side for up to `depth`
                # chunks; the per-device ledger handles ride the pipeline
                # handle and release at finish (or cancel on a drained
                # pipeline).  Meshed engines split the chunk into one
                # staging lane per device here.
                dev, mw = stage_rows(
                    buf,
                    self._mesh,
                    real_rows=max(0, min(max_rows, total - ci * max_rows)),
                )
            return (digest, dev, False, mw)

        def execute(ci, staged):
            digest, dev, hit, mw = staged
            if hit:
                self.stats.resident_hits += 1
                return (digest, dev, True, mw)
            self._note_dispatch()
            with obs_trace.span("chunk.exec", chunk=ci):
                faults.fire("device.exec")
                # traced runs take the per-kernel attributed path (fenced
                # unpack/sieve-step sections); untraced runs keep the
                # donated fused dispatch and full pipeline overlap
                out = (
                    self._exec_attributed(dev)
                    if obs_trace.enabled()
                    else exec_fn(dev)
                )
            return (digest, out, False, mw)

        def finish(ci, handle):
            digest, out, hit, mw = handle
            mw.release()
            if not hit:
                with obs_trace.span("chunk.fetch", chunk=ci):
                    faults.fire("device.fetch")
                    ph = obs_metrics.device_phase("compact")
                    out = self._fetch_hits(out)
                    ph.done()
                if digest is not None:
                    self._resident.put(digest, out)
            outs[ci] = out

        def cancel(ci, handle):
            handle[3].release()

        pipe = ChunkPipeline(
            stage, execute, finish, depth=self.pipeline_depth,
            cancel=cancel,
        )
        pipe.run(range(n_chunks))
        self.stats.h2d_overlap_s += pipe.stats.h2d_overlap_s
        return np.concatenate(outs)[:total]

    def _use_fused_derive(self) -> bool:
        """Fused residency + on-device lane derive applies on the gram
        jax path — meshed included: the derivation runs under the
        partition plan (row tensors sharded, membership matmul constants
        replicated; GSPMD keeps the cross-shard cumsum exact) — and never
        under sync-timing decomposition (whose phase boundaries assume
        the serial host path)."""
        return (
            self._fused
            and self.sieve == "gram"
            and self.gset.num_grams > 0
            and not os.environ.get("TRIVY_TPU_SYNC_TIMING")
        )

    def _get_row_store(self):
        if self._row_store is None:
            from trivy_tpu.engine.pipeline import ResidentRowStore

            self._row_store = ResidentRowStore()
        return self._row_store

    def _sieve_rows_fused(self, rows: np.ndarray):
        """`_sieve_rows` with device residency: chunk hit words STAY on
        device (the return is a [Tpad, W] device array — Tpad is the
        bucket-padded row count, so downstream jit shapes stay bounded),
        and each chunk's staged rows + hit words enter the
        ResidentRowStore under the chunk digest, where the fused verify
        walk (engine/nfa_device.py) and digest-identical rescans read
        them back without re-crossing the link.  The exec path is the
        NON-donated sieve: donation would hand the staged rows'
        allocation back to XLA and invalidate the residency."""
        import jax.numpy as jnp

        from trivy_tpu.engine.pipeline import (
            ChunkPipeline,
            chunk_digest,
            stage_rows,
        )

        store = self._get_row_store()
        buckets = self._buckets()
        max_rows = buckets[-1]
        total = len(rows)
        fit = next((b for b in buckets if total <= b), None)
        if fit is not None:
            buf, raw_n = self._encode_chunk(self._pad_chunk(rows, 0, fit))
            digest = chunk_digest(buf) + self._codec_tag
            if store.capacity:
                res = store.rows(digest)
                if res is not None:
                    self.stats.resident_hits += 1
                    return res[1]
            self._note_dispatch()
            self._count_link(raw_n, buf.nbytes)
            with obs_trace.span("chunk.h2d", bytes=buf.nbytes):
                faults.fire("device.put")
                # Residency owns the ledger entry (store.put_rows tracks
                # per device); staging itself stays untracked here.
                dev, _mw = stage_rows(
                    buf, self._mesh, real_rows=total, track=False
                )
            with obs_trace.span("chunk.exec"):
                faults.fire("device.exec")
                out = self._exec_attributed(dev)
            if store.capacity:
                store.put_rows(digest, dev, out)
            return out
        n_chunks = -(-total // max_rows)
        outs: list = [None] * n_chunks

        def stage(ci):
            part = self._pad_chunk(rows, ci * max_rows, max_rows)
            buf, raw_n = self._encode_chunk(part)
            digest = chunk_digest(buf) + self._codec_tag
            if store.capacity:
                res = store.rows(digest)
                if res is not None:
                    return (digest, res[0], res[1], True)
            self._count_link(raw_n, buf.nbytes)
            with obs_trace.span("chunk.h2d", chunk=ci, bytes=buf.nbytes):
                faults.fire("device.put")
                dev, _mw = stage_rows(
                    buf,
                    self._mesh,
                    real_rows=max(0, min(max_rows, total - ci * max_rows)),
                    track=False,
                )
            return (digest, dev, None, False)

        def execute(ci, staged):
            digest, dev, out, hit = staged
            if hit:
                self.stats.resident_hits += 1
                return staged
            self._note_dispatch()
            with obs_trace.span("chunk.exec", chunk=ci):
                faults.fire("device.exec")
                out = self._exec_attributed(dev)
            return (digest, dev, out, False)

        def finish(ci, handle):
            digest, dev, out, hit = handle
            if not hit and store.capacity:
                # residency bytes ledger through the store's memwatch
                # component ("resident-rows"); capacity-0 stores keep the
                # arrays only until `outs` is consumed
                store.put_rows(digest, dev, out)
            outs[ci] = out

        pipe = ChunkPipeline(
            stage, execute, finish, depth=self.pipeline_depth
        )
        pipe.run(range(n_chunks))
        self.stats.h2d_overlap_s += pipe.stats.h2d_overlap_s
        return jnp.concatenate(outs)

    def _derive_fn(self):
        """Jitted on-device candidate derivation, built once per engine:
        hit words -> per-file gram intervals (cumsum + row-range
        difference, mirroring DenseBatch.file_hits) -> window/probe/gate
        membership resolution as int8 MXU contractions -> [Fp, R] uint8
        candidates.  The membership matmuls run int8 x int8 -> int32
        `dot_general` against baked 0/1 constant matrices (the MXU-native
        form — the PR 5 class-space alphabet bounds every operand to a
        membership bit), and the interval cumsum stays int32; every value
        is an exact small-integer count, so the device result is
        bit-identical to the host f32 derivation it replaced (integer
        thresholds on integer counts — see ops/megakernel.py module doc
        for the bound argument)."""
        cached = getattr(self, "_derive_jit", None)
        if cached is not None:
            return cached
        import jax
        import jax.numpy as jnp

        gset = self.gset
        pallas_obj = getattr(self, "_pallas_obj", None)
        if pallas_obj is not None and len(pallas_obj.gram_expand):
            expand = jnp.asarray(
                np.asarray(pallas_obj.gram_expand, dtype=np.int32)
            )
        else:
            n = (
                pallas_obj.num_distinct
                if pallas_obj is not None
                else gset.num_grams
            )
            expand = jnp.arange(n, dtype=jnp.int32)
        wmember = np.asarray(gset._wmember).astype(np.int8)  # [G, W] 0/1
        pmember = np.asarray(gset._pmember).astype(np.int8)  # [W, P] 0/1
        pwindows = np.asarray(gset._pwindows).astype(np.int32)  # [P]
        nogram = jnp.asarray(~gset.probe_has_gram)  # [P] bool
        gate_member = np.asarray(self._gate_member).astype(np.int8)
        conj_member = np.asarray(self._conj_member).astype(np.int8)
        gate_any = jnp.asarray(self._gate_any)  # [R] bool
        conj_any = jnp.asarray(self._conj_any)  # [R, K] bool
        r = len(self.pset.plans)
        k = self._num_conjuncts

        def idot(a, b):
            return jax.lax.dot_general(
                a.astype(jnp.int8), jnp.asarray(b),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )

        @jax.jit
        def derive(hits, lo, hi, valid):
            # hits [T, W] uint32 packed gram words; lo/hi [Fp] int32 row
            # ranges (hi INCLUSIVE, packing.DenseBatch contract); valid
            # [Fp] bool (False rows — padding, empty files — derive all
            # zero gram hits, same as file_hits)
            t = hits.shape[0]
            bits = (
                (hits[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
            ).reshape(t, -1)[:, expand].astype(jnp.int32)  # [T, G]
            cs = jnp.cumsum(bits, axis=0)
            csz = jnp.concatenate(
                [jnp.zeros((1, bits.shape[1]), jnp.int32), cs]
            )
            lo_c = jnp.clip(lo, 0, t)
            hi_c = jnp.clip(hi + 1, 0, t)
            gh = ((csz[hi_c] - csz[lo_c]) > 0) & valid[:, None]  # [Fp, G]
            win = idot(gh, wmember) > 0
            ph = (idot(win, pmember) >= pwindows[None, :]) | nogram[None, :]
            gate_ok = (~gate_any[None, :]) | (idot(ph, gate_member) > 0)
            conj_hit = idot(ph, conj_member).reshape(-1, r, k) > 0
            conj_ok = (~conj_any[None] | conj_hit).all(-1)
            return (gate_ok & conj_ok).astype(jnp.uint8)

        self._derive_jit = derive
        return derive

    def _derive_candidates_device(self, batch, hits_dev) -> np.ndarray:
        """Candidate lane derivation without the hit-matrix round-trip:
        the sieve's device-resident hit words feed the jitted derivation
        and only the (compacted) [F, R] candidate matrix crosses the
        link.  File count pads to a power of two so the jit
        specializations stay bounded at log2(F)."""
        import jax.numpy as jnp

        f = batch.num_files
        if f == 0:
            return np.zeros((0, len(self.pset.plans)), dtype=bool)
        fp = max(8, 1 << (f - 1).bit_length())
        lo = np.zeros(fp, np.int32)
        hi = np.full(fp, -1, np.int32)  # padded files: hi < lo -> invalid
        lo[:f] = batch.file_row_lo
        hi[:f] = batch.file_row_hi
        valid = hi >= lo
        derive = self._derive_fn()
        ph = obs_metrics.device_phase("lane.derive")
        out = derive(
            hits_dev, jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(valid)
        )
        ph.done(out)
        arr = self._fetch_hits(out)  # compacted d2h + byte accounting
        return arr[:f].astype(bool)

    @property
    def megakernel_active(self) -> bool:
        """True when the fused one-dispatch program is built and enabled
        (the scheduler's step-down rung keys on this)."""
        return self._mega is not None and self._mega_on

    def _use_megakernel(self) -> bool:
        return self.megakernel_active and self._use_fused_derive()

    def _mega_exec(self, rows: int, fp: int):
        """Compiled megakernel executable for the (rows, fp) shape pair,
        engine-cached.  With an AOT cache dir configured, executables
        persist in the registry artifact store keyed (platform, jax
        version, ruleset digest, kernel id, shape) — a warm fleet start
        deserializes instead of compiling (registry/aotcache.py; any
        validation failure falls back to a fresh compile)."""
        cache = getattr(self, "_mega_exec_cache", None)
        if cache is None:
            cache = self._mega_exec_cache = {}
        key = (rows, fp)
        fn = cache.get(key)
        if fn is not None:
            return fn
        mega = self._mega
        fused = mega.fused_fn()
        fn = fused
        if self._aot_dir:
            import jax

            from trivy_tpu.registry import aotcache

            exe = aotcache.get_or_compile(
                self._aot_dir,
                platform=jax.devices()[0].platform,
                ruleset_digest=self.ruleset_digest,
                kernel_id=mega.kernel_id,
                shape=key,
                lower_fn=lambda: fused.lower(
                    *mega.aot_specs(rows, fp)
                ).compile(),
            )
            if exe is not None:
                fn = exe
        cache[key] = fn
        return fn

    def _mega_candidates(self, batch) -> np.ndarray | None:
        """One fused dispatch from packed bytes to verdict bits: stage
        the coded rows, run the megakernel (unpack/sieve/derive live in
        VMEM — no intermediate ever lands in HBM), fetch the packed
        1-bit-per-lane mask.  Returns the [F, R] bool candidate matrix,
        or None when the batch exceeds the single-dispatch envelope
        (multi-chunk row counts, > MEGA_MAX_FILES files) — the staged
        fused path takes over, byte-identically."""
        import hashlib as _hashlib

        from trivy_tpu.engine import link as link_mod
        from trivy_tpu.engine.pipeline import chunk_digest, stage_rows
        from trivy_tpu.ops.megakernel import MEGA_MAX_FILES

        import jax.numpy as jnp
        import time as _time

        f = batch.num_files
        if f == 0:
            return np.zeros((0, len(self.pset.plans)), dtype=bool)
        total = len(batch.rows)
        fit = next((b for b in self._buckets() if total <= b), None)
        if fit is None or f > MEGA_MAX_FILES:
            return None
        fp = max(8, 1 << (f - 1).bit_length())
        lo = np.zeros((1, fp), np.int32)
        hi = np.full((1, fp), -1, np.int32)  # padding: hi < lo -> invalid
        lo[0, :f] = batch.file_row_lo
        hi[0, :f] = batch.file_row_hi
        valid = (hi >= lo).astype(np.int8).reshape(fp, 1)

        t0 = _time.perf_counter()
        buf, raw_n = self._encode_chunk(self._pad_chunk(batch.rows, 0, fit))
        # Store key: chunk digest + codec + KERNEL id + the file-interval
        # digest — identical row bytes under a different file split (or a
        # re-baked program) must never alias a cached verdict mask.
        digest = (
            chunk_digest(buf) + self._codec_tag + self._kernel_tag + ":"
            + _hashlib.blake2b(
                lo.tobytes() + hi.tobytes(), digest_size=8
            ).hexdigest()
        )
        store = self._get_row_store()
        mask_dev = None
        if store.capacity:
            res = store.rows(digest)
            if res is not None:
                self.stats.resident_hits += 1
                mask_dev = res[1]
        if mask_dev is None:
            self._note_dispatch()
            self._count_link(raw_n, buf.nbytes)
            with obs_trace.span("chunk.h2d", bytes=buf.nbytes):
                faults.fire("device.put")
                dev, _mw = stage_rows(
                    buf, self._mesh, real_rows=total, track=False
                )
            lo_d = jnp.asarray(lo)
            hi_d = jnp.asarray(hi)
            v_d = jnp.asarray(valid)
            with obs_trace.span("sieve.megakernel", rows=fit, files=f):
                faults.fire("device.exec")
                ph = obs_metrics.device_phase("sieve.megakernel")
                fn = (
                    self._mega_fn if self._mega_fn is not None
                    else self._mega_exec(fit, fp)
                )
                mask_dev = fn(dev, lo_d, hi_d, v_d)
                ph.done(mask_dev)
            if store.capacity:
                store.put_rows(digest, dev, mask_dev)
        self.stats.sieve_s += _time.perf_counter() - t0

        t0 = _time.perf_counter()
        with obs_trace.span("chunk.fetch"):
            faults.fire("device.fetch")
            r = len(self.pset.plans)
            # raw_bytes: what the staged path's [Fp, R] uint8 candidate
            # fetch would have moved for the same derive.
            lanes, raw_b, got_b = link_mod.fetch_mask_packed(
                mask_dev, fp * r
            )
            self.stats.d2h_bytes_raw += raw_b
            self.stats.d2h_bytes += got_b
        cand = lanes.reshape(fp, self._mega.mask_bytes * 8)[:f, :r]
        self.stats.candidate_s += _time.perf_counter() - t0
        return cand

    def scan_batch_staged_sieve(self, items: list[tuple[str, bytes]]):
        """scan_batch with the megakernel held off for this call — the
        serve scheduler's one-rung step-down when the fused dispatch
        raises; the staged fused path (whose own legacy/host rungs sit
        below) scans the batch instead."""
        prev = self._mega_on
        self._mega_on = False
        try:
            return self.scan_batch(items)
        finally:
            self._mega_on = prev

    def _exec_attributed(self, dev):
        """One sieve execution with per-kernel attribution.  When tracing
        is enabled the codec's device-side unpack stage and the match
        kernel run as separate fenced `device_phase` sections (the fence —
        block_until_ready before reading the clock — is what pins an
        async dispatch's wall time to ITS kernel).  Tracing off runs the
        fused jitted composition untouched: no fences, no split, the
        disabled path costs one predicate."""
        if not obs_trace.enabled():
            return self._sieve_fn(dev)
        unpack = getattr(self, "_unpack_fn", None)
        core = getattr(self, "_sieve_core", None)
        if unpack is None or core is None:
            ph = obs_metrics.device_phase("sieve-step")
            out = self._sieve_fn(dev)
            ph.done(out)
            return out
        ph = obs_metrics.device_phase("unpack")
        rows = unpack(dev)
        ph.done(rows)
        ph = obs_metrics.device_phase("sieve-step")
        out = core(rows)
        ph.done(out)
        return out

    def _dispatch_rows(
        self, buf: np.ndarray, real_rows: int | None = None
    ) -> np.ndarray:
        """One sieve dispatch over an already-staged (possibly coded)
        buffer.  Under TRIVY_TPU_SYNC_TIMING=1 the h2d transfer is forced
        to complete (a 1-element fetch round-trip — block_until_ready
        returns early on relay links) before the kernel runs, splitting
        stats.h2d_s from stats.exec_s; bench uses this to measure how
        link-bound the all-device engine really is without trusting a
        probe's rate estimate."""
        import time as _time

        import jax
        import jax.numpy as jnp

        if not os.environ.get("TRIVY_TPU_SYNC_TIMING"):
            # Split so the trace shows where a synchronous dispatch's time
            # lands (dispatch is async; the fetch span absorbs the wait).
            with obs_trace.span("chunk.h2d", bytes=buf.nbytes):
                faults.fire("device.put")
                if self._mesh is not None:
                    from trivy_tpu.engine.pipeline import stage_rows

                    dev, _mw = stage_rows(
                        buf, self._mesh, real_rows=real_rows, track=False
                    )
                else:
                    dev = jnp.asarray(buf)
            with obs_trace.span("chunk.exec"):
                faults.fire("device.exec")
                out = self._exec_attributed(dev)
            with obs_trace.span("chunk.fetch"):
                faults.fire("device.fetch")
                ph = obs_metrics.device_phase("compact")
                arr = self._fetch_hits(out)
                ph.done()
                return arr
        t0 = _time.perf_counter()
        dev = jax.device_put(buf)
        np.asarray(dev[:1, :1])  # forced round-trip  # graftlint: ignore[GL004]
        self.stats.h2d_s += _time.perf_counter() - t0
        t0 = _time.perf_counter()
        out = self._fetch_hits(self._sieve_fn(dev))
        self.stats.exec_s += _time.perf_counter() - t0
        return out

    def _candidates(self, contents: list[bytes]) -> np.ndarray:
        """[F, R] bool candidate matrix for a content batch."""
        import time as _time

        if self.sieve == "lut":
            batch = pack(contents, self.tile_len, self.overlap)
            self.stats.tiles += len(batch.tiles)
            tile_hits = self._sieve_rows(batch.tiles)
            return self.candidate_matrix(batch.file_hits(tile_hits))

        t0 = _time.perf_counter()
        batch = pack_dense(contents, self.tile_len, self.overlap)
        self.stats.pack_s += _time.perf_counter() - t0
        self.stats.tiles += len(batch.rows)
        if self.sieve == "native":
            from trivy_tpu.native import gram_sieve_native
            from trivy_tpu.ops.gram_sieve import gram_sieve_numpy

            hits = gram_sieve_native(batch.rows, self._masks_np, self._vals_np)
            if hits is None:
                hits = gram_sieve_numpy(batch.rows, self._masks_np, self._vals_np)
            # Pack per-row bools into the shared word layout for file OR-ing.
            gw = -(-max(self.gset.num_grams, 1) // 32)
            padded = np.zeros((len(hits), gw * 32), dtype=np.uint32)
            padded[:, : self.gset.num_grams] = hits
            weights = np.uint32(1) << (np.arange(gw * 32, dtype=np.uint32) % 32)
            word_hits = (
                (padded * weights[None, :])
                .reshape(len(hits), gw, 32)
                .sum(axis=-1, dtype=np.uint32)
            )
        else:  # device gram sieve
            if self._use_megakernel():
                # Megakernel: the whole sieve->candidate chain is ONE
                # dispatch whose only d2h is the packed verdict mask.
                # None = batch outside the single-dispatch envelope;
                # fall through to the staged fused path below.
                cand = self._mega_candidates(batch)
                if cand is not None:
                    return cand
            if self._use_fused_derive():
                # Fused path: hit words never leave the device — the
                # sieve output feeds candidate derivation in place, and
                # the only d2h of the whole sieve->candidate chain is
                # the compacted [F, R] matrix.  Byte-identical to the
                # host derivation below (same int-exact matmul pipeline).
                t0 = _time.perf_counter()
                hits_dev = self._sieve_rows_fused(batch.rows)
                self.stats.sieve_s += _time.perf_counter() - t0
                t0 = _time.perf_counter()
                cand = self._derive_candidates_device(batch, hits_dev)
                self.stats.candidate_s += _time.perf_counter() - t0
                return cand
            t0 = _time.perf_counter()
            word_hits = self._sieve_rows(batch.rows)  # [T, Gw] packed grams
            self.stats.sieve_s += _time.perf_counter() - t0

        t0 = _time.perf_counter()
        file_words = batch.file_hits(word_hits)  # [F, Gw] (or [F, Dw] pallas)
        bits = (
            (file_words[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
        ).astype(bool).reshape(len(file_words), -1)
        pallas_obj = getattr(self, "_pallas_obj", None)
        if pallas_obj is not None:
            # Pallas words are over distinct (mask, val) pairs; expand back
            # to the gset's per-gram attribution order.
            gram_hits = pallas_obj.expand_bool(bits[:, : pallas_obj.num_distinct])
        else:
            gram_hits = bits[:, : self.gset.num_grams]
        cand = self.candidate_matrix_bool(self.gset.probe_hits_bool(gram_hits))
        self.stats.candidate_s += _time.perf_counter() - t0
        return cand

    def _host_verifier(self):
        """Lazily-built host automaton verifier (engine/redfa.py): the
        same claim-killer the hybrid runs between its sieve and the
        oracle.  The gram-level candidate matrix has no per-hit class
        precision, so common-substring rules (twilio-api-key's 'SK')
        claim broadly; one C walk per (file, rule) pair keeps those out
        of the ~100us/pair Python oracle confirm."""
        if not hasattr(self, "_dfa_verifier_cache"):
            from trivy_tpu.native import load_native

            self._dfa_verifier_cache = None
            if load_native() is not None:
                from trivy_tpu.engine.redfa import DfaVerifier

                self._dfa_verifier_cache = DfaVerifier(self.ruleset.rules)
        return self._dfa_verifier_cache

    def _verify_candidates(
        self, items: list[tuple[str, bytes]], cand: np.ndarray
    ) -> np.ndarray:
        """Drop candidate (file, rule) pairs the host automaton refutes."""
        verifier = self._host_verifier()
        if verifier is None:
            return cand
        import ctypes
        import time as _time

        t0 = _time.perf_counter()
        with obs_trace.span("verify", files=len(items)):
            fis, ris = np.nonzero(cand)
            if len(fis):
                contents = [c for _, c in items]
                lens = np.fromiter(
                    (len(c) for c in contents), dtype=np.int64, count=len(items)
                )
                ptr_arr = (ctypes.c_char_p * len(items))(*contents)
                ok = verifier.verify_pairs_files(
                    ptr_arr, lens,
                    fis.astype(np.int32), ris.astype(np.int32),
                )
                cand = cand.copy()
                cand[fis[~ok.astype(bool)], ris[~ok.astype(bool)]] = False
        self.stats.verify_s += _time.perf_counter() - t0
        return cand

    def scan_batch(self, items: list[tuple[str, bytes]]) -> list[Secret]:
        """Scan (path, content) blobs; returns per-file Secret results."""
        import time as _time

        if self.program_table is not None:
            # Multi-program engine: the rule axis is the merged table, so
            # the single-program confirm below would hand the oracle
            # foreign rule indices.  Route through the demux (secret
            # program's slice keeps indices 0..N-1, so results are
            # byte-identical to a secret-only engine).
            return self.scan_programs(items, only=("secret",)).get(
                "secret", [Secret() for _ in items]
            )
        if not items:
            return []
        self.stats.files += len(items)
        self.stats.bytes += sum(len(c) for _, c in items)
        self.stats.pipeline_depth = self.pipeline_depth

        # Content-digest dedupe in front of the link: sieve/verify run over
        # distinct blobs only, candidates fan back out to every alias (the
        # byte-exact confirm below stays per (path, content) — path gating
        # is per-file).
        contents = [c for _, c in items]
        scan_items = items
        dd = None
        if self.dedupe and len(items) > 1:
            t0 = _time.perf_counter()
            dd = dedupe_blobs(contents)
            self.stats.pack_s += _time.perf_counter() - t0
            if dd.any_duplicates():
                self.stats.dedupe_saved_bytes += dd.saved_bytes
                scan_items = [items[int(i)] for i in dd.unique_index]
                contents = [c for _, c in scan_items]
            else:
                dd = None

        cand = self._candidates(contents)
        cand = self._verify_candidates(scan_items, cand)
        if dd is not None:
            cand = cand[dd.inverse]

        t0 = _time.perf_counter()
        results: list[Secret] = []
        with obs_trace.span("confirm", files=len(items)):
            for fi, (path, content) in enumerate(items):
                idxs = np.flatnonzero(cand[fi])
                if len(idxs) == 0:
                    # Preserve the reference's allow-path result shape
                    # (scanner.go:375-380 returns Secret{FilePath} for allowed
                    # paths, empty Secret otherwise) even when the sieve lets
                    # us skip the oracle entirely.
                    if self.oracle.allow_path(path):
                        results.append(Secret(file_path=path))
                    else:
                        results.append(Secret())
                    continue
                self.stats.candidate_pairs += len(idxs)
                res = self.oracle.scan(
                    path, content, rule_indices=idxs.tolist()
                )
                self.stats.confirmed_findings += len(res.findings)
                results.append(res)
        self.stats.confirm_s += _time.perf_counter() - t0
        return results

    def scan(self, file_path: str, content: bytes) -> Secret:
        return self.scan_batch([(file_path, content)])[0]

    def scan_programs(
        self,
        items: list[tuple[str, bytes]],
        only: tuple[str, ...] | None = None,
    ) -> dict[str, list]:
        """One device pass, per-program verdicts.

        Sieves the merged rule axis exactly like scan_batch (same pack,
        dedupe, candidate derivation), applies the host-DFA claim-killer
        only to columns whose program opted in (verify=True), then slices
        the candidate matrix per program and hands each slice to that
        program's resolve hook.  Returns {program_id: [verdict per item,
        in item order]}.  `only` restricts which programs RESOLVE — the
        device pass is one either way; skipping a resolve just skips its
        host-side confirm cost.
        """
        import time as _time

        table = self.program_table
        if table is None:
            raise RuntimeError(
                "scan_programs needs an engine built with a program_table "
                "(programs.make_program_engine)"
            )
        wanted = [
            (p, sl)
            for p, sl in table.slices()
            if only is None or p.program_id in only
        ]
        if not items:
            return {p.program_id: [] for p, _ in wanted}
        self.stats.files += len(items)
        self.stats.bytes += sum(len(c) for _, c in items)
        self.stats.pipeline_depth = self.pipeline_depth

        # Same dedupe-in-front-of-the-link as scan_batch: one sieve over
        # distinct blobs, candidates fan back out to every alias.
        contents = [c for _, c in items]
        scan_items = items
        dd = None
        if self.dedupe and len(items) > 1:
            t0 = _time.perf_counter()
            dd = dedupe_blobs(contents)
            self.stats.pack_s += _time.perf_counter() - t0
            if dd.any_duplicates():
                self.stats.dedupe_saved_bytes += dd.saved_bytes
                scan_items = [items[int(i)] for i in dd.unique_index]
                contents = [c for _, c in scan_items]
            else:
                dd = None

        cand = self._candidates(contents)
        vmask = table.verify_column_mask(cand.shape[1])
        if vmask.any():
            # The claim-killer refutes (file, rule) pairs by exact DFA
            # match — only sound for columns whose program opted in.
            # Zero the opt-out columns going in, splice their raw
            # candidacy back after (np.where keeps cand's dtype/shape).
            verified = self._verify_candidates(scan_items, cand & vmask[None, :])
            cand = np.where(vmask[None, :], verified, cand)
        if dd is not None:
            cand = cand[dd.inverse]

        out: dict[str, list] = {}
        for prog, sl in wanted:
            pslice = cand[:, sl]
            t0 = _time.perf_counter()
            verdicts = prog.resolve(self, items, pslice, sl.start)
            resolve_s = _time.perf_counter() - t0
            if len(verdicts) != len(items):
                raise RuntimeError(
                    f"program {prog.program_id!r} returned "
                    f"{len(verdicts)} verdicts for {len(items)} items"
                )
            st = self.program_stats.setdefault(
                prog.program_id,
                {
                    "files": 0,
                    "candidate_files": 0,
                    "candidate_pairs": 0,
                    "verdicts": 0,
                    "resolve_s": 0.0,
                },
            )
            st["files"] += len(items)
            st["candidate_files"] += int(pslice.any(axis=1).sum())
            st["candidate_pairs"] += int(pslice.sum())
            st["verdicts"] += prog.verdict_count(verdicts)
            st["resolve_s"] = round(st["resolve_s"] + resolve_s, 6)
            out[prog.program_id] = verdicts
        return out

    def programs_snapshot(self) -> dict:
        """Program-table attribution for /debug/programs and Explain."""
        if self.program_table is None:
            return {"enabled": False}
        snap = self.program_table.snapshot()
        for p in snap["programs"]:
            p.update(self.program_stats.get(p["id"], {}))
        snap["enabled"] = True
        return snap
