"""The TPU secret engine: device sieve -> candidate rules -> exact host confirm.

Pipeline (the TPU-native reformulation of pkg/fanal/secret/scanner.go Scan):

  1. Host packs blobs into overlapping tiles (scanner/packing.py).
  2. Device runs the packed shift-AND sieve (ops/sieve.py) over every byte,
     producing per-tile probe-hit bitmaps; tile axis shards over the mesh.
  3. Host ORs bitmaps per file, resolves per-file candidate rule sets via the
     precompiled gate/anchor masks (vectorized; typically empty).
  4. Host confirms candidates byte-exactly with the oracle restricted to the
     candidate subset — findings are byte-identical to the reference engine by
     construction (probes are necessary conditions; see engine/probes.py).

Per-file path gating (AllowPath etc.) happens in the oracle exactly as the
reference does it, so gating order is preserved.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from trivy_tpu.ftypes import Secret
from trivy_tpu.engine.oracle import OracleScanner
from trivy_tpu.engine.probes import ProbeSet, build_probe_set
from trivy_tpu.rules.model import RuleSet, SecretConfig, build_ruleset
from trivy_tpu.scanner.packing import DEFAULT_OVERLAP, DEFAULT_TILE_LEN, pack


def _round_up_pow2(n: int, lo: int = 8) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


@dataclass
class SieveStats:
    files: int = 0
    bytes: int = 0
    tiles: int = 0
    candidate_pairs: int = 0
    confirmed_findings: int = 0


class TpuSecretEngine:
    """Drop-in engine with the oracle's Scan semantics, device-accelerated."""

    def __init__(
        self,
        ruleset: RuleSet | None = None,
        config: SecretConfig | None = None,
        tile_len: int = DEFAULT_TILE_LEN,
        mesh=None,
        max_batch_tiles: int = 4096,
    ):
        self.ruleset = ruleset if ruleset is not None else build_ruleset(config)
        self.oracle = OracleScanner(self.ruleset)
        self.pset: ProbeSet = build_probe_set(self.ruleset.rules)
        self.tile_len = tile_len
        self.overlap = max(DEFAULT_OVERLAP, self.pset.jmax)
        self.max_batch_tiles = max_batch_tiles
        self.stats = SieveStats()

        self._gate, self._gate_any, self._conj, self._conj_any = self.pset.gate_masks()

        import jax.numpy as jnp

        self._lut = jnp.asarray(self.pset.build_lut())
        if mesh is not None:
            from trivy_tpu.ops.sieve import make_sharded_sieve

            self._mesh = mesh
            self._sieve_fn = make_sharded_sieve(mesh)
            self._tile_align = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        else:
            from trivy_tpu.ops import sieve as sieve_mod

            self._mesh = None
            self._sieve_fn = lambda tiles, lut: sieve_mod._sieve_jit(
                tiles, lut, tiles.shape[1]
            )
            self._tile_align = 1

    # ------------------------------------------------------------------

    def candidate_matrix(self, file_hits: np.ndarray) -> np.ndarray:
        """[F, R] bool candidate matrix from per-file probe bitmaps."""
        h = file_hits[:, None, :]  # [F, 1, Pw]
        gate_ok = ~self._gate_any[None, :] | (h & self._gate[None]).any(-1)
        conj_hit = (file_hits[:, None, None, :] & self._conj[None]).any(-1)  # [F,R,K]
        conj_ok = (~self._conj_any[None] | conj_hit).all(-1)
        return gate_ok & conj_ok

    def _run_sieve(self, contents: list[bytes]) -> np.ndarray:
        import jax.numpy as jnp

        from trivy_tpu.scanner.packing import count_tiles

        total = count_tiles(contents, self.tile_len, self.overlap)
        padded = _round_up_pow2(total, lo=self._tile_align or 8)
        padded = -(-padded // self._tile_align) * self._tile_align
        batch = pack(contents, self.tile_len, self.overlap, pad_tiles_to=padded)
        tile_hits = np.asarray(self._sieve_fn(jnp.asarray(batch.tiles), self._lut))
        self.stats.tiles += len(batch.tiles)
        return batch.file_hits(tile_hits)

    def scan_batch(self, items: list[tuple[str, bytes]]) -> list[Secret]:
        """Scan (path, content) blobs; returns per-file Secret results."""
        if not items:
            return []
        self.stats.files += len(items)
        self.stats.bytes += sum(len(c) for _, c in items)

        file_hits = self._run_sieve([c for _, c in items])
        cand = self.candidate_matrix(file_hits)

        results: list[Secret] = []
        for fi, (path, content) in enumerate(items):
            idxs = np.flatnonzero(cand[fi])
            if len(idxs) == 0:
                # Preserve the reference's allow-path result shape
                # (scanner.go:375-380 returns Secret{FilePath} for allowed
                # paths, empty Secret otherwise) even when the sieve lets us
                # skip the oracle entirely.
                if self.oracle.allow_path(path):
                    results.append(Secret(file_path=path))
                else:
                    results.append(Secret())
                continue
            self.stats.candidate_pairs += len(idxs)
            res = self.oracle.scan(path, content, rule_indices=idxs.tolist())
            self.stats.confirmed_findings += len(res.findings)
            results.append(res)
        return results

    def scan(self, file_path: str, content: bytes) -> Secret:
        return self.scan_batch([(file_path, content)])[0]
