"""Hybrid secret engine: native host pre-sieve -> candidate confirm.

The deployment-shape problem this solves: the device link is the scarce
resource.  Shipping every byte of a 100k-file corpus through a host<->TPU
link bounds throughput by link bandwidth no matter how fast the kernel is
(the measured axon relay moves ~50-80 MB/s end to end).  The reference
engine has the same structure in miniature: a cheap keyword prefilter
(bytes.Contains, pkg/fanal/secret/scanner.go:403) guards the expensive
regex loop.  The hybrid engine makes the same cut at system scale:

  1. HOST: the C++ anchored-pair-screen gram sieve (native/gram_sieve.cpp
     gram_sieve_files) runs over the joined byte stream at memory-ish speed
     with exact per-file attribution — every byte is seen once, on the host,
     where the bytes already live.
  2. HOST: gram hits -> probe hits -> per-file candidate rule sets via the
     precompiled gate/anchor masks (engine/probes.py), only for the few
     files with any gram hit.
  3. DEVICE (optional): the batched bit-parallel NFA verifies candidate
     (file, rule) pairs — only candidate bytes cross the link (a few % of
     the corpus on hit-sparse trees), and rule width is absorbed by the
     automaton batch instead of a host regex loop (engine/nfa_device.py).
  4. HOST: byte-exact confirm with the oracle restricted to verified pairs
     (findings byte-identical to the reference by construction).

Phases overlap: a worker thread sieves chunk k+1 while the main thread
resolves candidates and confirms chunk k, so wall-clock approaches
max(sieve, confirm) instead of their sum.

The all-device path (TpuSecretEngine, gram/Pallas sieve over the mesh) stays
the production path for hosts with wide device links and for multi-chip
scans; `make_secret_engine` picks per availability.
"""

from __future__ import annotations

import contextvars
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from trivy_tpu import lockcheck
from trivy_tpu.engine.device import SieveStats, TpuSecretEngine
from trivy_tpu.ftypes import Secret
from trivy_tpu.mesh import topology as mesh_topology
from trivy_tpu.obs import gatelog
from trivy_tpu.obs import trace as obs_trace

# Shared empty result for non-candidate files (see the confirm loop): reads
# only — consumers filter on findings and empties never reach mutation sites.
_EMPTY_SECRET = Secret()

DEFAULT_CHUNK_BYTES = 32 << 20
GAP = 4  # zero bytes between files: no 4-byte window spans two files


def _tpu_default_backend() -> bool:
    """True when jax is ALREADY initialized in this process and its
    default backend is a TPU.  The guard is deliberate: importing jax
    here would boot the TPU runtime (libtpu measured at ~4.5GB host RSS
    and seconds of init) just to ask whether a chip exists — a host-only
    scan must never pay that.  Processes that already use the device
    (the all-device engine, meshed scans) have paid it, and only they
    get the device verify seat by default."""
    import sys

    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        # Backend registry cache: populated only after something in this
        # process actually initialized a backend (ran a computation /
        # queried devices).  jax merely being imported (transitively via
        # flax/optax in an embedding app) must not trigger init here —
        # jax.default_backend() itself would boot the runtime.
        if not getattr(xla_bridge, "_backends", None):
            return False
        import jax

        return jax.default_backend() == "tpu"
    except Exception:  # graftlint: swallow(any backend probe error reads as no-tpu)
        return False


# Process-wide probe cache keyed by the active TRIVY_TPU_LINK override, so
# repeated engine construction never re-measures the link (each real probe
# ships 3x8MB through the relay — ~0.4s per HybridSecretEngine before the
# cache) while tests that flip the override still see their value.  Guarded
# by a lock: engines are built from thread pools in the server path.
_LINK_PROBE_LOCK = lockcheck.make_lock("engine.hybrid.link_probe")
_LINK_PROBE: dict[str, tuple[float, float]] = {}  # owner: _LINK_PROBE_LOCK


def probe_link(size: int = 8 << 20, attempts: int = 3):  # graftlint: fetch-boundary
    """(mb_per_sec, round_trip_s) of the host<->device link, measured once
    per process as the best of `attempts` `size`-byte transfers (relay
    tunnels jitter by 10x+ on small probes, so one sample misclassifies).
    The number that decides whether device verify can pay: candidate
    bytes must cross this link, so a relay-attached chip (bench host:
    ~50 MB/s, ~100ms RTT) loses to the host C verifier (0.3-37 GB/s) no
    matter how fast the kernel is, while PCIe/ICI-attached parts
    (10+ GB/s, ~100us) win whenever verify work dominates.
    TRIVY_TPU_LINK=wide|relay overrides (tests, known deployments)."""
    import os

    override = os.environ.get("TRIVY_TPU_LINK", "")
    with _LINK_PROBE_LOCK:
        cached = _LINK_PROBE.get(override)
        if cached is not None:
            return cached
        if override == "wide":
            result = (10_000.0, 1e-4)
        elif override == "relay":
            result = (50.0, 0.1)
        else:
            import time

            try:
                import jax

                # Incompressible probe payload: relay tunnels compress in
                # flight, and an all-zeros buffer measures 2-3x the rate
                # scan-shaped bytes actually get.
                buf = np.random.default_rng(0).integers(
                    0, 256, size=size, dtype=np.uint8
                )
                jax.device_put(buf[:8]).block_until_ready()  # wake the path
                best_dt, best_rtt = float("inf"), float("inf")
                for _ in range(attempts):
                    t0 = time.perf_counter()
                    np.asarray(jax.device_put(buf)[:1])
                    best_dt = min(best_dt, time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    np.asarray(jax.device_put(buf[:8])[:1])
                    best_rtt = min(best_rtt, time.perf_counter() - t0)
                result = (
                    size / max(best_dt - best_rtt, 1e-6) / 1e6,
                    best_rtt,
                )
            except Exception:  # graftlint: swallow(probe failure reads as a dead link)
                result = (0.0, 1.0)
        _LINK_PROBE[override] = result
        return result


# The device-verify bar: effective post-codec rate and dispatch RTT the
# link must clear before the gate routes verify to the device NFA.
GATE_EFF_MB_S = 1000.0
GATE_RTT_S = 0.01
# The fused path's RTT bar is far looser: the whole batch resolves in
# O(1) dispatches whose verify bytes never re-cross the link (resident
# rows + one keep-mask bit per lane), so per-dispatch latency amortizes
# over the batch instead of multiplying per round-trip.  Even a
# relay-attached chip clears this unless a single dispatch costs a
# visible fraction of a second.
FUSED_GATE_RTT_S = 0.25
# Megakernel exec floor (raw MB/s through the fused sieve): the one-rung
# fusion only beats the staged fused path when the single dispatch also
# EXECUTES fast — a chip whose fused program crawls is better served by
# the staged pipeline, whose chunk stages overlap transfer with compute.
# Priced from a MEASURED warm dispatch (device.py warmup), not a model.
MEGA_GATE_EXEC_MB_S = 500.0


def gate_terms(
    h2d_ratio: float = 1.0, d2h_ratio: float = 1.0,
    profile: str = "stream", devices: int = 1,
    exec_mb_s: float | None = None,
) -> dict:
    """Measure the link and price it against the device-verify bar;
    returns every term the decision used (the gate-audit record body).

    `profile` selects the backend cost model being priced: "stream" (the
    legacy flag-map path — every verify byte re-crosses the link, d2h at
    the compaction ratio), "fused" (verify rows stay device-resident,
    so the verify stage's marginal re-upload is ~zero —
    link_mod.FUSED_REUPLOAD_RATIO — and the RTT bar loosens to
    FUSED_GATE_RTT_S because the batch rides O(1) dispatches), "mesh"
    (the fused cost model at `devices` chips: each device has its own
    staging lane, per-shard h2d and the per-shard keep-mask d2h overlap
    across chips, so the effective aggregate rate is the per-link rate x
    device count — the whole reason a mesh can win where one chip loses),
    or "mega" (the megakernel's one-dispatch fusion: the fused link model
    at `devices` chips PLUS an absolute exec-rate floor — pass the
    measured `exec_mb_s` and the decision additionally requires it to
    clear MEGA_GATE_EXEC_MB_S, folding the worse of the two distances
    into `margin`).

    `margin` is the signed distance from the flip point: the worse of
    (effective rate vs GATE_EFF_MB_S) and (RTT vs the profile's RTT bar)
    — and, under "mega", (exec rate vs MEGA_GATE_EXEC_MB_S) — each as a
    fraction of its threshold.  Positive = the link cleared the bar."""
    from trivy_tpu.engine import link as link_mod

    mb_s, rtt = probe_link()
    devices = max(int(devices), 1)
    fused_model = profile in ("fused", "mesh", "mega")
    reupload = link_mod.FUSED_REUPLOAD_RATIO if fused_model else 1.0
    rtt_bar = FUSED_GATE_RTT_S if fused_model else GATE_RTT_S
    eff = link_mod.effective_link_rate(
        mb_s, h2d_ratio, d2h_ratio, reupload_ratio=reupload
    )
    if profile in ("mesh", "mega"):
        eff *= devices
    wide = eff >= GATE_EFF_MB_S and rtt < rtt_bar
    margin = min(eff / GATE_EFF_MB_S - 1.0, 1.0 - rtt / rtt_bar)
    out = {
        "profile": profile,
        "devices": devices,
        "link_mb_per_sec": mb_s,
        "link_rtt_s": rtt,
        "h2d_ratio": h2d_ratio,
        "d2h_ratio": d2h_ratio,
        "eff_mb_per_sec": eff,
        "eff_threshold_mb_per_sec": GATE_EFF_MB_S,
        "rtt_threshold_s": rtt_bar,
        "codec": link_mod.codec_mode(),
        "wide": wide,
        "margin": margin,
    }
    if profile == "mega" and exec_mb_s is not None:
        out["exec_mb_per_sec"] = exec_mb_s
        out["exec_threshold_mb_per_sec"] = MEGA_GATE_EXEC_MB_S
        out["wide"] = wide and exec_mb_s >= MEGA_GATE_EXEC_MB_S
        out["margin"] = min(margin, exec_mb_s / MEGA_GATE_EXEC_MB_S - 1.0)
    return out


def _link_is_wide(h2d_ratio: float = 1.0, d2h_ratio: float = 1.0) -> bool:
    """Device verify by default only when the link can beat the host C
    verifier's NFA-mode walk (~300-900 MB/s measured): candidate bytes
    stream at the link rate, so the bar is link >= ~1 GB/s with sub-10ms
    dispatch.

    The bar is priced against the EFFECTIVE post-codec rate
    (engine/link.py): h2d transcoding and d2h compaction shrink the bytes
    a raw payload costs, so a physical link below 1 GB/s can still clear
    the bar when the codec is available — codec availability flips
    backend selection, which is the point of pricing it here instead of
    at the probe."""
    return gate_terms(h2d_ratio, d2h_ratio)["wide"]


def normalize_grams(
    masks: np.ndarray, vals: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Strip leading masked-out bytes so byte 0 of every gram is kept, then
    sort by (mask, val) so mask groups are contiguous.

    Returns (norm_masks, norm_vals, perm, strip) with perm mapping
    sorted-normalized index -> original gram index (callers scatter hits
    back with ``orig[:, perm] = hits_norm``) and strip[k] the stripped
    prefix length of sorted-normalized gram k.  Anchoring at the first kept
    byte shifts each gram's match position by the stripped prefix length —
    per-file attribution resolves by anchor position, and the per-hit
    probe-class confirm adds strip to the window's probe offset.
    """
    g = len(masks)
    if g == 0:
        return masks, vals, np.zeros(0, dtype=np.int64), np.zeros(0, np.int32)
    nm = masks.astype(np.uint64).copy()
    nv = vals.astype(np.uint64).copy()
    strip = np.zeros(g, dtype=np.int32)
    for _ in range(3):
        shift = (nm != 0) & (nm & 0xFF == 0)
        nm[shift] >>= np.uint64(8)
        nv[shift] >>= np.uint64(8)
        strip[shift] += 1
    nm = nm.astype(np.uint32)
    nv = nv.astype(np.uint32)
    perm = np.lexsort((nv, nm)).astype(np.int64)
    return nm[perm], nv[perm], perm, strip[perm]


class HybridSecretEngine(TpuSecretEngine):
    """Host-sieve + candidate-confirm engine with the oracle's semantics.

    Inherits the rule/probe/gram compilation and candidate matrices from
    TpuSecretEngine (constructed with its JAX-free native path) and replaces
    scan_batch with the chunk-pipelined hybrid flow.
    """

    def __init__(
        self,
        ruleset=None,
        config=None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        verify: str = "auto",
        mesh=None,
        probe_confirm: bool = True,
        pipeline_depth: int | None = None,
        dedupe: bool = True,
        resident_chunks: int | None = None,
        compiled=None,
        program_table=None,
    ):
        super().__init__(
            ruleset=ruleset,
            config=config,
            sieve="native",
            pipeline_depth=pipeline_depth,
            dedupe=dedupe,
            resident_chunks=resident_chunks,
            compiled=compiled,
            program_table=program_table,
        )
        self.chunk_bytes = chunk_bytes
        if verify not in ("auto", "dfa", "none", "device", "fused"):
            raise ValueError(f"unknown verify mode: {verify!r}")
        requested = verify
        if verify == "auto":
            # TPU hosts with a wide (PCIe/ICI-class) link get the device
            # NFA verify stage by default — the TPU's seat in the hybrid:
            # the sieve's candidate (file, rule) pairs verify as batched
            # automata on the MXU instead of the host automaton loop.
            # Relay-attached chips (candidate bytes would cross a ~50 MB/s
            # tunnel the host verifier outruns 6-700x) and CPU-only hosts
            # keep the C walk; see probe_link for the measured economics.
            # The verify stream ships RAW span bytes h2d (class semantics
            # live in the per-byte accept tensors), so only the d2h side
            # is discounted: with compaction on, the match-map fetch
            # shrinks to ~STREAM_D2H_RATIO of its raw size.
            from trivy_tpu.engine import link as link_mod

            d2h_ratio = (
                link_mod.STREAM_D2H_RATIO
                if link_mod.d2h_compaction_enabled()
                else 1.0
            )
            if not _tpu_default_backend():
                verify = "dfa"
                self.gate_decision = gatelog.record(
                    requested="auto", backend="dfa", reason="no-device",
                )
            else:
                # Price the MESH cost model first when a multi-device
                # partition plan is in play (fused economics at N chips:
                # per-device staging lanes overlap h2d/d2h across chips,
                # so the effective aggregate rate scales by the device
                # count), else the single-chip FUSED model: rows stay
                # resident so the verify stage re-uploads ~nothing and
                # the RTT bar loosens — a link too narrow for the legacy
                # stream can still clear the fused bar.  Fall back to
                # the legacy stream pricing, then host DFA.
                n_dev = (
                    mesh_topology.mesh_device_count(mesh)
                    if mesh is not None
                    else mesh_topology.capacity_hint()
                )
                fterms = gate_terms(
                    d2h_ratio=link_mod.FUSED_MASK_D2H_RATIO,
                    profile="mesh" if n_dev > 1 else "fused",
                    devices=n_dev,
                )
                if fterms["wide"]:
                    verify, terms = "fused", fterms
                else:
                    terms = gate_terms(d2h_ratio=d2h_ratio)
                    verify = "device" if terms["wide"] else "dfa"
                if terms["wide"] and terms["profile"] == "mesh":
                    reason = "mesh-wide"
                else:
                    reason = "link-wide" if terms["wide"] else "link-narrow"
                self.gate_decision = gatelog.record(
                    requested="auto",
                    backend=verify,
                    reason=reason,
                    profile=terms["profile"],
                    devices=terms["devices"],
                    link_mb_per_sec=terms["link_mb_per_sec"],
                    link_rtt_s=terms["link_rtt_s"],
                    h2d_ratio=terms["h2d_ratio"],
                    d2h_ratio=terms["d2h_ratio"],
                    eff_mb_per_sec=terms["eff_mb_per_sec"],
                    eff_threshold_mb_per_sec=terms[
                        "eff_threshold_mb_per_sec"
                    ],
                    rtt_threshold_s=terms["rtt_threshold_s"],
                    codec=terms["codec"],
                    margin=terms["margin"],
                )
        else:
            self.gate_decision = gatelog.record(
                requested=requested, backend=verify, reason="forced",
            )
        self.verify = verify
        self._nfa_verifier = None
        self._dfa_verifier = None
        bounds = None
        if verify in ("dfa", "device", "fused"):
            from trivy_tpu.engine.redfa import compute_prefix_bounds

            # One shared trim-bound array: host and device verifiers must
            # clip walk windows identically for refutation to stay sound.
            bounds = compute_prefix_bounds(
                self.ruleset.rules, self._trimmable_rules()
            )
        if verify in ("device", "fused"):
            # One mesh for the whole device path: the verifier joins the
            # same partition plan the sieve resolved (topology.get_mesh
            # is memoised, so this never disagrees with the engine's).
            if mesh is None:
                mesh = mesh_topology.get_mesh()
            try:
                from trivy_tpu.engine.nfa_device import NfaVerifier

                self._nfa_verifier = NfaVerifier(
                    self.ruleset.rules, mesh=mesh, prefix_bounds=bounds,
                    fused=(verify == "fused"),
                    rule_stack=getattr(self._compiled, "vstack", None),
                )
            except Exception as e:
                if requested in ("device", "fused"):
                    raise NotImplementedError(
                        "device NFA verify stage is not available"
                    ) from e
                self.verify = verify = "dfa"  # auto falls back to host DFA
                self.gate_decision = gatelog.record(
                    requested=requested, backend="dfa", reason="fallback",
                    error=f"{type(e).__name__}: {e}",
                )
        if verify in ("dfa", "device", "fused"):
            # In device mode the DFA still verifies pass-through lanes
            # (rules with no 64-position automaton, oversized windows).
            from trivy_tpu.engine.redfa import DfaVerifier

            self._dfa_verifier = DfaVerifier(
                self.ruleset.rules, prefix_bounds=bounds
            )
        from trivy_tpu.native import load_native

        self._native_ok = load_native() is not None
        (
            self._norm_masks,
            self._norm_vals,
            self._norm_perm,
            self._norm_strip,
        ) = normalize_grams(self.gset.masks, self.gset.vals)
        self.probe_confirm = probe_confirm
        # Rules that are candidates even with zero gram hits (all their
        # gating probes are gram-less): resolved once on an all-zero row.
        zero = np.zeros((1, self.gset.num_grams), dtype=bool)
        base = self.candidate_matrix_bool(self.gset.probe_hits_bool(zero))[0]
        self._base_cand = np.flatnonzero(base)
        # reduceat metadata for the O(F*G) probe resolution: grams grouped
        # by window (OR within a window), windows grouped by probe (AND
        # across a probe's windows).  Diagnostic-only: the differential test
        # (tests/test_hybrid_engine.py) re-derives candidates from a hits
        # matrix through these tables to cross-check the fused C++ scan.
        gw = self.gset.gram_window
        self._gperm = np.argsort(gw, kind="stable")
        sorted_w = gw[self._gperm]
        self._wstarts = (
            np.flatnonzero(np.r_[True, sorted_w[1:] != sorted_w[:-1]])
            if len(sorted_w)
            else np.zeros(0, dtype=np.int64)
        )
        wp = self.gset.window_probe
        self._pstarts = (
            np.flatnonzero(np.r_[True, wp[1:] != wp[:-1]])
            if len(wp)
            else np.zeros(0, dtype=np.int64)
        )
        self._p_ids = wp[self._pstarts] if len(wp) else wp
        self._build_scan_tables()

    def _build_scan_tables(self) -> None:
        """Flat CSR tables for the fused C++ scan (gram_sieve_scan)."""
        # gram_window in the normalized-sorted gram order
        self._gw_norm = np.ascontiguousarray(
            self.gset.gram_window[self._norm_perm], dtype=np.int32
        )
        self._window_probe_i32 = np.ascontiguousarray(
            self.gset.window_probe, dtype=np.int32
        )
        p = len(self.pset.probes)
        n_win = np.zeros(p, dtype=np.int32)
        for pr in self.gset.window_probe:
            n_win[pr] += 1
        self._probe_n_windows = n_win
        gate_ptr = [0]
        gate_probes: list[int] = []
        rule_conj_ptr = [0]
        conj_ptr = [0]
        conj_probes: list[int] = []
        for plan in self.pset.plans:
            gate_probes.extend(plan.gate_probe_ids)
            gate_ptr.append(len(gate_probes))
            for conj in plan.anchor_conjuncts:
                conj_probes.extend(conj)
                conj_ptr.append(len(conj_probes))
            rule_conj_ptr.append(len(conj_ptr) - 1)
        self._gate_ptr = np.array(gate_ptr, dtype=np.int32)
        self._gate_probes = np.array(gate_probes, dtype=np.int32)
        self._rule_conj_ptr = np.array(rule_conj_ptr, dtype=np.int32)
        self._conj_ptr = np.array(conj_ptr, dtype=np.int32)
        self._conj_probes = np.array(conj_probes, dtype=np.int32)
        self._build_confirm_tables()

    def _build_confirm_tables(self) -> None:
        """Per-hit probe-class confirm tables (gram_sieve.cpp confirm_hit):
        each gram carries its probe's FULL class sequence as case-folded
        256-bit membership bitmaps plus the gram anchor's offset within
        that sequence.  The C scan rejects screen hits whose surrounding
        bytes break the class sequence — the precision the LUT shift-AND
        sieve has and coarse masked grams lack (a hex-class position is
        unmaskable as a gram but one AND away as a bitmap; 'task_struct'
        stops claiming twilio-api-key at byte 3)."""
        g = len(self._norm_perm)
        self._gram_cls_start = np.zeros(g, dtype=np.int32)
        self._gram_cls_len = np.zeros(g, dtype=np.int32)
        self._gram_align = np.zeros(g, dtype=np.int32)
        self._cls_blob = np.zeros(0, dtype=np.uint8)
        if not self.probe_confirm:
            return
        from trivy_tpu.engine.grams import fold_members

        p_count = len(self.pset.probes)
        cls_off = np.zeros(p_count, dtype=np.int32)
        cls_len = np.zeros(p_count, dtype=np.int32)
        blobs: list[np.ndarray] = []
        total = 0
        need = set(
            int(self.gset.window_probe[self.gset.gram_window[orig]])
            for orig in self._norm_perm
        )
        for p in range(p_count):
            if p not in need:
                continue
            classes = self.pset.probes[p].classes
            cls_off[p] = total
            cls_len[p] = len(classes)
            bmap = np.zeros((len(classes), 32), dtype=np.uint8)
            for j, bs in enumerate(classes):
                for fb in fold_members(bs):
                    bmap[j, fb >> 3] |= 1 << (fb & 7)
            blobs.append(bmap.reshape(-1))
            total += len(classes)
        self._cls_blob = (
            np.concatenate(blobs) if blobs else np.zeros(0, dtype=np.uint8)
        )
        for k, orig in enumerate(self._norm_perm):
            w = int(self.gset.gram_window[orig])
            p = int(self.gset.window_probe[w])
            self._gram_cls_start[k] = cls_off[p]
            self._gram_cls_len[k] = cls_len[p]
            self._gram_align[k] = (
                int(self.gset.window_start[w]) + int(self._norm_strip[k])
            )

    # ------------------------------------------------------------------

    def _trimmable_rules(self) -> np.ndarray:
        """bool[R]: rule has an anchor conjunct whose probes are all
        gram-backed, so every match contains a gram occurrence and the
        verify walk may be start-trimmed (see DfaVerifier)."""
        has_gram = self.gset.probe_has_gram
        out = np.zeros(len(self.pset.plans), dtype=bool)
        for i, plan in enumerate(self.pset.plans):
            out[i] = any(
                conj and all(has_gram[p] for p in conj)
                for conj in plan.anchor_conjuncts
            )
        return out

    def warmup(self) -> None:
        from trivy_tpu.native import load_native

        load_native()
        if self._nfa_verifier is not None:
            # Pre-compile the jit specializations bulk work hits (see
            # NfaVerifier.warmup) so common first-scan latency stays out
            # of callers' timed regions.
            self._nfa_verifier.warmup(compile_buckets=True)

    # ------------------------------------------------------------------

    def _sieve_chunk(self, contents: list[bytes]):
        """Run the fused native scan over the chunk's file buffers
        directly (gram_sieve_scan_files folds straight from them — no
        packed-stream copy exists on this path).  Returns (pairs,
        dev_mask, ptr_arr, lens): UNVERIFIED candidate (file, rule,
        first_hint, last_hint) quads [N, 4] int32 ordered by file then
        rule, a bool[N] marking device-eligible lanes, and the pointer
        array + lengths the verify stage walks (_finish_chunk runs the
        host automaton verify; device lanes verify at end of scan)."""
        import ctypes

        from trivy_tpu.native import load_native

        t0 = time.perf_counter()
        nfiles = len(contents)
        lens = np.fromiter(
            (len(c) for c in contents), dtype=np.int64, count=nfiles
        )
        # Pointer array straight at the bytes objects' buffers (no copy;
        # `contents` stays referenced for the duration of both calls).
        ptr_arr = (ctypes.c_char_p * nfiles)(*contents)
        starts = np.zeros(nfiles, dtype=np.int64)  # filled by the C scan
        pack_dt = time.perf_counter() - t0

        t0 = time.perf_counter()
        lib = load_native()
        cap = max(1024, 4 * nfiles)
        while True:
            out = np.empty((cap, 4), dtype=np.int32)
            found = lib.gram_sieve_scan_files(
                ctypes.cast(ptr_arr, ctypes.c_void_p),
                lens.ctypes.data, nfiles,
                self._norm_masks.ctypes.data, self._norm_vals.ctypes.data,
                len(self._norm_masks),
                self._gw_norm.ctypes.data, len(self._window_probe_i32),
                self._window_probe_i32.ctypes.data,
                self._probe_n_windows.ctypes.data, len(self._probe_n_windows),
                self._gate_ptr.ctypes.data, self._gate_probes.ctypes.data,
                self._rule_conj_ptr.ctypes.data, self._conj_ptr.ctypes.data,
                self._conj_probes.ctypes.data, len(self.pset.plans),
                self._cls_blob.ctypes.data if self.probe_confirm else None,
                self._gram_cls_start.ctypes.data,
                self._gram_cls_len.ctypes.data,
                self._gram_align.ctypes.data,
                starts.ctypes.data,
                out.ctypes.data, cap,
            )
            if found <= cap:
                break
            cap = int(found) + 64
        sieve_dt = time.perf_counter() - t0

        pairs = out[: int(found)]
        dev = (
            self._nfa_verifier.device_eligible(pairs, lens)
            if self._nfa_verifier is not None
            else np.zeros(len(pairs), dtype=bool)
        )
        # The automaton verify runs in _finish_chunk on the MAIN thread
        # (the ctypes call drops the GIL, so it overlaps the worker's
        # sieve of the next chunk — on verify-heavy corpora this turns
        # wall-clock from sieve+verify into max(sieve, verify+confirm)).
        # ptr_arr/lens travel along: the verify walks the ORIGINAL file
        # buffers (case-sensitive rules must not see folded bytes).
        # Timings return as data: this runs on pool workers, and a
        # concurrent ``self.stats.X += dt`` from two workers is a lost
        # update — the finish stage merges them single-threaded.
        return pairs, dev, ptr_arr, lens, (pack_dt, sieve_dt)

    def _chunks(self, items: list[tuple[str, bytes]]):
        """Split items into contiguous chunks of ~chunk_bytes."""
        out: list[tuple[int, int]] = []
        start, size = 0, 0
        for i, (_p, c) in enumerate(items):
            size += len(c) + GAP
            if size >= self.chunk_bytes and i + 1 > start:
                out.append((start, i + 1))
                start, size = i + 1, 0
        if start < len(items):
            out.append((start, len(items)))
        return out

    def scan_batch(self, items: list[tuple[str, bytes]]) -> list[Secret]:
        if self.program_table is not None:
            # Multi-program table: route through the shared demux (the
            # merged rule axis would feed the chunked confirm below
            # foreign rule indices).  TpuSecretEngine.scan_programs runs
            # on this engine's native sieve via _candidates.
            return self.scan_programs(items, only=("secret",)).get(
                "secret", [Secret() for _ in items]
            )
        if not items:
            return []
        if not self._native_ok:
            return super().scan_batch(items)  # NumPy gram path
        self.stats.files += len(items)
        self.stats.bytes += sum(len(c) for _, c in items)
        gd = getattr(self, "gate_decision", None)
        if gd is not None and obs_trace.enabled():
            # Pin the gate's routing verdict onto this batch's span tree:
            # a flight capture or --explain then shows WHY verify ran on
            # the DFA/device without consulting /debug/gate separately.
            with obs_trace.span(
                "hybrid.gate",
                backend=gd["backend"],
                reason=gd["reason"],
                margin=gd.get("margin"),
            ):
                pass

        from trivy_tpu import deadline

        results: list[Secret | None] = [None] * len(items)
        spans = self._chunks(items)
        # Allowed paths for the whole batch in one multiline search
        # (scanner.go:375-380 semantics; a per-file regex call was ~half of
        # the confirm phase at 100k files).
        t0 = time.perf_counter()
        allowed_pos = np.flatnonzero(
            np.fromiter(
                self.ruleset.allow_paths([p for p, _ in items]),
                dtype=bool,
                count=len(items),
            )
        )
        self.stats.confirm_s += time.perf_counter() - t0
        self.stats.pipeline_depth = self.pipeline_depth
        from trivy_tpu.engine.pipeline import ChunkPipeline

        # The bounded scheduler keeps up to pipeline_depth sieve chunks in
        # flight (workers sieve chunk N+1.. while the main thread finishes
        # chunk N — the ctypes sieve drops the GIL, so this is real
        # overlap).  Device-destined lanes accumulate across chunks ([N, 5]
        # blocks of global-file, rule, first, last, preverified) and verify
        # in ONE batched pass after the chunk pipeline — dispatch count
        # must stay O(length buckets), not O(chunks), when the link
        # round-trip is the fixed cost.
        dev_lanes: list[np.ndarray] = []
        pool = ThreadPoolExecutor(max_workers=max(1, self.pipeline_depth - 1))

        def _finish(span, fut):
            deadline.check()
            pairs, dev, ptr_arr, lens, (pack_dt, sieve_dt) = fut.result()
            self.stats.pack_s += pack_dt
            self.stats.sieve_s += sieve_dt
            self._finish_chunk(
                items, span[0], span[1], (pairs, dev, ptr_arr, lens),
                results, allowed_pos, dev_lanes,
            )

        def _sieve_traced(contents):
            with obs_trace.span(
                "sieve", files=len(contents),
                bytes=sum(len(c) for c in contents),
            ):
                return self._sieve_chunk(contents)

        pipe = ChunkPipeline(
            # copy_context: the pool worker inherits the ambient
            # (trace_id, span_id), so worker-side sieve spans land in the
            # batch's tree instead of starting orphan traces.
            stage=lambda span: pool.submit(
                contextvars.copy_context().run, _sieve_traced,
                [c for _p, c in items[span[0] : span[1]]],
            ),
            execute=lambda span, fut: fut,
            finish=_finish,
            depth=self.pipeline_depth,
            # On deadline/interrupt, drop queued chunks so shutdown only
            # waits for sieve calls already executing.
            cancel=lambda span, fut: fut.cancel(),
        )
        try:
            pipe.run(spans)
        finally:
            pool.shutdown(wait=True)
        self.stats.h2d_overlap_s += pipe.stats.h2d_overlap_s
        if dev_lanes:
            deadline.check()
            self._finish_device(items, np.concatenate(dev_lanes), results)
        return results  # type: ignore[return-value]

    def scan_batch_host(self, items: list[tuple[str, bytes]]) -> list[Secret]:
        """Degraded re-run with the device verifier OUT of the loop: every
        candidate lane verifies on the host DFA instead.  Byte-identical
        to the device path by construction — both verifiers clip walk
        windows with the same shared prefix bounds (see __init__), and
        the final confirm is the same byte-exact oracle either way.  The
        serve scheduler calls this after a device-engine failure (and for
        every batch while the circuit breaker is open), so a sick device
        costs latency, never correctness.

        Runs on the engine-owner thread only (like scan_batch): the
        verifier swap below is not concurrency-safe against a concurrent
        scan_batch on the SAME engine, which the scheduler's single
        dispatch thread already precludes."""
        nfa = self._nfa_verifier
        if nfa is None:
            return self.scan_batch(items)  # already host-only
        self._nfa_verifier = None
        try:
            return self.scan_batch(items)
        finally:
            self._nfa_verifier = nfa

    def scan_batch_device_legacy(
        self, items: list[tuple[str, bytes]]
    ) -> list[Secret]:
        """Degraded re-run one rung ABOVE scan_batch_host: keep the
        device verifier but flip its fused mode off, so lane verdicts
        resolve through the legacy flag-map stream instead of the fused
        on-device path.  The serve scheduler's failure ladder tries this
        first after a fused-engine failure (fused -> legacy-device ->
        host-DFA) — a bug in the fused kernels costs one retry, not the
        whole device.

        Runs on the engine-owner thread only (like scan_batch_host): the
        fused flag flip is not concurrency-safe against a concurrent
        scan_batch on the SAME engine, which the scheduler's single
        dispatch thread already precludes."""
        nfa = self._nfa_verifier
        if nfa is None or not getattr(nfa, "fused", False):
            return self.scan_batch(items)  # no fused mode to step down
        nfa.fused = False
        try:
            return self.scan_batch(items)
        finally:
            nfa.fused = True

    def _finish_chunk(
        self,
        items: list[tuple[str, bytes]],
        lo: int,
        hi: int,
        sieved: tuple[np.ndarray, np.ndarray, object, np.ndarray],
        results: list,
        allowed_pos: np.ndarray,
        dev_lanes: list[np.ndarray] | None = None,
    ) -> None:
        scan_pairs, dev_mask, ptr_arr, lens = sieved
        host = ~dev_mask
        if self._dfa_verifier is not None and host.any():
            # Host automaton verify over the chunk's original buffers.
            # Columns 2/3 are the file's first/last screen-pass offsets —
            # sound walk-start and walk-end trims for bounded rules.  With
            # a device verifier present, only its pass-through lanes walk
            # here; the rest verify on device at end of scan.
            t0 = time.perf_counter()
            with obs_trace.span("verify", pairs=int(host.sum())):
                sub = scan_pairs[host]
                ok = self._dfa_verifier.verify_pairs_files(
                    ptr_arr, lens,
                    sub[:, 0], sub[:, 1], sub[:, 2], sub[:, 3],
                )
                keep = np.ones(len(scan_pairs), dtype=bool)
                keep[host] = ok.astype(bool)
                scan_pairs, dev_mask = scan_pairs[keep], dev_mask[keep]
            self.stats.verify_s += time.perf_counter() - t0
        dev_files: set[int] = set()
        if dev_mask.any():
            # Files with >= 1 device-destined lane defer entirely to the
            # end-of-scan device pass (their host-verified lanes travel
            # along as preverified so the final confirm sees the union).
            dev_files = set(scan_pairs[dev_mask, 0].tolist())
            sel = np.isin(scan_pairs[:, 0], np.fromiter(dev_files, np.int32))
            block = np.empty((int(sel.sum()), 5), dtype=np.int64)
            block[:, :4] = scan_pairs[sel]
            block[:, 0] += lo  # global file index
            block[:, 4] = ~dev_mask[sel]  # host-verified already
            dev_lanes.append(block)
            scan_pairs = scan_pairs[~sel]

        t0 = time.perf_counter()
        cand_rows: dict[int, np.ndarray] = {}
        if len(scan_pairs):
            fis, ris = scan_pairs[:, 0], scan_pairs[:, 1]
            splits = np.flatnonzero(fis[1:] != fis[:-1]) + 1
            for fi, idxs in zip(fis[np.r_[0, splits]], np.split(ris, splits)):
                cand_rows[int(fi)] = idxs
        self.stats.candidate_s += time.perf_counter() - t0

        base = self._base_cand
        if len(base):
            # Gram-less rules are candidates everywhere: every file pays.
            # Deferred (device) files get their base rules as preverified
            # lanes instead, so the final confirm still unions them.
            pairs = []
            for fi in range(hi - lo):
                if fi in dev_files:
                    block = np.empty((len(base), 5), dtype=np.int64)
                    block[:, 0] = lo + fi
                    block[:, 1] = base
                    block[:, 2:4] = 0
                    block[:, 4] = 1
                    dev_lanes.append(block)
                    continue
                pairs.append(
                    (
                        fi,
                        np.union1d(cand_rows[fi], base)
                        if fi in cand_rows
                        else base,
                    )
                )
        else:
            pairs = list(cand_rows.items())

        t0 = time.perf_counter()
        # Non-candidate fast path (VERDICT r2 #1: build Secret objects only
        # for candidate files): the plain-empty result is one shared
        # instance — empties never reach the applier's merge (the analyzer
        # filters on findings), so nothing mutates it.  Allowed paths carry
        # FilePath (scanner.go:375-380) — prefilled here, and for allowed
        # candidates the oracle's own allow_path gate reproduces the same
        # result when the loop below overwrites the slot.
        empty = _EMPTY_SECRET
        with obs_trace.span("confirm", files=hi - lo):
            results[lo:hi] = [empty] * (hi - lo)
            a0, a1 = np.searchsorted(allowed_pos, (lo, hi))
            for i in allowed_pos[a0:a1].tolist():
                results[i] = Secret(file_path=items[i][0])
            for fi, idxs in pairs:
                self._confirm_file(items, lo + int(fi), idxs, results)
        self.stats.confirm_s += time.perf_counter() - t0

    def _confirm_file(self, items, gi: int, idxs, results) -> None:
        """Byte-exact oracle confirm of rule candidates for one file."""
        if len(idxs) == 0:
            return
        path, content = items[gi]
        self.stats.candidate_pairs += len(idxs)
        res = self.oracle.scan(path, content, rule_indices=list(map(int, idxs)))
        self.stats.confirmed_findings += len(res.findings)
        results[gi] = res

    def _finish_device(
        self,
        items: list[tuple[str, bytes]],
        lanes: np.ndarray,
        results: list,
    ) -> None:
        """End-of-scan device verify: one batched NFA pass over every
        deferred lane ([N, 5]: gfile, rule, first, last, preverified),
        then oracle confirm of the surviving (file, rule) sets."""
        t0 = time.perf_counter()
        unver = lanes[lanes[:, 4] == 0]
        # Lanes of the same file share one contents entry so the stream
        # verifier can ship each file's span once (multi-rule dedupe), and
        # content-digest dedupe collapses DIFFERENT files with identical
        # bytes (vendored copies, repeated container-layer files) to one
        # shipped blob — verify verdicts are content-determined, so lanes
        # of every alias ride the same spans.
        ufiles, inv = np.unique(unver[:, 0], return_inverse=True)
        contents = [items[int(g)][1] for g in ufiles]
        if self.dedupe and len(contents) > 1:
            from trivy_tpu.scanner.packing import dedupe_blobs

            dd = dedupe_blobs(contents)
            if dd.any_duplicates():
                self.stats.dedupe_saved_bytes += dd.saved_bytes
                contents = [contents[int(i)] for i in dd.unique_index]
                inv = dd.inverse[inv]
        lens = np.fromiter(
            (len(c) for c in contents), dtype=np.int64, count=len(contents)
        )
        sub = unver[:, :4].copy()
        sub[:, 0] = inv
        with obs_trace.span("verify", pairs=len(unver), device=True):
            ok = self._nfa_verifier.verify_lanes(contents, sub, lens)
        self.stats.device_pairs += len(unver)
        surviving = np.concatenate(
            [lanes[lanes[:, 4] == 1][:, :2], unver[ok][:, :2]]
        )
        self.stats.verify_s += time.perf_counter() - t0

        t0 = time.perf_counter()
        with obs_trace.span("confirm", lanes=len(surviving)):
            order = np.lexsort((surviving[:, 1], surviving[:, 0]))
            surviving = surviving[order]
            if len(surviving):
                fis = surviving[:, 0]
                splits = np.flatnonzero(fis[1:] != fis[:-1]) + 1
                for gi, idxs in zip(
                    fis[np.r_[0, splits]], np.split(surviving[:, 1], splits)
                ):
                    self._confirm_file(
                        items, int(gi), np.unique(idxs).tolist(), results
                    )
        self.stats.confirm_s += time.perf_counter() - t0


def make_secret_engine(
    ruleset=None,
    config=None,
    backend: str = "auto",
    mesh=None,
    rules_cache_dir: str | None = None,
    **kw,
):
    """Engine factory.

    backend:
      auto    hybrid when the native sieve builds, else the device engine
      hybrid  host pre-sieve + confirm (optionally device NFA verify)
      device  all bytes through the device gram sieve (wide-link hosts, mesh)
      oracle  pure-Python reference engine
    CLI aliases (cli.py --secret-backend): tpu = device, cpu = oracle,
    native = device engine over the C++ host sieve.

    `rules_cache_dir` routes construction through the compiled-artifact
    registry: the ruleset digests to a cache key, a valid cached artifact
    supplies the probe/gram/NFA tensors (warm start, no compile), and a
    miss compiles once and persists for the next process.  None (the
    default) leaves the registry out entirely.

    A `program_table` kwarg (programs/base.py) turns the engine
    multi-program: `ruleset` must then be the table's merged ruleset —
    use `programs.make_program_engine`, which also warms the registry
    program-id-keyed, instead of threading the table by hand.
    """
    backend = {"tpu": "device", "cpu": "oracle"}.get(backend, backend)
    if backend == "oracle":
        from trivy_tpu.engine.oracle import OracleScanner

        return OracleScanner(ruleset=ruleset, config=config)
    if rules_cache_dir is not None and "compiled" not in kw:
        from trivy_tpu.registry.store import get_or_compile
        from trivy_tpu.rules.model import build_ruleset

        if ruleset is None:
            ruleset = build_ruleset(config)
        kw["compiled"], _ = get_or_compile(ruleset, cache_dir=rules_cache_dir)
    if backend == "device":
        return TpuSecretEngine(ruleset=ruleset, config=config, mesh=mesh, **kw)
    if backend == "native":
        return TpuSecretEngine(
            ruleset=ruleset, config=config, mesh=mesh, sieve="native", **kw
        )
    if backend == "hybrid":
        return HybridSecretEngine(ruleset=ruleset, config=config, mesh=mesh, **kw)
    if backend != "auto":
        raise ValueError(f"unknown secret-engine backend: {backend!r}")
    from trivy_tpu.native import load_native

    if load_native() is not None:
        return HybridSecretEngine(ruleset=ruleset, config=config, mesh=mesh, **kw)
    return TpuSecretEngine(ruleset=ruleset, config=config, mesh=mesh, **kw)
