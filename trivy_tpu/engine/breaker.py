"""Device circuit breaker: stop feeding a sick device, re-probe on a timer.

A device that starts failing (lost TPU, wedged runtime, persistent OOM)
fails every batch sent to it, and each failure costs a full degraded
re-run on the host.  The breaker converts "N failures in a window" into
a *routing* decision: while OPEN the scheduler skips the device engine
entirely and goes straight to the host DFA path, and after a cooldown
the breaker goes HALF-OPEN — exactly one probe batch is allowed through;
success re-closes, failure re-opens and restarts the cooldown.

States (exported as trivy_tpu_device_breaker_state):

    0 closed     healthy; failures counted in a sliding window
    1 half-open  cooldown elapsed; one probe batch in flight
    2 open       tripped; all batches degrade to host until cooldown

Thread model: record_success/record_failure run on the engine-owner
thread; allow() also runs there, but snapshot() is read by /metrics,
/readyz, and flight captures from server threads — hence the lock.
Transitions invoke ``on_transition(old, new, reason)`` synchronously
*outside* the lock (listeners write gatelog/metrics, which take their
own locks).
"""

from __future__ import annotations

import time
from typing import Callable

from trivy_tpu import lockcheck

STATE_CODES = {"closed": 0, "half-open": 1, "open": 2}


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 3,
        window_s: float = 30.0,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Callable[[str, str, str], None] | None = None,
    ):
        self._lock = lockcheck.make_lock("breaker")
        self.failure_threshold = max(1, int(failure_threshold))
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.on_transition = on_transition
        self.state = "closed"  # owner: _lock
        self._failures: list[float] = []  # owner: _lock
        self._opened_at = 0.0  # owner: _lock
        self._probing = False  # owner: _lock
        self.opened_total = 0  # owner: _lock
        self.reclosed_total = 0  # owner: _lock
        self.probes_total = 0  # owner: _lock

    # -- engine-owner side -------------------------------------------------

    def allow(self) -> bool:
        """May the next batch use the device?  OPEN converts to HALF-OPEN
        once the cooldown elapses, admitting exactly one probe."""
        fired: tuple[str, str, str] | None = None
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "half-open":
                # One probe at a time: batches behind the probe degrade.
                if self._probing:
                    return False
                self._probing = True
                self.probes_total += 1
                return True
            if self._clock() - self._opened_at >= self.cooldown_s:
                fired = (self.state, "half-open", "cooldown elapsed")
                self.state = "half-open"
                self._probing = True
                self.probes_total += 1
        if fired is not None:
            self._notify(*fired)
            return True
        return False

    def record_success(self) -> None:
        fired: tuple[str, str, str] | None = None
        with self._lock:
            if self.state == "half-open":
                fired = (self.state, "closed", "probe succeeded")
                self.state = "closed"
                self._probing = False
                self.reclosed_total += 1
            del self._failures[:]
        if fired is not None:
            self._notify(*fired)

    def record_failure(self) -> None:
        fired: tuple[str, str, str] | None = None
        now = self._clock()
        with self._lock:
            if self.state == "half-open":
                fired = (self.state, "open", "probe failed")
                self.state = "open"
                self._probing = False
                self._opened_at = now
                self.opened_total += 1
            elif self.state == "closed":
                self._failures.append(now)
                cutoff = now - self.window_s
                self._failures[:] = [t for t in self._failures if t >= cutoff]
                if len(self._failures) >= self.failure_threshold:
                    fired = (
                        self.state,
                        "open",
                        f"{len(self._failures)} failures in "
                        f"{self.window_s:g}s",
                    )
                    self.state = "open"
                    self._opened_at = now
                    self.opened_total += 1
                    del self._failures[:]
        if fired is not None:
            self._notify(*fired)

    # -- observer side -----------------------------------------------------

    def state_code(self) -> int:
        with self._lock:
            return STATE_CODES[self.state]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "state_code": STATE_CODES[self.state],
                "failures_in_window": len(self._failures),
                "failure_threshold": self.failure_threshold,
                "window_s": self.window_s,
                "cooldown_s": self.cooldown_s,
                # How long until an open breaker admits its half-open
                # probe (0 when closed, or already probe-eligible).  The
                # readiness surface turns this into a Retry-After hint.
                "cooldown_remaining_s": (
                    round(
                        max(
                            0.0,
                            self._opened_at
                            + self.cooldown_s
                            - self._clock(),
                        ),
                        3,
                    )
                    if self.state == "open"
                    else 0.0
                ),
                "opened_total": self.opened_total,
                "reclosed_total": self.reclosed_total,
                "probes_total": self.probes_total,
            }

    def _notify(self, old: str, new: str, reason: str) -> None:
        fn = self.on_transition
        if fn is None:
            return
        try:
            fn(old, new, reason)
        except Exception:  # graftlint: swallow(listener must not poison routing)
            pass
