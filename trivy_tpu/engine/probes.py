"""Literal-factor probe extraction and the packed shift-AND sieve tables.

This is stage A of the TPU secret engine: a multi-pattern matcher that decides,
per file, which rules *could* match, replacing the reference's per-rule scalar
loop (keyword prefilter bytes.Contains, scanner.go:169-181, plus the regex scan
itself scanner.go:403-408) with one data-parallel pass over all probes at once.

Two probe kinds, both expressed as short byte-class sequences:

* **keyword probes** — Trivy's keyword gate, bit-exact: a case-folded literal
  per (rule, keyword).  Long keywords are trimmed to a window (a substring of a
  keyword is an over-approximating gate).
* **anchor probes** — *necessary literal factors* mined from the rule's regex
  IR: every match of the regex must contain one of the rule's anchor factors,
  so "no anchor hit in file" soundly proves "no match in file".  Rules whose
  best factor set is too weak fall back to keyword gating alone (exactly the
  reference's behavior for those rules).

All probes compile into one LUT tensor [Jmax, 256, Pw]·uint32 where bit p of
word w says "byte b is acceptable at offset j of probe p" (always-true beyond
the probe's length).  The sieve is then, per position i:

    hits(i) = AND_{j<Jmax} LUT[j, content[i+j]]      (packed over all probes)

which is J gathers + J bitwise-ANDs per byte — VPU-shaped, batchable, and
shardable over a device mesh.  Content must be zero-padded by >= Jmax bytes at
file ends; probe classes never accept 0x00 within their true length.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from trivy_tpu.engine import goregex
from trivy_tpu.engine.ir import (
    Alt,
    Empty,
    Lit,
    Rep,
    Seq,
    UnsupportedRegex,
    bs_fold_case,
    bs_popcount,
    parse_ir,
)
from trivy_tpu.rules.model import Rule

MAX_PROBE_LEN = 12
MAX_FACTORS_PER_SET = 16
MIN_ANCHOR_SCORE = 10.0  # bits of selectivity required to trust an anchor
_WIDE_CLASS = 48  # popcount above which an element can't be part of a probe


Factor = list[int]  # list of byte-set bitmasks (256-bit ints)


def _byte_freqs() -> np.ndarray:
    """Rough byte-frequency model of source/config text, for probe selectivity.

    Probes are chosen to minimize expected false-positive rate on real corpora;
    a uniform model over-values wide classes like [A-Z0-9]{16} relative to
    exact literals like "AKIA"."""
    f = np.full(256, 5e-4)
    lower = dict(
        e=0.10, t=0.07, a=0.065, o=0.06, i=0.055, n=0.055, s=0.05, r=0.05,
        h=0.035, l=0.035, d=0.03, u=0.025, c=0.025, m=0.02, f=0.018, g=0.016,
        w=0.015, p=0.015, y=0.014, b=0.012, v=0.008, k=0.006, x=0.003,
        j=0.002, q=0.001, z=0.001,
    )
    for ch, v in lower.items():
        f[ord(ch)] = v
        f[ord(ch.upper())] = v / 10
    for d in range(10):
        f[ord("0") + d] = 0.008
    for ch, v in {
        " ": 0.12, "\n": 0.03, "\t": 0.01, "_": 0.02, ".": 0.02, ",": 0.01,
        '"': 0.01, "'": 0.008, ":": 0.012, "/": 0.012, "-": 0.015, "=": 0.01,
        "(": 0.006, ")": 0.006, "[": 0.004, "]": 0.004, "{": 0.004, "}": 0.004,
        "<": 0.003, ">": 0.003, "#": 0.003, "*": 0.002, "+": 0.002, "&": 0.002,
        ";": 0.005, "%": 0.002, "$": 0.001, "@": 0.001, "!": 0.001, "\\": 0.002,
        "|": 0.001, "?": 0.002, "~": 0.0005, "^": 0.0005, "`": 0.0005,
    }.items():
        f[ord(ch)] = v
    return f / f.sum()


_FREQ = _byte_freqs()


def _elem_bits(bs: int) -> float:
    pc = bs_popcount(bs)
    p = float(sum(_FREQ[b] for b in range(256) if bs >> b & 1))
    bits = -math.log2(max(p, 1 / 4096))
    if pc > 16:
        return min(bits, 1.0)
    if pc > 4:
        return min(bits, 4.0)
    return bits


def _score_factor(f: Factor) -> float:
    return sum(_elem_bits(bs) for bs in f)


def _score_set(fs: list[Factor]) -> float:
    if not fs:
        return 0.0
    return min(_score_factor(f) for f in fs)


def _trim_factor(f: Factor) -> Factor:
    """Keep the best usable sub-factor of <= MAX_PROBE_LEN.

    A contiguous sub-sequence of a necessary factor is itself necessary, so we
    may split on elements that are unusable as probe classes (too wide, or
    accepting the 0x00 padding byte) and keep the highest-selectivity window.
    """
    NUL = 1
    segments: list[Factor] = []
    cur: Factor = []
    for bs in f:
        if bs_popcount(bs) > _WIDE_CLASS or bs & NUL:
            if cur:
                segments.append(cur)
                cur = []
        else:
            cur.append(bs)
    if cur:
        segments.append(cur)

    best: Factor = []
    best_s = -1.0
    for seg in segments:
        if len(seg) <= MAX_PROBE_LEN:
            windows = [seg]
        else:
            windows = [
                seg[i : i + MAX_PROBE_LEN]
                for i in range(len(seg) - MAX_PROBE_LEN + 1)
            ]
        for w in windows:
            s = _score_factor(w)
            if s > best_s:
                best, best_s = w, s
    return best


def _best(cands: list[list[Factor] | None]) -> list[Factor] | None:
    best, best_s = None, -1.0
    for c in cands:
        if c is None:
            continue
        s = _score_set(c)
        if s > best_s:
            best, best_s = c, s
    return best


def necessary_factors(node) -> list[Factor] | None:
    """Return a factor set (OR semantics) every match must contain, or None."""
    if isinstance(node, Empty):
        return None
    if isinstance(node, Lit):
        return [[node.bs]]
    if isinstance(node, Rep):
        if node.min >= 1:
            return necessary_factors(node.item)
        return None
    if isinstance(node, Alt):
        out: list[Factor] = []
        for b in node.branches:
            fs = necessary_factors(b)
            if fs is None:
                return None
            out.extend(fs)
            if len(out) > MAX_FACTORS_PER_SET:
                return None
        return out
    if isinstance(node, Seq):
        return _best(_seq_candidates(node))
    raise TypeError(node)


def _seq_candidates(node: Seq) -> list[list[Factor] | None]:
    """All independently-mandatory factor sets of a sequence.

    Each returned set (runs of consecutive mandatory literals, and each
    non-literal child's own factor set) must occur in every match, so any
    subset of them may be AND-combined as a sieve condition.
    """
    cands: list[list[Factor] | None] = []
    run: Factor = []
    runs: list[Factor] = []

    def close():
        nonlocal run
        if run:
            runs.append(run)
            run = []

    for item in node.items:
        if isinstance(item, Lit):
            run.append(item.bs)
        elif isinstance(item, Rep) and isinstance(item.item, Lit) and item.min >= 1:
            run.extend([item.item.bs] * min(item.min, MAX_PROBE_LEN))
            if item.max != item.min:
                close()
        else:
            close()
            cands.append(necessary_factors(item))
    close()
    cands.extend([[r] for r in runs])
    return cands


MAX_CONJUNCTS = 4


def necessary_factor_conjunction(node) -> list[list[Factor]]:
    """A conjunction (AND) of disjunctive factor sets, all mandatory.

    E.g. for the aws-secret-access-key shape `...aws...key...<token>...` this
    yields [{aws}, {key}, ...]: a file must contain every conjunct's factor for
    the rule to possibly match.  Returns [] when nothing usable exists.
    """
    if isinstance(node, Seq):
        sets = [c for c in _seq_candidates(node) if c is not None]
    else:
        one = necessary_factors(node)
        sets = [one] if one is not None else []
    usable = []
    for s in sets:
        trimmed = [t for t in (_trim_factor(f) for f in s) if t]
        if len(trimmed) == len(s) and _score_set(trimmed) >= MIN_ANCHOR_SCORE:
            usable.append(trimmed)
    usable.sort(key=_score_set, reverse=True)
    return usable[:MAX_CONJUNCTS]


# ---------------------------------------------------------------------------
# Probe set assembly
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Probe:
    classes: tuple[int, ...]  # byte-set bitmask per offset


@dataclass
class RuleProbePlan:
    """Per-rule sieve plan.

    candidate(file) = gate AND all conjuncts, where the gate is an OR over
    keyword probes (empty = always passes, like a keyword-less rule) and each
    anchor conjunct is an OR over factor probes (no conjuncts = no usable
    anchor, anchor side always passes).
    """

    rule_id: str
    gate_probe_ids: list[int] = field(default_factory=list)
    anchor_conjuncts: list[list[int]] = field(default_factory=list)


@dataclass
class ProbeSet:
    probes: list[Probe]
    plans: list[RuleProbePlan]
    jmax: int

    @property
    def num_probes(self) -> int:
        return len(self.probes)

    @property
    def num_words(self) -> int:
        return (len(self.probes) + 31) // 32

    def build_lut(self) -> np.ndarray:
        """LUT [Jmax, 256, Pw] uint32 for the packed shift-AND sieve."""
        pw = self.num_words
        lut = np.zeros((self.jmax, 256, pw), dtype=np.uint32)
        for p, probe in enumerate(self.probes):
            w, bit = p // 32, np.uint32(1 << (p % 32))
            for j in range(self.jmax):
                if j < len(probe.classes):
                    bs = probe.classes[j]
                    for b in range(256):
                        if bs >> b & 1:
                            lut[j, b, w] |= bit
                else:
                    lut[j, :, w] |= bit  # always-true padding beyond probe length
        return lut

    def gate_masks(self) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Per-rule packed probe masks for candidate resolution.

        Returns (gate_mask[R,Pw], gate_any[R], conj_mask[R,K,Pw], conj_any[R,K]):
        candidate(file, r) = (not gate_any[r] or hits & gate_mask[r])
                         and all_k (not conj_any[r,k] or hits & conj_mask[r,k])
        """
        r = len(self.plans)
        pw = self.num_words
        gate = np.zeros((r, pw), dtype=np.uint32)
        gate_any = np.zeros(r, dtype=bool)
        conj = np.zeros((r, MAX_CONJUNCTS, pw), dtype=np.uint32)
        conj_any = np.zeros((r, MAX_CONJUNCTS), dtype=bool)
        for i, plan in enumerate(self.plans):
            for p in plan.gate_probe_ids:
                gate[i, p // 32] |= np.uint32(1 << (p % 32))
                gate_any[i] = True
            for k, conjunct in enumerate(plan.anchor_conjuncts):
                for p in conjunct:
                    conj[i, k, p // 32] |= np.uint32(1 << (p % 32))
                    conj_any[i, k] = True
        return gate, gate_any, conj, conj_any


def _keyword_factor(kw: str) -> Factor:
    return [bs_fold_case(1 << b) for b in kw.lower().encode()]


def build_probe_set(rules: list[Rule]) -> ProbeSet:
    probes: list[Probe] = []
    index: dict[tuple[int, ...], int] = {}

    def intern(f: Factor) -> int | None:
        f = _trim_factor(f)
        if not f:
            return None
        key = tuple(f)
        if key not in index:
            index[key] = len(probes)
            probes.append(Probe(classes=key))
        return index[key]

    plans: list[RuleProbePlan] = []
    for rule in rules:
        plan = RuleProbePlan(rule_id=rule.id)
        for kw in rule.keywords:
            pid = intern(_keyword_factor(kw))
            if pid is None:
                # Keyword unusable as a probe => the gate must pass always.
                plan.gate_probe_ids = []
                break
            plan.gate_probe_ids.append(pid)
        if rule.regex_src:
            try:
                irn = parse_ir(goregex.go_to_python(rule.regex_src))
                conjunction = necessary_factor_conjunction(irn)
            except (UnsupportedRegex, goregex.GoRegexError):
                conjunction = []
            for conjunct in conjunction:
                ids = [intern(f) for f in conjunct]
                if all(i is not None for i in ids):
                    plan.anchor_conjuncts.append(sorted({i for i in ids if i is not None}))
        plans.append(plan)

    jmax = max((len(p.classes) for p in probes), default=1)
    return ProbeSet(probes=probes, plans=plans, jmax=jmax)


# ---------------------------------------------------------------------------
# NumPy reference sieve (oracle for the JAX/Pallas implementations)
# ---------------------------------------------------------------------------


def sieve_hits_numpy(content: bytes, pset: ProbeSet, lut: np.ndarray | None = None) -> np.ndarray:
    """Probe presence bitmap [Pw] uint32 for one blob (reference implementation)."""
    if lut is None:
        lut = pset.build_lut()
    jmax = pset.jmax
    data = np.frombuffer(content + b"\x00" * jmax, dtype=np.uint8)
    n = len(data)
    acc = lut[0, data[: n - jmax + 1]]
    for j in range(1, jmax):
        acc &= lut[j, data[j : n - jmax + 1 + j]]
    return np.bitwise_or.reduce(acc, axis=0)


def candidate_rules(hits: np.ndarray, pset: ProbeSet) -> list[int]:
    """Rule indices that could match given a probe-hit bitmap."""
    gate, gate_any, conj, conj_any = pset.gate_masks()
    out = []
    for i in range(len(pset.plans)):
        if gate_any[i] and not (hits & gate[i]).any():
            continue
        ok = True
        for k in range(conj.shape[1]):
            if conj_any[i, k] and not (hits & conj[i, k]).any():
                ok = False
                break
        if ok:
            out.append(i)
    return out
