"""Bounded-depth chunk pipeline: overlap h2d staging, device exec, d2h fetch.

The device path is link-starved, not compute-starved (BENCH r05:
`link_bound_fraction` 0.933 on the 10k device-engine config — 1.03s of h2d
against 0.07s of exec+fetch, while the Pallas kernel itself sustains ~30 GB/s
on-device).  The link floor only binds wall-clock if nothing else runs while
bytes move, so the fix is structural, not a faster kernel: split a scan batch
into fixed-bucket chunks and keep three stages in flight at once —

  stage   h2d staging of chunk N+1 (async `jax.device_put`, never
          `block_until_ready` before exec needs the buffer)
  exec    device exec of chunk N (donated input on TPU so XLA reuses the
          staging allocation instead of copying)
  finish  d2h fetch + host confirm of chunk N-1

`ChunkPipeline` is the small scheduler both device engines drive
(`engine/device.py::TpuSecretEngine._sieve_rows`, the stream verifier in
`engine/nfa_device.py`) and that `HybridSecretEngine.scan_batch` uses in
place of its hand-rolled two-deep sieve deque.  Depth is bounded (default
2 chunks in flight beyond the one being finished) so host and device
memory stay O(depth * chunk), and a chunk that raises drains the pipeline
cleanly: queued work is cancelled, the in-flight tail is dropped, and the
exception propagates.

`ResidentChunkCache` is the companion device-side dedupe: a bounded LRU of
sieve results keyed by packed-chunk content digest (interface mirrors
`trivy_tpu/cache/store.py::ArtifactCache.missing_blobs`), so a rescan of a
mostly-unchanged corpus ships only changed rows across the link.

`ResidentRowStore` is the fused-pipeline extension of the same idea: it
keeps the STAGED row buffers themselves (plus their sieve hit words)
device-resident under the same digest-keyed LRU discipline, so the fused
sieve→verify path (engine/device.py `_sieve_rows_fused`, the lane-derive
kernel, engine/nfa_device.py's fused verify) reads from residency instead
of paying a host round-trip — the zero-re-upload assumption the hybrid
gate prices (engine/link.py FUSED_REUPLOAD_RATIO).
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Iterable

from trivy_tpu.obs import memwatch
from trivy_tpu.obs import trace as obs_trace

DEFAULT_DEPTH = 2
DEFAULT_RESIDENT_CHUNKS = 32


def default_depth() -> int:
    """Pipeline depth: chunks staged/executing beyond the one finishing.
    1 = fully serial (stage, exec, finish each chunk before the next).
    TRIVY_TPU_PIPELINE_DEPTH overrides (bench serial-vs-pipelined A/B)."""
    try:
        return max(1, int(os.environ.get("TRIVY_TPU_PIPELINE_DEPTH", "")))
    except ValueError:
        return DEFAULT_DEPTH


@dataclass
class PipelineStats:
    """Per-run accounting, merged into SieveStats by the engines."""

    depth: int = 0
    chunks: int = 0
    stage_s: float = 0.0  # host-side issue cost of staging (async h2d)
    finish_s: float = 0.0  # d2h fetch + host confirm
    # Finish time during which >= 1 LATER chunk was staged or executing —
    # the transfer/compute wall-clock the pipeline actually hid.  Serial
    # depth=1 runs report 0 here by construction.
    h2d_overlap_s: float = 0.0


class ChunkPipeline:
    """Three-stage bounded scheduler over an ordered chunk sequence.

    stage(chunk)            -> staged   issue async work (device_put / worker
                                        submit); must not block on the device
    execute(chunk, staged)  -> handle   issue the async device exec (or pass
                                        `staged` through for host pipelines)
    finish(chunk, handle)   -> None     block on the handle, fetch, confirm

    Chunks finish strictly in submission order (engines write results into
    order-indexed slots, and the hybrid's oracle confirm must see files in
    corpus order for byte-identical findings).  `cancel(chunk, handle)` is
    called for never-finished in-flight chunks when a stage raises.
    """

    def __init__(
        self,
        stage: Callable,
        execute: Callable,
        finish: Callable,
        depth: int | None = None,
        cancel: Callable | None = None,
    ):
        self._stage = stage
        self._execute = execute
        self._finish = finish
        self._cancel = cancel
        self.stats = PipelineStats(depth=depth or default_depth())

    def run(self, chunks: Iterable) -> None:
        with obs_trace.span("pipeline", depth=self.stats.depth) as sp:
            self._run(chunks)
            sp.set(
                chunks=self.stats.chunks,
                h2d_overlap_s=round(self.stats.h2d_overlap_s, 4),
            )

    def _run(self, chunks: Iterable) -> None:
        depth = self.stats.depth
        inflight: deque = deque()
        try:
            for chunk in chunks:
                while len(inflight) >= depth:
                    self._finish_one(inflight)
                t0 = time.perf_counter()
                staged = self._stage(chunk)
                self.stats.stage_s += time.perf_counter() - t0
                inflight.append((chunk, self._execute(chunk, staged)))
                self.stats.chunks += 1
            while inflight:
                self._finish_one(inflight)
        except BaseException:
            # Drain cleanly: drop (and cancel) whatever is still in flight
            # so the caller's partial results stay consistent and worker
            # pools shut down without finishing abandoned chunks.
            if self._cancel is not None:
                for chunk, handle in inflight:
                    try:
                        self._cancel(chunk, handle)
                    except Exception:  # graftlint: swallow(best-effort cancel mid-drain; outer raise carries the cause)
                        pass
            inflight.clear()
            raise

    def _finish_one(self, inflight: deque) -> None:
        chunk, handle = inflight.popleft()
        overlapped = len(inflight) > 0  # later chunks staged/executing now
        t0 = time.perf_counter()
        self._finish(chunk, handle)
        dt = time.perf_counter() - t0
        self.stats.finish_s += dt
        if overlapped:
            self.stats.h2d_overlap_s += dt


def chunk_digest(buf) -> str:
    """Content digest of a packed chunk (any buffer-protocol object);
    keys the ResidentChunkCache the way blob digests key ArtifactCache."""
    return hashlib.blake2b(memoryview(buf), digest_size=16).hexdigest()


class StagingHandles:
    """Release-once group over the per-shard memwatch handles of one
    staged chunk — the pipeline's finish/cancel sites hold exactly one
    handle per chunk regardless of how many devices it landed on."""

    __slots__ = ("_handles",)

    def __init__(self, handles):
        self._handles = tuple(handles)

    def release(self) -> None:
        for h in self._handles:
            h.release()
        self._handles = ()


def stage_rows(buf, mesh=None, real_rows=None,
               component: str = "pipeline-staging", track: bool = True):
    """H2d-stage one packed row chunk; returns (device_array, handles).

    Unmeshed: one async `jax.device_put`, exactly the staging the
    pipeline always did.  Meshed: the chunk splits row-wise into one
    shard per device — each device gets its own double-buffered staging
    lane, every shard `device_put` on its own chip so the transfers
    overlap ACROSS chips as well as against exec — and the shards
    assemble into one global array laid out per the partition plan
    ("coded_rows").  Per-shard bytes are memwatch-ledgered per device,
    and the topology occupancy ledger records each device's REAL row
    share (`real_rows` excludes bucket padding), which is what
    `/debug/mesh` and the MULTICHIP bench's scaling efficiency read.
    """
    import jax

    if mesh is not None:
        from trivy_tpu.mesh import plan as mesh_plan
        from trivy_tpu.mesh import topology as mesh_topology

        devices = mesh_topology.mesh_devices(mesh)
        n = len(devices)
        rows = buf.shape[0]
        if n > 1 and rows % n == 0:
            if real_rows is None:
                real_rows = rows
            rpd = rows // n
            shards, handles = [], []
            for i, d in enumerate(devices):
                part = buf[i * rpd : (i + 1) * rpd]
                shards.append(jax.device_put(part, d))
                tag = mesh_topology.device_tag(d)
                real = max(0, min(rpd, real_rows - i * rpd))
                mesh_topology.record_occupancy(tag, real, part.nbytes)
                if track:
                    handles.append(
                        memwatch.track(component, part.nbytes, device=tag)
                    )
            dev = jax.make_array_from_single_device_arrays(
                buf.shape, mesh_plan.sharding_for(mesh, "coded_rows"), shards
            )
            return dev, StagingHandles(handles)
        # Engine buckets are device-aligned; an unaligned chunk (or a
        # degenerate 1-device mesh) stages unsharded rather than crash.
    dev = jax.device_put(buf)
    handles = [memwatch.track(component, buf.nbytes)] if track else []
    return dev, StagingHandles(handles)


def shard_nbytes(value) -> dict[str, int]:
    """Per-device byte map for (tuples of) multi-device jax arrays; {}
    when nothing in `value` spans more than one device (numpy buffers,
    single-device arrays — the aggregate ledger path covers those)."""
    out: dict[str, int] = {}

    def walk(v) -> None:
        if isinstance(v, (tuple, list)):
            for x in v:
                walk(x)
            return
        shards = getattr(v, "addressable_shards", None)
        if not shards or len(shards) <= 1:
            return
        for s in shards:
            d = s.device
            tag = f"{d.platform}:{getattr(d, 'id', 0)}"
            out[tag] = out.get(tag, 0) + int(
                getattr(s.data, "nbytes", 0) or 0
            )

    walk(value)
    return out


class ResidentChunkCache:
    """Bounded LRU of per-chunk sieve results keyed by chunk digest.

    The device-resident analogue of the blob-level ArtifactCache: a rescan
    whose packed chunks digest identically never re-ships those rows (the
    cached hit words ARE the chunk's device output, so neither the h2d
    transfer nor the dispatch happens again).  Interface mirrors
    `ArtifactCache.missing_blobs` so callers can diff before staging.
    """

    def __init__(self, capacity: int | None = None,
                 component: str = "chunk-cache"):
        if capacity is None:
            try:
                capacity = int(
                    os.environ.get("TRIVY_TPU_RESIDENT_CHUNKS", "")
                )
            except ValueError:
                capacity = DEFAULT_RESIDENT_CHUNKS
        self.capacity = max(0, capacity)
        self._lru: OrderedDict[str, object] = OrderedDict()
        self.hits = 0
        self.misses = 0
        # Memwatch attribution: every cached result's bytes are ledgered
        # under `component` for as long as the entry is resident.
        self._component = component
        self._mw: dict[str, object] = {}

    def __len__(self) -> int:
        return len(self._lru)

    def get(self, digest: str):
        """Cached chunk result or None; a hit refreshes LRU order."""
        if self.capacity == 0:
            return None
        val = self._lru.get(digest)
        if val is None:
            self.misses += 1
            return None
        self._lru.move_to_end(digest)
        self.hits += 1
        return val

    def _track(self, value) -> StagingHandles:
        """Ledger one entry's bytes: sharded device values get one handle
        per device (the shard layout the entry carries), anything else a
        single aggregate handle; any unsharded remainder of a mixed tuple
        is ledgered on the default device so sums stay exact."""
        per_dev = shard_nbytes(value)
        handles = [
            memwatch.track(self._component, nb, device=dev, owner=self)
            for dev, nb in sorted(per_dev.items())
        ]
        rest = memwatch.nbytes_of(value) - sum(per_dev.values())
        if rest > 0 or not handles:
            handles.append(
                memwatch.track(self._component, rest, owner=self)
            )
        return StagingHandles(handles)

    def put(self, digest: str, value) -> None:
        if self.capacity == 0:
            return
        old = self._mw.pop(digest, None)
        if old is not None:
            old.release()
        self._lru[digest] = value
        self._lru.move_to_end(digest)
        self._mw[digest] = self._track(value)
        while len(self._lru) > self.capacity:
            evicted, _ = self._lru.popitem(last=False)
            mw = self._mw.pop(evicted, None)
            if mw is not None:
                mw.release()

    def missing_chunks(self, digests: Iterable[str]) -> list[str]:
        """ArtifactCache.missing_blobs shape: digests NOT resident (these
        are the rows a rescan must actually ship)."""
        return [d for d in digests if d not in self._lru]

    def clear(self) -> None:
        self._lru.clear()
        for mw in self._mw.values():
            mw.release()
        self._mw.clear()

    def nbytes(self) -> int:
        """Total resident bytes across live entries (ledger cross-check)."""
        return sum(memwatch.nbytes_of(v) for v in self._lru.values())


class ResidentRowStore(ResidentChunkCache):
    """Digest-keyed LRU of STAGED row buffers + their sieve hit words,
    both kept as device arrays for the fused sieve→verify pipeline.

    Where ResidentChunkCache memoises only the sieve OUTPUT (hit words,
    so a duplicate chunk skips the dispatch), this store also retains the
    sieve INPUT rows on device so the fused verify walk can gather its
    windows in place — the re-upload the legacy path pays per verify
    dispatch never happens.  Entries are `(rows_dev, hits_dev)` tuples;
    eviction follows the same LRU + memwatch discipline as the parent
    (component "resident-rows", capacity TRIVY_TPU_RESIDENT_CHUNKS).

    Entry invariant: `rows_dev` is the uint8 staged row block exactly as
    shipped (coded or raw per the chunk's codec tag — callers key the
    digest with the tag, mirroring `_sieve_rows`'s resident-LRU key), and
    `hits_dev` the matching [rows, n_words] uint32 hit bitmap.

    Megakernel entries (engine/device.py `_mega_candidates`) reuse the
    same store with `(rows_dev, mask_dev)` tuples — the packed verdict
    mask instead of hit words — under digests additionally suffixed with
    the KERNEL id and the batch's file-interval digest.  The kernel id
    changes whenever the fused program's baked constants change (ruleset
    or codec rebake), so staged-path hit words and fused verdict masks
    can never alias each other or a stale program's output.
    """

    def __init__(self, capacity: int | None = None):
        super().__init__(capacity, component="resident-rows")

    def put_rows(self, digest: str, rows_dev, hits_dev) -> None:
        self.put(digest, (rows_dev, hits_dev))

    def rows(self, digest: str):
        """Resident (rows_dev, hits_dev) or None; refreshes LRU order."""
        return self.get(digest)
